"""Per-site per-step reuse schedules — the generalized phase gate (ISSUE 15).

PR 1's single static ``gate`` is the crudest point in the TAD/A-SDM design
space: it flips *all* cross-attention sites from full-CFG compute to cached
reuse at one step. TAD (arXiv 2404.02747) measures that temporal redundancy
differs per attention block, and A-SDM (arXiv 2406.00210) shows self-attn
features can be inherited across adjacent steps — so the win left on the
table is a schedule that decides, **per attention site and per scan step**,
one of three actions:

- **compute-full-CFG** — the site runs normally (and, if it will ever be
  reused, overwrites its cache slot with this step's output);
- **reuse-cross-attn-from-AttnCache** — a cross site returns its cached
  output (the TAD mechanism PR 1 applied uniformly);
- **inherit-feature-from-previous-step** — a self site returns the output
  frozen at its last computed step (A-SDM feature inheritance; mechanically
  the same cache, applied to self-attention sites).

A :class:`ReuseSchedule` is a **frozen static table** (hashable pytree-free
dataclass), so each distinct schedule is ONE compiled program: it joins
``compile_key`` — and the phase-1/phase-2 split keys, via the
:func:`phase1_view`/:func:`phase2_view` projections — exactly like ``gate``
does today. The step where the CFG (uncond) branch drops, ``cfg_gate``, IS
the serve engine's phase boundary: the two-pool hand-off machinery
(``PhaseCarry``/``spill_carry``/``stack_carries``) carries the scheduled
per-site cache state with no new hand-off plumbing.

The **uniform** schedule — every cross site reused from step ``g``, no self
site ever reused, CFG dropped at ``g`` — is semantically ``gate=g``;
:func:`ReuseSchedule.uniform_gate` detects it and callers normalize it back
onto the exact PR-1 gate path, so uniform schedules are *bitwise-identical*
to ``gate=g`` by construction (and pool with plain gated requests). The
segmented executor reproducing the gate path on a uniform table is pinned
separately (tests/test_schedule.py), the PR-6 split-equals-monolith idiom.

Execution model: the scan is cut into contiguous **segments** over which the
per-site action vector is constant; each segment is one ``lax.scan`` with a
static :func:`SitePlan` (see ``engine.sampler._scheduled_phase1/2``).
Compile time grows with the number of distinct flip steps, not with S.

Resblock-level inheritance (the remaining A-SDM axis) is deliberately out of
scope: resnets are not layout sites, so scheduling them is a layout change —
noted in PERF.md as follow-up.
"""

from __future__ import annotations

import dataclasses
import json
import re
from typing import Dict, List, Optional, Tuple

#: Per-site, per-segment actions. ``store``: compute and overwrite the cache
#: slot with the *conditional half* of the CFG-doubled output (the PR-1
#: phase-1 capture — the leaf a post-``cfg_gate`` segment consumes).
#: ``store_all``: compute and overwrite with the full batch (a site reused
#: while CFG is still active needs both halves; post-gate the "full batch"
#: is the cond-only batch, so ``store_all`` is also the post-gate store).
#: ``use``: return the cached output, computing nothing. ``off``: plain
#: compute, no cache slot.
MODE_OFF = "off"
MODE_STORE = "store"
MODE_STORE_ALL = "store_all"
MODE_USE = "use"

_SITE_NAME_RE = re.compile(r"^(cross_attn|self_attn)/(down|mid|up)\d+$")

_SPEC_KEYS = {"version", "cfg_gate", "cross", "self", "comment", "provenance"}


def site_name(meta) -> str:
    """The canonical name of one attention site — identical to the
    ``jax.named_scope`` the U-Net wraps the site in (``cross_attn/down3``),
    so a schedule artifact, a Perfetto trace and the cost attribution all
    speak the same site vocabulary."""
    kind = "cross_attn" if meta.is_cross else "self_attn"
    return f"{kind}/{meta.place}{meta.layer_idx}"


def site_names(layout, kind: str) -> Tuple[str, ...]:
    """Site names of one kind (``'cross'``/``'self'``) in call order — the
    order the per-kind reuse tuples of a :class:`ReuseSchedule` index."""
    cross = kind == "cross"
    return tuple(site_name(m) for m in layout.metas if m.is_cross == cross)


@dataclasses.dataclass(frozen=True)
class ReuseSchedule:
    """The resolved static reuse table for one scan length.

    ``steps`` is the scan length S (PLMS includes its warm-up step, same as
    ``resolve_gate``). ``cfg_gate`` ∈ [1, S] is the first step without the
    uncond batch half (S = CFG everywhere — a schedule may cache sites
    without ever dropping CFG). ``cross``/``selfa`` hold one entry per
    cross/self attention site in layout call order: the first scan step the
    site is served from its cache (S = never reused). All static ints, so
    the whole table is hashable and rides ``jax.jit`` static arguments."""

    steps: int
    cfg_gate: int
    cross: Tuple[int, ...]
    selfa: Tuple[int, ...]

    def __post_init__(self):
        s = self.steps
        if s < 1:
            raise ValueError(f"schedule needs steps >= 1, got {s}")
        if not 1 <= self.cfg_gate <= s:
            raise ValueError(f"cfg_gate {self.cfg_gate} outside [1, {s}]")
        for kind, table in (("cross", self.cross), ("self", self.selfa)):
            for i, r in enumerate(table):
                if not 1 <= r <= s:
                    raise ValueError(
                        f"{kind} site {i}: reuse step {r} outside [1, {s}] "
                        f"(use {s} for 'never')")

    @property
    def gated(self) -> bool:
        """Does this schedule drop the CFG branch before the end — i.e.
        does it cross the serve engine's two-pool phase boundary?"""
        return self.cfg_gate < self.steps

    @property
    def uniform_gate(self) -> Optional[int]:
        """The gate step this schedule is exactly equivalent to, or None.

        Uniform-at-g means: CFG drops at g, every cross site flips to its
        cache at g, no self site is ever reused — the PR-1 ``gate=g``
        program. ``g == steps`` (nothing gated, nothing cached) is the
        ungated program, returned as ``steps`` (callers map it to
        ``gate=None``). Callers normalize uniform schedules onto the gate
        path so they are bitwise-identical to — and pool with — plain
        gated requests."""
        g = self.cfg_gate
        if any(r != self.steps for r in self.selfa):
            return None
        if g == self.steps:
            return g if all(r == self.steps for r in self.cross) else None
        return g if all(r == g for r in self.cross) else None

    def key(self) -> Tuple:
        """The schedule's compile-key component: the table CONTENTS, so two
        identical tables loaded from different files derive equal keys (and
        pool), while tables differing in a single site-step entry differ."""
        return ("sched", self.steps, self.cfg_gate, self.cross, self.selfa)

    @classmethod
    def from_key(cls, key: Tuple) -> "ReuseSchedule":
        """Rebuild the schedule from its :meth:`key` tuple — the serve
        runners reconstruct the static table from the compile key alone."""
        tag, steps, cfg_gate, cross, selfa = key
        assert tag == "sched", key
        return cls(steps=steps, cfg_gate=cfg_gate, cross=tuple(cross),
                   selfa=tuple(selfa))

    def sites_cached(self) -> Dict[str, int]:
        """How many sites the schedule ever serves from cache, by kind —
        the bench ``gate.schedule`` sub-record's histogram source."""
        return {
            "cross": sum(1 for r in self.cross if r < self.steps),
            "self": sum(1 for r in self.selfa if r < self.steps),
            "cross_sites": len(self.cross),
            "self_sites": len(self.selfa),
        }

    def cached_site_steps_fraction(self) -> float:
        """Fraction of all (site, step) cells served from cache — the
        scalar 'how much compute does this table skip' summary."""
        total = (len(self.cross) + len(self.selfa)) * self.steps
        saved = sum(self.steps - r for r in self.cross)
        saved += sum(self.steps - r for r in self.selfa)
        return saved / total if total else 0.0


def phase1_view(sched: ReuseSchedule) -> ReuseSchedule:
    """The phase-1 projection: the part of the table that shapes the
    program for steps ``[0, cfg_gate)``. Reuse steps at or past the gate
    collapse to the gate (phase 1 only sees "stores until the boundary");
    never-reused stays never (the site has no cache leaf at all). Two
    schedules with equal phase-1 views compile — and must pool — the same
    phase-1 program, so this projection (via :meth:`ReuseSchedule.key`) is
    the ``phase1_key`` schedule component."""
    g, s = sched.cfg_gate, sched.steps

    def clamp(r: int) -> int:
        return r if r < g else (g if r < s else s)

    return ReuseSchedule(steps=s, cfg_gate=g,
                         cross=tuple(clamp(r) for r in sched.cross),
                         selfa=tuple(clamp(r) for r in sched.selfa))


def phase2_view(sched: ReuseSchedule) -> ReuseSchedule:
    """The phase-2 projection: the part of the table that shapes the
    program for steps ``[cfg_gate, S)``. Reuse steps before the gate
    collapse to the gate (phase 2 only sees "reused from entry"); a site
    that flips inside phase 2 keeps its exact step. Schedules differing
    only before the gate share a phase-2 view — their phase-2 lanes pack
    into one pool program (the ``phase2_key`` schedule component)."""
    g, s = sched.cfg_gate, sched.steps

    def clamp(r: int) -> int:
        return r if r >= g else g

    return ReuseSchedule(steps=s, cfg_gate=g,
                         cross=tuple(clamp(r) if r < s else s
                                     for r in sched.cross),
                         selfa=tuple(clamp(r) if r < s else s
                                     for r in sched.selfa))


# ---------------------------------------------------------------------------
# Spec: the user-facing (JSON) schedule table
# ---------------------------------------------------------------------------


def validate_spec(spec: dict) -> None:
    """Structural validation of a schedule spec — admission-time cheap, no
    layout needed. A spec is a JSON object::

        {"version": 1,
         "cfg_gate": 0.5 | <int step> | "auto" | null,
         "cross": {"*": 0.5, "cross_attn/down3": 0.25, ...},
         "self":  {"*": null, "self_attn/up8": 0.85, ...}}

    Fractions are of the scan length (resolved per request, like ``gate``);
    ``null`` means never reused (for ``cfg_gate``: CFG never drops). Site
    keys must be canonical site names (the ``jax.named_scope`` vocabulary)
    or ``"*"`` (the default for unlisted sites); names that parse as a
    site of ANOTHER model's layout are tolerated at resolve time (one
    committed artifact serves models with different site counts), anything
    else is an error — the honored-flags discipline."""
    if not isinstance(spec, dict):
        raise ValueError(f"schedule spec must be a JSON object, "
                         f"got {type(spec).__name__}")
    unknown = set(spec) - _SPEC_KEYS
    if unknown:
        raise ValueError(f"unknown schedule spec key(s) {sorted(unknown)}; "
                         f"valid: {sorted(_SPEC_KEYS)}")
    if spec.get("version", 1) != 1:
        raise ValueError(f"unsupported schedule spec version "
                         f"{spec.get('version')!r} (expected 1)")
    _check_step_spec(spec.get("cfg_gate"), "cfg_gate", allow_auto=True)
    for kind in ("cross", "self"):
        table = spec.get(kind)
        if table is None:
            continue
        if not isinstance(table, dict):
            raise ValueError(f"schedule spec {kind!r} must be an object "
                             f"mapping site names to steps, got "
                             f"{type(table).__name__}")
        for name, v in table.items():
            if name != "*" and not _SITE_NAME_RE.match(name):
                raise ValueError(
                    f"schedule spec {kind!r} has invalid site key {name!r}"
                    " (expected '*' or a canonical site name like "
                    "'cross_attn/down3')")
            if name != "*" and not name.startswith(
                    "cross_attn/" if kind == "cross" else "self_attn/"):
                raise ValueError(
                    f"schedule spec {kind!r} key {name!r} names a site of "
                    "the other kind")
            _check_step_spec(v, f"{kind}[{name}]", allow_auto=False)


def _check_step_spec(v, what: str, allow_auto: bool) -> None:
    if v is None:
        return
    if isinstance(v, str):
        if allow_auto and v == "auto":
            return
        raise ValueError(f"schedule {what} must be null, a fraction or a "
                         f"step index{', or auto' if allow_auto else ''}, "
                         f"got {v!r}")
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise ValueError(f"schedule {what} must be numeric, got {v!r}")
    if isinstance(v, float) and not 0.0 < v <= 1.0:
        raise ValueError(f"schedule {what} fraction {v} outside (0, 1]")
    if isinstance(v, int) and v < 1:
        raise ValueError(f"schedule {what} step {v} must be >= 1")


def _resolve_step(v, num_scan: int, default: int,
                  controller=None) -> int:
    """One spec cell → a static scan step, ``resolve_gate`` semantics:
    float = fraction of the scan (rounded), int = absolute step, None =
    ``default``. Clamped to [1, S]."""
    if v is None:
        return default
    if v == "auto":
        from ..controllers.base import controller_step_window

        return min(num_scan,
                   max(num_scan // 2,
                       controller_step_window(controller, num_scan), 1))
    if isinstance(v, float):
        # Same boundary discipline as resolve_gate: a fraction that
        # rounds outside [1, S] is a rejected typo, never a silent clamp.
        step = int(round(v * num_scan))
    else:
        step = int(v)
    if not 1 <= step <= num_scan:
        raise ValueError(f"schedule step {v!r} resolves to {step}, "
                         f"outside [1, {num_scan}]")
    return step


def resolve_schedule(spec, layout, num_scan: int,
                     controller=None) -> ReuseSchedule:
    """Resolve a spec (or pass through an already-resolved table) against a
    concrete layout and scan length. Unlisted sites take the kind's ``"*"``
    default; without one, cross sites default to the ``cfg_gate`` (the
    uniform gate behavior) and self sites to never-reused — so
    ``{"cfg_gate": 0.5}`` alone IS the PR-1 ``gate=0.5``."""
    if isinstance(spec, ReuseSchedule):
        if spec.steps != num_scan:
            raise ValueError(
                f"resolved schedule is for a {spec.steps}-step scan, "
                f"request runs {num_scan}")
        n_cross = sum(1 for m in layout.metas if m.is_cross)
        n_self = sum(1 for m in layout.metas if not m.is_cross)
        if len(spec.cross) != n_cross or len(spec.selfa) != n_self:
            raise ValueError(
                f"resolved schedule has {len(spec.cross)} cross / "
                f"{len(spec.selfa)} self entries; layout has "
                f"{n_cross}/{n_self}")
        return spec
    validate_spec(spec)
    cfg_gate = _resolve_step(spec.get("cfg_gate"), num_scan, num_scan,
                             controller=controller)

    def table(kind: str, metas, default: int) -> Tuple[int, ...]:
        raw = dict(spec.get(kind) or {})
        # An EXPLICIT null means "never reused" — distinct from an absent
        # key, which falls back to the kind default (cfg_gate for cross,
        # never for self). ``{"*": null}`` therefore pins every unlisted
        # site of the kind to never.
        if "*" in raw:
            star = raw.pop("*")
            kind_default = (num_scan if star is None
                            else _resolve_step(star, num_scan, default))
        else:
            kind_default = default
        out = []
        for m in metas:
            name = site_name(m)
            if name in raw:
                v = raw.pop(name)
                out.append(num_scan if v is None
                           else _resolve_step(v, num_scan, kind_default))
            else:
                out.append(kind_default)
        # Leftover names target sites this layout doesn't have (an
        # artifact shared across models) — already shape-validated by
        # validate_spec, so they are silently inapplicable here.
        return tuple(out)

    cross = table("cross", [m for m in layout.metas if m.is_cross],
                  default=cfg_gate)
    selfa = table("self", [m for m in layout.metas if not m.is_cross],
                  default=num_scan)
    return ReuseSchedule(steps=num_scan, cfg_gate=cfg_gate, cross=cross,
                         selfa=selfa)


def load_spec(path: str) -> dict:
    """Load + validate a schedule artifact (``tools/schedules/*.json``)."""
    with open(path) as f:
        spec = json.load(f)
    validate_spec(spec)
    return spec


# ---------------------------------------------------------------------------
# Segmentation: the static per-segment site plans the executor scans with
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Segment:
    """One contiguous scan range with a constant per-site action vector.
    ``plan`` has one mode per layout site in call order (the
    ``apply_unet(site_plan=)`` argument); sites whose mode is not ``off``
    own one cache leaf each, in the same order."""

    start: int
    stop: int
    cfg: bool                  # uncond batch half present (CFG active)
    plan: Tuple[str, ...]


def _reuse_step(sched: ReuseSchedule, meta, cross_idx: int,
                self_idx: int) -> int:
    return (sched.cross[cross_idx] if meta.is_cross
            else sched.selfa[self_idx])


def _site_table(layout, sched: ReuseSchedule) -> List[int]:
    """Per layout site (call order): its resolved reuse step."""
    out, ci, si = [], 0, 0
    for m in layout.metas:
        if m.is_cross:
            out.append(sched.cross[ci])
            ci += 1
        else:
            out.append(sched.selfa[si])
            si += 1
    return out


def cached_sites(layout, sched: ReuseSchedule) -> List[int]:
    """Layout indices of sites that ever hit their cache (r < S) — the
    sites that own a cache leaf, in call order."""
    return [i for i, r in enumerate(_site_table(layout, sched))
            if r < sched.steps]


def segments(layout, sched: ReuseSchedule, phase: int) -> List[Segment]:
    """Cut one phase of the scan into constant-plan segments.

    ``phase=1``: steps ``[0, cfg_gate)`` (CFG active). ``phase=2``: steps
    ``[cfg_gate, S)`` (single branch). Within each segment every site has a
    static mode; flips happen only at segment boundaries, so each segment
    compiles as one ``lax.scan``."""
    s, g = sched.steps, sched.cfg_gate
    table = _site_table(layout, sched)
    lo, hi = (0, g) if phase == 1 else (g, s)
    if lo >= hi:
        return []
    cuts = sorted({lo, hi} | {r for r in table if lo < r < hi})
    segs = []
    for a, b in zip(cuts, cuts[1:]):
        plan = []
        for i, m in enumerate(layout.metas):
            r = table[i]
            if r >= s:
                plan.append(MODE_OFF)
            elif a >= r:
                plan.append(MODE_USE)
            elif phase == 1 and r >= g:
                # Flips at-or-after the boundary: phase 1 captures the
                # cond half every step, exactly the PR-1 phase-1 store.
                plan.append(MODE_STORE)
            else:
                # Flips inside this phase: keep the full current batch
                # (2B under CFG, B past it) so the flip segment can serve
                # the site whichever batch shape is live.
                plan.append(MODE_STORE_ALL)
        segs.append(Segment(start=a, stop=b, cfg=(phase == 1),
                            plan=tuple(plan)))
    return segs


def lower_kernel_plan(layout, sched: ReuseSchedule, controller, kernels,
                      phase: int) -> List[Tuple[Segment, Tuple[str, ...]]]:
    """Static kernel lowering of one phase: for every constant-plan segment
    (:func:`segments`), the attention variant each site compiles to under
    ``kernels`` (a ``kernels.KernelConfig`` or None) — the
    ``kernels.dispatch.site_variant`` vocabulary (``use`` / ``flash`` /
    ``fused-edit`` / ``materialized``). Pure trace-time introspection over
    the same static inputs the executors consume: what
    ``_scheduled_phase1/2`` + ``apply_unet`` will actually lower, without
    building the program. ``use`` segments lower to the cache side-input
    (no attention math); ``store``/``store_all`` segments capture the site
    output *after* whichever attention variant runs — the fused
    side-output — so a controller-edited site keeps its fused-edit
    lowering while storing."""
    from ..kernels.dispatch import site_variant

    out = []
    for seg in segments(layout, sched, phase):
        variants = tuple(
            site_variant(kernels, controller, m, mode)
            for m, mode in zip(layout.metas, seg.plan))
        out.append((seg, variants))
    return out


def init_schedule_cache(layout, sched: ReuseSchedule, batch_cond: int,
                        phase: int, dtype) -> Tuple:
    """Zero cache leaves for every ever-cached site, in call order.

    ``phase=1`` leaves are the CFG-phase shapes: a site reused *while CFG
    is active* (r < cfg_gate) caches the full doubled batch ``(2B, P, C)``;
    every other cached site holds the conditional half ``(B, P, C)`` (the
    PR-1 AttnCache shape). ``phase=2`` leaves are all ``(B, P, C)`` — the
    hand-off shapes ``slice_cache_to_cond`` produces at the boundary."""
    import jax.numpy as jnp

    table = _site_table(layout, sched)
    leaves = []
    for i in cached_sites(layout, sched):
        m = layout.metas[i]
        if m.channels <= 0:
            raise ValueError(
                f"site {site_name(m)} has no channel info (layout built "
                "from 5-tuple specs); the reuse cache needs channels — "
                "rebuild the layout via unet_attn_specs")
        b = batch_cond
        if phase == 1 and table[i] < sched.cfg_gate:
            b = 2 * batch_cond
        leaves.append(jnp.zeros((b, m.pixels, m.channels), dtype))
    return tuple(leaves)


def slice_cache_to_cond(layout, sched: ReuseSchedule, cache: Tuple,
                        batch_cond: int) -> Tuple:
    """The phase boundary's cache hand-off: leaves captured at the full
    CFG batch (sites reused under CFG) drop their uncond half, so every
    leaf crossing the hand-off is ``(B, P, C)`` — the shape the phase-2
    pool program (and the journal spill template) expects."""
    table = _site_table(layout, sched)
    out = []
    for leaf, i in zip(cache, cached_sites(layout, sched)):
        if table[i] < sched.cfg_gate:
            leaf = leaf[batch_cond:]
        out.append(leaf)
    return tuple(out)


# ---------------------------------------------------------------------------
# Schedule-vs-controller-window conflicts (generalizes warn_gate_truncation)
# ---------------------------------------------------------------------------

_warned_conflicts: set = set()


def warn_schedule_conflicts(sched: ReuseSchedule, layout, controller,
                            num_scan: int) -> List[str]:
    """Warn — once per distinct conflict set — when a schedule reuses a
    site *inside* its controller's active edit window: a reused site's
    attention probabilities are never materialized, so the edit at that
    site is silently dropped past the reuse step. The generalization of
    ``warn_gate_truncation``: instead of one all-site gate-vs-window
    check, every site is checked against the window that governs its KIND
    (cross sites vs the cross-replace schedule's support, self sites vs
    the self-injection window), and the warning NAMES the offending
    sites. Returns the offending site names (for tests and the search
    tool's pruning)."""
    from ..controllers.base import (controller_edit_windows,
                                   controller_step_window)

    if controller is None:
        return []
    if getattr(controller, "store", False) and sched.gated:
        # Same explicit-store caveat as the gate path (and independent of
        # any edit window — a pure observability store has none):
        # accumulation stops at the CFG boundary.
        import warnings

        warnings.warn(
            f"schedule cfg_gate {sched.cfg_gate} < {num_scan}: the "
            "attention store stops accumulating at the CFG boundary, so "
            "averaged maps cover phase 1 only", stacklevel=3)
    window = controller_step_window(controller, num_scan)
    cross_end, self_end = controller_edit_windows(controller, num_scan)
    if window <= 0:
        return []
    table = _site_table(layout, sched)
    offending = []
    for i, r in enumerate(table):
        m = layout.metas[i]
        end = cross_end if m.is_cross else self_end
        if r < end:
            offending.append(f"{site_name(m)}@{r}<{end}")
    if sched.cfg_gate < window:
        offending.append(f"cfg_gate@{sched.cfg_gate}<{window}")
    if offending:
        key = (tuple(offending), window)
        if key not in _warned_conflicts:
            _warned_conflicts.add(key)
            import warnings

            warnings.warn(
                f"reuse schedule conflicts with the controller's edit "
                f"window (ends at step {window}): "
                f"{', '.join(offending)} reuse/truncate inside it — "
                "attention edits at those sites are dropped past their "
                "reuse step. Move the reuse steps to >= the window end "
                "(or shorten the edit window) to keep P2P semantics.",
                stacklevel=3)
    return offending
