"""Null-text inversion: DDIM inversion + per-step uncond-embedding optimization.

Behavioral spec: `/root/reference/null_text.py:447-630`. The reference drives
~50 + 50×(10×2) U-Net forwards and 500 Adam steps from Python; here the whole
procedure is **two compiled programs**:

1. :func:`ddim_invert` — a ``lax.scan`` over ascending timesteps with
   guidance 1 (cond-only ε, `/root/reference/null_text.py:499,558`), recording
   all T+1 latents.
2. :func:`null_optimize` — a ``lax.scan`` over the T outer steps; each step
   re-initializes Adam state over the uncond embedding and runs a
   ``lax.while_loop`` of ≤``num_inner_steps`` gradient iterations with the
   reference's decaying lr ``1e-2·(1−i/100)`` and early-stop threshold
   ``eps + i·2e-5`` (`/root/reference/null_text.py:574-606`).

The result is a serializable artifact (x_T + per-step uncond embeddings):
expensive to compute, reusable across many edits of the same image — the
persistence the reference never had (SURVEY §5 checkpoint/resume).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models import vae as vae_mod
from ..models.config import PipelineConfig
from ..models.unet import apply_unet
from ..ops import schedulers as sched_mod
from ..utils import progress as progress_mod
from .sampler import Pipeline, encode_prompts


@dataclasses.dataclass
class InversionArtifact:
    """Persistable output of :func:`invert`: everything needed to replay the
    image under CFG editing (`/root/reference/null_text.py:618` returns these
    in memory and loses them on exit)."""

    x_t: np.ndarray                  # (1, h, w, c) inverted terminal latent
    uncond_embeddings: np.ndarray    # (T, 1, L, D) per-step optimized uncond
    prompt: str
    num_steps: int
    image_gt: Optional[np.ndarray] = None   # (H, W, 3) uint8
    image_rec: Optional[np.ndarray] = None  # VAE round-trip reconstruction

    def save(self, path: str) -> None:
        np.savez(path, x_t=self.x_t, uncond_embeddings=self.uncond_embeddings,
                 prompt=np.asarray(self.prompt), num_steps=self.num_steps,
                 image_gt=self.image_gt if self.image_gt is not None else np.zeros(0),
                 image_rec=self.image_rec if self.image_rec is not None else np.zeros(0))

    @classmethod
    def load(cls, path: str) -> "InversionArtifact":
        z = np.load(path, allow_pickle=False)
        gt = z["image_gt"]
        rec = z["image_rec"]
        return cls(x_t=z["x_t"], uncond_embeddings=z["uncond_embeddings"],
                   prompt=str(z["prompt"]), num_steps=int(z["num_steps"]),
                   image_gt=gt if gt.size else None,
                   image_rec=rec if rec.size else None)


def load_image(path: str, size: int = 512, left: int = 0, right: int = 0,
               top: int = 0, bottom: int = 0) -> np.ndarray:
    """Crop-then-resize to (size, size, 3) uint8 — `/root/reference/
    null_text.py:447-466` (with its `top = min(top, h - left - 1)` copy-paste
    bug fixed: offsets clamp against their own axis)."""
    from PIL import Image

    img = np.array(Image.open(path).convert("RGB"))
    h, w = img.shape[:2]
    left = min(left, w - 1)
    right = min(right, w - left - 1)
    top = min(top, h - 1)
    bottom = min(bottom, h - top - 1)
    img = img[top:h - bottom, left:w - right]
    h, w = img.shape[:2]
    if h < w:
        off = (w - h) // 2
        img = img[:, off:off + h]
    elif w < h:
        off = (h - w) // 2
        img = img[off:off + w]
    img = np.array(Image.fromarray(img).resize((size, size)))
    return img


@partial(jax.jit, static_argnames=("cfg", "progress", "sp", "metrics"))
def _ddim_invert_jit(unet_params, vae_params, cfg: PipelineConfig,
                     schedule: sched_mod.DiffusionSchedule,
                     image: jax.Array, cond: jax.Array,
                     progress: bool = False, sp=None, metrics: bool = False):
    """image (1,H,W,3) in [-1,1] → all T+1 latents, ascending noise."""
    latent0 = vae_mod.encode(vae_params, cfg.vae, image)

    # Ascending timesteps: reversed sampling order
    # (`/root/reference/null_text.py:555-560` uses timesteps[-(i+1)]).
    ts = schedule.timesteps[::-1]

    def body(latent, scan_in):
        i, t = scan_in
        progress_mod.emit_step(progress or metrics, i, phase="invert",
                               report=progress)
        eps, _ = apply_unet(unet_params, cfg.unet, latent, t, cond, sp=sp)
        eps = sched_mod.to_epsilon(schedule, eps, t, latent)
        nxt = sched_mod.ddim_next_step(schedule, eps, t, latent)
        return nxt, nxt

    idx = jnp.arange(ts.shape[0], dtype=jnp.int32)
    x_t, all_latents = jax.lax.scan(body, latent0, (idx, ts))
    return latent0, x_t, jnp.concatenate([latent0[None], all_latents], axis=0)


def _adam_update(g, m, v, j, lr, b1=0.9, b2=0.999, eps=1e-8):
    """One Adam step (matches torch.optim.Adam defaults,
    `/root/reference/null_text.py:582`)."""
    m = b1 * m + (1 - b1) * g
    v = b2 * v + (1 - b2) * (g * g)
    mhat = m / (1 - b1 ** j)
    vhat = v / (1 - b2 ** j)
    return -lr * mhat / (jnp.sqrt(vhat) + eps), m, v


@partial(jax.jit, static_argnames=("cfg", "num_inner_steps", "progress",
                                   "sp", "metrics"))
def _null_optimize_jit(unet_params, cfg: PipelineConfig,
                       schedule: sched_mod.DiffusionSchedule,
                       latents: jax.Array,        # (T+1, 1, h, w, c) ascending
                       uncond0: jax.Array,        # (1, L, D) "" embedding
                       cond: jax.Array,           # (1, L, D) prompt embedding
                       guidance_scale: jax.Array,
                       num_inner_steps: int,
                       epsilon: jax.Array,
                       progress: bool = False, sp=None,
                       metrics: bool = False):
    """Per-timestep uncond-embedding optimization
    (`/root/reference/null_text.py:574-606`). Returns (T, 1, L, D) f32.

    The optimized embedding and its Adam state live in f32 whatever the
    model compute dtype (the reference optimizes a f32 torch tensor); the
    embedding is cast to the model dtype at each U-Net application. This is
    also what keeps the while_loop carry well-typed on the bf16 TPU path —
    Adam's f32 scalar schedule would otherwise promote the update and break
    the carry contract."""
    t_count = schedule.timesteps.shape[0]
    model_dtype = cond.dtype
    uncond0 = uncond0.astype(jnp.float32)

    def outer(carry, scan_in):
        latent_cur, uncond = carry
        i, t = scan_in
        progress_mod.emit_step(progress or metrics, i, phase="null_text",
                               report=progress)
        # Reference decay is the literal `1e-2 * (1 - i/100)` at T=50
        # (`/root/reference/null_text.py:582`) — i.e. lr halves over the run.
        # Generalized as i/(2T): identical numbers at T=50, and the schedule
        # stays positive/meaningful for any other step count.
        lr = 0.01 * (1.0 - i.astype(jnp.float32) / (2.0 * t_count))
        stop_at = epsilon + i.astype(jnp.float32) * 2e-5
        # Target: the recorded inversion latent one step less noisy
        # (`/root/reference/null_text.py:584` latents[len - i - 2]).
        target = jax.lax.dynamic_index_in_dim(
            latents, t_count - 1 - i, axis=0, keepdims=False)
        eps_cond, _ = apply_unet(unet_params, cfg.unet, latent_cur, t, cond,
                                 sp=sp)
        eps_cond = jax.lax.stop_gradient(eps_cond)
        # The loss's step math and compare run in f32 whatever the model
        # dtype (only the U-Net forwards stay in model dtype): on the bf16
        # path a bf16 (prev - target) would bottom out at ~1e-5 quantization
        # noise — the same magnitude as early_stop_epsilon, turning the
        # early-stop into a coin flip. ddim_step computes in f32 internally
        # and casts to its sample's dtype, so feed it the f32 latent.
        latent_f = latent_cur.astype(jnp.float32)
        target_f = target.astype(jnp.float32)

        def loss_fn(u):
            eps_u, _ = apply_unet(unet_params, cfg.unet, latent_cur, t,
                                  u.astype(model_dtype), sp=sp)
            eps = eps_u + guidance_scale * (eps_cond - eps_u)
            eps = sched_mod.to_epsilon(schedule, eps, t, latent_cur)
            prev = sched_mod.ddim_step(schedule, eps, t, latent_f)
            return jnp.mean(jnp.square(prev - target_f))

        def inner_cond(state):
            _, _, _, j, loss = state
            return jnp.logical_and(j < num_inner_steps, loss >= stop_at)

        def inner_body(state):
            u, m, v, j, _ = state
            loss, g = jax.value_and_grad(loss_fn)(u)
            upd, m, v = _adam_update(g, m, v, j + 1.0, lr)
            # Early-stop semantics of the reference: it breaks *after* the
            # step when the post-step loss clears the bar; we keep the update
            # unconditionally and re-test in inner_cond, same fixed point.
            return (u + upd, m, v, j + 1.0, loss)

        init = (uncond, jnp.zeros_like(uncond), jnp.zeros_like(uncond),
                jnp.float32(0.0), jnp.float32(jnp.inf))
        u_opt, _, _, j_done, _ = jax.lax.while_loop(inner_cond, inner_body,
                                                    init)
        # Inner-iteration telemetry: how many Adam steps each outer step
        # actually ran before the early-stop bar (the distribution is the
        # knob num_inner_steps should be tuned against). Traced value,
        # static tag; nothing is traced in when metrics is off.
        progress_mod.emit_event(metrics, "invert.inner_steps", j_done)

        # Advance with the optimized uncond under full CFG
        # (`/root/reference/null_text.py:602-604`).
        eps_u, _ = apply_unet(unet_params, cfg.unet, latent_cur, t,
                              u_opt.astype(model_dtype), sp=sp)
        eps = eps_u + guidance_scale * (eps_cond - eps_u)
        eps = sched_mod.to_epsilon(schedule, eps, t, latent_cur)
        latent_next = sched_mod.ddim_step(schedule, eps, t, latent_cur)
        return (latent_next, u_opt), u_opt

    steps = jnp.arange(t_count, dtype=jnp.int32)
    x_t = latents[-1]
    (_, _), uncond_list = jax.lax.scan(
        outer, (x_t, uncond0), (steps, schedule.timesteps))
    return uncond_list


def invert(
    pipe: Pipeline,
    image: np.ndarray,            # (H, W, 3) uint8 or (1, H, W, 3) float [-1,1]
    prompt: str,
    *,
    num_steps: int = 50,
    guidance_scale: Optional[float] = None,
    num_inner_steps: int = 10,
    early_stop_epsilon: float = 1e-5,
    dtype=jnp.float32,
    progress: bool = False,
    sp=None,
    gate=None,
    metrics: bool = False,
) -> InversionArtifact:
    """Full null-text inversion (`/root/reference/null_text.py:608-618`):
    DDIM-invert with guidance 1, then optimize per-step uncond embeddings so
    CFG sampling at full guidance reproduces the input image.

    ``gate`` exists only to force the phase-gating decision explicitly: the
    null-text procedure optimizes a *per-step* uncond embedding at every DDIM
    step, so CFG truncation (``gate < T``) has no valid interpretation here —
    any value other than ``None``/``num_steps`` is rejected. Replays of the
    artifact are likewise gate-free (``text2image`` rejects ``gate`` whenever
    ``uncond_embeddings`` are active).

    ``sp`` (a :class:`p2p_tpu.models.unet.SpConfig`) shards large
    self-attention sites with ring attention through both compiled
    programs — including the optimization's gradient, which recomputes
    ring-flash blocks through the einsum VJP (`parallel/ring.py`). The
    long-context path for inverting high-resolution images.

    ``metrics`` traces the telemetry callbacks into both programs
    (phase-tagged step timing plus the per-outer-step inner-iteration count
    as an ``invert.inner_steps`` host event); collected when the caller
    installed ``obs.device.instrument`` (the CLI ``--metrics`` flag does).
    Disabled, both compiled programs are unchanged."""
    if gate is not None and gate != num_steps:
        raise ValueError(
            f"null-text inversion is incompatible with phase-gated sampling "
            f"(gate={gate!r}): the optimization targets a per-step uncond "
            "embedding at every DDIM step, which CFG truncation would drop. "
            "Run invert() with gate=None; apply --gate to plain "
            "generation/editing only.")
    cfg = pipe.config
    gs = jnp.asarray(cfg.guidance_scale if guidance_scale is None else guidance_scale,
                     jnp.float32)
    if image.dtype == np.uint8:
        image_f = image.astype(np.float32) / 127.5 - 1.0
    else:
        image_f = np.asarray(image, np.float32)
    if image_f.ndim == 3:
        image_f = image_f[None]
    image_j = jnp.asarray(image_f, dtype)

    # Always DDIM (`/root/reference/null_text.py:23` — the null-text path is
    # DDIM-only), but β/α constants come from the backend's scheduler config.
    schedule = sched_mod.schedule_from_config(num_steps, cfg.scheduler, kind="ddim")
    cond = encode_prompts(pipe, [prompt], dtype=dtype)
    uncond0 = encode_prompts(pipe, [""], dtype=dtype)

    from ..obs.spans import span

    if progress:
        progress_mod.activate(num_steps, "ddim-invert")
    with span("invert.ddim", steps=num_steps):
        latent0, x_t, all_latents = _ddim_invert_jit(
            pipe.unet_params, pipe.vae_params, cfg, schedule, image_j, cond,
            progress=progress, sp=sp, metrics=metrics)

    if progress:
        # activate() drains phase-1 callbacks first (block_until_ready only
        # waits on the computation, not on host callback delivery).
        progress_mod.activate(num_steps, "null-text opt")
    with span("invert.null_optimize", steps=num_steps,
              inner_steps=num_inner_steps):
        uncond_list = _null_optimize_jit(
            pipe.unet_params, cfg, schedule, all_latents, uncond0, cond, gs,
            num_inner_steps, jnp.float32(early_stop_epsilon),
            progress=progress, sp=sp, metrics=metrics)

    rec = vae_mod.to_uint8(vae_mod.decode(
        pipe.vae_params, cfg.vae, latent0.astype(jnp.float32)))

    gt = image if image.dtype == np.uint8 else vae_mod.to_uint8(
        jnp.asarray(image_f))[0]
    return InversionArtifact(
        x_t=np.asarray(x_t),
        uncond_embeddings=np.asarray(uncond_list),
        prompt=prompt,
        num_steps=num_steps,
        image_gt=np.asarray(gt).reshape(image_f.shape[1:]) if np.asarray(gt).size else None,
        image_rec=np.asarray(rec)[0],
    )
