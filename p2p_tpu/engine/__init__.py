"""Sampling and inversion engines."""

from .inversion import InversionArtifact, invert, load_image
from .sampler import (
    Pipeline,
    encode_prompts,
    init_latent,
    resolve_gate,
    text2image,
)

__all__ = ["InversionArtifact", "invert", "load_image",
           "Pipeline", "encode_prompts", "init_latent", "resolve_gate",
           "text2image"]
