"""The sampling engine: jit-compiled text→image with attention control.

Behavioral spec: `/root/reference/ptp_utils.py:65-172` (`diffusion_step`,
`text2image_ldm_stable`, `init_latent`, `latent2image`). TPU re-design:

- The T-step denoising loop is a single ``lax.scan`` whose carry is
  ``(latents, controller store state, PLMS multistep state)`` — the step index
  arrives from the scanned-over ``(step, timestep)`` pair, replacing the
  reference's ``cur_step`` mutation.
- CFG rides batch-doubling exactly as `/root/reference/ptp_utils.py:70-73`:
  one U-Net call on ``[uncond; cond]`` of batch 2B. (The reference's
  ``low_resource`` two-call variant is a GPU-memory workaround we don't need;
  see `/root/reference/ptp_utils.py:66-68`.)
- The controller is a pytree *argument* of the jitted function: edit
  parameters, thresholds and step windows are traced leaves, so sweeping them
  reuses one compiled program. Controller *structure* (kind, which sites are
  touched) is static and changes the program — the identity controller
  compiles to a plain sampler with zero hook overhead.
- The shared-seed expansion of `/root/reference/ptp_utils.py:88-95` (all
  prompts in an edit group start from ONE latent — essential to P2P) lives in
  :func:`init_latent`.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import TYPE_CHECKING, Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

if TYPE_CHECKING:  # the sp plan type; runtime stays import-cycle-free
    from ..models.unet import SpConfig

from ..controllers.base import (
    AttnLayout,
    Controller,
    StoreState,
    apply_step_callback,
    controller_step_window,
    init_store_state,
)
from ..models import vae as vae_mod
from ..models.config import PipelineConfig
from ..models.text_encoder import apply_text_encoder
from ..models.unet import apply_unet, init_attn_cache
from ..ops import schedulers as sched_mod
from ..utils import progress as progress_mod
from ..utils.tokenizer import Tokenizer, pad_ids


@dataclasses.dataclass(frozen=True)
class Pipeline:
    """A bound backend: config + parameter pytrees. The analogue of the
    reference's `StableDiffusionPipeline` handle (`/root/reference/main.py:29`),
    but immutable — controllers are sampling-call arguments, never installed
    into the model."""

    config: PipelineConfig
    unet_params: Any
    text_params: Any
    vae_params: Any
    tokenizer: Tokenizer

    @property
    def latent_shape(self) -> Tuple[int, int, int]:
        s = self.config.latent_size
        return (s, s, self.config.unet.in_channels)


@partial(jax.jit, static_argnames=("cfg", "dtype"))
def _encode_jit(params, cfg, ids, dtype):
    return apply_text_encoder(params, cfg, ids, dtype=dtype)


def stage_host(x, mesh=None):
    """Explicitly stage a host value onto the device(s) — the h2d form
    that passes ``jax.transfer_guard("disallow")``, which the serve
    dispatch hot path runs under (tests/test_serve.py).

    ``mesh`` (a ``jax.sharding.Mesh``) stages the value *replicated over
    the mesh* via an explicit ``NamedSharding`` — the mesh-dispatch form
    of the same contract, so sharded serve programs receive their
    host-born scalars (seeds, guidance) without an implicit per-device
    broadcast (pinned under the virtual 8-device mesh by
    tests/test_serve_mesh.py). On a *multiprocess* mesh ``jax.device_put``
    of an unsharded value runs a cross-host equality collective the CPU
    backend can't execute, so multihost runs keep the implicit path —
    there the transfer-guard contract is explicitly out of scope
    (single-process serving property; see ``parallel.sweep._stage_sharded``
    for the collective-free multihost staging of *sharded* values)."""
    if jax.process_count() > 1:
        return jnp.asarray(x)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        return jax.device_put(x, NamedSharding(mesh, PartitionSpec()))
    return jax.device_put(x)


def encode_prompts(pipe: Pipeline, prompts, dtype=jnp.float32) -> jax.Array:
    """Tokenize + encode to (B, L, D) hidden states
    (`/root/reference/ptp_utils.py:144-156`)."""
    tok = pipe.tokenizer
    max_len = pipe.config.unet.context_len
    # Token ids are the one host-born input of every dispatch: staged
    # explicitly (stage_host) so the serve hot path stays clean under
    # jax.transfer_guard("disallow").
    ids = stage_host(np.asarray(
        [pad_ids(tok.encode(p), max_len, getattr(tok, "pad_token_id", tok.eos_token_id))
         for p in prompts], dtype=np.int32))
    return _encode_jit(pipe.text_params, pipe.config.text, ids, dtype)


def init_latent(latent: Optional[jax.Array], shape: Tuple[int, ...], rng: jax.Array,
                batch: int, dtype=jnp.float32) -> Tuple[jax.Array, jax.Array]:
    """One latent expanded over the edit group
    (`/root/reference/ptp_utils.py:88-95`). Returns (single, batched)."""
    if latent is None:
        latent = jax.random.normal(rng, (1,) + tuple(shape), dtype=dtype)
    latents = jnp.broadcast_to(latent, (batch,) + tuple(latent.shape[1:])).astype(dtype)
    return latent, latents


def lane_select(outputs, lanes):
    """Batch-lane masking hook for the serving layer.

    A padded serve batch runs ``sweep`` with ``G = bucket`` lanes of which
    only the first ``len(lanes)`` carry real requests (padding replicates a
    real lane; a poisoned lane is dropped on the isolation retry). This is
    the single place lane → request resolution happens: it gathers the
    selected lanes of a ``(G, ...)`` output to host numpy, so padded or
    masked-out lanes can never leak into a response record.
    """
    import numpy as np

    out = np.asarray(outputs)
    return [out[i] for i in lanes]


@jax.jit
def _lane_finite_jit(x):
    # f32 view: bf16/f16 lanes reduce identically and uint8 is trivially
    # finite (which is why validation runs on latents, pre-decode).
    xf = x.astype(jnp.float32)
    return jnp.all(jnp.isfinite(xf), axis=tuple(range(1, xf.ndim)))


def lane_finite(outputs):
    """Output-validation hook for the serving layer: one finite flag per
    leading-axis lane of a ``(G, ...)`` float array (final latents of a
    padded sweep batch).

    A NaN/Inf-poisoned lane decodes to a black or garbage image that would
    otherwise ship as a healthy ``ok`` record — this is the single reduction
    that catches it. It is a separate tiny jitted program applied to the
    sweep's *output*, so the sampling program itself is identical whether
    validation runs or not (the serve layer's disabled-mode contract), and
    the cost is one all-reduce per lane, off the denoising hot path.
    """
    import numpy as np

    return np.asarray(_lane_finite_jit(jnp.asarray(outputs)))


def resolve_gate(gate, num_scan_steps: int,
                 controller: Optional[Controller] = None) -> int:
    """Resolve a user-facing ``gate`` spec to a static scan-step index.

    ``None`` (or the full step count) disables phase-gated sampling. A float
    in ``(0, 1]`` is a fraction of the scan length; an int is the scan step
    where phase 2 begins (≥ 1 — the cache needs at least one phase-1 step).
    ``'auto'`` resolves to ``max(S // 2, controller edit-window end, 1)`` —
    the SD-Acc midpoint, but never truncating inside an active edit window
    (`controllers.base.controller_step_window`).
    """
    s = num_scan_steps
    if gate is None:
        return s
    if gate == "auto":
        return min(s, max(s // 2, controller_step_window(controller, s), 1))
    if isinstance(gate, float):
        if not 0.0 < gate <= 1.0:
            raise ValueError(f"fractional gate must be in (0, 1], got {gate}")
        g = int(round(gate * s))
    elif isinstance(gate, int):
        g = gate
    else:
        raise ValueError(f"gate must be None, 'auto', a float fraction or an "
                         f"int step, got {gate!r}")
    if not 1 <= g <= s:
        raise ValueError(f"gate step {g} outside [1, {s}]")
    return g


def resolve_reuse(gate, schedule, layout, num_scan: int,
                  controller: Optional[Controller] = None):
    """Resolve the (``gate``, ``schedule``) pair every sampling surface
    accepts into ``(gate_step, reuse_or_None)``.

    ``schedule`` is a reuse-schedule spec (JSON dict), an already-resolved
    ``engine.reuse.ReuseSchedule``, or None. The two knobs are mutually
    exclusive — a schedule IS a generalized gate. A schedule that resolves
    to the UNIFORM table normalizes to a plain gate step (``reuse=None``):
    it is then bitwise-identical to — and compiles/pools as — today's
    ``gate=g`` program. Non-uniform schedules return the static table; the
    per-site window-conflict warning fires here (the generalized
    ``warn_gate_truncation``)."""
    if schedule is None:
        return resolve_gate(gate, num_scan, controller), None
    if gate is not None:
        raise ValueError("gate and schedule are mutually exclusive: a "
                         "reuse schedule generalizes the gate (its "
                         "cfg_gate is the phase boundary)")
    from . import reuse as reuse_mod

    sched = reuse_mod.resolve_schedule(schedule, layout, num_scan,
                                       controller)
    u = sched.uniform_gate
    if u is not None:
        warn_gate_truncation(u, num_scan, controller)
        return u, None
    reuse_mod.warn_schedule_conflicts(sched, layout, controller, num_scan)
    return sched.cfg_gate, sched


def warn_gate_truncation(gate_step: int, num_scan: int,
                         controller: Optional[Controller]) -> None:
    """Warn when an explicit gate changes controller semantics: truncating
    inside an active edit window, or freezing an explicit attention store.
    Shared by the sequential (``text2image``) and batched (``sweep``) paths
    so both surfaces report the same conditions the same way."""
    if gate_step >= num_scan:
        return
    import warnings

    window = controller_step_window(controller, num_scan)
    if gate_step < window:
        warnings.warn(
            f"gate step {gate_step} truncates inside the controller's "
            f"edit window (ends at {window}): attention edits past the "
            "gate are dropped. Use gate='auto' to clamp to the window.",
            stacklevel=3)
    if controller is not None and controller.store:
        warnings.warn(
            f"gate step {gate_step} < {num_scan}: the attention store "
            "stops accumulating at the gate, so averaged maps cover "
            "phase 1 only", stacklevel=3)


class PhaseCarry(NamedTuple):
    """The phase-1 → phase-2 hand-off, packaged as ONE pytree.

    This is the unit of transfer between the serve layer's two program
    pools (phase-disaggregated continuous batching): everything a phase-2
    program needs to continue a trajectory whose CFG/controller phase
    already ran. The treedef is *pinned* per compiled program —
    :func:`carry_spec` renders it (structure + leaf shapes/dtypes) and the
    hand-off path validates it, so a carry can never silently feed a
    mismatched phase-2 program. All leaves are plain arrays, so a carry
    round-trips through host memory (``jax.device_get`` → ``.npz`` → device)
    for the journal's crash-replay spill.
    """

    latents: jax.Array    # (B, h, w, c) latents after the last phase-1 step
    resid: jax.Array      # (B, h, w, c) CFG residual ε_text − ε_uncond there
    cache: Tuple          # AttnCache: every cross-attn site's cached output
    ms: Any               # multistep scheduler state (None for DDIM)
    state: Tuple          # frozen phase-1 StoreState (LocalBlend source)


def carry_spec(carry: PhaseCarry) -> str:
    """The pinned treedef of a hand-off carry: pytree structure plus every
    leaf's shape/dtype. Two carries with equal specs are exchangeable
    inputs of the same phase-2 program; the hand-off path hard-errors on a
    mismatch instead of letting XLA fail (or worse, retrace) later."""
    leaves, treedef = jax.tree_util.tree_flatten(carry)
    leaf_sig = ",".join(f"{tuple(x.shape)}/{x.dtype}" for x in leaves)
    return f"{treedef}|{leaf_sig}"


def phase2_controller(controller: Optional[Controller]
                      ) -> Optional[Controller]:
    """The slice of a controller the phase-2 program actually consumes.

    Past the gate the U-Net runs with ``controller=None`` (attention hooks
    are structurally gone); only the latent-space step callback survives —
    SpatialReplace injection and LocalBlend compositing against the frozen
    phase-1 store. Attention-edit parameters and the store flag are
    dropped, so e.g. a ``replace`` and a ``refine`` edit reduce to the SAME
    phase-2 controller (``None``) and their phase-2 lanes can share one
    compiled pool program — the serve layer's phase-2 compile key is
    derived from this reduction. For controllers the reduction maps to
    ``None`` the emitted ops are identical to passing the full controller
    (both step-callback branches are static no-ops), which is what keeps
    the pooled program bitwise-equal to the monolithic gated scan."""
    if controller is None:
        return None
    if controller.blend is None and controller.spatial_stop_inject is None:
        return None
    return controller.replace(edit=None, store=False)


def _make_ms_step(schedule: sched_mod.DiffusionSchedule, scheduler_kind: str):
    use_plms = scheduler_kind == "plms"
    use_dpm = scheduler_kind == "dpm"

    def ms_step(ms, eps, t, latents):
        if use_plms:
            return sched_mod.plms_step(schedule, ms, eps, t, latents)
        if use_dpm:
            return sched_mod.dpm_step(schedule, ms, eps, t, latents)
        return ms, sched_mod.ddim_step(schedule, eps, t, latents)

    return ms_step


def _make_scheduled_body(
    unet_params: Any,
    cfg: PipelineConfig,
    layout: AttnLayout,
    schedule: sched_mod.DiffusionSchedule,
    scheduler_kind: str,
    context: jax.Array,
    b: int,
    controller: Optional[Controller],
    guidance_scale: jax.Array,
    emit: bool,
    progress: bool,
    sp: Optional["SpConfig"],
    *,
    cfg_active: bool,
    site_plan: Tuple[str, ...],
    resid_const: Optional[jax.Array] = None,
    state_const: Tuple = (),
    kernels=None,
):
    """One reuse-schedule SEGMENT's scan body (engine.reuse): the per-site
    action vector ``site_plan`` is constant over the segment, so each
    segment compiles as one ``lax.scan``.

    ``cfg_active`` segments run the CFG-doubled U-Net with full controller
    hooks at computed sites, capturing the guidance residual each step —
    the latent math of ``_make_phase1_body(capture=True)``. Past the CFG
    boundary the body is the single-branch extrapolation of
    ``_phase2_scan``'s ``body2`` (``resid_const``/``state_const`` are the
    frozen hand-off values), with the cache riding the carry so sites that
    flip to reuse *inside* phase 2 can keep storing until their step."""
    ms_step = _make_ms_step(schedule, scheduler_kind)

    def body(carry, scan_in):
        step, t = scan_in
        if cfg_active:
            latents, state, ms, cache, resid = carry
            progress_mod.emit_step(emit, step, phase="phase1",
                                   report=progress)
            latent_in = jnp.concatenate([latents] * 2, axis=0)
            eps, state, cache = apply_unet(
                unet_params, cfg.unet, latent_in, t, context,
                layout=layout, controller=controller, state=state,
                step=step, sp=sp, attn_cache=cache, site_plan=site_plan,
                kernels=kernels)
            eps_uncond, eps_text = eps[:b], eps[b:]
            resid = eps_text - eps_uncond
            eps = eps_uncond + guidance_scale * resid
            eps = sched_mod.to_epsilon(schedule, eps, t, latents)
            ms, latents = ms_step(ms, eps, t, latents)
            latents = apply_step_callback(controller, layout, state,
                                          latents, step)
            return (latents, state, ms, cache, resid), None
        latents, ms, cache = carry
        progress_mod.emit_step(emit, step, phase="phase2", report=progress)
        eps_text, _, cache = apply_unet(
            unet_params, cfg.unet, latents, t, context,
            layout=layout, controller=None, state=(), step=step, sp=sp,
            attn_cache=cache, site_plan=site_plan, kernels=kernels)
        eps = eps_text + (guidance_scale - 1.0) * resid_const
        eps = sched_mod.to_epsilon(schedule, eps, t, latents)
        ms, latents = ms_step(ms, eps, t, latents)
        latents = apply_step_callback(controller, layout, state_const,
                                      latents, step)
        return (latents, ms, cache), None

    return body


def _scheduled_phase1(
    unet_params: Any,
    cfg: PipelineConfig,
    layout: AttnLayout,
    schedule: sched_mod.DiffusionSchedule,
    scheduler_kind: str,
    context: jax.Array,            # (2B, L, D) [uncond; cond]
    latents: jax.Array,            # (B, h, w, c)
    controller: Optional[Controller],
    guidance_scale: jax.Array,
    *,
    reuse,                         # engine.reuse.ReuseSchedule (static)
    progress: bool = False,
    metrics: bool = False,
    sp: Optional["SpConfig"] = None,
    kernels=None,                  # kernels.KernelConfig (static)
) -> PhaseCarry:
    """The generalized phase-1 executor: steps ``[0, cfg_gate)`` under full
    CFG, cut into constant-plan segments (engine.reuse.segments). Sites
    whose reuse step falls inside this range flip to their cache
    mid-phase; the rest capture exactly like ``_phase1_scan``. Returns the
    :class:`PhaseCarry` with full-batch leaves sliced to the cond half —
    the same hand-off pytree the uniform gate produces, just with the
    schedule's leaf set."""
    from . import reuse as reuse_mod

    sched1 = reuse_mod.phase1_view(reuse)
    emit = progress or metrics
    b = latents.shape[0]
    state = (init_store_state(layout, b, dtype=jnp.float32)
             if (controller is not None and controller.needs_store) else ())
    ms_state = sched_mod.init_multistep_state(scheduler_kind, latents.shape,
                                              latents.dtype)
    num_scan = schedule.timesteps.shape[0]
    assert sched1.steps == num_scan, (sched1.steps, num_scan)
    steps = jnp.arange(num_scan, dtype=jnp.int32)
    cache = reuse_mod.init_schedule_cache(layout, sched1, b, phase=1,
                                          dtype=latents.dtype)
    resid = jnp.zeros_like(latents)
    carry = (latents, state, ms_state, cache, resid)
    for seg in reuse_mod.segments(layout, sched1, phase=1):
        body = _make_scheduled_body(unet_params, cfg, layout, schedule,
                                    scheduler_kind, context, b, controller,
                                    guidance_scale, emit, progress, sp,
                                    cfg_active=True, site_plan=seg.plan,
                                    kernels=kernels)
        carry, _ = jax.lax.scan(
            body, carry,
            (steps[seg.start:seg.stop],
             schedule.timesteps[seg.start:seg.stop]))
    latents, state, ms_state, cache, resid = carry
    cache = reuse_mod.slice_cache_to_cond(layout, sched1, cache, b)
    return PhaseCarry(latents=latents, resid=resid, cache=cache,
                      ms=ms_state, state=state)


def _scheduled_phase2(
    unet_params: Any,
    cfg: PipelineConfig,
    layout: AttnLayout,
    schedule: sched_mod.DiffusionSchedule,
    scheduler_kind: str,
    context_cond: jax.Array,       # (B, L, D) — the uncond half is GONE
    carry: PhaseCarry,
    controller: Optional[Controller],
    guidance_scale: jax.Array,
    *,
    reuse,                         # engine.reuse.ReuseSchedule (static)
    progress: bool = False,
    metrics: bool = False,
    sp: Optional["SpConfig"] = None,
    kernels=None,                  # kernels.KernelConfig (static)
) -> jax.Array:
    """The generalized phase-2 executor: steps ``[cfg_gate, S)`` off a
    :class:`PhaseCarry`, segmented so sites may keep computing
    single-branch past the CFG boundary and flip to reuse at their own
    step (their cache slots keep storing until then). The uniform table —
    every cross site reused from the boundary — reduces to exactly one
    segment with every cross site in ``use``: ``_phase2_scan``'s body."""
    from . import reuse as reuse_mod

    sched2 = reuse_mod.phase2_view(reuse)
    emit = progress or metrics
    num_scan = schedule.timesteps.shape[0]
    assert sched2.steps == num_scan, (sched2.steps, num_scan)
    steps = jnp.arange(num_scan, dtype=jnp.int32)
    c2 = (carry.latents, carry.ms, carry.cache)
    for seg in reuse_mod.segments(layout, sched2, phase=2):
        body = _make_scheduled_body(unet_params, cfg, layout, schedule,
                                    scheduler_kind, context_cond,
                                    context_cond.shape[0], controller,
                                    guidance_scale, emit, progress, sp,
                                    cfg_active=False, site_plan=seg.plan,
                                    resid_const=carry.resid,
                                    state_const=carry.state,
                                    kernels=kernels)
        c2, _ = jax.lax.scan(
            body, c2,
            (steps[seg.start:seg.stop],
             schedule.timesteps[seg.start:seg.stop]))
    return c2[0]


def _make_phase1_body(
    unet_params: Any,
    cfg: PipelineConfig,
    layout: AttnLayout,
    schedule: sched_mod.DiffusionSchedule,
    scheduler_kind: str,
    context: jax.Array,
    b: int,
    controller: Optional[Controller],
    guidance_scale: jax.Array,
    uncond_per_step: Optional[jax.Array],
    emit: bool,
    progress: bool,
    sp: Optional["SpConfig"],
    capture: bool,
    kernels=None,
):
    """The CFG scan body — phase 1 of a gated scan (``capture=True``:
    carries the AttnCache + CFG residual) or the whole ungated scan
    (``capture=False``: the exact pre-gate program)."""
    ms_step = _make_ms_step(schedule, scheduler_kind)

    def body(carry, scan_in):
        if capture:
            latents, state, ms, cache, resid = carry
        else:
            latents, state, ms = carry
        step, t = scan_in
        progress_mod.emit_step(emit, step, phase="phase1", report=progress)
        ctx = context
        if uncond_per_step is not None:
            # Null-text: substitute this step's optimized uncond embedding.
            # Cast to the sampling dtype — the artifact stores f32 (the
            # optimizer's dtype), and a f32 leak here would silently promote
            # the whole CFG context (and the U-Net matmuls) on the bf16 path.
            u = jax.lax.dynamic_index_in_dim(uncond_per_step, step, 0,
                                             keepdims=False)
            ctx = jnp.concatenate([jnp.broadcast_to(u.astype(context.dtype),
                                                    context[:b].shape),
                                   context[b:]], axis=0)
        latent_in = jnp.concatenate([latents] * 2, axis=0)
        if capture:
            eps, state, cache = apply_unet(
                unet_params, cfg.unet, latent_in, t, ctx,
                layout=layout, controller=controller, state=state, step=step,
                sp=sp, attn_cache=cache, cache_mode="store", kernels=kernels)
        else:
            eps, state = apply_unet(
                unet_params, cfg.unet, latent_in, t, ctx,
                layout=layout, controller=controller, state=state, step=step,
                sp=sp, kernels=kernels)
        eps_uncond, eps_text = eps[:b], eps[b:]
        if capture:
            resid = eps_text - eps_uncond
            eps = eps_uncond + guidance_scale * resid
        else:
            eps = eps_uncond + guidance_scale * (eps_text - eps_uncond)
        # v-prediction models (SD-2.1 768-v): convert to ε once per step.
        # Linear in the model output, so combining CFG first is equivalent.
        eps = sched_mod.to_epsilon(schedule, eps, t, latents)
        ms, latents = ms_step(ms, eps, t, latents)
        latents = apply_step_callback(controller, layout, state, latents,
                                      step)
        if capture:
            return (latents, state, ms, cache, resid), None
        return (latents, state, ms), None

    return body


def _phase1_scan(
    unet_params: Any,
    cfg: PipelineConfig,
    layout: AttnLayout,
    schedule: sched_mod.DiffusionSchedule,
    scheduler_kind: str,
    context: jax.Array,            # (2B, L, D) [uncond; cond]
    latents: jax.Array,            # (B, h, w, c)
    controller: Optional[Controller],
    guidance_scale: jax.Array,
    *,
    gate: int,                     # static: first phase-2 scan step
    progress: bool = False,
    metrics: bool = False,
    sp: Optional["SpConfig"] = None,
    reuse=None,                    # engine.reuse.ReuseSchedule (static)
    kernels=None,                  # kernels.KernelConfig (static)
) -> PhaseCarry:
    """Scan steps ``[0, gate)`` with full CFG + controller hooks, capturing
    every cross-attention output and the CFG residual. Returns the
    :class:`PhaseCarry` a phase-2 program continues from. Latent math is
    identical to the ungated body (the capture only adds carry writes), so
    phase-1 latents match the baseline bitwise.

    ``reuse`` (a non-uniform ``engine.reuse.ReuseSchedule``) generalizes
    the gate: the scan is segmented so sites flip to their caches at their
    own steps (``_scheduled_phase1``). A uniform table routes back here —
    bitwise the PR-1 program by construction."""
    if reuse is not None and reuse.uniform_gate is None:
        assert reuse.cfg_gate == gate, (reuse.cfg_gate, gate)
        return _scheduled_phase1(unet_params, cfg, layout, schedule,
                                 scheduler_kind, context, latents,
                                 controller, guidance_scale, reuse=reuse,
                                 progress=progress, metrics=metrics, sp=sp,
                                 kernels=kernels)
    emit = progress or metrics
    b = latents.shape[0]
    state = (init_store_state(layout, b, dtype=jnp.float32)
             if (controller is not None and controller.needs_store) else ())
    ms_state = sched_mod.init_multistep_state(scheduler_kind, latents.shape,
                                              latents.dtype)
    body = _make_phase1_body(unet_params, cfg, layout, schedule,
                             scheduler_kind, context, b, controller,
                             guidance_scale, None, emit, progress, sp,
                             capture=True, kernels=kernels)
    num_scan = schedule.timesteps.shape[0]
    assert 1 <= gate <= num_scan, (gate, num_scan)
    steps = jnp.arange(num_scan, dtype=jnp.int32)
    cache = init_attn_cache(layout, b, dtype=latents.dtype)
    resid = jnp.zeros_like(latents)
    (latents, state, ms_state, cache, resid), _ = jax.lax.scan(
        body, (latents, state, ms_state, cache, resid),
        (steps[:gate], schedule.timesteps[:gate]))
    return PhaseCarry(latents=latents, resid=resid, cache=cache,
                      ms=ms_state, state=state)


def _phase2_scan(
    unet_params: Any,
    cfg: PipelineConfig,
    layout: AttnLayout,
    schedule: sched_mod.DiffusionSchedule,
    scheduler_kind: str,
    context_cond: jax.Array,       # (B, L, D) — the uncond half is GONE
    carry: PhaseCarry,
    controller: Optional[Controller],
    guidance_scale: jax.Array,
    *,
    gate: int,                     # static: first phase-2 scan step
    progress: bool = False,
    metrics: bool = False,
    sp: Optional["SpConfig"] = None,
    reuse=None,                    # engine.reuse.ReuseSchedule (static)
    kernels=None,                  # kernels.KernelConfig (static)
) -> jax.Array:
    """Scan steps ``[gate, S)`` off a :class:`PhaseCarry`: single-branch
    U-Net (no uncond batch half), guidance as a fixed extrapolation off the
    captured residual (SD-Acc), cross-attention served from the cache
    (TAD). ``controller`` here is the phase-2 slice
    (:func:`phase2_controller` for pooled serving; the monolithic path
    passes the full controller — both emit identical ops). ``reuse`` (a
    non-uniform schedule) segments the scan per the table
    (``_scheduled_phase2``)."""
    if reuse is not None and reuse.uniform_gate is None:
        assert reuse.cfg_gate == gate, (reuse.cfg_gate, gate)
        return _scheduled_phase2(unet_params, cfg, layout, schedule,
                                 scheduler_kind, context_cond, carry,
                                 controller, guidance_scale, reuse=reuse,
                                 progress=progress, metrics=metrics, sp=sp,
                                 kernels=kernels)
    emit = progress or metrics
    ms_step = _make_ms_step(schedule, scheduler_kind)
    cache, resid, state = carry.cache, carry.resid, carry.state

    def body2(c2, scan_in):
        latents, ms = c2
        step, t = scan_in
        progress_mod.emit_step(emit, step, phase="phase2", report=progress)
        eps_text, _ = apply_unet(
            unet_params, cfg.unet, latents, t, context_cond,
            layout=layout, controller=None, state=(), step=step, sp=sp,
            attn_cache=cache, cache_mode="use")
        # SD-Acc-style fixed extrapolation: CFG's uncond branch is gone;
        # ε = ε_text + (g−1)·(ε_text − ε_uncond)|_gate reuses the captured
        # last-phase-1 residual as the guidance direction.
        eps = eps_text + (guidance_scale - 1.0) * resid
        eps = sched_mod.to_epsilon(schedule, eps, t, latents)
        ms, latents = ms_step(ms, eps, t, latents)
        # Latent-space controller effects (LocalBlend compositing /
        # SpatialReplace injection) continue against the frozen phase-1
        # store; attention hooks are structurally gone.
        latents = apply_step_callback(controller, layout, state, latents,
                                      step)
        return (latents, ms), None

    num_scan = schedule.timesteps.shape[0]
    assert 1 <= gate <= num_scan, (gate, num_scan)
    steps = jnp.arange(num_scan, dtype=jnp.int32)
    (latents, _), _ = jax.lax.scan(
        body2, (carry.latents, carry.ms),
        (steps[gate:], schedule.timesteps[gate:]))
    return latents


def _denoise_scan(
    unet_params: Any,
    cfg: PipelineConfig,
    layout: AttnLayout,
    schedule: sched_mod.DiffusionSchedule,
    scheduler_kind: str,
    context: jax.Array,            # (2B, L, D) [uncond; cond]
    latents: jax.Array,            # (B, h, w, c)
    controller: Optional[Controller],
    guidance_scale: jax.Array,
    uncond_per_step: Optional[jax.Array] = None,  # (T, 1, L, D) null-text embeddings
    progress: bool = False,
    sp: Optional["SpConfig"] = None,
    gate: Optional[int] = None,    # static: first phase-2 scan step; None/S = off
    metrics: bool = False,         # static: trace the telemetry callback in
    reuse=None,                    # engine.reuse.ReuseSchedule (static)
    kernels=None,                  # kernels.KernelConfig (static)
) -> Tuple[jax.Array, StoreState]:
    """Scan over timesteps. Returns (final latents, final store state).

    ``gate`` splits the scan into two phases (TAD arXiv 2404.02747 + SD-Acc
    arXiv 2507.01309, mapped onto P2P's explicit step windows):

    - phase 1 (steps ``0..gate``): the batch-doubled CFG U-Net with full
      controller hooks, capturing every cross-attention output and the CFG
      residual ``ε_text − ε_uncond`` (each overwritten per step, so the final
      carry holds the last phase-1 step's values);
    - phase 2 (steps ``gate..S``): a single-branch U-Net — no uncond half,
      guidance folded into a fixed extrapolation off the captured residual,
      cross-attention replaced by the cached outputs. The controller is
      dropped at the U-Net level (edit windows end before the gate under
      ``gate='auto'``); its latent-space step callback (LocalBlend /
      SpatialReplace) still runs against the frozen phase-1 store.

    ``gate=None`` (or ``gate == S``) compiles the exact pre-existing
    single-scan program — bitwise-identical output, zero new ops.

    ``metrics`` traces the per-step host callback in even when ``progress``
    is off (phase-tagged, so ``obs.device.StepCollector`` can histogram
    phase-1 vs phase-2 ms/step); with both off the program carries no
    callback at all — the telemetry-disabled jaxpr-identity contract.
    """
    emit = progress or metrics
    b = latents.shape[0]
    num_scan = schedule.timesteps.shape[0]
    if reuse is not None:
        # Per-site per-step reuse schedule (engine.reuse, ISSUE 15). The
        # UNIFORM table is semantically gate=cfg_gate: normalize onto the
        # gate path below, so it is bitwise-identical by construction. A
        # non-uniform table runs the segmented executors — whose uniform
        # reduction is additionally pinned bitwise-equal by
        # tests/test_schedule.py (the generalization proof).
        u = reuse.uniform_gate
        if u is not None:
            gate = u if gate is None else gate
            assert gate == u, (gate, u)
            reuse = None
        else:
            if uncond_per_step is not None:
                raise ValueError(
                    "reuse schedules cannot run under per-step null-text "
                    "uncond embeddings (validated upstream)")
            carry = _scheduled_phase1(
                unet_params, cfg, layout, schedule, scheduler_kind,
                context, latents, controller, guidance_scale, reuse=reuse,
                progress=progress, metrics=metrics, sp=sp, kernels=kernels)
            if reuse.cfg_gate >= num_scan:
                # CFG never drops: the whole scan ran in the (segmented)
                # CFG phase; cached sites still saved their compute.
                return carry.latents, carry.state
            latents = _scheduled_phase2(
                unet_params, cfg, layout, schedule, scheduler_kind,
                context[b:], carry, controller, guidance_scale,
                reuse=reuse, progress=progress, metrics=metrics, sp=sp,
                kernels=kernels)
            return latents, carry.state
    if gate is None:
        gate = num_scan
    assert 1 <= gate <= num_scan, (gate, num_scan)
    gated = gate < num_scan
    if gated and uncond_per_step is not None:
        raise ValueError("phase-gated sampling cannot run under per-step "
                         "null-text uncond embeddings (validated upstream)")

    if not gated:
        # Feature off: the exact pre-existing program (no cache buffers, no
        # residual carry) — gate=S is bitwise-identical by construction.
        state = (init_store_state(layout, b, dtype=jnp.float32)
                 if (controller is not None and controller.needs_store)
                 else ())
        # Multistep-solver state carried through the scan (PLMS ring buffer
        # or DPM x0 history; None for single-step DDIM). The gated path
        # initializes its own inside ``_phase1_scan`` and hands the SAME
        # carry across the phase boundary.
        ms_state = sched_mod.init_multistep_state(
            scheduler_kind, latents.shape, latents.dtype)
        body = _make_phase1_body(unet_params, cfg, layout, schedule,
                                 scheduler_kind, context, b, controller,
                                 guidance_scale, uncond_per_step, emit,
                                 progress, sp, capture=False, kernels=kernels)
        steps = jnp.arange(num_scan, dtype=jnp.int32)
        (latents, state, _), _ = jax.lax.scan(
            body, (latents, state, ms_state),
            (steps, schedule.timesteps))
        return latents, state

    # Gated: the same two phase programs the serve layer's disaggregated
    # pools compile separately (``_phase1_scan`` / ``_phase2_scan``),
    # composed here into one monolithic program — op-for-op the split
    # execution, which is what makes a pooled hand-off bitwise-equal to a
    # single-program gated run.
    carry = _phase1_scan(unet_params, cfg, layout, schedule, scheduler_kind,
                         context, latents, controller, guidance_scale,
                         gate=gate, progress=progress, metrics=metrics,
                         sp=sp, kernels=kernels)
    # Slice the conditional context half once, outside the phase-2 body: a
    # slice inside the scan would pull the full [uncond; cond] tensor into
    # the body as a constant — the uncond half must not even be an input.
    latents = _phase2_scan(unet_params, cfg, layout, schedule,
                           scheduler_kind, context[b:], carry, controller,
                           guidance_scale, gate=gate, progress=progress,
                           metrics=metrics, sp=sp, kernels=kernels)
    return latents, carry.state


@partial(jax.jit, static_argnames=("cfg", "layout", "scheduler_kind",
                                   "return_store", "progress", "sp", "gate",
                                   "metrics", "reuse", "kernels"))
def _text2image_jit(
    unet_params: Any,
    vae_params: Any,
    cfg: PipelineConfig,
    layout: AttnLayout,
    schedule: sched_mod.DiffusionSchedule,
    scheduler_kind: str,
    context_cond: jax.Array,
    context_uncond: jax.Array,
    latents: jax.Array,
    controller: Optional[Controller],
    guidance_scale: jax.Array,
    uncond_per_step: Optional[jax.Array],
    return_store: bool,
    progress: bool = False,
    sp: Optional["SpConfig"] = None,
    gate: Optional[int] = None,
    metrics: bool = False,
    reuse=None,
    kernels=None,
):
    context = jnp.concatenate([context_uncond, context_cond], axis=0)
    latents, state = _denoise_scan(
        unet_params, cfg, layout, schedule, scheduler_kind, context, latents,
        controller, guidance_scale, uncond_per_step, progress=progress, sp=sp,
        gate=gate, metrics=metrics, reuse=reuse, kernels=kernels)
    image = vae_mod.decode(vae_params, cfg.vae, latents.astype(jnp.float32))
    image = vae_mod.to_uint8(image)
    return (image, latents, state) if return_store else (image, latents, ())


def text2image(
    pipe: Pipeline,
    prompts,
    controller: Optional[Controller] = None,
    *,
    num_steps: Optional[int] = None,
    guidance_scale: Optional[float] = None,
    scheduler: Optional[str] = None,
    latent: Optional[jax.Array] = None,
    rng: Optional[jax.Array] = None,
    uncond_embeddings: Optional[jax.Array] = None,
    negative_prompt: Optional[str] = None,
    layout: Optional[AttnLayout] = None,
    dtype=jnp.float32,
    return_store: bool = False,
    progress: bool = False,
    sp: Optional["SpConfig"] = None,
    gate=None,
    metrics: bool = False,
    schedule=None,
    kernels=None,
):
    """Generate an edit group of images from prompts under attention control —
    the `/root/reference/ptp_utils.py:129-172` entry point.

    ``uncond_embeddings``: optional (T, 1, L, D) per-step null-text
    embeddings; otherwise the encoded unconditional prompt is broadcast over
    all steps. ``negative_prompt`` replaces the default ``""`` unconditional
    text (classifier-free guidance then steers *away* from it — a diffusers
    capability the reference lacks); mutually exclusive with
    ``uncond_embeddings``. ``sp`` (a :class:`p2p_tpu.models.unet.SpConfig`)
    shards the pixel axis of large untouched self-attention sites over a
    mesh axis with ring attention — the long-context scaling axis (image
    resolution; SURVEY §5) the reference lacks entirely.

    ``gate`` enables phase-gated sampling (see :func:`resolve_gate`): steps
    past the gate run a single-branch U-Net (no CFG uncond half) with every
    cross-attention site served from the cached last-phase-1-step output —
    the per-step cost drops roughly in half past the gate at a small,
    bounded drift (PERF.md "Beyond the XLA ceiling"). ``gate=None`` (or the
    full step count) is bitwise-identical to ungated sampling. Incompatible
    with ``uncond_embeddings``: the null-text artifact optimizes the uncond
    branch at *every* step, so truncating it would silently misalign the
    replay — rejected with an error instead. Returns
    ``(images uint8 (B,H,W,3), x_T, store)``.

    ``schedule`` (mutually exclusive with ``gate``) is a per-site per-step
    reuse schedule — a spec dict (``engine.reuse.validate_spec``; the CLI
    loads ``--schedule FILE`` artifacts like
    ``tools/schedules/default_v1.json``) or an already-resolved
    ``engine.reuse.ReuseSchedule``. Each attention site flips from
    computing to serving its cached cross-attention output (TAD) or
    inherited self-attention feature (A-SDM) at its own step;
    ``cfg_gate`` plays the gate's role for the CFG branch. The uniform
    table normalizes onto the exact ``gate=g`` program (bitwise).

    ``kernels`` (a static :class:`p2p_tpu.kernels.KernelConfig`) routes
    covered controller-edited attention sites to the fused-edit Pallas
    kernel — the prompt-to-prompt edit applied inside the attention tile, so
    the ``(2B·heads, P, K)`` probability tensor never reaches HBM (PERF.md
    "In-kernel editing"). It is a pure lowering choice threaded through the
    jit static args: each distinct config is one compiled program, composing
    with ``gate``/``schedule`` segment lowering (``use`` segments skip
    attention entirely; attention-store sites keep the materialized path).
    ``kernels=None`` compiles the exact pre-existing program.

    ``metrics`` enables device-side telemetry (docs/OBSERVABILITY.md):
    phase-tagged step callbacks are traced into the program and the resolved
    gate step / scan length / CFG batch land in the default registry as
    gauges. Numerics-neutral — callbacks are pure side channel — and with
    ``metrics=False`` (and ``progress=False``) the compiled program is
    identical to one built before this flag existed. Callers that want the
    step stream collected must install the host sink
    (``obs.device.instrument``); the CLI ``--metrics`` flag does.
    """
    if negative_prompt and uncond_embeddings is not None:
        raise ValueError("negative_prompt and uncond_embeddings are mutually "
                         "exclusive (null-text already optimized the uncond)")
    cfg = pipe.config
    num_steps = num_steps or cfg.num_steps
    scheduler = scheduler or cfg.scheduler.kind
    if uncond_embeddings is not None:
        if scheduler != "ddim":
            # PLMS scans T+1 steps (warm-up double-evaluation); per-step
            # null-text embeddings are optimized against the DDIM trajectory
            # and would silently misalign (`/root/reference/null_text.py:23`
            # — the null-text path is DDIM-only).
            raise ValueError("uncond_embeddings require scheduler='ddim'")
        if uncond_embeddings.shape[0] != num_steps:
            raise ValueError(
                f"uncond_embeddings has {uncond_embeddings.shape[0]} steps, "
                f"sampling uses {num_steps}")
    gs = jnp.asarray(cfg.guidance_scale if guidance_scale is None else guidance_scale,
                     dtype=jnp.float32)
    if layout is None:
        from ..models.config import unet_layout
        layout = unet_layout(cfg.unet)
    if rng is None:
        rng = jax.random.PRNGKey(0)

    tsched = sched_mod.schedule_from_config(num_steps, cfg.scheduler,
                                            kind=scheduler)
    num_scan = tsched.timesteps.shape[0]
    gate_step, reuse_sched = resolve_reuse(gate, schedule, layout, num_scan,
                                           controller)
    if gate_step < num_scan and uncond_embeddings is not None:
        # The null-text window spans every step (validated (T,1,L,D)
        # above): any gate < T truncates inside it. Reject loudly — a
        # silently misaligned replay looks plausible and is wrong.
        raise ValueError(
            f"gate={gate!r} (step {gate_step}) conflicts with per-step "
            f"null-text uncond_embeddings, which are active through all "
            f"{num_scan} steps: CFG truncation would drop the optimized "
            "uncond branch mid-window. Run null-text replays with "
            "gate=None.")
    if reuse_sched is not None and uncond_embeddings is not None:
        # A non-uniform schedule reroutes per-site features even when its
        # cfg_gate keeps CFG alive: the per-step optimized uncond would
        # replay against a different trajectory — same loud rejection.
        raise ValueError(
            "schedule conflicts with per-step null-text "
            "uncond_embeddings: cached/inherited sites change the "
            "trajectory the uncond branch was optimized against. Run "
            "null-text replays with schedule=None.")
    if reuse_sched is None:
        warn_gate_truncation(gate_step, num_scan, controller)
    context_cond = encode_prompts(pipe, prompts, dtype=dtype)
    context_uncond = encode_prompts(
        pipe, [negative_prompt or ""] * len(prompts), dtype=dtype)

    x_t, latents = init_latent(latent, pipe.latent_shape, rng, len(prompts), dtype)
    if progress:
        progress_mod.activate(tsched.timesteps.shape[0])
    if metrics:
        # Host-side run descriptors for the snapshot: the gate decomposition
        # (per-phase ms/step arrives via the step callbacks) plus the CFG
        # batch shape phase 1 actually runs.
        from ..obs import metrics as obs_metrics

        reg = obs_metrics.registry()
        reg.gauge("sampler_gate_step",
                  "first phase-2 scan step (== scan length: ungated)"
                  ).set(float(gate_step))
        reg.gauge("sampler_scan_steps", "scan length").set(float(num_scan))
        reg.gauge("sampler_cfg_batch",
                  "CFG-doubled U-Net batch in phase 1 (2B)"
                  ).set(float(2 * len(prompts)))
    from ..obs.spans import span

    with span("sampler.text2image", steps=int(num_scan), gate=int(gate_step),
              batch=len(prompts)):
        # Span covers trace/compile + async dispatch (execution completes
        # when the caller materializes the arrays) — it marks the host
        # region for Perfetto alignment, not device wall time.
        image, latents_out, state = _text2image_jit(
            pipe.unet_params, pipe.vae_params, cfg, layout, tsched,
            scheduler, context_cond, context_uncond, latents, controller, gs,
            uncond_embeddings, return_store, progress=progress, sp=sp,
            gate=gate_step, metrics=metrics, reuse=reuse_sched,
            kernels=kernels)
    return image, x_t, state
