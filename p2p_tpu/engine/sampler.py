"""The sampling engine: jit-compiled text→image with attention control.

Behavioral spec: `/root/reference/ptp_utils.py:65-172` (`diffusion_step`,
`text2image_ldm_stable`, `init_latent`, `latent2image`). TPU re-design:

- The T-step denoising loop is a single ``lax.scan`` whose carry is
  ``(latents, controller store state, PLMS multistep state)`` — the step index
  arrives from the scanned-over ``(step, timestep)`` pair, replacing the
  reference's ``cur_step`` mutation.
- CFG rides batch-doubling exactly as `/root/reference/ptp_utils.py:70-73`:
  one U-Net call on ``[uncond; cond]`` of batch 2B. (The reference's
  ``low_resource`` two-call variant is a GPU-memory workaround we don't need;
  see `/root/reference/ptp_utils.py:66-68`.)
- The controller is a pytree *argument* of the jitted function: edit
  parameters, thresholds and step windows are traced leaves, so sweeping them
  reuses one compiled program. Controller *structure* (kind, which sites are
  touched) is static and changes the program — the identity controller
  compiles to a plain sampler with zero hook overhead.
- The shared-seed expansion of `/root/reference/ptp_utils.py:88-95` (all
  prompts in an edit group start from ONE latent — essential to P2P) lives in
  :func:`init_latent`.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import TYPE_CHECKING, Any, Optional, Tuple

import jax
import jax.numpy as jnp

if TYPE_CHECKING:  # the sp plan type; runtime stays import-cycle-free
    from ..models.unet import SpConfig

from ..controllers.base import (
    AttnLayout,
    Controller,
    StoreState,
    apply_step_callback,
    init_store_state,
)
from ..models import vae as vae_mod
from ..models.config import PipelineConfig
from ..models.text_encoder import apply_text_encoder
from ..models.unet import apply_unet
from ..ops import schedulers as sched_mod
from ..utils import progress as progress_mod
from ..utils.tokenizer import Tokenizer, pad_ids


@dataclasses.dataclass(frozen=True)
class Pipeline:
    """A bound backend: config + parameter pytrees. The analogue of the
    reference's `StableDiffusionPipeline` handle (`/root/reference/main.py:29`),
    but immutable — controllers are sampling-call arguments, never installed
    into the model."""

    config: PipelineConfig
    unet_params: Any
    text_params: Any
    vae_params: Any
    tokenizer: Tokenizer

    @property
    def latent_shape(self) -> Tuple[int, int, int]:
        s = self.config.latent_size
        return (s, s, self.config.unet.in_channels)


@partial(jax.jit, static_argnames=("cfg", "dtype"))
def _encode_jit(params, cfg, ids, dtype):
    return apply_text_encoder(params, cfg, ids, dtype=dtype)


def encode_prompts(pipe: Pipeline, prompts, dtype=jnp.float32) -> jax.Array:
    """Tokenize + encode to (B, L, D) hidden states
    (`/root/reference/ptp_utils.py:144-156`)."""
    tok = pipe.tokenizer
    max_len = pipe.config.unet.context_len
    ids = jnp.asarray(
        [pad_ids(tok.encode(p), max_len, getattr(tok, "pad_token_id", tok.eos_token_id))
         for p in prompts], dtype=jnp.int32)
    return _encode_jit(pipe.text_params, pipe.config.text, ids, dtype)


def init_latent(latent: Optional[jax.Array], shape: Tuple[int, ...], rng: jax.Array,
                batch: int, dtype=jnp.float32) -> Tuple[jax.Array, jax.Array]:
    """One latent expanded over the edit group
    (`/root/reference/ptp_utils.py:88-95`). Returns (single, batched)."""
    if latent is None:
        latent = jax.random.normal(rng, (1,) + tuple(shape), dtype=dtype)
    latents = jnp.broadcast_to(latent, (batch,) + tuple(latent.shape[1:])).astype(dtype)
    return latent, latents


def _denoise_scan(
    unet_params: Any,
    cfg: PipelineConfig,
    layout: AttnLayout,
    schedule: sched_mod.DiffusionSchedule,
    scheduler_kind: str,
    context: jax.Array,            # (2B, L, D) [uncond; cond]
    latents: jax.Array,            # (B, h, w, c)
    controller: Optional[Controller],
    guidance_scale: jax.Array,
    uncond_per_step: Optional[jax.Array] = None,  # (T, 1, L, D) null-text embeddings
    progress: bool = False,
    sp: Optional["SpConfig"] = None,
) -> Tuple[jax.Array, StoreState]:
    """Scan over timesteps. Returns (final latents, final store state)."""
    b = latents.shape[0]
    state = (init_store_state(layout, b, dtype=jnp.float32)
             if (controller is not None and controller.needs_store) else ())

    use_plms = scheduler_kind == "plms"
    use_dpm = scheduler_kind == "dpm"
    # Multistep-solver state carried through the scan (PLMS ring buffer or
    # DPM x0 history; None for single-step DDIM).
    if use_plms:
        ms_state = sched_mod.init_plms_state(latents.shape, latents.dtype)
    elif use_dpm:
        ms_state = sched_mod.init_dpm_state(latents.shape, latents.dtype)
    else:
        ms_state = None

    def body(carry, scan_in):
        latents, state, ms = carry
        step, t = scan_in
        progress_mod.emit_step(progress, step)
        ctx = context
        if uncond_per_step is not None:
            # Null-text: substitute this step's optimized uncond embedding.
            # Cast to the sampling dtype — the artifact stores f32 (the
            # optimizer's dtype), and a f32 leak here would silently promote
            # the whole CFG context (and the U-Net matmuls) on the bf16 path.
            u = jax.lax.dynamic_index_in_dim(uncond_per_step, step, 0, keepdims=False)
            ctx = jnp.concatenate([jnp.broadcast_to(u.astype(context.dtype),
                                                    context[:b].shape),
                                   context[b:]], axis=0)
        latent_in = jnp.concatenate([latents] * 2, axis=0)
        eps, state = apply_unet(
            unet_params, cfg.unet, latent_in, t, ctx,
            layout=layout, controller=controller, state=state, step=step,
            sp=sp)
        eps_uncond, eps_text = eps[:b], eps[b:]
        eps = eps_uncond + guidance_scale * (eps_text - eps_uncond)
        # v-prediction models (SD-2.1 768-v): convert to ε once per step.
        # Linear in the model output, so combining CFG first is equivalent.
        eps = sched_mod.to_epsilon(schedule, eps, t, latents)
        if use_plms:
            ms, latents = sched_mod.plms_step(schedule, ms, eps, t, latents)
        elif use_dpm:
            ms, latents = sched_mod.dpm_step(schedule, ms, eps, t, latents)
        else:
            latents = sched_mod.ddim_step(schedule, eps, t, latents)
        latents = apply_step_callback(controller, layout, state, latents, step)
        return (latents, state, ms), None

    steps = jnp.arange(schedule.timesteps.shape[0], dtype=jnp.int32)
    (latents, state, _), _ = jax.lax.scan(
        body, (latents, state, ms_state), (steps, schedule.timesteps))
    return latents, state


@partial(jax.jit, static_argnames=("cfg", "layout", "scheduler_kind",
                                   "return_store", "progress", "sp"))
def _text2image_jit(
    unet_params: Any,
    vae_params: Any,
    cfg: PipelineConfig,
    layout: AttnLayout,
    schedule: sched_mod.DiffusionSchedule,
    scheduler_kind: str,
    context_cond: jax.Array,
    context_uncond: jax.Array,
    latents: jax.Array,
    controller: Optional[Controller],
    guidance_scale: jax.Array,
    uncond_per_step: Optional[jax.Array],
    return_store: bool,
    progress: bool = False,
    sp: Optional["SpConfig"] = None,
):
    context = jnp.concatenate([context_uncond, context_cond], axis=0)
    latents, state = _denoise_scan(
        unet_params, cfg, layout, schedule, scheduler_kind, context, latents,
        controller, guidance_scale, uncond_per_step, progress=progress, sp=sp)
    image = vae_mod.decode(vae_params, cfg.vae, latents.astype(jnp.float32))
    image = vae_mod.to_uint8(image)
    return (image, latents, state) if return_store else (image, latents, ())


def text2image(
    pipe: Pipeline,
    prompts,
    controller: Optional[Controller] = None,
    *,
    num_steps: Optional[int] = None,
    guidance_scale: Optional[float] = None,
    scheduler: Optional[str] = None,
    latent: Optional[jax.Array] = None,
    rng: Optional[jax.Array] = None,
    uncond_embeddings: Optional[jax.Array] = None,
    negative_prompt: Optional[str] = None,
    layout: Optional[AttnLayout] = None,
    dtype=jnp.float32,
    return_store: bool = False,
    progress: bool = False,
    sp: Optional["SpConfig"] = None,
):
    """Generate an edit group of images from prompts under attention control —
    the `/root/reference/ptp_utils.py:129-172` entry point.

    ``uncond_embeddings``: optional (T, 1, L, D) per-step null-text
    embeddings; otherwise the encoded unconditional prompt is broadcast over
    all steps. ``negative_prompt`` replaces the default ``""`` unconditional
    text (classifier-free guidance then steers *away* from it — a diffusers
    capability the reference lacks); mutually exclusive with
    ``uncond_embeddings``. ``sp`` (a :class:`p2p_tpu.models.unet.SpConfig`)
    shards the pixel axis of large untouched self-attention sites over a
    mesh axis with ring attention — the long-context scaling axis (image
    resolution; SURVEY §5) the reference lacks entirely. Returns
    ``(images uint8 (B,H,W,3), x_T, store)``.
    """
    if negative_prompt and uncond_embeddings is not None:
        raise ValueError("negative_prompt and uncond_embeddings are mutually "
                         "exclusive (null-text already optimized the uncond)")
    cfg = pipe.config
    num_steps = num_steps or cfg.num_steps
    scheduler = scheduler or cfg.scheduler.kind
    if uncond_embeddings is not None:
        if scheduler != "ddim":
            # PLMS scans T+1 steps (warm-up double-evaluation); per-step
            # null-text embeddings are optimized against the DDIM trajectory
            # and would silently misalign (`/root/reference/null_text.py:23`
            # — the null-text path is DDIM-only).
            raise ValueError("uncond_embeddings require scheduler='ddim'")
        if uncond_embeddings.shape[0] != num_steps:
            raise ValueError(
                f"uncond_embeddings has {uncond_embeddings.shape[0]} steps, "
                f"sampling uses {num_steps}")
    gs = jnp.asarray(cfg.guidance_scale if guidance_scale is None else guidance_scale,
                     dtype=jnp.float32)
    if layout is None:
        from ..models.config import unet_layout
        layout = unet_layout(cfg.unet)
    if rng is None:
        rng = jax.random.PRNGKey(0)

    schedule = sched_mod.schedule_from_config(num_steps, cfg.scheduler,
                                              kind=scheduler)
    context_cond = encode_prompts(pipe, prompts, dtype=dtype)
    context_uncond = encode_prompts(
        pipe, [negative_prompt or ""] * len(prompts), dtype=dtype)

    x_t, latents = init_latent(latent, pipe.latent_shape, rng, len(prompts), dtype)
    if progress:
        progress_mod.activate(schedule.timesteps.shape[0])
    image, latents_out, state = _text2image_jit(
        pipe.unet_params, pipe.vae_params, cfg, layout, schedule, scheduler,
        context_cond, context_uncond, latents, controller, gs,
        uncond_embeddings, return_store, progress=progress, sp=sp)
    return image, x_t, state
