"""Native pipeline snapshots: orbax param trees + a JSON config manifest.

The diffusers-format loader (`checkpoint.load_pipeline`) converts torch
tensor names/layouts on every process start; a native snapshot saves the
*converted* JAX pytrees once and restores them directly — the idiomatic
TPU checkpoint path (orbax is JAX's checkpointing library, sharding-aware
on restore). The reference has no equivalent: its weights always come from
`StableDiffusionPipeline.from_pretrained` (`/root/reference/main.py:29`).

Layout on disk::

    <dir>/config.json        dataclasses.asdict(PipelineConfig) + format tag
    <dir>/params/            orbax PyTreeCheckpointer tree
                             {"unet": ..., "text": ..., "vae": ...}

The tokenizer is deliberately NOT serialized — it is host-side code, not
arrays; pass the same tokenizer (HF-backed or hash) to
:func:`load_pipeline_native` that the snapshot was built with.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

from .config import (
    PipelineConfig,
    SchedulerConfig,
    TextEncoderConfig,
    UNetConfig,
    VAEConfig,
)

_FORMAT = 1


def _tuplify(d: dict) -> dict:
    """JSON round-trip turns tuples into lists; the frozen configs want
    tuples back (they're hashed as static jit arguments)."""
    return {k: tuple(v) if isinstance(v, list) else v for k, v in d.items()}


def config_to_dict(cfg: PipelineConfig) -> dict:
    out = dataclasses.asdict(cfg)
    out["_format"] = _FORMAT
    return out


def config_from_dict(d: dict) -> PipelineConfig:
    fmt = d.get("_format", _FORMAT)
    if fmt != _FORMAT:
        raise ValueError(f"unsupported native-snapshot format {fmt} "
                         f"(this build reads format {_FORMAT})")
    return PipelineConfig(
        name=d["name"],
        unet=UNetConfig(**_tuplify(d["unet"])),
        text=TextEncoderConfig(**_tuplify(d["text"])),
        vae=VAEConfig(**_tuplify(d["vae"])),
        image_size=d["image_size"],
        guidance_scale=d["guidance_scale"],
        num_steps=d["num_steps"],
        scheduler=SchedulerConfig(**_tuplify(d["scheduler"])),
    )


def save_pipeline_native(pipe, path: str, overwrite: bool = False) -> None:
    """Snapshot a bound pipeline's params + config under ``path``.

    Refuses an existing snapshot unless ``overwrite=True`` (which removes
    it first); the manifest is written only after the params commit, so a
    failed save can never leave a fresh config.json over stale params."""
    import shutil

    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    params_dir = os.path.join(path, "params")
    if os.path.exists(params_dir):
        if not overwrite:
            raise FileExistsError(
                f"native snapshot already exists at {path}; "
                f"pass overwrite=True to replace it")
        shutil.rmtree(path)
    os.makedirs(path, exist_ok=True)
    ocp.PyTreeCheckpointer().save(
        params_dir,
        {"unet": pipe.unet_params, "text": pipe.text_params,
         "vae": pipe.vae_params})
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(config_to_dict(pipe.config), f, indent=1)


def load_pipeline_native(path: str, tokenizer,
                         config: Optional[PipelineConfig] = None,
                         shard=None):
    """Restore a pipeline saved by :func:`save_pipeline_native`.

    The params restore to HOST numpy arrays regardless of the topology the
    snapshot was saved on (replaying a saved device sharding on a different
    topology is unsafe — orbax's own warning), so placement is explicit:
    pass ``shard``, a callable over the ``{"unet","text","vae"}`` tree
    (e.g. ``lambda t: {**t, "unet": shard_params(t["unet"], mesh)}``), or
    let jit move the host arrays on first use. ``config`` overrides the
    stored manifest."""
    import numpy as np

    import jax
    import orbax.checkpoint as ocp

    from ..engine.sampler import Pipeline

    path = os.path.abspath(path)
    if config is None:
        with open(os.path.join(path, "config.json")) as f:
            config = config_from_dict(json.load(f))
    from .compat import metadata_tree

    ckptr = ocp.PyTreeCheckpointer()
    params_dir = os.path.join(path, "params")
    # metadata() return shape drifted across orbax releases — shimmed.
    meta = metadata_tree(ckptr, params_dir)
    restore_args = jax.tree.map(
        lambda _: ocp.RestoreArgs(restore_type=np.ndarray), meta)
    params = ckptr.restore(params_dir, restore_args=restore_args)
    if shard is not None:
        params = shard(params)
    return Pipeline(config=config, unet_params=params["unet"],
                    text_params=params["text"], vae_params=params["vae"],
                    tokenizer=tokenizer)
