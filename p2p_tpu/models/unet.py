"""Conditional U-Net with attention-processor injection — the denoising model.

Topology matches diffusers' `UNet2DConditionModel` as configured for SD-v1.4
(the model the reference drives, `/root/reference/main.py:29`): conv_in →
attentive down blocks → mid → attentive up blocks with skip concats → conv_out,
where every transformer block holds a self- and a cross-attention site.

The prompt-to-prompt integration point is designed in, not monkey-patched
(`/root/reference/ptp_utils.py:175-242` is the behavior spec): every attention
site has a static :class:`AttnMeta`, and :func:`apply_unet` threads the
controller's store state through the sites in call order. Sites the controller
provably never touches (``controller_touches`` is False) run fused attention —
no probability tensor exists in the compiled program; touched sites
materialize f32 probabilities, route them through
``apply_attention_control``, then finish ``probs @ v``.

All tensors NHWC; params f32; compute dtype is the caller's (`x.dtype`).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..controllers.base import (
    AttnLayout,
    Controller,
    StoreState,
    apply_attention_control,
    controller_touches,
)
from .config import UNetConfig, unet_layout
from . import nn

Params = Dict[str, Any]


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------


def _attn_init(key, query_dim: int, context_dim: int, inner_dim: int) -> Params:
    k1, k2, k3, k4 = jax.random.split(key, 4)
    return {
        "to_q": nn.linear_init(k1, query_dim, inner_dim, bias=False),
        "to_k": nn.linear_init(k2, context_dim, inner_dim, bias=False),
        "to_v": nn.linear_init(k3, context_dim, inner_dim, bias=False),
        "to_out": nn.linear_init(k4, inner_dim, query_dim),
    }


def _transformer_block_init(key, dim: int, context_dim: int, ff_mult: int) -> Params:
    k1, k2, k3 = jax.random.split(key, 3)
    ff_inner = dim * ff_mult
    return {
        "ln1": nn.norm_init(dim),
        "attn1": _attn_init(k1, dim, dim, dim),
        "ln2": nn.norm_init(dim),
        "attn2": _attn_init(k2, dim, context_dim, dim),
        "ln3": nn.norm_init(dim),
        # GEGLU: one projection to 2·ff_inner (value ‖ gate), then back.
        "ff_in": nn.linear_init(jax.random.split(k3)[0], dim, ff_inner * 2),
        "ff_out": nn.linear_init(jax.random.split(k3)[1], ff_inner, dim),
    }


def _spatial_transformer_init(key, ch: int, cfg: UNetConfig) -> Params:
    keys = jax.random.split(key, cfg.transformer_depth + 2)
    return {
        "norm": nn.norm_init(ch),
        "proj_in": nn.conv_init(keys[0], ch, ch, kernel=1),
        "blocks": [
            _transformer_block_init(keys[1 + i], ch, cfg.context_dim, cfg.ff_mult)
            for i in range(cfg.transformer_depth)
        ],
        "proj_out": nn.conv_init(keys[-1], ch, ch, kernel=1),
    }


def _resnet_init(key, in_ch: int, out_ch: int, temb_dim: int) -> Params:
    k1, k2, k3, k4 = jax.random.split(key, 4)
    p = {
        "norm1": nn.norm_init(in_ch),
        "conv1": nn.conv_init(k1, in_ch, out_ch),
        "time_proj": nn.linear_init(k2, temb_dim, out_ch),
        "norm2": nn.norm_init(out_ch),
        "conv2": nn.conv_init(k3, out_ch, out_ch),
    }
    if in_ch != out_ch:
        p["skip"] = nn.conv_init(k4, in_ch, out_ch, kernel=1)
    return p


def init_unet(key: jax.Array, cfg: UNetConfig) -> Params:
    """Random-init parameter pytree with SD-faithful shapes."""
    n_levels = cfg.levels
    keys = iter(jax.random.split(key, 64))
    ch0 = cfg.block_channels[0]
    temb = cfg.time_embed_dim

    params: Params = {
        "time_fc1": nn.linear_init(next(keys), cfg.freq_dim or ch0, temb),
        "time_fc2": nn.linear_init(next(keys), temb, temb),
        "conv_in": nn.conv_init(next(keys), cfg.in_channels, ch0),
        "down": [],
        "up": [],
        "norm_out": nn.norm_init(ch0),
        "conv_out": nn.conv_init(next(keys), ch0, cfg.out_channels),
    }

    # Down path. Skip-channel bookkeeping mirrors diffusers exactly so up-block
    # concat widths match real checkpoints.
    skip_chs = [ch0]
    in_ch = ch0
    for level in range(n_levels):
        out_ch = cfg.block_channels[level]
        block: Params = {"resnets": [], "attns": []}
        for _ in range(cfg.layers_per_block):
            block["resnets"].append(_resnet_init(next(keys), in_ch, out_ch, temb))
            if cfg.attn_levels[level]:
                block["attns"].append(_spatial_transformer_init(next(keys), out_ch, cfg))
            in_ch = out_ch
            skip_chs.append(out_ch)
        if level != n_levels - 1:
            block["downsample"] = nn.conv_init(next(keys), out_ch, out_ch)
            skip_chs.append(out_ch)
        params["down"].append(block)

    mid_ch = cfg.block_channels[-1]
    params["mid"] = {
        "resnet1": _resnet_init(next(keys), mid_ch, mid_ch, temb),
        "attn": _spatial_transformer_init(next(keys), mid_ch, cfg),
        "resnet2": _resnet_init(next(keys), mid_ch, mid_ch, temb),
    }

    # Up path (reverse level order).
    in_ch = mid_ch
    for level in reversed(range(n_levels)):
        out_ch = cfg.block_channels[level]
        block = {"resnets": [], "attns": []}
        for _ in range(cfg.layers_per_block + 1):
            skip_ch = skip_chs.pop()
            block["resnets"].append(
                _resnet_init(next(keys), in_ch + skip_ch, out_ch, temb))
            if cfg.attn_levels[level]:
                block["attns"].append(_spatial_transformer_init(next(keys), out_ch, cfg))
            in_ch = out_ch
        if level != 0:
            block["upsample"] = nn.conv_init(next(keys), out_ch, out_ch)
        params["up"].append(block)

    return params


# ---------------------------------------------------------------------------
# Apply
# ---------------------------------------------------------------------------


def _apply_resnet(p: Params, x: jax.Array, temb: jax.Array, groups: int) -> jax.Array:
    h = nn.conv2d(p["conv1"], nn.silu(nn.group_norm(p["norm1"], x, groups)))
    h = h + nn.linear(p["time_proj"], nn.silu(temb))[:, None, None, :]
    h = nn.conv2d(p["conv2"], nn.silu(nn.group_norm(p["norm2"], h, groups)))
    if "skip" in p:
        x = nn.conv2d(p["skip"], x)
    return x + h


# Phase-gated sampling's cross-attention cache: one ``(B_cond, P, C)`` array
# per cross site in call order — the attn2 *output* (post-``to_out``) of the
# conditional batch half, captured on the last phase-1 step. Consuming it in
# phase 2 removes the whole q/k/v-projection + softmax(QKᵀ)V + ``to_out``
# pipeline of every cross site from the compiled program (TAD, arXiv
# 2404.02747: cross-attention outputs converge after an early gate step).
AttnCache = Tuple[jax.Array, ...]


def init_attn_cache(layout: AttnLayout, batch_cond: int,
                    dtype=jnp.float32) -> AttnCache:
    """Zero-initialized cache buffers for every cross-attention site.

    Requires a layout whose metas carry ``channels`` (built from
    ``unet_attn_specs``); hand-built 5-tuple layouts can't size the buffers.
    """
    caches = []
    for m in layout.metas:
        if not m.is_cross:
            continue
        if m.channels <= 0:
            raise ValueError(
                f"cross site {m.layer_idx} has no channel info "
                "(layout built from 5-tuple specs); the attention cache "
                "needs channels — rebuild the layout via unet_attn_specs")
        caches.append(jnp.zeros((batch_cond, m.pixels, m.channels), dtype))
    return tuple(caches)


class _HookCtx:
    """Trace-time cursor over the attention layout, carrying the controller
    store state through the sites in call order. ``sp`` optionally names a
    mesh axis for sequence-parallel (ring) self-attention at large sites.

    ``cache_mode`` is the phase-gated sampling switch (static, so each mode
    compiles its own program): ``'off'`` — no cache interaction; ``'store'``
    — compute every site normally and overwrite the cache slot of each cross
    site with its conditional-half output; ``'use'`` — cross sites return
    their cached output directly, computing nothing."""

    def __init__(self, layout: AttnLayout, controller: Optional[Controller],
                 state: StoreState, step: jax.Array,
                 sp: Optional["SpConfig"] = None,
                 attn_cache: Optional[AttnCache] = None,
                 cache_mode: str = "off",
                 site_plan: Optional[Tuple[str, ...]] = None,
                 kernels=None):
        self.layout = layout
        self.controller = controller
        self.state = state
        self.step = step
        self.sp = sp
        self.cursor = 0
        self.attn_cache = attn_cache
        self.cache_mode = cache_mode
        # Per-site action vector (engine.reuse): one mode per layout site
        # in call order — the generalized form the global cache_mode
        # lowers to. The cache cursor walks the non-"off" sites, whose
        # leaves the cache tuple holds in the same order.
        self.site_plan = site_plan
        self.cross_cursor = 0
        # Fused-kernel dispatch plan (kernels.KernelConfig or None): static,
        # so each covered controller-touched site lowers to the in-kernel
        # edit program instead of the materialized f32 path.
        self.kernels = kernels

    def next_meta(self):
        meta = self.layout.metas[self.cursor]
        self.cursor += 1
        return meta


@dataclasses.dataclass(frozen=True)
class SpConfig:
    """Sequence-parallel plan for self-attention: shard the pixel axis of
    every *untouched* self site with ≥ ``min_pixels`` pixels over mesh axis
    ``axis``. This is the scaling axis the reference lacks entirely
    (SURVEY §5: resolution is quadratic in pixels); controller-touched
    sites stay local because edits read whole probability rows.

    ``mode`` selects the communication scheme: ``"ring"`` rotates k/v
    shards via ppermute (`parallel/ring.py`); ``"alltoall"`` redistributes
    to head sharding for one dense local attention per device
    (Ulysses-style, `parallel/alltoall.py`) — sites whose head count the
    axis doesn't divide fall back to the ring, which is always valid."""

    mesh: Any                 # jax.sharding.Mesh
    axis: str = "sp"
    min_pixels: int = 64 * 64
    mode: str = "ring"

    def __post_init__(self):
        if self.mode not in ("ring", "alltoall"):
            raise ValueError(f"unknown sp mode {self.mode!r} "
                             f"(expected 'ring' or 'alltoall')")


def _apply_attention(p: Params, x: jax.Array, context: jax.Array, heads: int,
                     ctx: _HookCtx, is_cross: bool) -> jax.Array:
    """One attention site. x: (B, P, C); context: (B, K, Cc).

    Every site's computation is wrapped in a ``jax.named_scope`` whose
    name encodes the site identity (``cross_attn/down3`` etc. — place +
    global layer index from the :class:`AttnMeta`): the scope lands in
    the HLO op metadata, so a Perfetto/XProf device trace splits step
    time *per attention site* — the per-site cost attribution the
    TAD-style reuse-schedule search (ROADMAP item 1) keys on. A trace-
    time name only: the lowered ops, numerics and jaxpr structure are
    identical with or without it."""
    meta = ctx.next_meta()
    assert meta.is_cross == is_cross, (
        f"layout order mismatch at site {meta.layer_idx}: layout says "
        f"is_cross={meta.is_cross}, model called is_cross={is_cross}")
    with jax.named_scope(f"{'cross_attn' if is_cross else 'self_attn'}"
                         f"/{meta.place}{meta.layer_idx}"):
        return _attention_site(p, x, context, heads, ctx, meta, is_cross)


def _site_mode(ctx: _HookCtx, meta, is_cross: bool) -> str:
    """This site's static cache action. The legacy global ``cache_mode``
    lowers to the per-site form (all cross sites, no self sites) so both
    surfaces run ONE code path; ``site_plan`` (engine.reuse schedules) may
    mix actions per site and cover self sites too."""
    if ctx.site_plan is not None:
        return ctx.site_plan[meta.layer_idx]
    if is_cross and ctx.cache_mode in ("store", "use"):
        return ctx.cache_mode
    return "off"


def _fused_edit_dispatch(ctx: _HookCtx, meta, q, k, v, scale):
    """Route a controller-touched site to the fused-edit Pallas kernel
    (``kernels.fused_edit``) when the static dispatch plan covers it; None →
    the caller keeps the materialized reference path. The kernel applies the
    controller's edit inside a tiled softmax, so the ``(2B, heads, P, K)``
    probability tensor never reaches HBM at fused sites. Compiled-kernel
    lowering only exists on TPU; ``interpret=True`` configs run the
    identical program through the pallas interpreter (the CPU parity
    surface). Attention-STORE sites are never fused (``kernel_edit_spec``
    returns None for them — the store needs the materialized tensor)."""
    if ctx.kernels is None:
        return None
    if not (ctx.kernels.interpret or nn._on_tpu()):
        return None
    from .. import kernels as kernels_mod

    if not ctx.kernels.covers(kernels_mod.dispatch.site_name(meta)):
        return None
    from ..kernels.fused_edit import fused_site_attention

    return fused_site_attention(q, k, v, scale, ctx.controller, meta,
                                ctx.step, block_q=ctx.kernels.block_q,
                                interpret=ctx.kernels.interpret)


def _attention_site(p: Params, x: jax.Array, context: jax.Array, heads: int,
                    ctx: _HookCtx, meta, is_cross: bool) -> jax.Array:
    mode = _site_mode(ctx, meta, is_cross)
    if mode == "use":
        # The site's output is served from its cache: for cross sites the
        # text context is untouched so the cached tensor is the TAD reuse;
        # for self sites it is the A-SDM feature inherited from the site's
        # last computed step. Returning it here removes q/k/v,
        # softmax(QKᵀ)V and to_out for the site from the compiled program
        # entirely.
        cached = ctx.attn_cache[ctx.cross_cursor]
        ctx.cross_cursor += 1
        assert cached.shape == (x.shape[0], x.shape[1], x.shape[2]), (
            f"attn cache shape {cached.shape} does not match site "
            f"{meta.layer_idx} input {x.shape} — was the cache captured at a "
            "different batch/resolution?")
        return cached

    b, pix, _ = x.shape
    src = context if is_cross else x
    q = nn.linear(p["to_q"], x)
    k = nn.linear(p["to_k"], src)
    v = nn.linear(p["to_v"], src)
    d_head = q.shape[-1] // heads
    scale = d_head ** -0.5

    def split_heads(t):
        return t.reshape(b, t.shape[1], heads, d_head).transpose(0, 2, 1, 3)

    q, k, v = split_heads(q), split_heads(k), split_heads(v)

    if controller_touches(ctx.controller, meta):
        out = _fused_edit_dispatch(ctx, meta, q, k, v, scale)
        if out is None:
            probs = nn.attention_probs(q, k, scale)        # (B, heads, P, K) f32
            ctx.state, probs = apply_attention_control(
                ctx.controller, meta, ctx.state, probs, ctx.step)
            out = jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)
    elif (ctx.sp is not None and not is_cross
          and meta.pixels >= ctx.sp.min_pixels):
        n = ctx.sp.mesh.shape[ctx.sp.axis]
        if meta.pixels % n:
            # Unsharded fallback is safe only when fused attention stays
            # blockwise (flash-tileable: S ≥ 2048 with a power-of-two block
            # dividing it). Otherwise the einsum path would materialize the
            # O(P²) scores on one device — the blow-up SpConfig exists to
            # avoid — so that case is an error, not a warning.
            flash_ok = meta.pixels >= 2048 and any(
                meta.pixels % b == 0 for b in (1024, 512, 256))
            if not flash_ok:
                raise ValueError(
                    f"sequence-parallel site {meta.layer_idx} has "
                    f"{meta.pixels} pixels, not divisible by mesh axis "
                    f"{ctx.sp.axis!r}={n}, and not flash-tileable locally; "
                    f"choose a divisor axis size or raise SpConfig.min_pixels")
            import warnings

            warnings.warn(
                f"sequence-parallel site {meta.layer_idx}: {meta.pixels} "
                f"pixels not divisible by mesh axis {ctx.sp.axis!r}={n}; "
                f"running this site unsharded (local flash)", stacklevel=2)
            out = nn.fused_attention(q, k, v, scale)
        elif ctx.sp.mode == "alltoall" and q.shape[1] % n == 0:
            from ..parallel.alltoall import alltoall_self_attention

            out = alltoall_self_attention(q, k, v, scale, ctx.sp.mesh,
                                          ctx.sp.axis)
        else:
            if ctx.sp.mode == "alltoall":
                # Same user-visible note as the pixel-indivisible fallback
                # above: someone benchmarking alltoall must not unknowingly
                # measure ring (warnings module dedups per call site).
                import warnings

                warnings.warn(
                    f"sequence-parallel site {meta.layer_idx}: "
                    f"{q.shape[1]} heads not divisible by mesh axis "
                    f"{ctx.sp.axis!r}={n}; alltoall falls back to ring "
                    f"at this site", stacklevel=2)
            from ..parallel.ring import ring_self_attention

            out = ring_self_attention(q, k, v, scale, ctx.sp.mesh, ctx.sp.axis)
    else:
        out = nn.fused_attention(q, k, v, scale)

    out = out.transpose(0, 2, 1, 3).reshape(b, pix, heads * d_head)
    out = nn.linear(p["to_out"], out)
    if mode == "store":
        # Capture the conditional half of the CFG-doubled batch (rows B:).
        # Overwritten every step, so after the scan the cache holds
        # exactly the last stored step's outputs — no per-step select.
        lst = list(ctx.attn_cache)
        lst[ctx.cross_cursor] = out[out.shape[0] // 2:]
        ctx.attn_cache = tuple(lst)
        ctx.cross_cursor += 1
    elif mode == "store_all":
        # A site that flips to reuse inside its current batch regime
        # (engine.reuse MODE_STORE_ALL) keeps the whole live batch — 2B
        # while CFG is active, B past the gate — so the flip segment can
        # serve it without a shape change.
        lst = list(ctx.attn_cache)
        lst[ctx.cross_cursor] = out
        ctx.attn_cache = tuple(lst)
        ctx.cross_cursor += 1
    return out


def _apply_transformer_block(p: Params, x: jax.Array, context: jax.Array,
                             heads: int, ctx: _HookCtx) -> jax.Array:
    x = x + _apply_attention(p["attn1"], nn.layer_norm(p["ln1"], x), context,
                             heads, ctx, is_cross=False)
    x = x + _apply_attention(p["attn2"], nn.layer_norm(p["ln2"], x), context,
                             heads, ctx, is_cross=True)
    h = nn.linear(p["ff_in"], nn.layer_norm(p["ln3"], x))
    val, gate = jnp.split(h, 2, axis=-1)
    x = x + nn.linear(p["ff_out"], val * nn.gelu(gate))
    return x


def _apply_spatial_transformer(p: Params, x: jax.Array, context: jax.Array,
                               cfg: UNetConfig, ctx: _HookCtx) -> jax.Array:
    b, h, w, c = x.shape
    residual = x
    x = nn.group_norm(p["norm"], x, cfg.groups, eps=1e-6)
    # proj_in/proj_out are 1×1 convs in the checkpoint; applied as linears in
    # token-major space so the whole transformer stack stays (B, P, C) with no
    # spatial relayouts between the convs and the attention matmuls.
    x = x.reshape(b, h * w, c)
    x = nn.linear_1x1(p["proj_in"], x)
    for block in p["blocks"]:
        x = _apply_transformer_block(block, x, context, cfg.heads_for(c), ctx)
    x = nn.linear_1x1(p["proj_out"], x)
    return x.reshape(b, h, w, c) + residual


def apply_unet(
    params: Params,
    cfg: UNetConfig,
    x: jax.Array,                  # (B, H, W, C) latents, NHWC
    t: jax.Array,                  # scalar or (B,) timestep
    context: jax.Array,            # (B, K, Cc) text embeddings
    layout: Optional[AttnLayout] = None,
    controller: Optional[Controller] = None,
    state: StoreState = (),
    step: Optional[jax.Array] = None,
    sp: Optional[SpConfig] = None,
    attn_cache: Optional[AttnCache] = None,
    cache_mode: str = "off",
    site_plan: Optional[Tuple[str, ...]] = None,
    kernels=None,
):
    """Predict ε(x_t, t, context). Returns ``(eps, controller_store_state)``,
    plus the updated cache as a third element iff ``cache_mode='store'``
    or a ``site_plan`` is given.

    ``kernels`` (a static ``kernels.KernelConfig``) routes covered
    controller-touched sites to the fused-edit Pallas kernel — the edit
    runs inside a tiled softmax and the probability tensor never
    materializes in HBM (see :func:`_fused_edit_dispatch` for the exact
    dispatch conditions). ``kernels=None`` is byte-identical to the
    pre-existing program.

    With ``controller=None`` this is a plain conditional U-Net forward and the
    returned state is the input state — the `EmptyControl ≡ no controller`
    equivalence holds at the XLA-program level. ``sp`` enables ring
    (sequence-parallel) attention for large untouched self sites.

    ``cache_mode`` (static) is phase-gated sampling's switch over the
    cross-attention cache ``attn_cache`` (one ``(B_cond, P, C)`` leaf per
    cross site): ``'store'`` runs the normal CFG-doubled forward and
    overwrites each cross slot with the site's conditional-half output;
    ``'use'`` runs the single-branch (no uncond half) forward with every
    cross site replaced by its cached output — a genuinely smaller program.
    ``'use'`` is incompatible with an active controller: cross edits and
    stores read the probability tensor, which no longer exists.
    """
    if cache_mode not in ("off", "store", "use"):
        raise ValueError(f"unknown cache_mode {cache_mode!r} "
                         "(expected 'off', 'store' or 'use')")
    if layout is None:
        layout = unet_layout(cfg)
    if site_plan is not None:
        # The per-site generalization (engine.reuse schedules): a static
        # action per layout site. Mutually exclusive with the legacy
        # global switch — a caller mixing both has a bug.
        if cache_mode != "off":
            raise ValueError("site_plan and cache_mode are mutually "
                             "exclusive; the plan subsumes the mode")
        if len(site_plan) != len(layout.metas):
            raise ValueError(
                f"site_plan has {len(site_plan)} entries for a layout "
                f"with {len(layout.metas)} attention sites")
        bad = set(site_plan) - {"off", "store", "store_all", "use"}
        if bad:
            raise ValueError(f"unknown site_plan mode(s) {sorted(bad)}")
        n_cached = sum(1 for m in site_plan if m != "off")
        if (attn_cache is None and n_cached) or \
                (attn_cache is not None and len(attn_cache) != n_cached):
            raise ValueError(
                f"site_plan has {n_cached} cached site(s); attn_cache has "
                f"{None if attn_cache is None else len(attn_cache)} "
                "leaf/leaves")
        # Edits at a reused site are structurally impossible (no
        # probability tensor): schedule resolution warns about window
        # conflicts upstream (engine.reuse.warn_schedule_conflicts), so
        # here a controller may legitimately coexist with "use" sites.
    elif cache_mode != "off":
        n_cross = sum(1 for m in layout.metas if m.is_cross)
        if attn_cache is None or len(attn_cache) != n_cross:
            raise ValueError(
                f"cache_mode={cache_mode!r} needs an attn_cache with one "
                f"entry per cross site ({n_cross}), got "
                f"{None if attn_cache is None else len(attn_cache)}")
    if cache_mode == "use" and controller is not None \
            and not controller.is_identity:
        # The needs_store/edit guard: a controller's cross hooks need the
        # materialized probability tensor, which the cached path never
        # computes. Gate resolution ('auto') keeps edit windows inside
        # phase 1; phase 2 must drop the controller at the U-Net level and
        # apply only the latent-space step callback with the frozen store.
        raise ValueError(
            "cache_mode='use' cannot run with an active controller: "
            "cross-attention probabilities are not computed in phase 2 — "
            "pass controller=None and keep controller effects to "
            "apply_step_callback")
    if step is None:
        step = jnp.int32(0)
    ctx = _HookCtx(layout, controller, state, step, sp=sp,
                   attn_cache=attn_cache, cache_mode=cache_mode,
                   site_plan=site_plan, kernels=kernels)
    g = cfg.groups

    t = jnp.broadcast_to(jnp.asarray(t), (x.shape[0],))
    temb = nn.timestep_embedding(t, cfg.freq_dim or cfg.block_channels[0],
                                 dtype=x.dtype)
    temb = nn.linear(params["time_fc2"], nn.silu(nn.linear(params["time_fc1"], temb)))

    h = nn.conv2d(params["conv_in"], x)
    skips = [h]
    for level, block in enumerate(params["down"]):
        for i, resnet in enumerate(block["resnets"]):
            h = _apply_resnet(resnet, h, temb, g)
            if block["attns"]:
                h = _apply_spatial_transformer(block["attns"][i], h, context, cfg, ctx)
            skips.append(h)
        if "downsample" in block:
            # Symmetric pad 1 (diffusers downsample_padding=1) — XLA SAME would
            # pad (0,1) on even inputs and shift every downstream feature map.
            h = nn.conv2d(block["downsample"], h, stride=2, padding=1)
            skips.append(h)

    h = _apply_resnet(params["mid"]["resnet1"], h, temb, g)
    h = _apply_spatial_transformer(params["mid"]["attn"], h, context, cfg, ctx)
    h = _apply_resnet(params["mid"]["resnet2"], h, temb, g)

    for block in params["up"]:
        for i, resnet in enumerate(block["resnets"]):
            h = jnp.concatenate([h, skips.pop()], axis=-1)
            h = _apply_resnet(resnet, h, temb, g)
            if block["attns"]:
                h = _apply_spatial_transformer(block["attns"][i], h, context, cfg, ctx)
        if "upsample" in block:
            h = nn.conv2d(block["upsample"], nn.upsample_nearest_2x(h))

    assert ctx.cursor == len(layout.metas), (
        f"attention layout mismatch: model has {ctx.cursor} sites, "
        f"layout has {len(layout.metas)}")

    h = nn.silu(nn.group_norm(params["norm_out"], h, g))
    eps = nn.conv2d(params["conv_out"], h)
    if cache_mode == "store" or site_plan is not None:
        return eps, ctx.state, ctx.attn_cache
    return eps, ctx.state
