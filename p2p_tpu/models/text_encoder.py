"""Text encoders: CLIP-style causal transformer (SD) and BERT-style (LDM-256).

The reference consumes text encoders purely as ``ids -> (B, 77, D) hidden
states``: CLIP ViT-L/14's last hidden state for SD
(`/root/reference/ptp_utils.py:151-156`) and `model.bert` for LDM-256
(`/root/reference/ptp_utils.py:113-118`). One config-driven transformer covers
both: ``causal=True, quick_gelu`` is CLIP-L; ``causal=False, gelu`` is the
LDM's BERT-style encoder.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from .config import TextEncoderConfig
from . import nn

Params = Dict[str, Any]


def init_text_encoder(key: jax.Array, cfg: TextEncoderConfig) -> Params:
    keys = iter(jax.random.split(key, 4 + cfg.num_layers))
    d = cfg.hidden_dim
    params: Params = {
        "token_embed": jax.random.normal(next(keys), (cfg.vocab_size, d)) * 0.02,
        "pos_embed": jax.random.normal(next(keys), (cfg.max_length, d)) * 0.01,
        "layers": [],
        "final_ln": nn.norm_init(d),
    }
    inner = cfg.inner_dim
    for _ in range(cfg.num_layers):
        k1, k2, k3, k4, k5, k6 = jax.random.split(next(keys), 6)
        params["layers"].append({
            "ln1": nn.norm_init(d),
            "q": nn.linear_init(k1, d, inner, bias=cfg.attn_qkv_bias),
            "k": nn.linear_init(k2, d, inner, bias=cfg.attn_qkv_bias),
            "v": nn.linear_init(k3, d, inner, bias=cfg.attn_qkv_bias),
            "out": nn.linear_init(k4, inner, d),
            "ln2": nn.norm_init(d),
            "fc1": nn.linear_init(k5, d, d * cfg.ff_mult),
            "fc2": nn.linear_init(k6, d * cfg.ff_mult, d),
        })
    return params


def apply_text_encoder(params: Params, cfg: TextEncoderConfig,
                       ids: jax.Array, dtype=jnp.float32) -> jax.Array:
    """ids: (B, L) int32 → (B, L, D) final-layer hidden states (post-LN)."""
    b, length = ids.shape
    x = params["token_embed"][ids].astype(dtype)
    x = x + params["pos_embed"][:length].astype(dtype)

    mask = None
    if cfg.causal:
        # Additive causal mask, f32 -inf above the diagonal (CLIP text tower).
        mask = jnp.triu(jnp.full((length, length), -1e9, jnp.float32), k=1)
        mask = mask[None, None]

    heads = cfg.num_heads
    d_head = cfg.inner_dim // heads
    scale = d_head ** -0.5

    def split_heads(t):
        return t.reshape(b, length, heads, d_head).transpose(0, 2, 1, 3)

    for layer in params["layers"]:
        h = nn.layer_norm(layer["ln1"], x)
        q = split_heads(nn.linear(layer["q"], h))
        k = split_heads(nn.linear(layer["k"], h))
        v = split_heads(nn.linear(layer["v"], h))
        attn = nn.fused_attention(q, k, v, scale, mask)
        attn = attn.transpose(0, 2, 1, 3).reshape(b, length, cfg.inner_dim)
        x = x + nn.linear(layer["out"], attn)

        h = nn.layer_norm(layer["ln2"], x)
        act = nn.quick_gelu if cfg.activation == "quick_gelu" else nn.gelu
        x = x + nn.linear(layer["fc2"], act(nn.linear(layer["fc1"], h)))

    return nn.layer_norm(params["final_ln"], x)
