"""Minimal functional NN toolkit: explicit param pytrees + pure apply fns.

Why not flax.linen: the prompt-to-prompt hook must thread controller store
state through every attention call site *in call order* and return it from the
model forward. With explicit (params, x, state) -> (y, state) functions that
threading is plain dataflow, the param tree maps 1:1 onto checkpoint names,
and everything is trivially jit/pjit/scan-compatible. All spatial tensors are
NHWC (TPU-native layout); compute dtype is a caller choice (bf16 on TPU),
while normalization statistics and softmax run in f32.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


def _split(key, n):
    return jax.random.split(key, n)


# ---------------------------------------------------------------------------
# Linear / Conv
# ---------------------------------------------------------------------------


def linear_init(key, in_dim: int, out_dim: int, bias: bool = True,
                dtype=jnp.float32) -> Params:
    kk, _ = _split(key, 2)
    scale = 1.0 / math.sqrt(in_dim)
    p = {"kernel": jax.random.uniform(kk, (in_dim, out_dim), dtype, -scale, scale)}
    if bias:
        p["bias"] = jnp.zeros((out_dim,), dtype)
    return p


def linear(p: Params, x: jax.Array) -> jax.Array:
    y = x @ p["kernel"].astype(x.dtype)
    if "bias" in p:
        y = y + p["bias"].astype(x.dtype)
    return y


def conv_init(key, in_ch: int, out_ch: int, kernel: int = 3, bias: bool = True,
              dtype=jnp.float32) -> Params:
    kk, _ = _split(key, 2)
    fan_in = in_ch * kernel * kernel
    scale = 1.0 / math.sqrt(fan_in)
    p = {"kernel": jax.random.uniform(kk, (kernel, kernel, in_ch, out_ch), dtype,
                                      -scale, scale)}
    if bias:
        p["bias"] = jnp.zeros((out_ch,), dtype)
    return p


def conv2d(p: Params, x: jax.Array, stride: int = 1, padding: str | int = "SAME"
           ) -> jax.Array:
    """NHWC conv; weight layout HWIO."""
    if isinstance(padding, int):
        padding = [(padding, padding), (padding, padding)]
    y = jax.lax.conv_general_dilated(
        x, p["kernel"].astype(x.dtype),
        window_strides=(stride, stride),
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    if "bias" in p:
        y = y + p["bias"].astype(x.dtype)
    return y


# ---------------------------------------------------------------------------
# Norms (statistics in f32 regardless of compute dtype)
# ---------------------------------------------------------------------------


def norm_init(dim: int, dtype=jnp.float32) -> Params:
    return {"scale": jnp.ones((dim,), dtype), "bias": jnp.zeros((dim,), dtype)}


def group_norm(p: Params, x: jax.Array, groups: int = 32, eps: float = 1e-5
               ) -> jax.Array:
    """GroupNorm over an NHWC (or N...C) tensor."""
    orig_dtype = x.dtype
    x = x.astype(jnp.float32)
    c = x.shape[-1]
    g = min(groups, c)
    xg = x.reshape(x.shape[:-1] + (g, c // g))
    red = tuple(range(1, xg.ndim - 2)) + (xg.ndim - 1,)
    mean = xg.mean(axis=red, keepdims=True)
    var = xg.var(axis=red, keepdims=True)
    xg = (xg - mean) * jax.lax.rsqrt(var + eps)
    x = xg.reshape(x.shape)
    return (x * p["scale"] + p["bias"]).astype(orig_dtype)


def layer_norm(p: Params, x: jax.Array, eps: float = 1e-5) -> jax.Array:
    orig_dtype = x.dtype
    x = x.astype(jnp.float32)
    mean = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    return (y * p["scale"] + p["bias"]).astype(orig_dtype)


# ---------------------------------------------------------------------------
# Activations / embeddings
# ---------------------------------------------------------------------------


def silu(x):
    return x * jax.nn.sigmoid(x)


def gelu(x):
    return jax.nn.gelu(x, approximate=False)


def quick_gelu(x):
    """CLIP's activation: x * sigmoid(1.702 x)."""
    return x * jax.nn.sigmoid(1.702 * x)


def timestep_embedding(t: jax.Array, dim: int, max_period: float = 10000.0,
                       dtype=jnp.float32) -> jax.Array:
    """Sinusoidal timestep embedding, diffusers `Timesteps` semantics
    (flip_sin_to_cos=True, downscale_freq_shift=0): [cos | sin] halves."""
    half = dim // 2
    freqs = jnp.exp(-math.log(max_period) * jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[..., None] * freqs
    emb = jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)
    if dim % 2:
        emb = jnp.pad(emb, [(0, 0)] * (emb.ndim - 1) + [(0, 1)])
    return emb.astype(dtype)


# ---------------------------------------------------------------------------
# Attention core
# ---------------------------------------------------------------------------


def attention_probs(q: jax.Array, k: jax.Array, scale: float,
                    mask: Optional[jax.Array] = None) -> jax.Array:
    """Materialized softmax(QKᵀ·scale) in f32 — the tensor prompt-to-prompt
    edits (`/root/reference/ptp_utils.py:195-205`). q,k: (B, heads, S, D)."""
    sim = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                     preferred_element_type=jnp.float32) * scale
    if mask is not None:
        sim = sim + mask
    return jax.nn.softmax(sim.astype(jnp.float32), axis=-1)


def fused_attention(q: jax.Array, k: jax.Array, v: jax.Array, scale: float,
                    mask: Optional[jax.Array] = None) -> jax.Array:
    """Attention for call sites the controller provably never reads
    (`/root/reference/main.py:131,170` never touches 64²-pixel maps).

    Routed through `jax.nn.dot_product_attention` so XLA may lower to a
    flash/blockwise kernel that never materializes the (S, S) probability
    tensor — an explicit softmax-between-einsums chain would always
    materialize it. q,k,v: (B, heads, S, D); mask: additive, broadcastable
    to (B, heads, Sq, Sk)."""
    bias = None
    if mask is not None:
        bias = mask.astype(q.dtype)
    out = jax.nn.dot_product_attention(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3),
        bias=bias, scale=scale)
    return out.transpose(0, 2, 1, 3)
