"""Minimal functional NN toolkit: explicit param pytrees + pure apply fns.

Why not flax.linen: the prompt-to-prompt hook must thread controller store
state through every attention call site *in call order* and return it from the
model forward. With explicit (params, x, state) -> (y, state) functions that
threading is plain dataflow, the param tree maps 1:1 onto checkpoint names,
and everything is trivially jit/pjit/scan-compatible. All spatial tensors are
NHWC (TPU-native layout); compute dtype is a caller choice (bf16 on TPU),
while normalization statistics and softmax run in f32.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


def _split(key, n):
    return jax.random.split(key, n)


# ---------------------------------------------------------------------------
# Linear / Conv
# ---------------------------------------------------------------------------


def linear_init(key, in_dim: int, out_dim: int, bias: bool = True,
                dtype=jnp.float32) -> Params:
    kk, _ = _split(key, 2)
    scale = 1.0 / math.sqrt(in_dim)
    p = {"kernel": jax.random.uniform(kk, (in_dim, out_dim), dtype, -scale, scale)}
    if bias:
        p["bias"] = jnp.zeros((out_dim,), dtype)
    return p


def linear(p: Params, x: jax.Array) -> jax.Array:
    y = x @ p["kernel"].astype(x.dtype)
    if "bias" in p:
        y = y + p["bias"].astype(x.dtype)
    return y


def linear_1x1(p: Params, x: jax.Array) -> jax.Array:
    """Apply a 1×1-conv parameter (HWIO kernel (1,1,I,O)) as a linear over a
    token-major (B, P, C) tensor — same math, no spatial relayout."""
    q = {"kernel": p["kernel"][0, 0]}
    if "bias" in p:
        q["bias"] = p["bias"]
    return linear(q, x)


def conv_init(key, in_ch: int, out_ch: int, kernel: int = 3, bias: bool = True,
              dtype=jnp.float32) -> Params:
    kk, _ = _split(key, 2)
    fan_in = in_ch * kernel * kernel
    scale = 1.0 / math.sqrt(fan_in)
    p = {"kernel": jax.random.uniform(kk, (kernel, kernel, in_ch, out_ch), dtype,
                                      -scale, scale)}
    if bias:
        p["bias"] = jnp.zeros((out_ch,), dtype)
    return p


def conv2d(p: Params, x: jax.Array, stride: int = 1, padding: str | int = "SAME"
           ) -> jax.Array:
    """NHWC conv; weight layout HWIO."""
    if isinstance(padding, int):
        padding = [(padding, padding), (padding, padding)]
    y = jax.lax.conv_general_dilated(
        x, p["kernel"].astype(x.dtype),
        window_strides=(stride, stride),
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    if "bias" in p:
        y = y + p["bias"].astype(x.dtype)
    return y


# ---------------------------------------------------------------------------
# Norms (statistics in f32 regardless of compute dtype)
# ---------------------------------------------------------------------------


def norm_init(dim: int, dtype=jnp.float32) -> Params:
    return {"scale": jnp.ones((dim,), dtype), "bias": jnp.zeros((dim,), dtype)}


def group_norm(p: Params, x: jax.Array, groups: int = 32, eps: float = 1e-5
               ) -> jax.Array:
    """GroupNorm over an NHWC (or N...C) tensor.

    Statistics accumulate in f32 regardless of carrier dtype; the
    normalization arithmetic stays in the carrier dtype. On the bf16 TPU path
    this keeps the producing conv's output bf16 — profiling showed XLA
    otherwise folds an x.astype(f32) into the conv fusion and writes f32,
    doubling HBM write traffic on every GN-feeding conv (~8% of step time at
    SD-1.4 shapes). f32 inputs are unaffected (stats math is then pure f32).
    """
    if x.dtype == jnp.float32:
        # Full-precision path (CPU tests / parity harness): all math in f32.
        c = x.shape[-1]
        g = min(groups, c)
        xg = x.reshape(x.shape[:-1] + (g, c // g))
        red = tuple(range(1, xg.ndim - 2)) + (xg.ndim - 1,)
        mean = xg.mean(axis=red, keepdims=True)
        var = xg.var(axis=red, keepdims=True)
        xg = (xg - mean) * jax.lax.rsqrt(var + eps)
        return xg.reshape(x.shape) * p["scale"] + p["bias"]

    c = x.shape[-1]
    g = min(groups, c)
    xg = x.reshape(x.shape[:-1] + (g, c // g))
    red = tuple(range(1, xg.ndim - 2)) + (xg.ndim - 1,)
    # Shifted two-pass statistics, all full-tensor traffic in the carrier
    # dtype: center by the bf16-rounded mean (the subtraction x − m16 is
    # Sterbenz-exact for values near the mean, so no |mean|/std-scaled error),
    # accumulate the centered second moment in f32, and fold the f32 rounding
    # residual (mean − m16) into the per-group shift. XLA input-fuses the
    # f32-accumulating reductions — the bf16 tensor is never materialized
    # as f32 in HBM (that materialization was ~8% of SD-1.4 step time).
    mean = jnp.mean(xg, axis=red, keepdims=True, dtype=jnp.float32)
    m16 = mean.astype(x.dtype)
    centered = xg - m16
    cvar = jnp.mean(jnp.square(centered.astype(jnp.float32)), axis=red,
                    keepdims=True)
    resid = mean - m16.astype(jnp.float32)
    var = cvar - jnp.square(resid)
    expand = (None,) * (xg.ndim - 2)
    inv = (jax.lax.rsqrt(var + eps)
           * p["scale"].astype(jnp.float32).reshape((g, c // g))[expand])
    shift = (p["bias"].astype(jnp.float32).reshape((g, c // g))[expand]
             - resid * inv)
    y = centered * inv.astype(x.dtype) + shift.astype(x.dtype)
    return y.reshape(x.shape)


def layer_norm(p: Params, x: jax.Array, eps: float = 1e-5) -> jax.Array:
    """LayerNorm; f32 statistics, carrier-dtype tensor arithmetic (see
    group_norm for why and for the shifted-two-pass precision argument)."""
    if x.dtype == jnp.float32:
        mean = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        y = (x - mean) * jax.lax.rsqrt(var + eps)
        return y * p["scale"] + p["bias"]
    mean = jnp.mean(x, axis=-1, keepdims=True, dtype=jnp.float32)
    m16 = mean.astype(x.dtype)
    centered = x - m16
    cvar = jnp.mean(jnp.square(centered.astype(jnp.float32)), axis=-1,
                    keepdims=True)
    resid = mean - m16.astype(jnp.float32)
    var = cvar - jnp.square(resid)
    inv = jax.lax.rsqrt(var + eps)
    scale_shift = (p["bias"].astype(jnp.float32)
                   - resid * inv * p["scale"].astype(jnp.float32))
    y = (centered * inv.astype(x.dtype)) * p["scale"].astype(x.dtype)
    return y + scale_shift.astype(x.dtype)


# ---------------------------------------------------------------------------
# Activations / embeddings
# ---------------------------------------------------------------------------


def silu(x):
    return x * jax.nn.sigmoid(x)


def gelu(x):
    return jax.nn.gelu(x, approximate=False)


def quick_gelu(x):
    """CLIP's activation: x * sigmoid(1.702 x)."""
    return x * jax.nn.sigmoid(1.702 * x)


def upsample_nearest_2x(x: jax.Array) -> jax.Array:
    """Exact 2× nearest-neighbor upsample of an NHWC tensor.

    Bit-identical to ``jax.image.resize(..., method="nearest")`` at integer
    scale 2 (each output pixel reads input ``i // 2``), but expressed as
    broadcast+reshape so XLA lowers it to a tiled copy instead of the gather
    the general resize op can produce — this sits on the U-Net's per-step
    up path (3 levels × 50 steps) and the VAE decoder."""
    b, h, w, c = x.shape
    x = jnp.broadcast_to(x[:, :, None, :, None, :], (b, h, 2, w, 2, c))
    return x.reshape(b, h * 2, w * 2, c)


def timestep_embedding(t: jax.Array, dim: int, max_period: float = 10000.0,
                       dtype=jnp.float32) -> jax.Array:
    """Sinusoidal timestep embedding, diffusers `Timesteps` semantics
    (flip_sin_to_cos=True, downscale_freq_shift=0): [cos | sin] halves."""
    half = dim // 2
    freqs = jnp.exp(-math.log(max_period) * jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[..., None] * freqs
    emb = jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)
    if dim % 2:
        emb = jnp.pad(emb, [(0, 0)] * (emb.ndim - 1) + [(0, 1)])
    return emb.astype(dtype)


# ---------------------------------------------------------------------------
# Attention core
# ---------------------------------------------------------------------------


def attention_probs(q: jax.Array, k: jax.Array, scale: float,
                    mask: Optional[jax.Array] = None) -> jax.Array:
    """Materialized softmax(QKᵀ·scale) in f32 — the tensor prompt-to-prompt
    edits (`/root/reference/ptp_utils.py:195-205`). q,k: (B, heads, S, D)."""
    sim = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                     preferred_element_type=jnp.float32) * scale
    if mask is not None:
        sim = sim + mask
    return jax.nn.softmax(sim.astype(jnp.float32), axis=-1)


def fused_attention(q: jax.Array, k: jax.Array, v: jax.Array, scale: float,
                    mask: Optional[jax.Array] = None) -> jax.Array:
    """Attention for call sites the controller provably never reads
    (`/root/reference/main.py:131,170` never touches 64²-pixel maps).

    q,k,v: (B, heads, S, D); mask: additive, broadcastable to
    (B, heads, Sq, Sk). Large self-attention (S ≥ 2048, e.g. the 64²-pixel
    sites) runs the Pallas TPU flash kernel when ``flash_block`` finds a
    VMEM-feasible block for the head geometry — blockwise, never
    materializing the (S, S) probability tensor; measured ~3× over XLA's
    attention at the SD-1.4 64² shape on v5e. Small maps use a plain einsum
    chain (kernel launch would cost more than it saves)."""
    s_q, s_k = q.shape[-2], k.shape[-2]
    if mask is None and s_q == s_k and s_q >= 2048:
        blk = flash_block(s_q, q.shape[-1], q.dtype.itemsize)
        if blk and _on_tpu():
            return flash_attention_tpu(q, k, v, scale, blk)
        # Non-TPU accelerators, or no VMEM-feasible block for this head
        # geometry: let XLA pick its attention lowering rather than
        # materializing the (S, S) probabilities explicitly.
        out = jax.nn.dot_product_attention(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), scale=scale)
        return out.transpose(0, 2, 1, 3)
    probs = attention_probs(q, k, scale, mask).astype(v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


# Stay under the TPU's 16 MiB scoped-VMEM budget with headroom: the flash
# kernel's resident footprint per grid step is ~(q + k + v + double-buffered
# k/v) blocks in the input dtype plus f32 accumulator/statistics scratch,
# ≈ block·head_dim·(8·itemsize + 8) bytes (within ~5% of the 19 MiB the
# compiler reports for block 1024, D=512, f32 — the VAE mid-attention shape
# that OOMs scoped vmem if block size ignores head_dim).
_FLASH_VMEM_BUDGET = 14 * 2**20


def flash_block(seq_len: int, head_dim: int, itemsize: int) -> int:
    """Largest power-of-two block that tiles ``seq_len`` (the Pallas kernel
    requires seq_len % block == 0) AND keeps the kernel's scoped-VMEM
    footprint inside the TPU budget for this ``head_dim``/``itemsize``;
    0 → no viable block (einsum/XLA path instead). The geometry args are
    deliberately required: a default would make the VMEM guard opt-in, and
    a wide-head f32 call site (the VAE mid-attention shape) that omitted
    them would compile-time-OOM scoped VMEM on the chip."""
    for b in (1024, 512, 256):
        if seq_len % b == 0 and b * head_dim * (8 * itemsize + 8) <= _FLASH_VMEM_BUDGET:
            return b
    return 0


def edit_block(pixels: int, key_len: int, head_dim: int, itemsize: int) -> int:
    """Largest query block for the fused-edit kernel (``kernels.fused_edit``)
    that tiles ``pixels`` and stays inside the scoped-VMEM budget; 0 → no
    viable block (the site keeps the materialized reference path).

    The edit kernel's resident footprint per grid step differs from the
    flash kernel's (``flash_block``): the key axis is NOT blocked — a full
    lane-padded ``Kp`` lives in VMEM so edit rows see whole probability rows
    — and each instance holds its own + the base row's tiles. Per block:
    3 q/out tiles (own q, base q, out) + 3 key-axis tiles (k, base k, v) in
    the carrier dtype, 3 f32 probability tiles (own, base, edited), the
    ``(Kp, Kp)`` f32 edit transform, and f32 matmul accumulators. Same
    14 MiB budget (of the 16 MiB scoped VMEM) as the flash geometry —
    see the headroom note above ``_FLASH_VMEM_BUDGET``."""
    kp = max(128, -(-key_len // 128) * 128)

    def vmem(bq: int) -> int:
        return (3 * bq * head_dim * itemsize + 3 * kp * head_dim * itemsize
                + 3 * bq * kp * 4 + kp * kp * 4 + 2 * bq * head_dim * 4)

    for bq in (512, 256, 128):
        if pixels % bq == 0 and vmem(bq) <= _FLASH_VMEM_BUDGET:
            return bq
    # Small or non-power-of-two maps (edited self sites, tiny test configs):
    # one block over the whole query axis if it fits.
    if pixels < 128 or all(pixels % bq for bq in (512, 256, 128)):
        if vmem(pixels) <= _FLASH_VMEM_BUDGET:
            return pixels
    return 0


def _flash_block_sizes(blk: int):
    """The one BlockSizes geometry every flash call site uses — forward and
    residuals variants must stay on the same tiling.

    ALL backward blocks (dkv AND dq passes) must be specified or
    differentiating any program containing the kernel raises at trace time
    ("not all backward blocks are specified") — null-text inversion
    backprops through the U-Net's S=4096 flash sites, which is exactly how
    this surfaced on chip (2026-08-01). The backward passes hold more live
    tiles than the forward, so they get a capped block; correctness of the
    spec is pinned by an interpret-mode grad test
    (tests/test_flash_pallas.py)."""
    from jax.experimental.pallas.ops.tpu import flash_attention as _fa

    bwd = min(blk, 512)
    return _fa.BlockSizes(
        block_q=blk, block_k_major=blk, block_k=blk, block_b=1,
        block_q_major_dkv=bwd, block_k_major_dkv=bwd,
        block_q_dkv=bwd, block_k_dkv=bwd,
        block_k_major_dq=bwd, block_k_dq=bwd, block_q_dq=bwd)


def flash_attention_tpu(q: jax.Array, k: jax.Array, v: jax.Array,
                        scale: float, blk: int) -> jax.Array:
    """The Pallas TPU flash kernel call `fused_attention` takes at the big
    self-attention sites. Kept as a named function so the CPU suite can run
    the identical code under `pltpu.force_tpu_interpret_mode()`
    (tests/test_flash_pallas.py) — the kernel otherwise only executes on
    real TPU benchmark sessions."""
    from jax.experimental.pallas.ops.tpu import flash_attention as _fa

    return _fa.flash_attention(q, k, v, causal=False, sm_scale=scale,
                               block_sizes=_flash_block_sizes(blk))


def flash_attention_residuals(q: jax.Array, k: jax.Array, v: jax.Array,
                              scale: float, blk: int):
    """Flash kernel returning ``(out, l, m)`` — the normalized output plus
    per-row softmax statistics (sum ``l`` and max ``m`` of the local logits).
    These are the pieces ring attention needs to merge partial results across
    devices without ever materializing local (Sq, Sk) scores
    (`parallel/ring.py`). Semantics pinned by tests/test_flash_pallas.py in
    interpret mode."""
    from jax.experimental.pallas.ops.tpu import flash_attention as _fa

    return _fa._flash_attention(q, k, v, None, None, True, False, scale,
                                _flash_block_sizes(blk), False)


def _on_tpu() -> bool:
    """Static platform gate: the Pallas flash kernel only lowers on TPU
    (tests run on the CPU backend and take the einsum path)."""
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:  # pragma: no cover
        return False
