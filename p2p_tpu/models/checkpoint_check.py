"""Checkpoint-readiness report: verify a diffusers checkpoint dir against a
preset WITHOUT loading it into a model (CLI: `p2p-tpu check`, or
`python tools/check_checkpoint.py`).

First contact with real weights should be a config report, not a crash
(VERDICT r2 item 5). For each sub-model the tool diffs the checkpoint's
tensor names/shapes against the mapping tables in
`p2p_tpu/models/checkpoint.py` (both directions: mapped-but-missing and
present-but-unmapped), using `jax.eval_shape` over the init functions so the
expected tree costs no memory, and safetensors *header* parsing so multi-GB
weight files cost no I/O. It also diffs `scheduler_config.json` against the
preset's `SchedulerConfig` and checks the tokenizer files.

    python tools/check_checkpoint.py /path/to/sd14-checkpoint --preset sd14

The reference's ground truth for these directories is
`StableDiffusionPipeline.from_pretrained` (`/root/reference/main.py:29`,
`/root/reference/null_text.py:28-31`) and
`DiffusionPipeline.from_pretrained("CompVis/ldm-text2im-large-256")`
(`/root/reference/prompt-to-prompt_ldm.ipynb` per SURVEY §2.9).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import struct
import sys
from typing import Dict, List, Optional, Tuple

import numpy as np


# ---------------------------------------------------------------------------
# Shape-level reading + transforms (no tensor data movement)
# ---------------------------------------------------------------------------


def read_shapes(path: str) -> Dict[str, Tuple[int, ...]]:
    """{tensor_name: shape} for a weights file.

    ``.safetensors``: parsed straight from the 8-byte-length-prefixed JSON
    header — no tensor bytes are read. torch ``.bin``/``.pt``: falls back to a
    full ``torch.load`` (the pickle stream interleaves metadata and storage).
    """
    if path.endswith(".safetensors"):
        with open(path, "rb") as f:
            (hlen,) = struct.unpack("<Q", f.read(8))
            header = json.loads(f.read(hlen))
        return {k: tuple(v["shape"]) for k, v in header.items()
                if k != "__metadata__"}
    import torch

    sd = torch.load(path, map_location="meta", weights_only=True)
    return {k: tuple(v.shape) for k, v in sd.items()}


def _shape_fwd(kind: str, shape: Tuple[int, ...]) -> Tuple[int, ...]:
    """Checkpoint-side shape → our-side shape, per the layout transform."""
    if kind == "linear":
        return tuple(reversed(shape))
    if kind == "conv":
        o, i, kh, kw = shape
        return (kh, kw, i, o)
    return tuple(shape)


# ---------------------------------------------------------------------------
# Report structure
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SubReport:
    name: str
    weights_file: Optional[str] = None
    n_mapped: int = 0
    missing: List[str] = dataclasses.field(default_factory=list)
    unmapped: List[str] = dataclasses.field(default_factory=list)
    shape_mismatches: List[str] = dataclasses.field(default_factory=list)
    error: Optional[str] = None

    @property
    def ok(self) -> bool:
        return (self.error is None and not self.missing
                and not self.shape_mismatches and not self.unmapped)


@dataclasses.dataclass
class Report:
    preset: str
    submodels: List[SubReport] = dataclasses.field(default_factory=list)
    scheduler_diffs: List[str] = dataclasses.field(default_factory=list)
    scheduler_error: Optional[str] = None
    tokenizer_error: Optional[str] = None

    @property
    def ok(self) -> bool:
        # Scheduler diffs are genuine blockers (wrong betas → wrong images);
        # a missing scheduler_config.json is only a warning (our preset's
        # defaults apply), matching load_pipeline's behavior.
        return (all(s.ok for s in self.submodels)
                and not self.scheduler_diffs
                and self.tokenizer_error is None)


# ---------------------------------------------------------------------------
# Per-sub-model check
# ---------------------------------------------------------------------------

# Diffusers checkpoint-dir layouts: SD repos use unet/text_encoder/vae;
# the CompVis LDM repo names them unet/bert/vqvae.
_SUBDIRS = {
    "unet": ("unet",),
    "text_encoder": ("text_encoder", "bert"),
    "vae": ("vae", "vqvae"),
}
_WEIGHT_NAMES = {
    "unet": ("diffusion_pytorch_model.safetensors", "diffusion_pytorch_model.bin"),
    "text_encoder": ("model.safetensors", "pytorch_model.bin"),
    "vae": ("diffusion_pytorch_model.safetensors", "diffusion_pytorch_model.bin"),
}


def _expected_shapes(entries, init_fn) -> Dict[str, Tuple[str, Tuple[int, ...]]]:
    """{their_name: (kind, our_shape)} via eval_shape — zero allocation."""
    import jax

    from .checkpoint import _get

    tree = jax.eval_shape(lambda: init_fn(jax.random.PRNGKey(0)))
    out = {}
    for our_path, their_name, kind in entries:
        leaf = _get(tree, our_path)
        out[their_name] = (kind, tuple(leaf.shape))
    return out


def _check_submodel(name: str, dirpath: str, entries, init_fn) -> SubReport:
    from .checkpoint import _find_weights_file

    rep = SubReport(name=name)
    sub = next((os.path.join(dirpath, d) for d in _SUBDIRS[name]
                if os.path.isdir(os.path.join(dirpath, d))), None)
    if sub is None:
        rep.error = f"no {'/'.join(_SUBDIRS[name])} directory in {dirpath}"
        return rep
    try:
        rep.weights_file = _find_weights_file(sub, _WEIGHT_NAMES[name])
    except FileNotFoundError as e:
        rep.error = str(e)
        return rep

    got = read_shapes(rep.weights_file)
    want = _expected_shapes(entries, init_fn)
    rep.n_mapped = len(want)

    for their_name, (kind, our_shape) in want.items():
        if their_name not in got:
            rep.missing.append(their_name)
        elif _shape_fwd(kind, got[their_name]) != our_shape:
            rep.shape_mismatches.append(
                f"{their_name}: checkpoint {got[their_name]} "
                f"-> {_shape_fwd(kind, got[their_name])} vs ours {our_shape}")
    # Same ignore set as apply_state_dict's strict mode.
    rep.unmapped = [k for k in got if k not in want
                    and not k.endswith("position_ids")
                    and not k.startswith("to_logits")]
    return rep


# ---------------------------------------------------------------------------
# Scheduler + tokenizer checks
# ---------------------------------------------------------------------------

# diffusers scheduler_config.json field → our SchedulerConfig attribute.
_SCHED_FIELDS = (
    ("num_train_timesteps", "num_train_timesteps"),
    ("beta_start", "beta_start"),
    ("beta_end", "beta_end"),
    ("beta_schedule", "beta_schedule"),
    ("prediction_type", "prediction_type"),
    ("clip_sample", "clip_sample"),
    ("set_alpha_to_one", "set_alpha_to_one"),
)


def _check_scheduler(dirpath: str, sched) -> Tuple[List[str], Optional[str]]:
    path = os.path.join(dirpath, "scheduler", "scheduler_config.json")
    if not os.path.exists(path):
        return [], f"no {path} — preset scheduler defaults will apply"
    with open(path) as f:
        theirs = json.load(f)
    diffs = []
    for their_key, our_key in _SCHED_FIELDS:
        if their_key not in theirs:
            continue  # older configs omit e.g. prediction_type → default ok
        tv, ov = theirs[their_key], getattr(sched, our_key)
        same = (np.isclose(tv, ov) if isinstance(ov, float) else tv == ov)
        if not same:
            diffs.append(f"{their_key}: checkpoint {tv!r} vs preset {ov!r}")
    # steps_offset lives on the pipeline's one scheduler; ours is per-kind.
    if "steps_offset" in theirs:
        off = theirs["steps_offset"]
        if off not in (sched.plms_steps_offset, sched.ddim_steps_offset):
            diffs.append(f"steps_offset: checkpoint {off!r} vs preset "
                         f"plms={sched.plms_steps_offset} "
                         f"ddim={sched.ddim_steps_offset}")
    return diffs, None


def _check_tokenizer(dirpath: str, arch: str) -> Optional[str]:
    tok = os.path.join(dirpath, "tokenizer")
    if not os.path.isdir(tok):
        return f"no tokenizer/ directory in {dirpath}"
    need = (("vocab.txt",) if arch == "ldmbert"
            else ("vocab.json", "merges.txt"))
    missing = [n for n in need if not os.path.exists(os.path.join(tok, n))]
    if missing:
        return f"tokenizer/ missing {missing} (need {need} for {arch})"
    return None


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------

def _real_presets():
    # tiny* presets exist for tests (synthetic checkpoints pass config=);
    # the readiness report targets real released directories.
    from .config import PRESET_CONFIGS

    return tuple(k for k in PRESET_CONFIGS if not k.startswith("tiny"))


PRESETS = _real_presets()


def check_checkpoint(dirpath: str, preset: str, config=None) -> Report:
    """``config`` overrides the preset's PipelineConfig (tests use tiny
    configs against synthetic checkpoint dirs)."""
    from . import vae as vae_mod
    from .checkpoint import (ldm_text_encoder_entries, text_encoder_entries,
                             unet_entries, vae_entries)
    from .config import PRESET_CONFIGS
    from .text_encoder import init_text_encoder
    from .unet import init_unet

    cfg = config if config is not None else PRESET_CONFIGS[preset]
    text_entries = (ldm_text_encoder_entries(cfg.text)
                    if cfg.text.arch == "ldmbert"
                    else text_encoder_entries(cfg.text))

    rep = Report(preset=preset)
    rep.submodels = [
        _check_submodel("unet", dirpath, unet_entries(cfg.unet),
                        lambda k: init_unet(k, cfg.unet)),
        _check_submodel("text_encoder", dirpath, text_entries,
                        lambda k: init_text_encoder(k, cfg.text)),
        _check_submodel("vae", dirpath, vae_entries(cfg.vae),
                        lambda k: vae_mod.init_vae(k, cfg.vae)),
    ]
    rep.scheduler_diffs, rep.scheduler_error = _check_scheduler(
        dirpath, cfg.scheduler)
    rep.tokenizer_error = _check_tokenizer(dirpath, cfg.text.arch)
    return rep


def _print_report(rep: Report) -> None:
    def _head(items, n=5):
        return "".join(f"\n      {x}" for x in items[:n]) + (
            f"\n      ... +{len(items) - n} more" if len(items) > n else "")

    print(f"checkpoint-readiness report (preset {rep.preset})")
    for s in rep.submodels:
        mark = "OK " if s.ok else "FAIL"
        print(f"  [{mark}] {s.name}: "
              + (s.error or f"{s.n_mapped} mapped tensors "
                 f"({os.path.basename(s.weights_file)})"))
        if s.missing:
            print(f"    missing from checkpoint ({len(s.missing)}):"
                  + _head(s.missing))
        if s.shape_mismatches:
            print(f"    shape mismatches ({len(s.shape_mismatches)}):"
                  + _head(s.shape_mismatches))
        if s.unmapped:
            print(f"    unmapped checkpoint tensors ({len(s.unmapped)}):"
                  + _head(s.unmapped))
    if rep.scheduler_error:
        print(f"  [warn] scheduler: {rep.scheduler_error}")
    elif rep.scheduler_diffs:
        print(f"  [FAIL] scheduler config differs:" + _head(rep.scheduler_diffs))
    else:
        print("  [OK ] scheduler config matches preset")
    if rep.tokenizer_error:
        print(f"  [FAIL] tokenizer: {rep.tokenizer_error}")
    else:
        print("  [OK ] tokenizer files present")
    print("READY" if rep.ok else "NOT READY")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("checkpoint_dir")
    ap.add_argument("--preset", choices=PRESETS, required=True)
    args = ap.parse_args(argv)
    rep = check_checkpoint(args.checkpoint_dir, args.preset)
    _print_report(rep)
    return 0 if rep.ok else 1


if __name__ == "__main__":
    sys.exit(main())
