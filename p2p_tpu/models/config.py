"""Model configurations and static attention-layout derivation.

The reference discovers its attention structure by walking the live U-Net and
counting hooked modules at registration time (`/root/reference/ptp_utils.py:223-242`).
Here the structure is a pure function of the config: :func:`unet_attn_specs`
enumerates every attention call site (place, kind, resolution, heads, key
length) in exact call order, and feeds `controllers.base.build_layout` — so
layer bookkeeping is settled before tracing and costs nothing at runtime.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from ..controllers.base import AttnLayout, StoreConfig, build_layout


@dataclasses.dataclass(frozen=True)
class UNetConfig:
    """Shape config for the conditional U-Net (diffusers
    `UNet2DConditionModel` topology, e.g. SD-v1.4's 32 attention sites)."""

    sample_size: int = 64                  # latent side length
    in_channels: int = 4
    out_channels: int = 4
    block_channels: Tuple[int, ...] = (320, 640, 1280, 1280)
    # True → the down/up block at this level carries transformer blocks.
    attn_levels: Tuple[bool, ...] = (True, True, True, False)
    layers_per_block: int = 2
    num_heads: int = 8
    # When set, heads vary per level as channels // head_dim (LDM's fixed
    # per-head width); when None, num_heads applies uniformly (SD).
    head_dim: Optional[int] = None
    context_dim: int = 768                 # text-encoder hidden size
    context_len: int = 77
    transformer_depth: int = 1             # transformer blocks per attn site group
    groups: int = 32
    ff_mult: int = 4
    freq_dim: Optional[int] = None         # sinusoidal dim; default block_channels[0]

    @property
    def time_embed_dim(self) -> int:
        return self.block_channels[0] * 4

    @property
    def levels(self) -> int:
        return len(self.block_channels)

    def resolution_at(self, level: int) -> int:
        return self.sample_size >> level

    def heads_for(self, channels: int) -> int:
        if self.head_dim is not None:
            assert channels % self.head_dim == 0, (channels, self.head_dim)
            return channels // self.head_dim
        return self.num_heads


SD14_UNET = UNetConfig()

# Tiny config for tests: same topology class (2 of 3 levels attentive, mid
# attention, skip concats, CFG) at ~1/4000 the parameters. Latent 16² keeps a
# 16²→8²→4² pyramid so store/blend resolutions exist.
TINY_UNET = UNetConfig(
    sample_size=16,
    in_channels=4,
    out_channels=4,
    block_channels=(32, 64, 64),
    attn_levels=(True, True, False),
    layers_per_block=1,
    num_heads=2,
    context_dim=32,
    context_len=16,
    groups=8,
    ff_mult=2,
)


def unet_attn_specs(cfg: UNetConfig):
    """Every attention call site in forward-call order, as
    ``(place, is_cross, resolution, heads, key_len, channels)`` tuples.

    Order contract (must match ``unet.apply_unet``'s call order): down blocks
    (per transformer block: self then cross), mid, up blocks. For SD14_UNET
    this yields exactly the reference's 32 hooked sites with the store slice
    ``down_cross[2:4] + up_cross[:3]`` landing on the 16×16 cross maps
    (`/root/reference/main.py:37-38`). ``channels`` (the site's feature-map
    width = its attention output width) sizes the phase-2 cross-attention
    cache buffers before tracing."""
    specs = []

    def site(place, level):
        res = cfg.resolution_at(level)
        ch = cfg.block_channels[level]
        heads = cfg.heads_for(ch)
        for _ in range(cfg.transformer_depth):
            specs.append((place, False, res, heads, res * res, ch))       # self
            specs.append((place, True, res, heads, cfg.context_len, ch))  # cross

    for level in range(cfg.levels):                      # down
        if cfg.attn_levels[level]:
            for _ in range(cfg.layers_per_block):
                site("down", level)
    site("mid", cfg.levels - 1)                          # mid
    for level in reversed(range(cfg.levels)):            # up
        if cfg.attn_levels[level]:
            for _ in range(cfg.layers_per_block + 1):
                site("up", level)
    return specs


def unet_layout(cfg: UNetConfig, store_cfg: Optional[StoreConfig] = None
                ) -> AttnLayout:
    if store_cfg is None:
        # The reference stores every ≤32²-pixel map (`/root/reference/main.py:131`);
        # scale that bound with the latent size so tiny test models store their
        # two lower pyramid levels the same way SD stores 32²/16²/8².
        store_cfg = StoreConfig(max_pixels=(cfg.sample_size // 2) ** 2)
    return build_layout(unet_attn_specs(cfg), store_cfg)


@dataclasses.dataclass(frozen=True)
class TextEncoderConfig:
    """CLIP-style causal text transformer (SD-1.4: ViT-L/14 text tower)."""

    vocab_size: int = 49408
    hidden_dim: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_length: int = 77
    ff_mult: int = 4
    activation: str = "quick_gelu"         # CLIP-L uses quick_gelu
    causal: bool = True
    # Attention projection width (heads·head_dim). CLIP is square (None →
    # hidden_dim); LDMBert projects 1280 → 8·64 = 512 and back.
    attn_inner_dim: Optional[int] = None
    # LDMBert's q/k/v projections carry no bias (out_proj does).
    attn_qkv_bias: bool = True
    # Checkpoint-name architecture: 'clip' (CLIPTextModel) | 'ldmbert'.
    arch: str = "clip"

    @property
    def inner_dim(self) -> int:
        return self.attn_inner_dim or self.hidden_dim

SD14_TEXT = TextEncoderConfig()
TINY_TEXT = TextEncoderConfig(vocab_size=49408, hidden_dim=32, num_layers=2,
                              num_heads=2, max_length=16)


@dataclasses.dataclass(frozen=True)
class VAEConfig:
    """Latent autoencoder: KL (`AutoencoderKL`, SD) or VQ (`VQModel`, LDM).

    ``kind='vq'`` adds a codebook: decode first snaps each latent vector to
    its nearest codebook entry (the reference's `model.vqvae` decode path,
    `/root/reference/ptp_utils.py:124`)."""

    in_channels: int = 3
    latent_channels: int = 4
    base_channels: int = 128
    channel_mults: Tuple[int, ...] = (1, 2, 4, 4)
    layers_per_block: int = 2
    groups: int = 32
    scaling_factor: float = 0.18215        # `/root/reference/ptp_utils.py:80`
    kind: str = "kl"                       # 'kl' | 'vq'
    num_codebook: int = 16384              # VQ only: codebook entries

SD14_VAE = VAEConfig()
TINY_VAE = VAEConfig(base_channels=16, channel_mults=(1, 2, 2), layers_per_block=1,
                     groups=8)  # 2 downsamples: 64² image ⇄ 16² latent


@dataclasses.dataclass(frozen=True)
class SchedulerConfig:
    """Scheduler constants, scoped per backend — the knobs the reference
    scatters between pipeline defaults and explicit construction
    (`/root/reference/main.py:29` keeps SD's pipeline PNDM;
    `/root/reference/null_text.py:16-20` builds DDIM with clip_sample=False,
    set_alpha_to_one=False)."""

    kind: str = "ddim"              # default sampler: 'ddim' | 'plms' | 'dpm'
    num_train_timesteps: int = 1000
    beta_start: float = 0.00085
    beta_end: float = 0.012
    beta_schedule: str = "scaled_linear"
    set_alpha_to_one: bool = False
    clip_sample: bool = False
    # The SD pipeline's PNDM config uses steps_offset=1 (every sampled
    # timestep shifted up by one); the null-text DDIM construction leaves it 0.
    plms_steps_offset: int = 1
    ddim_steps_offset: int = 0
    # 'epsilon' (SD-1.x / SD-2.1-base) or 'v_prediction' (SD-2.1 768-v).
    prediction_type: str = "epsilon"

    def steps_offset(self, kind: str) -> int:
        return self.plms_steps_offset if kind == "plms" else self.ddim_steps_offset


@dataclasses.dataclass(frozen=True)
class PipelineConfig:
    """A full backend: text encoder + U-Net + VAE + scheduler defaults."""

    name: str
    unet: UNetConfig
    text: TextEncoderConfig
    vae: VAEConfig
    image_size: int = 512
    guidance_scale: float = 7.5            # `/root/reference/main.py:20`
    num_steps: int = 50
    scheduler: SchedulerConfig = SchedulerConfig()

    @property
    def latent_size(self) -> int:
        return self.unet.sample_size


SD14 = PipelineConfig("sd-v1.4", SD14_UNET, SD14_TEXT, SD14_VAE, image_size=512)
TINY = PipelineConfig("tiny", TINY_UNET, TINY_TEXT, TINY_VAE, image_size=64,
                      num_steps=4)

# LDM text2im-large-256 (`/root/reference/ptp_utils.py:98-126`): BERT-style
# (non-causal, gelu) 1280-d text encoder tokenized by BERT wordpiece
# (vocab 30522), 32² latent pyramid (256² image, f8 VQ autoencoder), heads at
# fixed head_dim 64 (5/10/20 per level), VQ codebook decode. Structure follows
# the CompVis txt2img-f8-large UNet: model_channels 320, mults (1,2,4,4),
# 2 res blocks/level, attention at the 32²/16²/8² levels.
LDM_UNET = UNetConfig(
    sample_size=32,
    in_channels=4,
    out_channels=4,
    block_channels=(320, 640, 1280, 1280),
    attn_levels=(True, True, True, False),
    layers_per_block=2,
    head_dim=64,
    context_dim=1280,
    context_len=77,
)
LDM_TEXT = TextEncoderConfig(vocab_size=30522, hidden_dim=1280, num_layers=32,
                             num_heads=8, max_length=77, activation="gelu",
                             causal=False, attn_inner_dim=8 * 64,
                             attn_qkv_bias=False, arch="ldmbert")
# scaling_factor stays 0.18215: the reference decodes BOTH backends through
# the same `latent2image` with the 1/0.18215 scale
# (`/root/reference/ptp_utils.py:79-85`, VQ call at `:124`).
# channel_mults (1,2,2,4) = 3 downsamples = f8 (the LDM VQ-f8 autoencoder):
# 256² image ⇄ 32² latent, matching LDM_UNET.sample_size.
LDM_VAE = VAEConfig(base_channels=128, channel_mults=(1, 2, 2, 4),
                    latent_channels=4, kind="vq", num_codebook=16384)
LDM256 = PipelineConfig("ldm-text2im-256", LDM_UNET, LDM_TEXT, LDM_VAE,
                        image_size=256, guidance_scale=5.0, num_steps=50,
                        scheduler=SchedulerConfig(
                            beta_start=0.0015, beta_end=0.0195,
                            plms_steps_offset=0))

# SD-2.1 family — the model the reference marks "Not work"
# (`/root/reference/main.py:27`); here a config, not a code change: OpenCLIP
# ViT-H text tower realized as 23 transformer layers (diffusers' checkpoint
# conversion truncates layer 24 so the final-LN output IS the penultimate
# hidden state SD-2 conditions on), gelu activation, 1024-wide context;
# U-Net at fixed head_dim 64. The 768-v variant predicts v, not ε.
SD21_TEXT = TextEncoderConfig(hidden_dim=1024, num_layers=23, num_heads=16,
                              activation="gelu")
SD21_UNET = UNetConfig(context_dim=1024, head_dim=64)
SD21_BASE = PipelineConfig("sd-v2.1-base", SD21_UNET, SD21_TEXT, SD14_VAE,
                           image_size=512)
SD21 = PipelineConfig(
    "sd-v2.1", dataclasses.replace(SD21_UNET, sample_size=96), SD21_TEXT,
    SD14_VAE, image_size=768,
    scheduler=SchedulerConfig(prediction_type="v_prediction"))

# High-resolution SD variant: same weights shapes, 128² latent (1024²
# image). The 128²-pixel self-attention sites (16384² score matrix, ~2GB
# per head in f32) are exactly the case ring/sequence-parallel attention
# exists for — pass an SpConfig to apply_unet to shard them over a mesh.
SD14_HR = PipelineConfig(
    "sd-v1.4-1024", dataclasses.replace(SD14_UNET, sample_size=128),
    SD14_TEXT, SD14_VAE, image_size=1024)

# Tiny LDM-shaped backend for tests: same architectural family as LDM256
# (per-level heads via head_dim, non-causal no-qkv-bias text encoder, VQ
# decoder, LDM β schedule) at toy sizes.
TINY_LDM_UNET = dataclasses.replace(
    TINY_UNET, num_heads=1, head_dim=16, block_channels=(32, 64, 64))
TINY_LDM_TEXT = dataclasses.replace(
    TINY_TEXT, causal=False, activation="gelu", attn_inner_dim=32,
    attn_qkv_bias=False, arch="ldmbert", vocab_size=30522)
TINY_LDM_VAE = dataclasses.replace(TINY_VAE, kind="vq", num_codebook=64)
TINY_LDM = PipelineConfig("tiny-ldm", TINY_LDM_UNET, TINY_LDM_TEXT,
                          TINY_LDM_VAE, image_size=64, num_steps=4,
                          guidance_scale=5.0,
                          scheduler=SchedulerConfig(
                              beta_start=0.0015, beta_end=0.0195,
                              plms_steps_offset=0))


# The one preset-name → PipelineConfig resolution map (CLI commands,
# `p2p-tpu check`, tools/parity_real_weights.py all resolve through it).
# The CLI's argparse `choices` tuples are deliberate literal copies — the
# parser must stay jax-free for instant --help — pinned against this dict
# by tests/test_cli.py::test_every_cli_preset_resolves_to_a_config; adding
# a preset means this dict plus those two tuples (the test fails loudly
# until all agree).
PRESET_CONFIGS = {
    "tiny": TINY,
    "sd14": SD14,
    "sd21": SD21,
    "sd21base": SD21_BASE,
    "ldm256": LDM256,
    "tiny_ldm": TINY_LDM,
}
