"""Model stack: config-driven text encoder, conditional U-Net, and VAE.

TPU-first re-design of the model surface the reference borrows from
diffusers 0.8.1 (`/root/reference/requirements.txt:1`): pure-functional
modules over explicit param pytrees, NHWC layouts, static attention layouts
derived from config (no runtime monkey-patching), fused attention everywhere
the prompt-to-prompt controller provably never looks.
"""

from .config import (
    LDM256,
    TINY_LDM,
    SD14_HR,
    SD21,
    SD21_BASE,
    SD14,
    TINY,
    PipelineConfig,
    TextEncoderConfig,
    UNetConfig,
    VAEConfig,
    unet_attn_specs,
    unet_layout,
)
from .text_encoder import apply_text_encoder, init_text_encoder
from .unet import apply_unet, init_unet
from . import vae

__all__ = [
    "LDM256", "SD14", "SD14_HR", "SD21", "SD21_BASE", "TINY", "TINY_LDM",
    "PipelineConfig", "TextEncoderConfig", "UNetConfig", "VAEConfig",
    "unet_attn_specs", "unet_layout",
    "apply_text_encoder", "init_text_encoder",
    "apply_unet", "init_unet",
    "vae",
]
