"""KL autoencoder (image ⇄ latent codecs), diffusers `AutoencoderKL` topology.

The reference touches the VAE at three points, which are the API here:
encode to the posterior **mean** scaled by 0.18215
(`/root/reference/null_text.py:519-531` — it uses ``latent_dist.mean``, not a
sample, for inversion), decode with the inverse scale
(`/root/reference/ptp_utils.py:79-85`), and the uint8 image conversion
``(x/2+.5).clamp(0,1)·255``. All NHWC.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from .config import VAEConfig
from . import nn

Params = Dict[str, Any]


def _resnet_init(key, in_ch, out_ch):
    k1, k2, k3 = jax.random.split(key, 3)
    p = {
        "norm1": nn.norm_init(in_ch),
        "conv1": nn.conv_init(k1, in_ch, out_ch),
        "norm2": nn.norm_init(out_ch),
        "conv2": nn.conv_init(k2, out_ch, out_ch),
    }
    if in_ch != out_ch:
        p["skip"] = nn.conv_init(k3, in_ch, out_ch, kernel=1)
    return p


def _apply_resnet(p, x, groups):
    h = nn.conv2d(p["conv1"], nn.silu(nn.group_norm(p["norm1"], x, groups)))
    h = nn.conv2d(p["conv2"], nn.silu(nn.group_norm(p["norm2"], h, groups)))
    if "skip" in p:
        x = nn.conv2d(p["skip"], x)
    return x + h


def _attn_init(key, ch):
    k1, k2, k3, k4 = jax.random.split(key, 4)
    return {
        "norm": nn.norm_init(ch),
        "q": nn.linear_init(k1, ch, ch),
        "k": nn.linear_init(k2, ch, ch),
        "v": nn.linear_init(k3, ch, ch),
        "out": nn.linear_init(k4, ch, ch),
    }


def _apply_attn(p, x, groups):
    """Single-head full self-attention over pixels (VAE mid block)."""
    b, h, w, c = x.shape
    residual = x
    y = nn.group_norm(p["norm"], x, groups).reshape(b, h * w, c)
    q = nn.linear(p["q"], y)[:, None]
    k = nn.linear(p["k"], y)[:, None]
    v = nn.linear(p["v"], y)[:, None]
    out = nn.fused_attention(q, k, v, c ** -0.5)[:, 0]
    out = nn.linear(p["out"], out).reshape(b, h, w, c)
    return residual + out


def init_vae(key: jax.Array, cfg: VAEConfig) -> Params:
    keys = iter(jax.random.split(key, 64))
    chs = [cfg.base_channels * m for m in cfg.channel_mults]
    top = chs[-1]
    lat = cfg.latent_channels

    enc: Params = {"conv_in": nn.conv_init(next(keys), cfg.in_channels, chs[0]),
                   "down": []}
    in_ch = chs[0]
    for level, out_ch in enumerate(chs):
        block = {"resnets": []}
        for _ in range(cfg.layers_per_block):
            block["resnets"].append(_resnet_init(next(keys), in_ch, out_ch))
            in_ch = out_ch
        if level != len(chs) - 1:
            block["downsample"] = nn.conv_init(next(keys), out_ch, out_ch)
        enc["down"].append(block)
    enc["mid"] = {
        "resnet1": _resnet_init(next(keys), top, top),
        "attn": _attn_init(next(keys), top),
        "resnet2": _resnet_init(next(keys), top, top),
    }
    enc["norm_out"] = nn.norm_init(top)
    if cfg.kind == "vq":
        # VQ encoder emits the embedding directly; KL emits mean ‖ logvar.
        enc["conv_out"] = nn.conv_init(next(keys), top, lat)
        enc["quant_conv"] = nn.conv_init(next(keys), lat, lat, kernel=1)
    else:
        enc["conv_out"] = nn.conv_init(next(keys), top, 2 * lat)
        enc["quant_conv"] = nn.conv_init(next(keys), 2 * lat, 2 * lat, kernel=1)

    dec: Params = {
        "post_quant_conv": nn.conv_init(next(keys), lat, lat, kernel=1),
        "conv_in": nn.conv_init(next(keys), lat, top),
        "mid": {
            "resnet1": _resnet_init(next(keys), top, top),
            "attn": _attn_init(next(keys), top),
            "resnet2": _resnet_init(next(keys), top, top),
        },
        "up": [],
    }
    in_ch = top
    for level in reversed(range(len(chs))):
        out_ch = chs[level]
        block = {"resnets": []}
        for _ in range(cfg.layers_per_block + 1):
            block["resnets"].append(_resnet_init(next(keys), in_ch, out_ch))
            in_ch = out_ch
        if level != 0:
            block["upsample"] = nn.conv_init(next(keys), out_ch, out_ch)
        dec["up"].append(block)
    dec["norm_out"] = nn.norm_init(chs[0])
    dec["conv_out"] = nn.conv_init(next(keys), chs[0], cfg.in_channels)

    params = {"encoder": enc, "decoder": dec}
    if cfg.kind == "vq":
        params["codebook"] = (jax.random.uniform(
            next(keys), (cfg.num_codebook, lat), jnp.float32,
            -1.0 / cfg.num_codebook, 1.0 / cfg.num_codebook))
    return params


def _encoder_trunk(params: Params, cfg: VAEConfig, image: jax.Array) -> jax.Array:
    """Shared encoder body through quant_conv: conv_in → down blocks (with
    diffusers' asymmetric (0,1)/(0,1) pad before each stride-2 conv) → mid →
    norm/conv_out → quant_conv. KL and VQ differ only in what the output
    means (mean‖logvar vs embedding)."""
    p = params["encoder"]
    g = cfg.groups
    h = nn.conv2d(p["conv_in"], image)
    for block in p["down"]:
        for resnet in block["resnets"]:
            h = _apply_resnet(resnet, h, g)
        if "downsample" in block:
            h = jnp.pad(h, ((0, 0), (0, 1), (0, 1), (0, 0)))
            h = nn.conv2d(block["downsample"], h, stride=2, padding="VALID")
    h = _apply_resnet(p["mid"]["resnet1"], h, g)
    h = _apply_attn(p["mid"]["attn"], h, g)
    h = _apply_resnet(p["mid"]["resnet2"], h, g)
    h = nn.conv2d(p["conv_out"], nn.silu(nn.group_norm(p["norm_out"], h, g)))
    return nn.conv2d(p["quant_conv"], h)


def encode_moments(params: Params, cfg: VAEConfig, image: jax.Array
                   ) -> Tuple[jax.Array, jax.Array]:
    """image (B,H,W,3) in [-1,1] → posterior (mean, logvar), each
    (B, H/8, W/8, latent_channels) for the SD VAE's 3 downsamples."""
    moments = _encoder_trunk(params, cfg, image)
    mean, logvar = jnp.split(moments, 2, axis=-1)
    return mean, jnp.clip(logvar, -30.0, 20.0)


def encode(params: Params, cfg: VAEConfig, image: jax.Array) -> jax.Array:
    """Deterministic latent: scaled posterior mean
    (`/root/reference/null_text.py:527` uses ``.mean * 0.18215``).
    For VQ the encoder output is the (pre-quantization) embedding."""
    if cfg.kind == "vq":
        return _encoder_trunk(params, cfg, image) * cfg.scaling_factor
    mean, _ = encode_moments(params, cfg, image)
    return mean * cfg.scaling_factor


def quantize(params: Params, cfg: VAEConfig, z: jax.Array) -> jax.Array:
    """Snap each latent vector to its nearest codebook entry (L2) — the VQ
    lookup diffusers' ``VQModel.decode`` performs before decoding. Distances
    expand to z·z − 2 z·e + e·e so the hot op is one (pixels, lat)×(lat, K)
    matmul; the argmin gather is trivially small."""
    cb = params["codebook"].astype(jnp.float32)           # (K, C)
    zf = z.astype(jnp.float32)
    flat = zf.reshape(-1, zf.shape[-1])                   # (P, C)
    d = (jnp.sum(flat * flat, axis=1, keepdims=True)
         - 2.0 * flat @ cb.T
         + jnp.sum(cb * cb, axis=1)[None])
    idx = jnp.argmin(d, axis=1)
    return cb[idx].reshape(z.shape).astype(z.dtype)


def decode(params: Params, cfg: VAEConfig, latents: jax.Array) -> jax.Array:
    """latents (B,h,w,4) → image (B,H,W,3) in [-1,1]
    (`/root/reference/ptp_utils.py:79-84`: input scaled by 1/0.18215 — the
    reference routes BOTH the SD KL-VAE and the LDM VQ decode through this
    same function, `/root/reference/ptp_utils.py:124`)."""
    p = params["decoder"]
    g = cfg.groups
    h = latents / cfg.scaling_factor
    if cfg.kind == "vq":
        h = quantize(params, cfg, h)
    h = nn.conv2d(p["post_quant_conv"], h)
    h = nn.conv2d(p["conv_in"], h)
    h = _apply_resnet(p["mid"]["resnet1"], h, g)
    h = _apply_attn(p["mid"]["attn"], h, g)
    h = _apply_resnet(p["mid"]["resnet2"], h, g)
    for block in p["up"]:
        for resnet in block["resnets"]:
            h = _apply_resnet(resnet, h, g)
        if "upsample" in block:
            h = nn.conv2d(block["upsample"], nn.upsample_nearest_2x(h))
    return nn.conv2d(p["conv_out"], nn.silu(nn.group_norm(p["norm_out"], h, g)))


def to_uint8(image: jax.Array) -> jax.Array:
    """[-1,1] float → uint8 HWC (`/root/reference/ptp_utils.py:82-84`)."""
    return (jnp.clip(image / 2 + 0.5, 0.0, 1.0) * 255).astype(jnp.uint8)
