"""orbax version compatibility shims for the checkpoint layer.

``PyTreeCheckpointer.metadata()`` drifted across orbax releases: newer
builds return a ``CheckpointMetadata`` wrapper (the tree hangs off
``.item_metadata.tree``), the 0.x line the container ships returns the
metadata tree itself (a plain dict/pytree). ``models/native.py`` targets
the modern surface; this shim keeps the native-snapshot restore path (and
its tier-1 tests) alive on both — same role as ``parallel/compat.py`` for
``shard_map``.
"""

from __future__ import annotations


def metadata_tree(checkpointer, path: str):
    """The restored tree's metadata pytree, on every orbax metadata()
    return shape: a ``CheckpointMetadata`` wrapper, a bare
    ``item_metadata`` holder, or the tree itself."""
    meta = checkpointer.metadata(path)
    item = getattr(meta, "item_metadata", meta)
    return getattr(item, "tree", item)
