"""Checkpoint I/O: load diffusers-format SD weights into our param trees.

The reference gets all weights via `StableDiffusionPipeline.from_pretrained`
(`/root/reference/main.py:29`, `/root/reference/null_text.py:28-31`). Here the
mapping diffusers-name → our-tree-path is explicit data (one table per
sub-model), applied in both directions:

- :func:`load_unet` / :func:`load_text_encoder` / :func:`load_vae` read a
  local checkpoint directory (torch ``.bin`` via ``torch.load`` on CPU, or
  ``.safetensors`` when the library is present) and return our pytrees.
- :func:`export_state_dict` produces a diffusers-named state dict from our
  tree — used by the round-trip tests, and the parity harness.

Weight-layout transforms: torch Linear stores (out, in) — ours is (in, out);
torch Conv stores (O, I, kH, kW) — ours is HWIO.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Tuple

import numpy as np

from .config import TextEncoderConfig, UNetConfig, VAEConfig

# A mapping entry: (our_path, their_name, kind) where kind selects the
# layout transform: 'linear' | 'conv' | 'none'.
Entry = Tuple[Tuple[Any, ...], str, str]


def _t_linear(w: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(w.T)


def _t_conv(w: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(np.transpose(w, (2, 3, 1, 0)))


_FWD = {"linear": _t_linear, "conv": _t_conv, "none": lambda w: w}
# All transforms are involutions up to transposition back.
_INV = {"linear": _t_linear, "conv": lambda w: np.transpose(w, (3, 2, 0, 1)),
        "none": lambda w: w}


def _lin(our_prefix, their_prefix, bias=True) -> List[Entry]:
    out = [(our_prefix + ("kernel",), their_prefix + ".weight", "linear")]
    if bias:
        out.append((our_prefix + ("bias",), their_prefix + ".bias", "none"))
    return out


def _conv(our_prefix, their_prefix) -> List[Entry]:
    return [(our_prefix + ("kernel",), their_prefix + ".weight", "conv"),
            (our_prefix + ("bias",), their_prefix + ".bias", "none")]


def _norm(our_prefix, their_prefix) -> List[Entry]:
    return [(our_prefix + ("scale",), their_prefix + ".weight", "none"),
            (our_prefix + ("bias",), their_prefix + ".bias", "none")]


def _resnet(our, their, has_skip: bool, time: bool = True) -> List[Entry]:
    e = (_norm(our + ("norm1",), their + ".norm1")
         + _conv(our + ("conv1",), their + ".conv1")
         + _norm(our + ("norm2",), their + ".norm2")
         + _conv(our + ("conv2",), their + ".conv2"))
    if time:
        e += _lin(our + ("time_proj",), their + ".time_emb_proj")
    if has_skip:
        e += _conv(our + ("skip",), their + ".conv_shortcut")
    return e


def _attn(our, their) -> List[Entry]:
    return (_lin(our + ("to_q",), their + ".to_q", bias=False)
            + _lin(our + ("to_k",), their + ".to_k", bias=False)
            + _lin(our + ("to_v",), their + ".to_v", bias=False)
            + _lin(our + ("to_out",), their + ".to_out.0"))


def _tblock(our, their) -> List[Entry]:
    return (_norm(our + ("ln1",), their + ".norm1")
            + _attn(our + ("attn1",), their + ".attn1")
            + _norm(our + ("ln2",), their + ".norm2")
            + _attn(our + ("attn2",), their + ".attn2")
            + _norm(our + ("ln3",), their + ".norm3")
            + _lin(our + ("ff_in",), their + ".ff.net.0.proj")
            + _lin(our + ("ff_out",), their + ".ff.net.2"))


def _spatial_transformer(our, their, depth: int) -> List[Entry]:
    e = (_norm(our + ("norm",), their + ".norm")
         + _conv(our + ("proj_in",), their + ".proj_in"))
    for d in range(depth):
        e += _tblock(our + ("blocks", d), their + f".transformer_blocks.{d}")
    e += _conv(our + ("proj_out",), their + ".proj_out")
    return e


def unet_entries(cfg: UNetConfig) -> List[Entry]:
    e: List[Entry] = []
    e += _lin(("time_fc1",), "time_embedding.linear_1")
    e += _lin(("time_fc2",), "time_embedding.linear_2")
    e += _conv(("conv_in",), "conv_in")

    n = cfg.levels
    ch = list(cfg.block_channels)
    in_ch = ch[0]
    skip_chs = [ch[0]]
    for lvl in range(n):
        out_ch = ch[lvl]
        for j in range(cfg.layers_per_block):
            e += _resnet(("down", lvl, "resnets", j),
                         f"down_blocks.{lvl}.resnets.{j}", has_skip=in_ch != out_ch)
            if cfg.attn_levels[lvl]:
                e += _spatial_transformer(("down", lvl, "attns", j),
                                          f"down_blocks.{lvl}.attentions.{j}",
                                          cfg.transformer_depth)
            in_ch = out_ch
            skip_chs.append(out_ch)
        if lvl != n - 1:
            e += _conv(("down", lvl, "downsample"),
                       f"down_blocks.{lvl}.downsamplers.0.conv")
            skip_chs.append(out_ch)

    e += _resnet(("mid", "resnet1"), "mid_block.resnets.0", has_skip=False)
    e += _spatial_transformer(("mid", "attn"), "mid_block.attentions.0",
                              cfg.transformer_depth)
    e += _resnet(("mid", "resnet2"), "mid_block.resnets.1", has_skip=False)

    in_ch = ch[-1]
    for pos, lvl in enumerate(reversed(range(n))):
        out_ch = ch[lvl]
        for j in range(cfg.layers_per_block + 1):
            skip_ch = skip_chs.pop()
            e += _resnet(("up", pos, "resnets", j),
                         f"up_blocks.{pos}.resnets.{j}",
                         has_skip=(in_ch + skip_ch) != out_ch)
            if cfg.attn_levels[lvl]:
                e += _spatial_transformer(("up", pos, "attns", j),
                                          f"up_blocks.{pos}.attentions.{j}",
                                          cfg.transformer_depth)
            in_ch = out_ch
        if lvl != 0:
            e += _conv(("up", pos, "upsample"),
                       f"up_blocks.{pos}.upsamplers.0.conv")

    e += _norm(("norm_out",), "conv_norm_out")
    e += _conv(("conv_out",), "conv_out")
    return e


def text_encoder_entries(cfg: TextEncoderConfig) -> List[Entry]:
    e: List[Entry] = [
        (("token_embed",), "text_model.embeddings.token_embedding.weight", "none"),
        (("pos_embed",), "text_model.embeddings.position_embedding.weight", "none"),
    ]
    for i in range(cfg.num_layers):
        base = f"text_model.encoder.layers.{i}"
        e += _norm(("layers", i, "ln1"), base + ".layer_norm1")
        e += _lin(("layers", i, "q"), base + ".self_attn.q_proj")
        e += _lin(("layers", i, "k"), base + ".self_attn.k_proj")
        e += _lin(("layers", i, "v"), base + ".self_attn.v_proj")
        e += _lin(("layers", i, "out"), base + ".self_attn.out_proj")
        e += _norm(("layers", i, "ln2"), base + ".layer_norm2")
        e += _lin(("layers", i, "fc1"), base + ".mlp.fc1")
        e += _lin(("layers", i, "fc2"), base + ".mlp.fc2")
    e += _norm(("final_ln",), "text_model.final_layer_norm")
    return e


def ldm_text_encoder_entries(cfg: TextEncoderConfig) -> List[Entry]:
    """diffusers ``LDMBertModel`` names (the `model.bert` the reference's LDM
    path encodes with, `/root/reference/ptp_utils.py:113`): pre-norm encoder
    layers under ``model.layers.N``, learned position embeddings, final
    ``model.layer_norm``. The unused ``to_logits`` head is ignored on load."""
    e: List[Entry] = [
        (("token_embed",), "model.embed_tokens.weight", "none"),
        (("pos_embed",), "model.embed_positions.weight", "none"),
    ]
    for i in range(cfg.num_layers):
        base = f"model.layers.{i}"
        e += _norm(("layers", i, "ln1"), base + ".self_attn_layer_norm")
        e += _lin(("layers", i, "q"), base + ".self_attn.q_proj",
                  bias=cfg.attn_qkv_bias)
        e += _lin(("layers", i, "k"), base + ".self_attn.k_proj",
                  bias=cfg.attn_qkv_bias)
        e += _lin(("layers", i, "v"), base + ".self_attn.v_proj",
                  bias=cfg.attn_qkv_bias)
        e += _lin(("layers", i, "out"), base + ".self_attn.out_proj")
        e += _norm(("layers", i, "ln2"), base + ".final_layer_norm")
        e += _lin(("layers", i, "fc1"), base + ".fc1")
        e += _lin(("layers", i, "fc2"), base + ".fc2")
    e += _norm(("final_ln",), "model.layer_norm")
    return e


def _vae_attn(our, their) -> List[Entry]:
    return (_norm(our + ("norm",), their + ".group_norm")
            + _lin(our + ("q",), their + ".query")
            + _lin(our + ("k",), their + ".key")
            + _lin(our + ("v",), their + ".value")
            + _lin(our + ("out",), their + ".proj_attn"))


def vae_entries(cfg: VAEConfig) -> List[Entry]:
    e: List[Entry] = []
    chs = [cfg.base_channels * m for m in cfg.channel_mults]
    n = len(chs)

    e += _conv(("encoder", "conv_in"), "encoder.conv_in")
    in_ch = chs[0]
    for lvl in range(n):
        out_ch = chs[lvl]
        for j in range(cfg.layers_per_block):
            e += _resnet(("encoder", "down", lvl, "resnets", j),
                         f"encoder.down_blocks.{lvl}.resnets.{j}",
                         has_skip=in_ch != out_ch, time=False)
            in_ch = out_ch
        if lvl != n - 1:
            e += _conv(("encoder", "down", lvl, "downsample"),
                       f"encoder.down_blocks.{lvl}.downsamplers.0.conv")
    e += _resnet(("encoder", "mid", "resnet1"), "encoder.mid_block.resnets.0",
                 has_skip=False, time=False)
    e += _vae_attn(("encoder", "mid", "attn"), "encoder.mid_block.attentions.0")
    e += _resnet(("encoder", "mid", "resnet2"), "encoder.mid_block.resnets.1",
                 has_skip=False, time=False)
    e += _norm(("encoder", "norm_out"), "encoder.conv_norm_out")
    e += _conv(("encoder", "conv_out"), "encoder.conv_out")
    e += _conv(("encoder", "quant_conv"), "quant_conv")
    if cfg.kind == "vq":
        # diffusers VQModel keeps the codebook at quantize.embedding.
        e.append((("codebook",), "quantize.embedding.weight", "none"))

    e += _conv(("decoder", "post_quant_conv"), "post_quant_conv")
    e += _conv(("decoder", "conv_in"), "decoder.conv_in")
    e += _resnet(("decoder", "mid", "resnet1"), "decoder.mid_block.resnets.0",
                 has_skip=False, time=False)
    e += _vae_attn(("decoder", "mid", "attn"), "decoder.mid_block.attentions.0")
    e += _resnet(("decoder", "mid", "resnet2"), "decoder.mid_block.resnets.1",
                 has_skip=False, time=False)
    in_ch = chs[-1]
    for pos, lvl in enumerate(reversed(range(n))):
        out_ch = chs[lvl]
        for j in range(cfg.layers_per_block + 1):
            e += _resnet(("decoder", "up", pos, "resnets", j),
                         f"decoder.up_blocks.{pos}.resnets.{j}",
                         has_skip=in_ch != out_ch, time=False)
            in_ch = out_ch
        if lvl != 0:
            e += _conv(("decoder", "up", pos, "upsample"),
                       f"decoder.up_blocks.{pos}.upsamplers.0.conv")
    e += _norm(("decoder", "norm_out"), "decoder.conv_norm_out")
    e += _conv(("decoder", "conv_out"), "decoder.conv_out")
    return e


# ---------------------------------------------------------------------------
# Tree navigation + load/export
# ---------------------------------------------------------------------------


def _get(tree: Any, path: Tuple[Any, ...]) -> Any:
    for p in path:
        tree = tree[p]
    return tree


def _set(tree: Any, path: Tuple[Any, ...], value: Any) -> None:
    for p in path[:-1]:
        tree = tree[p]
    tree[path[-1]] = value


def read_state_dict(path: str) -> Dict[str, np.ndarray]:
    """Read a torch ``.bin``/``.pt`` or ``.safetensors`` file to numpy."""
    if path.endswith(".safetensors"):
        from safetensors.numpy import load_file  # optional dependency

        return dict(load_file(path))
    import torch

    sd = torch.load(path, map_location="cpu", weights_only=True)
    return {k: v.numpy() for k, v in sd.items()}


def _find_weights_file(dirpath: str, names: Tuple[str, ...]) -> str:
    for n in names:
        p = os.path.join(dirpath, n)
        if os.path.exists(p):
            return p
    raise FileNotFoundError(f"no weights file in {dirpath} (tried {names})")


def apply_state_dict(params: Any, entries: List[Entry],
                     sd: Dict[str, np.ndarray], strict: bool = True) -> Any:
    """Fill our param tree (in place) from a diffusers-named state dict."""
    import jax.numpy as jnp

    missing, used = [], set()
    for our_path, their_name, kind in entries:
        if their_name not in sd:
            missing.append(their_name)
            continue
        w = _FWD[kind](sd[their_name])
        cur = _get(params, our_path)
        if tuple(cur.shape) != tuple(w.shape):
            raise ValueError(
                f"shape mismatch at {'/'.join(map(str, our_path))} ← {their_name}: "
                f"ours {tuple(cur.shape)} vs checkpoint {tuple(w.shape)}")
        _set(params, our_path, jnp.asarray(w, dtype=cur.dtype))
        used.add(their_name)
    if strict:
        if missing:
            raise KeyError(f"checkpoint missing {len(missing)} entries, "
                           f"first: {missing[:5]}")
        unused = [k for k in sd if k not in used
                  and not k.endswith("position_ids")
                  and not k.startswith("to_logits")]
        if unused:
            raise KeyError(f"checkpoint has {len(unused)} unmapped entries, "
                           f"first: {unused[:5]}")
    return params


def export_state_dict(params: Any, entries: List[Entry]) -> Dict[str, np.ndarray]:
    """Inverse of :func:`apply_state_dict` (for tests / parity tooling)."""
    out = {}
    for our_path, their_name, kind in entries:
        w = np.asarray(_get(params, our_path))
        out[their_name] = _INV[kind](w)
    return out


def load_unet(params: Any, cfg: UNetConfig, dirpath: str, strict: bool = True) -> Any:
    sd = read_state_dict(_find_weights_file(
        dirpath, ("diffusion_pytorch_model.safetensors", "diffusion_pytorch_model.bin")))
    return apply_state_dict(params, unet_entries(cfg), sd, strict)


def load_text_encoder(params: Any, cfg: TextEncoderConfig, dirpath: str,
                      strict: bool = True) -> Any:
    sd = read_state_dict(_find_weights_file(
        dirpath, ("model.safetensors", "pytorch_model.bin")))
    entries = (ldm_text_encoder_entries(cfg) if cfg.arch == "ldmbert"
               else text_encoder_entries(cfg))
    return apply_state_dict(params, entries, sd, strict)


def load_vae(params: Any, cfg: VAEConfig, dirpath: str, strict: bool = True) -> Any:
    sd = read_state_dict(_find_weights_file(
        dirpath, ("diffusion_pytorch_model.safetensors", "diffusion_pytorch_model.bin")))
    return apply_state_dict(params, vae_entries(cfg), sd, strict)


def _find_subdir(checkpoint_dir: str, names: Tuple[str, ...]) -> str:
    for n in names:
        p = os.path.join(checkpoint_dir, n)
        if os.path.isdir(p):
            return p
    raise FileNotFoundError(
        f"no {'/'.join(names)} directory in {checkpoint_dir}")


def load_pipeline(checkpoint_dir: str, config, tokenizer=None):
    """Load a full checkpoint directory into a Pipeline.

    Accepts both diffusers layouts: SD repos (``unet/``, ``text_encoder/``,
    ``vae/``, ``tokenizer/``) and the CompVis LDM repo's naming (``bert/``,
    ``vqvae/``) — the two directory trees the reference's
    ``from_pretrained`` calls resolve (`/root/reference/main.py:29`,
    LDM per SURVEY §3.3)."""
    import jax

    from ..engine.sampler import Pipeline
    from ..utils.tokenizer import ClipBpeTokenizer
    from .text_encoder import init_text_encoder
    from .unet import init_unet
    from . import vae as vae_mod

    unet_params = load_unet(init_unet(jax.random.PRNGKey(0), config.unet),
                            config.unet, _find_subdir(checkpoint_dir, ("unet",)))
    text_params = load_text_encoder(
        init_text_encoder(jax.random.PRNGKey(0), config.text), config.text,
        _find_subdir(checkpoint_dir, ("text_encoder", "bert")))
    vae_params = load_vae(vae_mod.init_vae(jax.random.PRNGKey(0), config.vae),
                          config.vae, _find_subdir(checkpoint_dir, ("vae", "vqvae")))
    if tokenizer is None:
        tok_dir = os.path.join(checkpoint_dir, "tokenizer")
        max_len = config.text.max_length
        if config.text.arch == "ldmbert":
            from ..utils.tokenizer import BertWordPieceTokenizer

            tokenizer = BertWordPieceTokenizer.from_dir(
                tok_dir, model_max_length=max_len)
        else:
            tokenizer = ClipBpeTokenizer.from_dir(
                tok_dir, model_max_length=max_len)
    return Pipeline(config=config, unet_params=unet_params,
                    text_params=text_params, vae_params=vae_params,
                    tokenizer=tokenizer)
