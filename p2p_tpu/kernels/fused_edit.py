"""The fused-edit attention kernel: softmax + prompt-to-prompt edit, tiled.

One Pallas program instance owns one ``(block_q, D)`` query tile of one
``(batch row, head)`` and computes, entirely in VMEM:

    logits = q·kᵀ·scale + pad_mask          (block_q, Kp)   f32
    probs  = softmax(logits)                 rows are FULL — K is the cross
                                             context length (77 → 128 padded)
                                             or an edited self site's pixels
                                             (≤ 1024), so no online-softmax
                                             streaming is needed
    base   = softmax(q_base·k_baseᵀ·scale)   the source prompt's row, computed
                                             in-tile from its own q/k blocks
                                             (edit rows depend on the base row;
                                             recomputing its tile keeps the
                                             kernel free of cross-instance
                                             communication)
    edited = blend(edit(base, probs))        the controllers.kernel_spec
                                             operand algebra — Replace/Refine
                                             as a (Kp, Kp) in-tile matmul,
                                             Reweight as a key-token scale,
                                             self-injection as an α ∈ {0,1}
                                             blend
    out    = rowselect(edited | probs) @ v   (block_q, D)

The ``(2B·heads, P, K)`` probability tensor therefore never exists outside a
VMEM tile: the kernel's only HBM traffic is q/k/v in and the attention
output out — the same footprint as flash attention. Edit rows are the CFG
batch's conditional rows ``b+1 … 2b−1``; uncond rows and the base row take
the plain-softmax path through the identical program (the edit algebra is
computed and discarded — cheap at these K, and it keeps the grid uniform).

Numerics: all probability math in f32, the Replace/Refine projection at
``Precision.HIGHEST`` — matching the materialized reference path
(``models/nn.py:attention_probs`` + ``controllers.base``). Non-edited rows
are exactly a (blockwise) softmax-attention; edited rows carry the
documented 1e-2 golden drift budget vs the reference (tiling changes
reduction order). Interpret mode (`.interpret`) runs the identical program
on CPU — the rehearsal surface every parity test pins.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import jax.experimental.pallas as pl

from ..controllers.base import apply_attention_control
from ..controllers.kernel_spec import EditSpec, edit_operands, kernel_edit_spec
from ..models import nn

# Additive mask value for lane-padded key columns — the library flash
# kernel's DEFAULT_MASK_VALUE, so padded columns underflow to exactly the
# same zero probability there and here.
_MASK_VALUE = -0.7 * float(np.finfo(np.float32).max)


def pad_to_lanes(x: jax.Array, axis: int, target: int) -> jax.Array:
    """Zero-pad ``axis`` of ``x`` up to ``target`` (a lane multiple)."""
    pad = target - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _softmax_rows(logits: jax.Array) -> jax.Array:
    m = jnp.max(logits, axis=-1, keepdims=True)
    p = jnp.exp(logits - m)
    return p / jnp.sum(p, axis=-1, keepdims=True)


def _edit_kernel(*refs, spec: EditSpec, scale: float, b_half: int,
                 num_edits: int):
    """Kernel body. ``refs`` order (built by :func:`edit_attention`):
    q, q_base, k, k_base, v, kmask, [transform], [refine_mix],
    [equalizer], blend, out."""
    it = iter(refs)
    q_ref, qb_ref, k_ref, kb_ref, v_ref, kmask_ref = (next(it) for _ in range(6))
    t_ref = next(it) if spec.has_transform else None
    ra_ref = next(it) if spec.kind == "refine" else None
    eq_ref = next(it) if spec.has_equalizer else None
    alpha_ref = next(it)
    o_ref = next(it)

    mask = kmask_ref[0][None, :]                               # (1, Kp)

    def probs_of(qr, kr):
        qt = qr[0, 0].astype(jnp.float32)                      # (bq, D)
        kt = kr[0, 0].astype(jnp.float32)                      # (Kp, D)
        logits = jax.lax.dot_general(
            qt, kt, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale + mask
        return _softmax_rows(logits)                           # (bq, Kp)

    probs = probs_of(q_ref, k_ref)
    base = probs_of(qb_ref, kb_ref)

    # The controllers.kernel_spec row-local edit algebra.
    if spec.has_transform:
        new = jax.lax.dot_general(
            base, t_ref[0], (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32)
    else:
        new = base
    if ra_ref is not None:
        ra = ra_ref[0][None, :]
        new = new * ra + probs * (1.0 - ra)
    if eq_ref is not None:
        new = new * eq_ref[0][None, :]
    alpha = alpha_ref[0][None, :]
    edited = new * alpha + (1.0 - alpha) * probs

    is_edit_row = pl.program_id(0) >= b_half + 1
    probs_out = jnp.where(is_edit_row, edited, probs)

    vt = v_ref[0, 0]                                           # (Kp, D)
    out = jax.lax.dot_general(
        probs_out.astype(vt.dtype), vt, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    o_ref[0, 0] = out.astype(o_ref.dtype)


def edit_attention(q: jax.Array, k: jax.Array, v: jax.Array, scale: float,
                   spec: EditSpec, operands: dict, *,
                   block_q: int = 0, interpret: bool = False) -> jax.Array:
    """Fused attention with the in-kernel prompt-to-prompt edit.

    q, k, v: ``(2B, heads, P, K|D)`` — the CFG-doubled batch
    ``[uncond(B); base; edits(E)]``; ``operands`` from
    :func:`controllers.kernel_spec.edit_operands` (already indexed at the
    step). Returns ``(2B, heads, P, D)`` in ``v.dtype``. ``block_q=0``
    picks the largest VMEM-feasible query block (``models.nn.edit_block``);
    ``interpret=True`` runs the pallas interpreter (the CPU parity surface,
    jax-0.4.37 discharge fix installed by ``kernels.interpret``)."""
    two_b, heads, pixels, d_head = q.shape
    b_half = two_b // 2
    num_edits = b_half - 1
    if num_edits < 1:
        raise ValueError(
            f"fused edit kernel needs a base row + ≥1 edit row in the cond "
            f"half, got CFG batch {two_b} (b={b_half})")
    kp = spec.pad_len
    assert k.shape[2] == spec.key_len, (k.shape, spec)
    if not block_q:
        block_q = nn.edit_block(pixels, spec.key_len, d_head,
                                jnp.dtype(q.dtype).itemsize)
    if not block_q or pixels % block_q:
        raise ValueError(
            f"no VMEM-feasible query block for P={pixels}, K={spec.key_len}, "
            f"D={d_head} (got block_q={block_q})")
    if interpret:
        from .interpret import install_discharge_fix

        install_discharge_fix()

    k_p = pad_to_lanes(k, 2, kp)
    v_p = pad_to_lanes(v, 2, kp)
    kmask = jnp.where(jnp.arange(kp) < spec.key_len, 0.0,
                      _MASK_VALUE).astype(jnp.float32)[None, :]    # (1, Kp)

    def qmap(b, h, i):
        return (b, h, i, 0)

    def qmap_base(b, h, i):
        return (b_half, h, i, 0)

    def kmap(b, h, i):
        return (b, h, 0, 0)

    def kmap_base(b, h, i):
        return (b_half, h, 0, 0)

    def rowmap(b, h, i):
        # Edit-operand row for this batch row; non-edit rows clamp to row 0
        # (their edit result is computed and discarded).
        return (jnp.clip(b - b_half - 1, 0, num_edits - 1), 0)

    def rowmap3(b, h, i):
        return (jnp.clip(b - b_half - 1, 0, num_edits - 1), 0, 0)

    q_spec = pl.BlockSpec((1, 1, block_q, d_head), qmap)
    qb_spec = pl.BlockSpec((1, 1, block_q, d_head), qmap_base)
    k_spec = pl.BlockSpec((1, 1, kp, d_head), kmap)
    kb_spec = pl.BlockSpec((1, 1, kp, d_head), kmap_base)

    inputs = [q, q, k_p, k_p, v_p, kmask]
    in_specs = [q_spec, qb_spec, k_spec, kb_spec, k_spec,
                pl.BlockSpec((1, kp), lambda b, h, i: (0, 0))]
    if spec.has_transform:
        inputs.append(operands["transform"])
        in_specs.append(pl.BlockSpec((1, kp, kp), rowmap3))
    if spec.kind == "refine":
        inputs.append(operands["refine_mix"])
        in_specs.append(pl.BlockSpec((1, kp), rowmap))
    if spec.has_equalizer:
        inputs.append(operands["equalizer"])
        in_specs.append(pl.BlockSpec((1, kp), rowmap))
    inputs.append(operands["blend"])
    in_specs.append(pl.BlockSpec((1, kp), rowmap))

    kernel = functools.partial(_edit_kernel, spec=spec, scale=scale,
                               b_half=b_half, num_edits=num_edits)
    return pl.pallas_call(
        kernel,
        grid=(two_b, heads, pixels // block_q),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, block_q, d_head), qmap),
        out_shape=jax.ShapeDtypeStruct((two_b, heads, pixels, d_head),
                                       v.dtype),
        interpret=interpret,
    )(*inputs)


def edit_attention_reference(q: jax.Array, k: jax.Array, v: jax.Array,
                             scale: float, controller, meta,
                             step: jax.Array) -> jax.Array:
    """The materialized reference path for one site, exactly as
    ``models/unet.py`` runs it when the kernel is off: f32 probabilities
    through ``apply_attention_control``, then ``probs @ v``. The parity
    harness ground truth (store-free sites only — which is all the kernel
    dispatches to)."""
    probs = nn.attention_probs(q, k, scale)
    _, probs = apply_attention_control(controller, meta, (), probs, step)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)


def fused_site_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                         scale: float, controller, meta, step: jax.Array, *,
                         block_q: int = 0,
                         interpret: bool = False) -> Optional[jax.Array]:
    """Site-level entry: extract the spec from the controller treedef, build
    the step's operands, run the kernel. ``None`` when the site is not
    kernel-compilable (caller falls back to the materialized path) — also
    when the batch has no edit rows, which only trace-time shapes reveal."""
    spec = kernel_edit_spec(controller, meta)
    if spec is None or q.shape[0] // 2 < 2:
        return None
    if not block_q:
        block_q = nn.edit_block(q.shape[2], spec.key_len, q.shape[3],
                                jnp.dtype(q.dtype).itemsize)
    if not block_q or q.shape[2] % block_q:
        return None
    ops = edit_operands(controller.edit, spec, step)
    return edit_attention(q, k, v, scale, spec, ops, block_q=block_q,
                          interpret=interpret)
