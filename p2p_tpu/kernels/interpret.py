"""Interpret-mode execution of Pallas TPU kernels on the CPU backend.

Two jax-0.4.37 gaps stand between the CPU test suite and the kernels:

1. ``pltpu.force_tpu_interpret_mode`` does not exist yet (it landed in a
   later jax). :func:`force_tpu_interpret_mode` provides the same contract
   by rebinding ``pl.pallas_call`` to force ``interpret=True`` inside the
   context — the library flash kernel and every kernel in this package go
   through that one symbol.

2. The pallas *interpreter* discharges ``masked_load``/``masked_swap`` with
   a rule that calls ``.shape`` on every index element
   (``jax/_src/pallas/primitives.py:482``) — but indices may be plain
   Python ints (any ``ref[i, j]`` with scalar components), so discharging
   the library flash kernel raises ``AttributeError: 'int' object has no
   attribute 'shape'``. That is the whole reason tests/test_flash_pallas.py
   carried xfail pins. :func:`install_discharge_fix` re-registers both
   rules with the upstream one-line repair (treat shapeless index elements
   as scalars via ``getattr(s, "shape", ())``) — byte-for-byte the stock
   rules otherwise, so compiled-TPU behavior (which never runs discharge)
   is untouched.

Both are CPU-rehearsal plumbing: on a real TPU the kernels lower through
Mosaic and neither code path runs.
"""

from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp
from jax import lax
from jax._src import dtypes
from jax._src.pallas import primitives as _pallas_primitives
from jax._src.state import discharge as _state_discharge
from jax._src.state.indexing import Slice
import jax.experimental.pallas as pl
import jax.experimental.pallas.tpu as pltpu

_FIX_INSTALLED = False


def _is_scalar_idx(s) -> bool:
    """A shapeless (scalar) index element: a 0-d array, or — the case the
    stock jax-0.4.37 rule crashes on — a plain Python/numpy int."""
    return not isinstance(s, Slice) and not getattr(s, "shape", ())


def _fixed_load_discharge_rule(in_avals, out_avals, *args_flat, args_tree,
                               **_):
    del out_avals
    ref, indexers, mask, other = args_tree.unflatten(args_flat)
    if len(indexers) > 1:
        raise NotImplementedError("Only one indexer supported in discharge rule.")
    idx = indexers[0]
    if all(isinstance(s, Slice) or _is_scalar_idx(s) for s in idx.indices):
        for s in idx.indices:
            if isinstance(s, Slice) and s.stride > 1:
                raise NotImplementedError("Unimplemented stride support.")
        indices = idx.indices
        scalar_dims = [_is_scalar_idx(s) for s in indices]
        slice_starts = [s.start if isinstance(s, Slice) else s for s in indices]
        slice_sizes = tuple(s.size if isinstance(s, Slice) else 1 for s in indices)
        ref = _pallas_primitives._pad_values_to_avoid_dynamic_slice_oob_shift(
            ref, slice_sizes)
        idx_dtype = dtypes.canonicalize_dtype(jnp.int64)
        out_ones = lax.dynamic_slice(
            ref, [jnp.astype(s, idx_dtype) for s in slice_starts],
            slice_sizes=slice_sizes)
        out_indexer = tuple(0 if scalar else slice(None) for scalar in scalar_dims)
        out = out_ones[out_indexer]
    elif all(not isinstance(s, Slice) for s in idx.indices):
        out = ref[idx.indices]
    else:
        raise NotImplementedError
    if mask is not None and other is not None:
        out = jnp.where(mask, out, other)
    return (None,) * len(in_avals), out


def _fixed_swap_discharge_rule(in_avals, out_avals, *args_flat, args_tree,
                               **_):
    del out_avals
    ref, indexers, val, mask = args_tree.unflatten(args_flat)
    if len(indexers) > 1:
        raise NotImplementedError("Only one indexer supported in discharge rule.")
    idx = indexers[0]
    if all(isinstance(s, Slice) or _is_scalar_idx(s) for s in idx.indices):
        for s in idx.indices:
            if isinstance(s, Slice) and s.stride > 1:
                raise NotImplementedError("Unimplemented stride support.")
        indices = idx.indices
        scalar_dims = [i for i, s in enumerate(indices) if _is_scalar_idx(s)]
        slice_starts = [s.start if isinstance(s, Slice) else s for s in indices]
        slice_sizes = tuple(s.size if isinstance(s, Slice) else 1 for s in indices)
        ref = _pallas_primitives._pad_values_to_avoid_dynamic_slice_oob_shift(
            ref, slice_sizes)
        out = lax.dynamic_slice(ref, slice_starts, slice_sizes=slice_sizes)
        out = jnp.squeeze(out, scalar_dims)
        if mask is not None:
            out_ = out
            out = jnp.where(mask, out, val)
            val = jnp.where(mask, val, out_)
        val = jnp.expand_dims(val, scalar_dims)
        x_new = lax.dynamic_update_slice(ref, val, start_indices=slice_starts)
        x_new = _pallas_primitives._unpad_values_to_avoid_dynamic_slice_oob_shift(
            x_new, slice_sizes)
    elif all(not isinstance(s, Slice) for s in idx.indices):
        out = ref[idx.indices]
        if mask is not None:
            out_ = out
            out = jnp.where(mask, out, val)
            val = jnp.where(mask, val, out_)
        x_new = ref.at[idx.indices].set(val)
    else:
        raise NotImplementedError
    return (x_new,) + (None,) * (len(in_avals) - 1), out


def install_discharge_fix() -> None:
    """Re-register the repaired masked-load/swap discharge rules (idempotent,
    process-global). Strictly widens the set of programs the interpreter can
    discharge: every case the stock rules handled takes the identical path."""
    global _FIX_INSTALLED
    if _FIX_INSTALLED:
        return
    _state_discharge.register_discharge_rule(_pallas_primitives.load_p)(
        _fixed_load_discharge_rule)
    _state_discharge.register_discharge_rule(_pallas_primitives.swap_p)(
        _fixed_swap_discharge_rule)
    _FIX_INSTALLED = True


@contextlib.contextmanager
def force_tpu_interpret_mode():
    """Run every ``pl.pallas_call`` in the context through the pallas
    interpreter (CPU-executable) — the jax-0.4.37 stand-in for
    ``pltpu.force_tpu_interpret_mode``, deferring to the real thing when the
    installed jax has it. Installs the discharge fix either way (newer jax
    ships it upstream, where installing ours is a no-op rebind of
    equivalent rules)."""
    install_discharge_fix()
    native = getattr(pltpu, "force_tpu_interpret_mode", None)
    if native is not None:
        with native():
            yield
        return
    original = pl.pallas_call

    def _interpreted_pallas_call(*args, **kwargs):
        kwargs["interpret"] = True
        return original(*args, **kwargs)

    pl.pallas_call = _interpreted_pallas_call
    try:
        yield
    finally:
        pl.pallas_call = original
