"""Pallas TPU kernels with in-kernel prompt-to-prompt editing.

The controller's map rewrites (Replace / Refine token remapping, Reweight
equalizers, self-attention injection) are structurally simple per-row
operations over the softmax probabilities — small matmuls and rescales along
the key axis. The materialized reference path
(`models/nn.py:attention_probs` → `controllers.base.apply_attention_control`)
pays a full ``(2B·heads, P, K)`` f32 HBM round-trip per edited site per step
for them; the kernels here apply the same algebra *inside* a tiled softmax,
so the probability tensor only ever exists as a ``(block_q, K)`` VMEM tile.

Layering: this package imports ``models.nn`` (block geometry) and
``controllers`` (edit semantics); ``models.unet`` imports this package for
site dispatch. Nothing here imports ``engine``.
"""

from ..controllers.kernel_spec import LANE
from .interpret import force_tpu_interpret_mode, install_discharge_fix
from .fused_edit import (
    edit_attention,
    edit_attention_reference,
    pad_to_lanes,
)
from .dispatch import (
    VARIANT_FLASH,
    VARIANT_FUSED,
    VARIANT_MATERIALIZED,
    VARIANT_USE,
    KernelConfig,
    site_variant,
)

__all__ = [
    "LANE",
    "KernelConfig",
    "VARIANT_FLASH",
    "VARIANT_FUSED",
    "VARIANT_MATERIALIZED",
    "VARIANT_USE",
    "edit_attention",
    "edit_attention_reference",
    "force_tpu_interpret_mode",
    "install_discharge_fix",
    "pad_to_lanes",
    "site_variant",
]
