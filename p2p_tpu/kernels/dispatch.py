"""Static kernel dispatch: which attention variant each site lowers to.

Dispatch is decided entirely at trace time from four static inputs — the
:class:`KernelConfig`, the controller structure, the site's ``AttnMeta``,
and the site's reuse-schedule mode for the current scan segment — so every
distinct (config, plan) pair is still ONE compiled program, mirroring how
``engine.reuse.segments`` already cuts the scan into constant-plan
``lax.scan`` segments:

=================  =========================================================
variant            lowering
=================  =========================================================
``use``            no attention math at all — the site serves its AttnCache
                   leaf (the fused "side-input": the cached tensor IS the
                   kernel-output representation a store segment emitted)
``flash``          plain fused attention (``models.nn.fused_attention``:
                   the library flash kernel at flash-tileable geometry) —
                   untouched sites, including ``store``/``store_all``
                   segments, whose cache capture is the site output the
                   kernel already produces (the fused "side-output")
``fused-edit``     the in-kernel edit program (``kernels.fused_edit``)
``materialized``   the reference f32 path — controller-touched sites the
                   kernel cannot express (attention-store sites) or that
                   the config doesn't cover
=================  =========================================================
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional, Tuple, Union

from ..controllers.base import AttnMeta, Controller, controller_touches
from ..controllers.kernel_spec import kernel_edit_spec

VARIANT_USE = "use"
VARIANT_FLASH = "flash"
VARIANT_FUSED = "fused-edit"
VARIANT_MATERIALIZED = "materialized"


def site_name(meta: AttnMeta) -> str:
    """Canonical site vocabulary — one definition (engine.reuse)."""
    from ..engine.reuse import site_name as _site_name

    return _site_name(meta)


@dataclasses.dataclass(frozen=True)
class KernelConfig:
    """Static fused-kernel dispatch plan (hashable — rides jit static args).

    ``sites``: ``"*"`` fuses every kernel-compilable site; a tuple of site
    names (``cross_attn/down3`` …) restricts fusion to those — the ordered
    fuse-first list ``tools/perfscope.py --fuse-plan`` emits. ``block_q=0``
    lets ``models.nn.edit_block`` pick the query tile per site geometry.
    ``interpret`` runs the kernels through the pallas interpreter — the CPU
    rehearsal/parity surface; on-chip runs leave it False."""

    sites: Union[str, Tuple[str, ...]] = "*"
    block_q: int = 0
    interpret: bool = False

    def __post_init__(self):
        if self.sites != "*" and not isinstance(self.sites, tuple):
            raise ValueError(
                f"KernelConfig.sites must be '*' or a tuple of site names, "
                f"got {self.sites!r}")

    def covers(self, name: str) -> bool:
        return self.sites == "*" or name in self.sites

    @classmethod
    def from_fuse_plan(cls, plan: Union[str, dict], take: Optional[int] = None,
                       **kwargs) -> "KernelConfig":
        """Build a config from a ``perfscope --fuse-plan`` artifact (a path
        or the loaded dict): take the top ``take`` sites of the ranked
        fuse-first order (all of them by default)."""
        if isinstance(plan, str):
            with open(plan) as f:
                plan = json.load(f)
        order = [entry["site"] for entry in plan["fuse_order"]]
        if take is not None:
            order = order[:take]
        return cls(sites=tuple(order), **kwargs)


def site_variant(kernels: Optional[KernelConfig],
                 controller: Optional[Controller],
                 meta: AttnMeta, mode: str) -> str:
    """The static attention variant for one site in one scan segment.
    ``mode`` is the site's reuse-schedule action (``engine.reuse`` MODE_*;
    the legacy global cache_mode lowers to the same vocabulary)."""
    if mode == "use":
        return VARIANT_USE
    if not controller_touches(controller, meta):
        return VARIANT_FLASH
    if (kernels is not None and kernels.covers(site_name(meta))
            and kernel_edit_spec(controller, meta) is not None):
        return VARIANT_FUSED
    return VARIANT_MATERIALIZED
