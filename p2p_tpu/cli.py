"""Command-line driver.

The reference's CLI (`/root/reference/main.py:386-482`) is a fixed-prompt seed
sweep with a `--type {global,local}` switch and an unread `--path config.yaml`
flag; its real edit surface (`make_controller`) is notebook-only. Here the
whole edit API is on the command line:

    python -m p2p_tpu.cli generate --prompt "a cat" --out out.png
    python -m p2p_tpu.cli edit --source "a cat riding a bike" \
        --target "a dog riding a bike" --mode replace --seeds 1,2,3 \
        --blend-words cat,dog --out-dir logs/run1
    python -m p2p_tpu.cli invert --image cat.png --prompt "a cat" \
        --artifact cat_inv.npz
    python -m p2p_tpu.cli replay --artifact cat_inv.npz \
        --target "a tiger" --mode replace --out-dir logs/replay

Presets: ``tiny``/``tiny_ldm`` (random weights, fast — ``tiny`` is the
default when no checkpoint is given), ``sd14``/``sd21``/``sd21base``/
``ldm256`` (real model shapes; random weights unless ``--checkpoint``
points at a diffusers-format directory; ``sd21`` is the 768-v v-prediction
family the reference marks "Not work"). Every edit run writes the
baseline/edited pair like `run_and_display`
(`/root/reference/main.py:353-383`).
"""

from __future__ import annotations

import argparse
import contextlib
import os
import sys
import time
from typing import List, Optional

import numpy as np


def _preset_config(name):
    from .models.config import PRESET_CONFIGS

    return PRESET_CONFIGS[name]


def _build_pipeline(args):
    import jax

    from .engine.sampler import Pipeline
    from .models import init_text_encoder, init_unet
    from .models import vae as vae_mod
    from .utils.tokenizer import HashWordTokenizer

    cfg = _preset_config(args.preset)
    if args.checkpoint:
        from .models.checkpoint import load_pipeline

        return load_pipeline(args.checkpoint, cfg)
    tok = HashWordTokenizer(model_max_length=cfg.text.max_length)
    return Pipeline(
        config=cfg,
        unet_params=init_unet(jax.random.PRNGKey(0), cfg.unet),
        text_params=init_text_encoder(jax.random.PRNGKey(1), cfg.text),
        vae_params=vae_mod.init_vae(jax.random.PRNGKey(2), cfg.vae),
        tokenizer=tok,
    )


def _save(img: np.ndarray, path: str) -> None:
    from PIL import Image

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    Image.fromarray(np.asarray(img)).save(path)
    print(f"wrote {path}")


@contextlib.contextmanager
def _metrics_session(path: Optional[str]):
    """``--metrics FILE``: run the block under the telemetry collector and
    write a Prometheus text snapshot to FILE afterwards.

    Yields the bool to pass as the engines' ``metrics=`` argument (False
    when no path was given — then nothing extra is traced into any
    program, the disabled-identity contract). On exit the collector drains
    the async callback stream, device ``memory_stats()`` gauges are
    sampled, and the registry (reset at entry, so the snapshot covers
    exactly this run) is rendered to ``path``."""
    if not path:
        yield False
        return
    from .obs import device as obs_device
    from .obs import metrics as obs_metrics

    obs_metrics.registry().reset()
    with obs_device.instrument():
        yield True
    obs_device.sample_device_memory()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        f.write(obs_metrics.registry().to_prometheus())
    print(f"wrote {path}", file=sys.stderr)


def _parse_equalizer(spec: Optional[str]):
    if not spec:
        return None
    words, values = [], []
    for part in spec.split(","):
        w, v = part.split("=")
        words.append(w.strip())
        values.append(float(v))
    return {"words": tuple(words), "values": tuple(values)}


def controller_from_opts(prompts, tokenizer, num_steps, *, mode,
                         cross_steps, self_steps, blend_words=None,
                         equalizer=None, blend_resolution=16):
    """The one controller assembly both request surfaces share: the CLI
    subcommands (via ``_make_controller``) and the serving layer
    (``serve.request.prepare``) build edit controllers through this exact
    call, so a spec accepted by one surface is accepted — and means the
    same program — on the other. ``blend_words``/``equalizer`` use the CLI
    string syntax ("cat,dog" / "word=scale,...")."""
    from .controllers.factory import make_controller

    blend = blend_words.split(",") if blend_words else None
    if blend is not None:
        blend = [blend] * len(prompts)
    return make_controller(
        prompts,
        is_replace_controller=mode == "replace",
        cross_replace_steps=cross_steps,
        self_replace_steps=self_steps,
        tokenizer=tokenizer,
        num_steps=num_steps,
        blend_words=blend,
        equalizer_params=_parse_equalizer(equalizer),
        blend_resolution=blend_resolution,
    )


def _schedule_spec(args):
    """Load the ``--schedule`` artifact (a reuse-schedule JSON spec) for
    the sampling subcommands; fail fast — before the model build — on a
    bad file or a ``--gate`` conflict (the schedule IS a generalized
    gate)."""
    path = getattr(args, "schedule", None)
    if path is None:
        return None
    if getattr(args, "gate", None) is not None:
        raise SystemExit("--gate and --schedule are mutually exclusive: "
                         "the schedule's cfg_gate is the gate")
    from .engine.reuse import load_spec

    try:
        return load_spec(path)
    except (OSError, ValueError) as e:
        raise SystemExit(f"--schedule {path}: {e}")


def _make_controller(args, prompts, tokenizer, num_steps):
    return controller_from_opts(
        prompts, tokenizer, num_steps, mode=args.mode,
        cross_steps=args.cross_steps, self_steps=args.self_steps,
        blend_words=args.blend_words, equalizer=args.equalizer,
        blend_resolution=args.blend_resolution)


def cmd_generate(args) -> int:
    import jax

    from .engine.sampler import text2image

    from .utils.progress import trace

    sched_spec = _schedule_spec(args)
    pipe = _build_pipeline(args)

    def out_path(seed):
        if len(args.seeds) == 1:
            return args.out
        root, ext = os.path.splitext(args.out)
        return f"{root}_{seed:05d}{ext}"

    if args.batch_seeds:
        from .parallel import sweep

        with _metrics_session(args.metrics) as met, trace(args.profile):
            ctx, lats, mesh = _group_setup(pipe, [args.prompt], args.seeds,
                                           args.negative_prompt)
            imgs, _ = sweep(pipe, ctx, lats, None, num_steps=args.steps,
                            guidance_scale=args.guidance,
                            scheduler=args.scheduler, mesh=mesh,
                            gate=args.gate, schedule=sched_spec,
                            progress=not args.quiet,
                            metrics=met)
            for i, seed in enumerate(args.seeds):
                _save(np.asarray(imgs[i][0]), out_path(seed))
        return 0

    with _metrics_session(args.metrics) as met, trace(args.profile):
        for seed in args.seeds:
            img, _, _ = text2image(pipe, [args.prompt], None,
                                   num_steps=args.steps,
                                   guidance_scale=args.guidance,
                                   scheduler=args.scheduler,
                                   rng=jax.random.PRNGKey(seed),
                                   negative_prompt=args.negative_prompt,
                                   gate=args.gate, schedule=sched_spec,
                                   progress=not args.quiet, metrics=met)
            _save(np.asarray(img[0]), out_path(seed))
    return 0


def _group_setup(pipe, prompts, seeds, negative_prompt):
    """Shared batched-sweep setup: per-group [uncond; cond] context, one
    base latent per seed shared across the group's prompts (the shared-seed
    expansion of `/root/reference/ptp_utils.py:88-95`), and a dp mesh over
    up to min(n_seeds, n_devices) devices (a 4-seed sweep on an 8-device
    slice still rides 4 — same gate as examples/equalizer_sweep.py).
    Returns (ctx (G,2B,L,D), lats (G,B,...), mesh-or-None)."""
    import jax
    import jax.numpy as jnp

    from .engine.sampler import encode_prompts
    from .parallel import make_mesh

    g = len(seeds)
    cond = encode_prompts(pipe, prompts)
    uncond = encode_prompts(pipe, [negative_prompt or ""] * len(prompts))
    ctx = jnp.concatenate([uncond, cond], axis=0)
    ctx = jnp.broadcast_to(ctx[None], (g,) + ctx.shape)
    base = jnp.stack([jax.random.normal(jax.random.PRNGKey(s),
                                        (1,) + pipe.latent_shape)
                      for s in seeds])
    lats = jnp.broadcast_to(base, (g, len(prompts)) + pipe.latent_shape)
    return ctx, lats, _dp_mesh(g, f"--batch-seeds: {g} seeds")


def _dp_mesh(g, what):
    """Shard over the largest divisor of g that fits the visible devices
    (g=6 on 4 devices rides 3, not 1); say so when parallelism degrades,
    rather than silently losing what the batch flag advertises."""
    import jax

    from .parallel import make_mesh

    cap = min(len(jax.devices()), g)
    n_dev = max((d for d in range(1, cap + 1) if g % d == 0), default=1)
    if n_dev < cap:
        print(f"{what} not divisible by {cap} devices; "
              f"sharding over {n_dev}", file=sys.stderr)
    return make_mesh(n_dev) if n_dev > 1 else None


def _edit_batched(args, pipe, prompts, controller, out_dir,
                  metrics: bool = False) -> int:
    """The seed sweep as two compiled programs total (baseline + edit), all
    seeds riding the group axis of the dp sweep engine — the reference's
    sequential per-seed loop (`/root/reference/main.py:417-444`) at sweep
    throughput."""
    import jax
    import jax.numpy as jnp

    from .parallel import sweep

    g = len(args.seeds)
    ctx, lats, mesh = _group_setup(pipe, prompts, args.seeds,
                                   args.negative_prompt)
    kw = dict(num_steps=args.steps, guidance_scale=args.guidance,
              scheduler=args.scheduler, mesh=mesh, gate=args.gate,
              schedule=_schedule_spec(args),
              progress=not args.quiet, metrics=metrics)
    base_imgs, _ = sweep(pipe, ctx, lats, None, **kw)
    ctrls = jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x, (g,) + x.shape), controller)
    edit_imgs, _ = sweep(pipe, ctx, lats, ctrls, **kw)
    for i, seed in enumerate(args.seeds):
        _save(np.asarray(base_imgs[i][0]),
              os.path.join(out_dir, f"{seed:05d}_y.jpg"))
        _save(np.asarray(edit_imgs[i][1]),
              os.path.join(out_dir, f"{seed:05d}_y_hat.jpg"))
    return 0


def cmd_edit(args) -> int:
    import jax

    from .engine.sampler import text2image

    from .utils.progress import trace

    if args.batch_seeds and (args.attn_maps or args.self_attn_maps):
        # Batched groups carry a leading G axis in the store state the viz
        # aggregation doesn't index; honored-flags discipline says reject
        # rather than silently ignore — and before the model load.
        raise SystemExit("--attn-maps/--self-attn-maps require the "
                         "sequential path (drop --batch-seeds)")
    pipe = _build_pipeline(args)
    prompts = [args.source, args.target]
    controller = _make_controller(args, prompts, pipe.tokenizer, args.steps)
    out_dir = args.out_dir or os.path.join("logs", time.strftime("%y%m%d_%H%M%S"))
    if args.batch_seeds:
        with _metrics_session(args.metrics) as met, trace(args.profile):
            return _edit_batched(args, pipe, prompts, controller, out_dir,
                                 metrics=met)
    from .models.config import unet_layout

    layout = unet_layout(pipe.config.unet)
    sched_spec = _schedule_spec(args)
    with _metrics_session(args.metrics) as met, trace(args.profile):
        for seed in args.seeds:
            rng = jax.random.PRNGKey(seed)
            base, x_t, _ = text2image(pipe, prompts, None,
                                      num_steps=args.steps,
                                      guidance_scale=args.guidance,
                                      scheduler=args.scheduler, rng=rng,
                                      negative_prompt=args.negative_prompt,
                                      gate=args.gate, schedule=sched_spec,
                                      progress=not args.quiet, layout=layout,
                                      metrics=met)
            img, _, store = text2image(pipe, prompts, controller,
                                       num_steps=args.steps,
                                       guidance_scale=args.guidance,
                                       scheduler=args.scheduler, latent=x_t,
                                       negative_prompt=args.negative_prompt,
                                       gate=args.gate, schedule=sched_spec,
                                       progress=not args.quiet, layout=layout,
                                       metrics=met,
                                       return_store=bool(args.attn_maps
                                                         or args.self_attn_maps))
            # y / y_hat naming per `/root/reference/main.py:375-380,435-444`.
            _save(np.asarray(base[0]),
                  os.path.join(out_dir, f"{seed:05d}_y.jpg"))
            _save(np.asarray(img[1]),
                  os.path.join(out_dir, f"{seed:05d}_y_hat.jpg"))
            if args.attn_maps:
                _save_attn_maps(args, pipe, layout, store, seed)
            if args.self_attn_maps:
                _save_self_attn_maps(args, pipe, layout, store, seed)
    return 0


def _save_attn_maps(args, pipe, layout, store, seed) -> None:
    """Per-token cross-attention heatmaps of the edited prompt — the
    reference's `show_cross_attention` notebook workflow
    (`/root/reference/main.py:310-327`) as a CLI artifact."""
    from .utils import viz

    res = _stored_res(layout, pipe, cross=True, flag="--attn-maps")
    os.makedirs(args.attn_maps, exist_ok=True)
    viz.show_cross_attention(
        pipe.tokenizer, args.target, layout, store, args.steps, res,
        ("up", "down"), select=1,
        save_path=os.path.join(args.attn_maps, f"{seed:05d}_cross_attn.png"))


def _stored_res(layout, pipe, cross: bool, flag: str) -> int:
    """Model-derived display resolution: the largest stored resolution ≤ a
    quarter of the latent side (the 16×16 level the reference reads at SD's
    64² latent, `/root/reference/main.py:302,327`), falling back to the
    largest stored at all (tiny test models)."""
    stored = sorted({m.resolution for m in layout.stored_metas()
                     if m.is_cross == cross and m.place in ("up", "down")})
    if not stored:
        kind = "cross" if cross else "self"
        raise SystemExit(f"{flag}: no stored up/down {kind}-attention "
                         "sites in this model config")
    want = pipe.config.unet.sample_size // 4
    return max((r for r in stored if r <= want), default=stored[-1])


def _save_self_attn_maps(args, pipe, layout, store, seed) -> None:
    """Top-10 SVD components of the self-attention matrix — the reference's
    `show_self_attention_comp` notebook workflow
    (`/root/reference/main.py:330-350`) as a CLI artifact."""
    from .utils import viz

    res = _stored_res(layout, pipe, cross=False, flag="--self-attn-maps")
    os.makedirs(args.self_attn_maps, exist_ok=True)
    viz.show_self_attention_comp(
        layout, store, args.steps, res, ("up", "down"), select=1,
        save_path=os.path.join(args.self_attn_maps,
                               f"{seed:05d}_self_attn_svd.png"))


def cmd_invert(args) -> int:
    from .engine.inversion import invert, load_image

    from .utils.progress import trace

    pipe = _build_pipeline(args)
    image = load_image(args.image, size=pipe.config.image_size)
    with _metrics_session(args.metrics) as met, trace(args.profile):
        art = invert(pipe, image, args.prompt, num_steps=args.steps,
                     guidance_scale=args.guidance,
                     num_inner_steps=args.inner_steps,
                     progress=not args.quiet, metrics=met)
    art.save(args.artifact)
    print(f"wrote {args.artifact}")
    if args.out_dir:
        _save(art.image_gt, os.path.join(args.out_dir, "gt.png"))
        _save(art.image_rec, os.path.join(args.out_dir, "vae_rec.png"))
    return 0


def cmd_replay(args) -> int:
    import jax.numpy as jnp

    from .engine.inversion import InversionArtifact
    from .engine.sampler import text2image
    from .utils.progress import trace

    targets = args.target or []
    if args.batch_targets and not targets:
        raise SystemExit("--batch-targets needs at least one --target")
    pipe = _build_pipeline(args)
    art = InversionArtifact.load(args.artifact)
    out_dir = args.out_dir or "outputs"

    def edited_path(i):
        return os.path.join(
            out_dir, "edited.png" if len(targets) == 1
            else f"edited_{i:02d}.png")

    if args.batch_targets:
        return _replay_batched(args, pipe, art, targets, out_dir, edited_path)

    x_t = jnp.asarray(art.x_t)
    ups = jnp.asarray(art.uncond_embeddings)
    with _metrics_session(args.metrics) as met, trace(args.profile):
        for i, target in enumerate(targets or [None]):
            prompts = [art.prompt, target] if target else [art.prompt]
            controller = (None if target is None else _make_controller(
                args, prompts, pipe.tokenizer, art.num_steps))
            img, _, _ = text2image(
                pipe, prompts, controller, num_steps=art.num_steps,
                guidance_scale=args.guidance, latent=x_t,
                uncond_embeddings=ups, progress=not args.quiet, metrics=met)
            if i == 0:
                _save(np.asarray(img[0]),
                      os.path.join(out_dir, "reconstruction.png"))
            if target is not None:
                _save(np.asarray(img[1]), edited_path(i))
    return 0


def _replay_batched(args, pipe, art, targets, out_dir, edited_path) -> int:
    """All target edits of one inversion artifact as ONE compiled dp-swept
    program: each group is [source, target_i] with the artifact's per-step
    null embeddings broadcast over groups — the missing-notebook workflow
    (`/root/reference/null_text.py:618` + SURVEY §3.2) at sweep throughput.
    Target controllers are traced leaves of one stacked pytree, so they must
    share structure: one --mode/--blend-words/--equalizer for all targets."""
    from .parallel import artifact_replay_inputs, sweep
    from .utils.progress import trace

    g = len(targets)
    ctrl_list = [_make_controller(args, [art.prompt, t], pipe.tokenizer,
                                  art.num_steps) for t in targets]
    ctx_g, lats, ups, ctrls = artifact_replay_inputs(
        pipe, art.x_t, art.uncond_embeddings, art.prompt, targets, ctrl_list)
    with _metrics_session(args.metrics) as met, trace(args.profile):
        imgs, _ = sweep(pipe, ctx_g, lats, ctrls, num_steps=art.num_steps,
                        guidance_scale=args.guidance,
                        mesh=_dp_mesh(g, f"--batch-targets: {g} targets"),
                        uncond_per_step=ups, progress=not args.quiet,
                        metrics=met)
        imgs = np.asarray(imgs)
    _save(imgs[0][0], os.path.join(out_dir, "reconstruction.png"))
    for i in range(g):
        _save(imgs[i][1], edited_path(i))
    return 0


def cmd_serve(args) -> int:
    """Request-level serving: drain a JSONL request trace through the
    serve subsystem (queue → dynamic batcher → program cache → worker
    loop), writing one JSONL record per request plus a summary. See
    docs/SERVING.md for the request schema."""
    import json

    from .obs import metrics as obs_metrics
    from .obs import spans as obs_spans
    from .serve import (DegradeConfig, DrainController, FaultPlan, Journal,
                        Request, parse_jsonl_line, parse_mesh,
                        serve_forever, signal_drain)

    if args.snapshot_every_ms is not None and not args.journal:
        # Fail fast, before the (expensive) pipeline build.
        raise SystemExit("--snapshot-every-ms snapshots the journal: it "
                         "needs --journal")
    mesh_spec = None
    if args.mesh:
        try:
            # Parse before the pipeline build (fail fast on a typo); the
            # device-count check happens when the engine builds the live
            # mesh, after backend init.
            mesh_spec = parse_mesh(args.mesh)
        except ValueError as e:
            raise SystemExit(str(e))
    elastic_cfg = None
    if args.elastic is not None:
        from .serve import parse_elastic

        try:
            elastic_cfg = parse_elastic(args.elastic)
        except ValueError as e:
            raise SystemExit(str(e))
    # One serve run == one snapshot/event-log: reset before the pipeline
    # build so prewarm compiles and the queue/batcher/cache timelines are
    # all covered by the exported artifacts.
    obs_metrics.registry().reset()
    obs_spans.clear()
    ring = args.events_ring
    if ring is None:
        env_ring = os.environ.get("P2P_OBS_EVENTS_RING")
        if env_ring:
            try:
                ring = int(env_ring)
            except ValueError:
                raise SystemExit(f"P2P_OBS_EVENTS_RING must be an integer, "
                                 f"got {env_ring!r}")
    if ring is not None:
        if ring < 1:
            raise SystemExit(f"--events-ring must be >= 1, got {ring}")
        obs_spans.set_capacity(ring)
    flight_tracer = None
    if args.flight_out or args.trace_out or args.blackbox:
        from .obs import flight as obs_flight

        flight_tracer = obs_flight.FlightTracer(blackbox_dir=args.blackbox)
    costscope = None
    if args.cost or args.programs_out:
        from .obs import costmodel as obs_costmodel

        costscope = obs_costmodel.CostScope()
    default_sched = _schedule_spec(args)
    prodscope = None
    if args.profile:
        from .obs import prodscope as obs_prodscope

        tags = {"preset": args.preset, "max_batch": args.max_batch}
        if args.mesh:
            tags["mesh"] = args.mesh
        if args.phase2_max_batch is not None:
            tags["phase2_max_batch"] = args.phase2_max_batch
        if default_sched is not None:
            tags["schedule"] = default_sched
        try:
            prodscope = obs_prodscope.ProdScope(
                args.profile, seed=args.profile_seed,
                period=args.profile_every,
                ring_max_bytes=args.profile_ring_bytes,
                ring_max_count=args.profile_ring_count, tags=tags)
        except ValueError as e:
            raise SystemExit(f"--profile: {e}")
    elif (args.profile_every != 8 or args.profile_seed != 0
          or args.profile_ring_bytes != 256 << 20
          or args.profile_ring_count != 16):
        raise SystemExit("--profile-every/--profile-seed/--profile-ring-"
                         "bytes/--profile-ring-count configure the "
                         "production profiler: they need --profile DIR")
    pipe = _build_pipeline(args)
    stream = sys.stdin if args.requests == "-" else open(args.requests)
    items = []
    with stream:
        for i, line in enumerate(stream):
            try:
                item = parse_jsonl_line(line)
            except (ValueError, KeyError) as e:
                raise SystemExit(f"--requests line {i + 1}: {e}")
            if item is None:
                continue
            if default_sched is not None and isinstance(item, Request) \
                    and item.gate is None and item.schedule is None:
                # The server default applies only where the request left
                # BOTH knobs unset: an explicit per-request gate or
                # schedule always wins (and gate+schedule stays a clean
                # per-request schema reject).
                import dataclasses as _dc

                item = _dc.replace(item, schedule=default_sched)
            items.append(item)
    prewarm = None
    if not args.no_prewarm:
        # Compile-ahead with the first request as the representative shape:
        # uniform traffic then never pays a compile in-band.
        prewarm = [r for r in items if isinstance(r, Request)][:1]

    journal = Journal(args.journal) if args.journal else None
    chaos = FaultPlan.load(args.chaos_plan) if args.chaos_plan else None
    if chaos is not None:
        # Some kinds are inert without their enabling flag: a drill that
        # "passes" without ever exercising the path is worse than one that
        # fails, so say so up front. The per-kind conditions and texts
        # live in the chaos-kind catalog (serve/chaos.CATALOG) next to
        # each kind's crash-window declaration.
        from .serve.chaos import inert_warnings

        kinds = set(chaos.by_batch.values()) | set(chaos.by_request.values())
        for msg in inert_warnings(kinds, {
                "validate_outputs": args.validate_outputs,
                "watchdog_ms": args.watchdog_ms,
                "journal": args.journal,
                "snapshot_every_ms": args.snapshot_every_ms,
                "cache": args.cache,
                "profile": args.profile,
                "elastic": args.elastic}):
            print(f"warning: {msg}", file=sys.stderr)
    degrade = None
    if args.degrade_depth is not None:
        degrade = DegradeConfig(depth_threshold=args.degrade_depth,
                                window_ms=args.degrade_window_ms,
                                min_bucket=args.degrade_min_bucket)
    semcache = None
    if args.cache:
        from .serve import SemCache

        try:
            semcache = SemCache(
                spill_dir=args.cache_dir,
                **({"l3_bytes": args.cache_l3_bytes}
                   if args.cache_l3_bytes is not None else {}))
        except ValueError as e:
            raise SystemExit(str(e))
    elif args.cache_dir is not None or args.cache_l3_bytes is not None:
        raise SystemExit("--cache-dir/--cache-l3-bytes configure the "
                         "semantic cache: they need --cache")
    slo = None
    if args.slo or args.tenant_quota is not None \
            or args.preempt_depth is not None:
        from .serve import SloConfig

        try:
            slo = SloConfig(tenant_quota=args.tenant_quota,
                            preempt_depth=args.preempt_depth)
        except ValueError as e:
            raise SystemExit(str(e))
        if args.preempt_depth is not None and not args.journal:
            print("warning: --preempt-depth without --journal parks "
                  "preempted carries in memory only — a crash mid-park "
                  "re-runs phase 1 instead of resuming off a spill",
                  file=sys.stderr)

    out = open(args.results, "w") if args.results else sys.stdout

    def emit(rec):
        rec = dict(rec)
        images = rec.pop("images", None)
        if images is not None and args.out_dir:
            names = ([f"{rec['request_id']}.png"] if len(images) == 1 else
                     [f"{rec['request_id']}_y.png",
                      f"{rec['request_id']}_y_hat.png"])
            rec["image_paths"] = [os.path.join(args.out_dir, n)
                                  for n in names]
            from PIL import Image

            os.makedirs(args.out_dir, exist_ok=True)
            for img, path in zip(images, rec["image_paths"]):
                # Not _save: its "wrote ..." print would interleave with
                # JSONL records when results go to stdout.
                Image.fromarray(np.asarray(img)).save(path)
        out.write(json.dumps(rec) + "\n")
        out.flush()

    # Lifecycle: SIGTERM/Ctrl-C request a graceful drain (finish in-flight
    # work, snapshot, emit the summary, exit 0); a second signal forces a
    # KeyboardInterrupt, caught below so the operator never sees a raw
    # traceback — artifacts still flush in the finally blocks and the
    # journal's crash contract covers whatever the force-quit abandoned.
    drain_ctl = DrainController()
    interrupted = False
    try:
        with signal_drain(drain_ctl):
            for rec in serve_forever(
                    pipe, items, max_batch=args.max_batch,
                    max_wait_ms=args.max_wait_ms, queue_cap=args.queue_cap,
                    program_cache_cap=args.program_cache_cap,
                    prewarm=prewarm, progress=not args.quiet,
                    journal=journal, chaos=chaos,
                    watchdog_ms=args.watchdog_ms,
                    validate_outputs=args.validate_outputs,
                    degrade=degrade,
                    phase_pools=not args.single_pool,
                    phase2_max_batch=args.phase2_max_batch,
                    mesh=mesh_spec,
                    elastic=elastic_cfg,
                    slo=slo,
                    semcache=semcache,
                    costscope=costscope,
                    prodscope=prodscope,
                    flight=flight_tracer,
                    lifecycle=drain_ctl,
                    snapshot_every_ms=args.snapshot_every_ms,
                    drain_timeout_ms=args.drain_timeout_ms):
                emit(rec)
    except KeyboardInterrupt:
        interrupted = True
        print("serve: force quit before the drain completed (journaled "
              "work resumes on restart)", file=sys.stderr)
    finally:
        if journal is not None:
            journal.close()
        if out is not sys.stdout:
            out.close()
        if prodscope is not None:
            # Written in the finally so a fatal drain's captures still
            # persist their ledger.
            try:
                path = prodscope.write_ledger()
            except OSError as e:
                print(f"--profile: ledger write failed: {e}",
                      file=sys.stderr)
            else:
                print(f"wrote {path}", file=sys.stderr)
        if costscope is not None and args.programs_out:
            # Written in the finally so a fatal drain's cards (and a
            # partially-drained trace) still produce the artifact.
            os.makedirs(os.path.dirname(args.programs_out) or ".",
                        exist_ok=True)
            with open(args.programs_out, "w") as f:
                costscope.write_programs_jsonl(f)
            print(f"wrote {args.programs_out}", file=sys.stderr)
        if flight_tracer is not None:
            # Written in the finally so a fatal drain's records (and a
            # partially-drained trace) still produce the artifacts.
            from .obs import flight as obs_flight

            if args.flight_out:
                os.makedirs(os.path.dirname(args.flight_out) or ".",
                            exist_ok=True)
                with open(args.flight_out, "w") as f:
                    obs_flight.write_flight_jsonl(f, flight_tracer.records)
                print(f"wrote {args.flight_out}", file=sys.stderr)
            if args.trace_out:
                os.makedirs(os.path.dirname(args.trace_out) or ".",
                            exist_ok=True)
                with open(args.trace_out, "w") as f:
                    json.dump(obs_flight.chrome_trace(flight_tracer), f)
                    f.write("\n")
                print(f"wrote {args.trace_out}", file=sys.stderr)
    if args.metrics_out or args.events_out:
        from .obs import device as obs_device

        obs_device.sample_device_memory()
        for path, render in ((args.metrics_out,
                              obs_metrics.registry().to_prometheus),
                             (args.events_out, None)):
            if not path:
                continue
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(path, "w") as f:
                if render is not None:
                    f.write(render())
                else:
                    obs_spans.write_jsonl(f)
            print(f"wrote {path}", file=sys.stderr)
    return 130 if interrupted else 0


def cmd_check(args) -> int:
    if args.ast_only and not args.static:
        # Honored-flags discipline: never accept-and-ignore.
        raise SystemExit("--ast-only only applies to --static")
    if args.static:
        if args.checkpoint_dir or args.preset:
            raise SystemExit("--static is the whole-stack analyzer; it "
                             "takes no checkpoint_dir/--preset")
        if not args.ast_only:
            # Same backend pinning as tools/jaxcheck.py (one shared
            # helper): the traced passes are structure checks, never
            # device work — tracing on an accelerator would initialize it
            # (and could lower donation differently), and a one-device
            # run would degrade the shardcheck sweep to dp=1, where a
            # real hidden all-gather at dp>=2 passes unseen.
            from .utils.platform import force_cpu_platform

            force_cpu_platform()
            import jax

            jax.config.update("jax_platforms", "cpu")
        from .analysis import report as report_mod

        report = report_mod.run_all(ast_only=args.ast_only)
        print(report_mod.render_text(report))
        return 0 if report["ok"] else 1
    if not args.checkpoint_dir or not args.preset:
        raise SystemExit("check needs a checkpoint_dir and --preset "
                         "(or --static for the static analyzer)")
    from .models.checkpoint_check import _print_report, check_checkpoint

    rep = check_checkpoint(args.checkpoint_dir, args.preset)
    _print_report(rep)
    return 0 if rep.ok else 1


def _int_list(s: str) -> List[int]:
    return [int(x) for x in s.split(",") if x]


def _gate_spec(s: str):
    """Parse ``--gate``: 'auto' | a fraction with a dot ('0.5') | an absolute
    step index ('25'). Kept jax-free; full validation (range, controller
    window, null-text conflicts) happens in ``engine.sampler.resolve_gate``."""
    if s == "auto":
        return "auto"
    try:
        return float(s) if "." in s else int(s)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"--gate expects 'auto', a fraction like 0.5, or a step index, "
            f"got {s!r}")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="p2p_tpu", description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = p.add_subparsers(dest="cmd", required=True)

    # Each subcommand declares exactly the flags it honors — no
    # accepted-but-ignored options (the reference's unread `--path
    # config.yaml`, `/root/reference/main.py:388`, is the anti-pattern).
    def model_opts(sp, guidance=True, metrics=True, profile=True):
        # Literal name tuples: build_parser must stay jax-free so --help and
        # argparse errors are instant. Drift against the canonical
        # PRESET_CONFIGS map is pinned by
        # tests/test_cli.py::test_every_cli_preset_resolves_to_a_config.
        sp.add_argument("--preset",
                        choices=("tiny", "sd14", "sd21", "sd21base",
                                 "ldm256", "tiny_ldm"),
                        default="tiny",
                        help="model family; sd21 is the 768-v v-prediction "
                             "variant the reference marks 'Not work' "
                             "(`/root/reference/main.py:27`) — supported "
                             "here")
        sp.add_argument("--checkpoint", default=None,
                        help="diffusers-format checkpoint dir (unet/ vae/ ...)")
        if guidance:
            # serve omits this: guidance is a per-request JSONL field there
            # (honored-flags discipline — no accepted-but-ignored options).
            sp.add_argument("--guidance", type=float, default=7.5)
        sp.add_argument("--quiet", action="store_true",
                        help="suppress per-step progress output")
        if profile:
            # serve defines its own --profile (the production profiler's
            # ring + ledger directory, ISSUE 18) — a whole-run
            # jax.profiler trace of a server is the wrong tool there.
            sp.add_argument("--profile", default=None, metavar="DIR",
                            help="write a jax.profiler trace of the run "
                                 "to DIR")
        if metrics:
            # serve surfaces its own --metrics-out/--events-out pair (the
            # registry there also carries queue/batcher/cache families).
            sp.add_argument("--metrics", default=None, metavar="FILE",
                            help="enable device-side telemetry (per-phase "
                                 "step timing via the host-callback "
                                 "channel, memory gauges) and write a "
                                 "Prometheus text snapshot of the run to "
                                 "FILE (docs/OBSERVABILITY.md)")

    def sampling_opts(sp):
        sp.add_argument("--steps", type=int, default=50)
        sp.add_argument("--scheduler", choices=("ddim", "plms", "dpm"), default="ddim")
        sp.add_argument("--seeds", type=_int_list, default=[8191],
                        help="comma-separated seed sweep")
        sp.add_argument("--gate", type=_gate_spec, default=None,
                        metavar="AUTO|FRAC|STEP",
                        help="phase-gated sampling: steps past the gate run "
                             "a single-branch U-Net (CFG folded into a "
                             "fixed extrapolation) with cached "
                             "cross-attention — 'auto' picks max(T/2, the "
                             "controller's edit-window end); 0.5 gates at "
                             "half the steps; an integer is an absolute "
                             "step. Omit for exact (ungated) sampling")
        sp.add_argument("--schedule", default=None, metavar="FILE",
                        help="per-site per-step reuse schedule artifact "
                             "(JSON, e.g. tools/schedules/default_v1.json):"
                             " the generalized gate — each attention site "
                             "flips to cached/inherited reuse at its own "
                             "step. Mutually exclusive with --gate")

    def edit_opts(sp):
        sp.add_argument("--mode", choices=("replace", "refine"),
                        default="refine")
        sp.add_argument("--cross-steps", type=float, default=0.8)
        sp.add_argument("--self-steps", type=float, default=0.4)
        sp.add_argument("--blend-words", default=None,
                        help="comma-separated words for LocalBlend masking")
        sp.add_argument("--equalizer", default=None,
                        help="word=scale[,word=scale...] reweighting")
        sp.add_argument("--blend-resolution", type=int, default=16)

    def negative_opt(sp):
        # generate/edit only — replay's uncond comes from the inversion
        # artifact, invert's from the null-text objective (honored-flags-only
        # discipline: no accepted-but-ignored options).
        sp.add_argument("--negative-prompt", default=None,
                        help='steer CFG away from this text instead of ""')

    g = sub.add_parser("generate", help="text-to-image, no editing")
    model_opts(g); sampling_opts(g); negative_opt(g)
    g.add_argument("--prompt", required=True)
    g.add_argument("--out", default="outputs/image.png",
                   help="output path; seed index suffixed when sweeping")
    g.add_argument("--batch-seeds", action="store_true",
                   help="run the whole seed sweep as one batched program "
                        "through the dp sweep engine")
    g.set_defaults(fn=cmd_generate)

    e = sub.add_parser("edit", help="prompt-to-prompt edit with seed sweep")
    model_opts(e); sampling_opts(e); edit_opts(e); negative_opt(e)
    e.add_argument("--source", required=True, help="source prompt")
    e.add_argument("--target", required=True, help="edited prompt")
    e.add_argument("--out-dir", default=None)
    e.add_argument("--batch-seeds", action="store_true",
                   help="run the whole seed sweep as batched edit groups "
                        "through the dp sweep engine (two compiled programs "
                        "total instead of two per seed; sharded over the "
                        "mesh when more than one device is visible)")
    e.add_argument("--attn-maps", default=None, metavar="DIR",
                   help="also write per-token cross-attention heatmaps of "
                        "the edited prompt (the reference's "
                        "show_cross_attention) into DIR")
    e.add_argument("--self-attn-maps", default=None, metavar="DIR",
                   help="also write the top-10 self-attention SVD "
                        "components of the edited image (the reference's "
                        "show_self_attention_comp) into DIR")
    e.set_defaults(fn=cmd_edit)

    # Inversion is DDIM by construction (`/root/reference/null_text.py:23`);
    # no --scheduler/--seeds here.
    i = sub.add_parser("invert", help="null-text inversion of a real image")
    model_opts(i)
    i.add_argument("--steps", type=int, default=50)
    i.add_argument("--image", required=True)
    i.add_argument("--prompt", required=True)
    i.add_argument("--artifact", default="outputs/inversion.npz")
    i.add_argument("--inner-steps", type=int, default=10)
    i.add_argument("--out-dir", default=None,
                   help="also write gt.png / vae_rec.png here")
    i.set_defaults(fn=cmd_invert)

    # Replay inherits step count and scheduler from the artifact.
    r = sub.add_parser("replay", help="edit a previously inverted image")
    model_opts(r); edit_opts(r)
    r.add_argument("--artifact", required=True)
    r.add_argument("--target", action="append", default=None,
                   help="edited prompt; repeatable for a target sweep "
                        "(omit for pure reconstruction)")
    r.add_argument("--out-dir", default=None)
    r.add_argument("--batch-targets", action="store_true",
                   help="run all --target edits of the artifact as one "
                        "batched program through the dp sweep engine "
                        "(one edit group per target, sharded over the mesh; "
                        "all targets share --mode/--blend-words/--equalizer)")
    r.set_defaults(fn=cmd_replay)

    s = sub.add_parser(
        "serve",
        help="request-level serving: JSONL requests in, JSONL records out")
    model_opts(s, guidance=False, metrics=False, profile=False)
    s.add_argument("--requests", required=True,
                   help="JSONL request trace: a file, a FIFO, or '-' for "
                        "stdin (schema: docs/SERVING.md; generator: "
                        "tools/loadgen.py)")
    s.add_argument("--results", default=None, metavar="FILE",
                   help="write per-request result records here "
                        "(default: stdout)")
    s.add_argument("--out-dir", default=None, metavar="DIR",
                   help="also write served images here "
                        "(<id>.png, or <id>_y.png/<id>_y_hat.png for edits)")
    s.add_argument("--max-batch", type=int, default=8, choices=(1, 2, 4, 8),
                   help="flush a compile-key bucket at this many requests "
                        "(must be one of the fixed padding buckets)")
    s.add_argument("--max-wait-ms", type=float, default=50.0,
                   help="flush a partial bucket after its oldest request "
                        "has waited this long")
    s.add_argument("--phase2-max-batch", type=int, default=None,
                   choices=(1, 2, 4, 8), metavar="N",
                   help="lane-bucket cap of the phase-2 pool (gated "
                        "requests past the hand-off; default: one fixed "
                        "bucket above --max-batch — phase-2 lanes carry no "
                        "CFG uncond half, so 2x the lanes fit the same "
                        "peak footprint)")
    s.add_argument("--mesh", default=None, metavar="dp=N",
                   help="mesh-parallel serving: shard every dispatched "
                        "batch over an N-device data-parallel mesh (lane "
                        "buckets become per-device sub-batches; --max-batch "
                        "and --phase2-max-batch keep their per-device "
                        "meaning, so the global bucket set scales to "
                        "N x {1,2,4,8}). N must be a power of two and at "
                        "most the process's device count. dp=1 is bitwise-"
                        "identical to serving without the flag; journal/"
                        "drain/crash semantics are mesh-agnostic "
                        "(docs/SERVING.md#mesh-parallel-serving)")
    s.add_argument("--elastic", default=None, nargs="?", const="on",
                   metavar="on|k=v,...",
                   help="elastic mesh serving: a pressure-driven controller "
                        "resizes the data-parallel mesh between powers of "
                        "two while serving (prewarm-before-cutover, "
                        "journaled resize protocol, in-flight work parks "
                        "and resumes exactly-once). 'on' takes the "
                        "defaults; otherwise a comma list over up_depth/"
                        "up_window_ms/down_depth/down_window_ms/"
                        "cooldown_ms/min_dp/max_dp. Combines with --mesh "
                        "as the starting topology (default dp=1) — "
                        "docs/SERVING.md#elastic-meshes")
    s.add_argument("--single-pool", action="store_true",
                   help="disable phase-disaggregated continuous batching: "
                        "gated requests run their monolithic program in "
                        "one pool (the pre-disaggregation engine; the A/B "
                        "baseline bench.py compares against)")
    s.add_argument("--schedule", default=None, metavar="FILE",
                   help="default per-site reuse schedule artifact (JSON, "
                        "e.g. tools/schedules/default_v1.json) applied to "
                        "every request that sets neither 'gate' nor its "
                        "own 'schedule' field; per-request schedules "
                        "override (docs/SERVING.md)")
    s.add_argument("--queue-cap", type=int, default=64,
                   help="admission bound on outstanding requests; beyond "
                        "it, requests are rejected with a reason "
                        "(backpressure, never a silent drop)")
    s.add_argument("--program-cache-cap", type=int, default=8,
                   help="LRU capacity of the compiled-program cache")
    s.add_argument("--no-prewarm", action="store_true",
                   help="skip compile-ahead of the first request's program "
                        "(compiles then happen in-band on first dispatch)")
    s.add_argument("--metrics-out", default=None, metavar="FILE",
                   help="write a Prometheus text snapshot of the serve "
                        "telemetry registry (queue depth, stage-latency "
                        "histograms, program-cache counters, memory "
                        "gauges) here after the trace drains "
                        "(docs/OBSERVABILITY.md)")
    s.add_argument("--events-out", default=None, metavar="FILE",
                   help="write the structured span event log "
                        "(serve.prewarm / serve.batch / serve.isolate_retry "
                        "start/stop events, JSONL) here after the trace "
                        "drains")
    s.add_argument("--events-ring", type=int, default=None, metavar="N",
                   help="span ring-buffer capacity (default 4096, or the "
                        "P2P_OBS_EVENTS_RING env var): two-pool serving "
                        "roughly doubles event volume, and an overflowing "
                        "ring silently evicts mid-trace — the --events-out "
                        "meta line's dropped count says when to raise this")
    s.add_argument("--flight-out", default=None, metavar="FILE",
                   help="request-scoped flight tracing: write one JSONL "
                        "flight record per terminal (ordered stage "
                        "segments across both pools, hand-off links, "
                        "attribution self-check) here after the trace "
                        "drains (docs/OBSERVABILITY.md)")
    s.add_argument("--trace-out", default=None, metavar="FILE",
                   help="write a Chrome-trace/Perfetto JSON of the run "
                        "(one track per program pool, one async flow per "
                        "request, hand-off arrows) here after the trace "
                        "drains — open in https://ui.perfetto.dev or "
                        "chrome://tracing")
    s.add_argument("--blackbox", default=None, metavar="DIR",
                   help="arm the flight recorder: on a fatal drain or a "
                        "watchdog kill, dump a post-mortem bundle (span "
                        "ring tail, in-flight flight records, pool/queue "
                        "snapshot) into a numbered subdirectory of DIR")
    s.add_argument("--journal", default=None, metavar="FILE",
                   help="crash-safe request journal (append-only JSONL WAL, "
                        "fsync'd at batch boundaries); restarting against "
                        "the same file warm-restarts from the snapshot + "
                        "WAL tail: non-terminal requests replay exactly "
                        "once, already-resolved ids are deduped "
                        "(docs/SERVING.md#lifecycle)")
    s.add_argument("--snapshot-every-ms", type=float, default=None,
                   metavar="MS",
                   help="periodic journal snapshot+compaction on the "
                        "virtual clock (needs --journal): the replay-"
                        "folded state is written atomically next to the "
                        "WAL, the WAL rotates, and orphaned carry spills "
                        "are garbage-collected — restart cost becomes "
                        "O(traffic since the last snapshot)")
    s.add_argument("--drain-timeout-ms", type=float, default=None,
                   metavar="MS",
                   help="wall-clock budget for the graceful drain "
                        "(SIGTERM/Ctrl-C): past it the loop falls back to "
                        "snapshot-and-exit — journaled leftovers stay "
                        "pending for the warm restart, un-journaled ones "
                        "resolve to 'rejected' draining records "
                        "(default: unbounded)")
    s.add_argument("--chaos-plan", default=None, metavar="FILE",
                   help="deterministic fault-injection plan (JSON, see "
                        "p2p_tpu/serve/chaos.py; generator: tools/loadgen.py "
                        "--fault-rate). Drill tooling — never set this in "
                        "production")
    s.add_argument("--watchdog-ms", type=float, default=None, metavar="MS",
                   help="arm a wall-clock watchdog around each dispatched "
                        "batch: a compile/execute that hangs past this "
                        "deadline (with no step progress) becomes 'timeout' "
                        "records and a quarantined program-cache entry "
                        "instead of a wedged server")
    s.add_argument("--validate-outputs", action="store_true",
                   help="post-run finite check per lane (one jnp.isfinite "
                        "reduction off the hot path): NaN/Inf lanes resolve "
                        "to 'invalid_output' instead of shipping black "
                        "images")
    s.add_argument("--degrade-depth", type=int, default=None, metavar="N",
                   help="enable graceful degradation: when outstanding "
                        "work stays above N for --degrade-window-ms, the "
                        "loop steps down (force gate='auto' -> shrink max "
                        "bucket -> shed) before rejecting")
    s.add_argument("--degrade-window-ms", type=float, default=2000.0,
                   metavar="MS",
                   help="sustained-pressure window per degradation step "
                        "(and sustained-calm window per recovery step)")
    s.add_argument("--degrade-min-bucket", type=int, default=2,
                   choices=(1, 2, 4),
                   help="floor for the level-2 max-lane-bucket shrink")
    s.add_argument("--slo", action="store_true",
                   help="enable SLO-tiered multi-tenant scheduling: "
                        "requests carrying tenant/tier fields get "
                        "weighted-fair admission ordering, tier-pure "
                        "batches, tier-ordered dispatch, and per-tier "
                        "degradation (best-effort sheds first; premium "
                        "is exempt from the level-1 force-gate) — "
                        "docs/SERVING.md#slo-tiers-and-preemption")
    s.add_argument("--tenant-quota", type=int, default=None, metavar="N",
                   help="max outstanding requests per named tenant "
                        "(implies --slo); excess submissions reject with "
                        "the 'quota' kind")
    s.add_argument("--preempt-depth", type=int, default=None, metavar="N",
                   help="phase-boundary preemption (implies --slo): when "
                        "outstanding work exceeds N while higher-tier "
                        "work waits, lower-tier requests parked between "
                        "their phases spill their carry (journaled "
                        "'preempted' record) and resume when pressure "
                        "clears")
    s.add_argument("--cache", action="store_true",
                   help="enable content-addressed semantic caching "
                        "(ISSUE 13): requests are keyed by every output-"
                        "determining field and served from three layers — "
                        "text-encoder outputs, phase-1 carry prefixes "
                        "(a prefix hit enters the engine directly in "
                        "phase 2) and bitwise exact results with single-"
                        "flight collapsing of identical in-flight "
                        "requests. Off (the default), the record stream, "
                        "journal bytes and metric families are byte-"
                        "identical to the cache-less engine — "
                        "docs/SERVING.md#semantic-caching")
    s.add_argument("--cache-dir", default=None, metavar="DIR",
                   help="spill directory for the cache's L2/L3 sidecar "
                        "files (content-addressed .npz; needs --cache; "
                        "default: a fresh tempdir). With --journal, "
                        "reusing the directory across restarts is what "
                        "lets a journaled insert serve followers after a "
                        "crash")
    s.add_argument("--cost", action="store_true",
                   help="enable the cost observatory (obs/costmodel.py, "
                        "docs/OBSERVABILITY.md): every program-cache miss "
                        "records an XLA cost card (flops, bytes, roofline "
                        "verdict, predicted ms) with compile_ms split into "
                        "build vs warm, every dispatch a measured-MFU "
                        "observation, and the summary gains a `cost` "
                        "block; per-request records are byte-identical "
                        "either way")
    s.add_argument("--programs-out", default=None, metavar="FILE",
                   help="write one JSON line per recorded program cost "
                        "card after the trace drains (implies --cost); "
                        "the artifact tools/perfscope.py --programs "
                        "renders")
    s.add_argument("--cache-l3-bytes", type=int, default=None, metavar="B",
                   help="in-memory byte budget for the exact-result layer "
                        "(LRU; eviction deletes the spill too; "
                        "default 256 MiB)")
    s.add_argument("--profile", default=None, metavar="DIR",
                   help="enable in-engine sampled device profiling "
                        "(ISSUE 18, docs/OBSERVABILITY.md#production-"
                        "profiling): every Nth dispatch (deterministic, "
                        "seeded, per-pool) runs under a programmatic "
                        "jax.profiler capture into a bounded trace ring "
                        "under DIR; captures fold into DIR/"
                        "workload_profile.json — the measured seed "
                        "artifact tools/schedule_search.py --profile and "
                        "tools/perfscope.py --sites consume — and EWMA "
                        "drift sentinels journal profile_drift events. "
                        "Off (the default), records, journal and "
                        "programs are byte-identical")
    s.add_argument("--profile-every", type=int, default=8, metavar="N",
                   help="sampling period: capture ~1 of every N "
                        "dispatches per pool (hash-mod on the seeded "
                        "plan, so the sampled set is reproducible; "
                        "default 8; 1 captures everything)")
    s.add_argument("--profile-seed", type=int, default=0, metavar="S",
                   help="sampling-plan seed (same seed => same sampled "
                        "dispatch set; default 0)")
    s.add_argument("--profile-ring-bytes", type=int, default=256 << 20,
                   metavar="B",
                   help="trace-ring size cap: oldest committed captures "
                        "are evicted past it (default 256 MiB)")
    s.add_argument("--profile-ring-count", type=int, default=16,
                   metavar="N",
                   help="trace-ring count cap (default 16 captures)")
    s.set_defaults(fn=cmd_serve)

    c = sub.add_parser(
        "check", help="checkpoint-readiness report (no weights loaded), "
                      "or --static: the jaxcheck static analyzer")
    c.add_argument("checkpoint_dir", nargs="?", default=None)
    c.add_argument("--preset", default=None,
                   choices=("sd14", "sd21", "sd21base", "ldm256"))
    c.add_argument("--static", action="store_true",
                   help="run the three-pass static analyzer instead (AST "
                        "lints + traced-program contracts + the "
                        "shardcheck collective-budget pass — "
                        "docs/STATIC_ANALYSIS.md); exits nonzero on new "
                        "findings or contract violations. Forces the "
                        "virtual 8-device CPU platform so the shardcheck "
                        "dp sweep matches the CI driver's. Full flag "
                        "surface (--only, --fix, --update-baseline): "
                        "tools/jaxcheck.py")
    c.add_argument("--ast-only", action="store_true",
                   help="with --static: skip the (slower) traced-program "
                        "and shardcheck passes")
    c.set_defaults(fn=cmd_check)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from .utils.cache import enable_persistent_cache

    enable_persistent_cache()
    try:
        return args.fn(args)
    except KeyboardInterrupt:
        # Ctrl-C outside a command's own graceful path (serve drains; see
        # cmd_serve) is a clean exit, never a raw traceback.
        print("interrupted", file=sys.stderr)
        return 130


if __name__ == "__main__":
    sys.exit(main())
