"""Request-scoped flight tracing for the two-pool serve engine.

PR 3's observability layer is process-global: the span ring and the metric
registry aggregate *across* requests, so once a gated request spans two
separately scheduled program pools (phase-disaggregated continuous
batching), a spill-to-disk carry hand-off, retries, isolation re-runs and
possibly a crash-replay in a different process, no single artifact can
answer "where did request X's latency go". This module is that missing
layer: every admitted request gets a **trace context** —

    trace_id = "<request_id>#<attempt epoch>"

created at admission (epoch 0) and propagated through queue wait, batcher
residency, phase-1 dispatch, the carry spill, the phase-2 batcher and
dispatch, transient retries/backoff, isolation re-runs, degradation
actions, and the terminal record. The journal's ``handoff`` record carries
the context (:meth:`FlightTracer.context`), so a request resumed in
phase 2 *by a different process* after a crash gets a stitched timeline:
epoch bumps to 1, the pre-crash phase-1 segments ride along tagged with
their original epoch, and an explicit ``handoff_resumed`` causal link
names the pre-crash trace id.

Three artifacts come out of the tracer:

- **Flight records** (:attr:`FlightTracer.records`, ``serve --flight-out``)
  — one JSON object per *terminal*: the ordered stage segments
  (``queue_wait`` / ``fault`` / ``backoff`` / ``compile`` / ``run`` /
  ``handoff_wait`` / ``requeue_wait``, each with virtual-clock start +
  duration and its pool), the causal events, and a self-check that the
  segment attribution sums to the recorded total
  (``attribution_ok``/``unattributed_ms``) — queue + compile + run +
  backoff + hand-off-wait must account for every virtual millisecond of
  an ``ok`` request's life.
- **Chrome trace** (:func:`chrome_trace`, ``serve --trace-out``) — the
  Perfetto/``chrome://tracing`` JSON view: one track per pool
  (mono / phase1 / phase2), stage segments as complete events, one async
  span per request from admission to terminal, and a flow arrow from each
  phase-1 ``run`` to its phase-2 ``run`` — the two-pool packing behavior
  is literally visible.
- **Blackbox bundle** (:meth:`FlightTracer.blackbox`, ``serve --blackbox
  DIR``) — the post-mortem flight recorder: on a fatal drain or a
  watchdog kill the engine dumps the span ring tail, every in-flight
  (unfinished) flight context, the finished records so far, and a
  pool/queue snapshot into a numbered bundle directory.

Everything is host-side and virtual-clock-driven: the tracer never touches
a traced program (the ``trace-invisible`` jaxpr contract in
``analysis.contracts`` pins this), never reads the wall clock itself
(every timestamp is handed in by the engine), and with a deterministic
runner/timer the flight records are **byte-identical across reruns** —
including the crash-resumed stitched timeline. ``flight=None`` (the
default everywhere) keeps the serve record stream byte-identical to a
tracer-enabled run: flight records are a sidecar artifact, never a change
to the per-request contract.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from . import metrics as metrics_mod
from . import spans as spans_mod

#: Stages whose durations are the latency *attribution* of a request: they
#: tile [arrival, terminal] in virtual time, so their sum must equal the
#: recorded total (the flight record's self-check). ``preempt_wait`` is
#: the span a request spent *parked* by the SLO scheduler's phase-boundary
#: preemption (serve.scheduling) — split out of the hand-off wait so the
#: scheduler owns its own milliseconds.
#: ``cache_hit`` (ISSUE 13) is the whole lifetime of a request served from
#: the semantic cache (an L3 exact hit or a single-flight follower): no
#: compute ran, so the one stage owns [arrival, terminal] entirely.
ATTRIBUTION_STAGES = ("queue_wait", "handoff_wait", "preempt_wait",
                      "requeue_wait", "fault", "backoff", "compile", "run",
                      "cache_hit")


def trace_id(request_id: str, epoch: int) -> str:
    return f"{request_id}#{epoch}"


class FlightTracer:
    """Per-request flight recorder for one serve loop.

    The engine owns the clock: every call takes virtual-time values, and
    the tracer only stores and assembles — which is what makes records
    deterministic. One tracer covers one ``serve_forever`` run; the CLI
    builds one per serve invocation.
    """

    def __init__(self, blackbox_dir: Optional[str] = None):
        self.records: List[dict] = []
        self.blackbox_dir = blackbox_dir
        self.blackbox_bundles: List[str] = []
        self.loop_events: List[dict] = []
        self._inflight: Dict[str, dict] = {}
        self._bundle_seq = 0
        self._m_records = metrics_mod.registry().counter(
            "serve_flight_records_total",
            "terminal flight records by status", labels=("status",))

    # -- context lifecycle -------------------------------------------------

    def admit(self, request_id: str, vnow: float, *,
              arrival_ms: Optional[float] = None, gated: bool = False,
              forced_gate: bool = False, replayed: bool = False) -> dict:
        """Open a trace context at admission (epoch 0). ``arrival_ms`` is
        the request's *trace* arrival — latency accounting starts there,
        exactly like the queue's (time blocked behind a running batch
        before the single-threaded loop admitted it is real queue wait);
        it defaults to ``vnow``. ``replayed`` marks a WAL-pending request
        re-queued by a restarted loop (its arrival restarts on the new
        incarnation's clock)."""
        arrival = vnow if arrival_ms is None else arrival_ms
        ctx = {"trace_id": trace_id(request_id, 0),
               "request_id": request_id, "epoch": 0,
               "arrival_ms": arrival, "cursor_ms": arrival,
               "gated": gated, "segments": [], "events": [], "links": []}
        self._inflight[request_id] = ctx
        self.event(request_id, "admitted", vnow,
                   **({"forced_gate": True} if forced_gate else {}),
                   **({"replayed": True} if replayed else {}))
        return ctx

    def resume(self, request_id: str, prior: Optional[dict],
               vnow: float) -> dict:
        """Open a stitched context for a crash-replayed request resuming in
        phase 2 off its journaled carry: the attempt epoch bumps, the
        pre-crash segments/events ride along under their original epoch,
        and a ``handoff_resumed`` link names the pre-crash trace id."""
        prior = prior if isinstance(prior, dict) else {}
        prev_epoch = int(prior.get("epoch", 0))
        epoch = prev_epoch + 1
        ctx = {"trace_id": trace_id(request_id, epoch),
               "request_id": request_id, "epoch": epoch,
               "arrival_ms": vnow, "cursor_ms": vnow,
               "gated": True, "resumed": True,
               "segments": list(prior.get("segments", ())),
               "events": list(prior.get("events", ())),
               "links": [{"kind": "handoff_resumed",
                          "from": prior.get("trace_id",
                                            trace_id(request_id,
                                                     prev_epoch))}]}
        self._inflight[request_id] = ctx
        self.event(request_id, "handoff_resumed", vnow)
        return ctx

    def current_trace_id(self, request_id: str) -> str:
        ctx = self._inflight.get(request_id)
        return ctx["trace_id"] if ctx else trace_id(request_id, 0)

    def context(self, request_id: str) -> Optional[dict]:
        """The serializable context the journal's ``handoff`` record
        carries — everything a restarted process needs to stitch the
        resumed timeline to this incarnation's segments."""
        ctx = self._inflight.get(request_id)
        if ctx is None:
            return None
        return {"trace_id": ctx["trace_id"], "epoch": ctx["epoch"],
                "segments": list(ctx["segments"]),
                "events": list(ctx["events"])}

    # -- timeline building -------------------------------------------------

    def _ctx(self, request_id: str) -> dict:
        ctx = self._inflight.get(request_id)
        if ctx is None:          # e.g. a rejected submission: minimal ctx
            ctx = self.admit(request_id, 0.0)
        return ctx

    def segment(self, request_id: str, stage: str, start_ms: float,
                dur_ms: float, *, pool: Optional[str] = None,
                **attrs: Any) -> None:
        """Record one stage segment and advance the attribution cursor to
        its end (segments are contiguous by construction)."""
        ctx = self._ctx(request_id)
        seg = {"stage": stage, "start_ms": start_ms, "dur_ms": dur_ms,
               "epoch": ctx["epoch"]}
        if pool is not None:
            seg["pool"] = pool
        seg.update(attrs)
        ctx["segments"].append(seg)
        ctx["cursor_ms"] = start_ms + dur_ms

    def wait(self, request_id: str, stage: str, until_ms: float, *,
             pool: Optional[str] = None, **attrs: Any) -> None:
        """A wait segment from the context's cursor (end of the previous
        segment, or arrival) to ``until_ms`` — how queue waits, hand-off
        waits and isolation re-queues are attributed without the call
        sites tracking interval starts."""
        ctx = self._ctx(request_id)
        start = ctx["cursor_ms"]
        self.segment(request_id, stage, start,
                     max(0.0, until_ms - start), pool=pool, **attrs)

    def event(self, request_id: str, kind: str, vnow: float,
              **attrs: Any) -> None:
        ctx = self._ctx(request_id)
        ctx["events"].append({"kind": kind, "ts_ms": vnow,
                              "epoch": ctx["epoch"], **attrs})

    def loop_event(self, kind: str, vnow: float, **attrs: Any) -> None:
        """Loop-level transitions with no single owning request
        (degradation level changes, fatal faults) — surfaced in the
        Chrome trace as instants and in every blackbox bundle."""
        self.loop_events.append({"kind": kind, "ts_ms": vnow, **attrs})

    # -- terminal ----------------------------------------------------------

    def finish(self, request_id: str, status: str, vnow: float, *,
               total_ms: Optional[float] = None,
               reason: Optional[str] = None) -> dict:
        """Close the context into a flight record (one per terminal).

        The self-check: the final epoch's attribution segments must sum to
        the recorded total — exact (to float tolerance) under the virtual
        clock for served requests; non-ok terminals report the residual
        without a verdict (an expired request legitimately has unattributed
        wait)."""
        ctx = self._inflight.pop(request_id, None)
        if ctx is None:
            ctx = {"trace_id": trace_id(request_id, 0),
                   "request_id": request_id, "epoch": 0,
                   "arrival_ms": vnow, "gated": False,
                   "segments": [], "events": [], "links": []}
        if total_ms is None:
            total_ms = vnow - ctx["arrival_ms"]
        attributed = sum(s["dur_ms"] for s in ctx["segments"]
                         if s["epoch"] == ctx["epoch"]
                         and s["stage"] in ATTRIBUTION_STAGES)
        rec = {"trace_id": ctx["trace_id"],
               "request_id": request_id,
               "epoch": ctx["epoch"],
               "status": status,
               "gated": ctx["gated"],
               "arrival_ms": ctx["arrival_ms"],
               "terminal_ms": vnow,
               "total_ms": total_ms,
               "attributed_ms": attributed,
               "unattributed_ms": total_ms - attributed,
               "links": ctx["links"],
               "segments": ctx["segments"],
               "events": ctx["events"] + [{"kind": "terminal",
                                           "ts_ms": vnow,
                                           "epoch": ctx["epoch"],
                                           "status": status}]}
        if ctx.get("resumed"):
            rec["resumed"] = True
        if reason is not None:
            rec["reason"] = reason
        if status == "ok":
            rec["attribution_ok"] = abs(rec["unattributed_ms"]) <= 1e-6
        self.records.append(rec)
        self._m_records.labels(status=status).inc()
        return rec

    def inflight(self) -> List[dict]:
        """Snapshot of every open context (admission order) — what the
        blackbox preserves for requests that never reached a terminal."""
        return [dict(ctx) for ctx in self._inflight.values()]

    # -- the flight recorder -----------------------------------------------

    def blackbox(self, reason: str, state: Optional[dict] = None,
                 extras: Optional[dict] = None) -> Optional[str]:
        """Dump a post-mortem bundle (no-op without ``blackbox_dir``):

        - ``state.json``   — the dump reason, the engine's pool/queue
          snapshot, and the loop-level event list
        - ``events.jsonl`` — the span ring tail (meta line first, so a
          truncated view is detectable)
        - ``inflight.jsonl`` — one line per open flight context
        - ``flights.jsonl``  — the flight records finished before the dump
        - one ``<name>.json`` per ``extras`` entry — sidecar context
          other subsystems attach at the dump site (ISSUE 18: the
          production profiler's latest workload-profile snapshot and
          active sampling plan, so a FATAL verdict ships with the
          performance context that preceded it)

        Bundles are numbered (``000_watchdog_timeout/``...) so repeated
        incidents in one run never clobber each other. Returns the bundle
        path, or None when disabled."""
        if not self.blackbox_dir:
            return None
        slug = "".join(c if c.isalnum() else "_" for c in reason[:40])
        bundle = os.path.join(self.blackbox_dir,
                              f"{self._bundle_seq:03d}_{slug}")
        self._bundle_seq += 1
        os.makedirs(bundle, exist_ok=True)
        with open(os.path.join(bundle, "state.json"), "w") as f:
            json.dump({"reason": reason, "state": state or {},
                       "loop_events": self.loop_events}, f, indent=1)
            f.write("\n")
        with open(os.path.join(bundle, "events.jsonl"), "w") as f:
            spans_mod.write_jsonl(f)
        with open(os.path.join(bundle, "inflight.jsonl"), "w") as f:
            for ctx in self.inflight():
                f.write(json.dumps(ctx) + "\n")
        with open(os.path.join(bundle, "flights.jsonl"), "w") as f:
            for rec in self.records:
                f.write(json.dumps(rec) + "\n")
        for name, doc in (extras or {}).items():
            slug = "".join(c if c.isalnum() else "_" for c in name[:40])
            with open(os.path.join(bundle, f"{slug}.json"), "w") as f:
                json.dump(doc, f, indent=1)
                f.write("\n")
        self.blackbox_bundles.append(bundle)
        return bundle


def write_flight_jsonl(fp, records: List[dict]) -> int:
    """One JSON line per flight record; returns lines written."""
    n = 0
    for rec in records:
        fp.write(json.dumps(rec) + "\n")
        n += 1
    return n


# ---------------------------------------------------------------------------
# Chrome-trace / Perfetto export
# ---------------------------------------------------------------------------

_POOL_TIDS = {"mono": 1, "phase1": 2, "phase2": 3}
_PID = 1


def chrome_trace(tracer_or_records, loop_events: Optional[List[dict]] = None
                 ) -> dict:
    """Render flight records as a Chrome-trace JSON object (the
    ``chrome://tracing`` / Perfetto ``trace.json`` format; timestamps are
    the virtual clock in microseconds):

    - one **track (thread) per pool** — ``mono``, ``phase1``, ``phase2`` —
      carrying every stage segment as a complete (``X``) event, so the
      two pools' packing is visible side by side;
    - one **async span per request** (``b``/``e`` with ``id=trace_id``)
      from arrival to terminal on its own async track;
    - a **flow arrow** (``s``→``f``) from each phase-1 ``run`` segment to
      the same request's phase-2 ``run``, crossing the hand-off;
    - loop-level events (degradation, fatal) as instant (``i``) events.

    A crash-stitched record's earlier-epoch segments carry the *previous
    process's* virtual clock; they are rebased to end exactly at the
    resumed incarnation's arrival, so the pre-crash phase-1 work renders
    immediately before the resume (inside the request's async span) and
    the hand-off flow arrow always points forward in time. If the rebase
    reaches below zero, the whole trace is shifted up uniformly —
    relative layout is the contract, the virtual epoch origin is not.
    """
    if isinstance(tracer_or_records, FlightTracer):
        records = tracer_or_records.records
        loop_events = (tracer_or_records.loop_events
                       if loop_events is None else loop_events)
    else:
        records = list(tracer_or_records)
    events: List[dict] = [
        {"ph": "M", "pid": _PID, "tid": 0, "name": "process_name",
         "args": {"name": "p2p-tpu serve (virtual clock)"}},
    ]
    for pool, tid in sorted(_POOL_TIDS.items(), key=lambda kv: kv[1]):
        events.append({"ph": "M", "pid": _PID, "tid": tid,
                       "name": "thread_name", "args": {"name": f"pool:{pool}"}})

    def us(ms: float) -> float:
        return round(ms * 1000.0, 3)

    for rec in records:
        tid_async = rec["trace_id"]
        # Rebase earlier-epoch (pre-crash) segments: they were stamped on
        # the previous process's virtual clock, so slide them to end at
        # this incarnation's arrival — causally just before the resume.
        prior = [s for s in rec["segments"] if s["epoch"] < rec["epoch"]]
        rebase = 0.0
        if prior:
            rebase = rec["arrival_ms"] - max(
                s["start_ms"] + s["dur_ms"] for s in prior)

        def seg_start(seg, rebase=rebase, epoch=rec["epoch"]):
            return seg["start_ms"] + (rebase if seg["epoch"] < epoch
                                      else 0.0)

        begin_ms = rec["arrival_ms"]
        if prior:
            begin_ms = min(begin_ms, min(seg_start(s) for s in prior))
        events.append({"ph": "b", "cat": "request", "id": tid_async,
                       "pid": _PID, "tid": 0, "name": rec["request_id"],
                       "ts": us(begin_ms),
                       "args": {"status": rec["status"],
                                "gated": rec["gated"]}})
        flow_end: Optional[float] = None
        for seg in rec["segments"]:
            pool = seg.get("pool", "mono")
            start = seg_start(seg)
            ev = {"ph": "X", "cat": seg["stage"],
                  "name": seg["stage"], "pid": _PID,
                  "tid": _POOL_TIDS.get(pool, 1),
                  "ts": us(start), "dur": us(seg["dur_ms"]),
                  "args": {"trace_id": rec["trace_id"],
                           "epoch": seg["epoch"]}}
            events.append(ev)
            if seg["stage"] == "run":
                if pool == "phase1":
                    flow_end = start + seg["dur_ms"]
                elif pool == "phase2" and flow_end is not None:
                    fid = rec["trace_id"] + "/handoff"
                    events.append({
                        "ph": "s", "cat": "handoff", "id": fid,
                        "name": "handoff", "pid": _PID,
                        "tid": _POOL_TIDS["phase1"],
                        "ts": us(min(flow_end, start))})
                    events.append({
                        "ph": "f", "cat": "handoff", "id": fid,
                        "name": "handoff", "bp": "e", "pid": _PID,
                        "tid": _POOL_TIDS["phase2"],
                        "ts": us(start)})
                    flow_end = None
        events.append({"ph": "e", "cat": "request", "id": tid_async,
                       "pid": _PID, "tid": 0, "name": rec["request_id"],
                       "ts": us(rec["terminal_ms"])})
    for ev in (loop_events or ()):
        events.append({"ph": "i", "cat": "loop", "s": "g",
                       "name": ev["kind"], "pid": _PID, "tid": 0,
                       "ts": us(ev["ts_ms"]),
                       "args": {k: v for k, v in ev.items()
                                if k not in ("kind", "ts_ms")}})
    # The rebase can reach below the epoch origin (a pre-crash history
    # longer than the resumed arrival offset): shift the whole trace up
    # uniformly so every timestamp is non-negative.
    min_ts = min((e["ts"] for e in events if "ts" in e), default=0.0)
    if min_ts < 0:
        for e in events:
            if "ts" in e:
                e["ts"] = round(e["ts"] - min_ts, 3)
    return {"traceEvents": events, "displayTimeUnit": "ms"}
