"""traceparse — shared chrome-trace / workload-profile parsing (ISSUE 18).

The ``perfscope --sites`` named_scope parser, factored out of the CLI so
the serve engine's production profiler (:mod:`p2p_tpu.obs.prodscope`) and
the tools (``perfscope``, ``schedule_search``) fold traces through one
code path. Three layers:

- **Chrome-trace loading** (:func:`load_trace_events`,
  :func:`parse_site_trace`): gz-aware ``traceEvents`` extraction and the
  PR-15 per-attention-site duration fold, behavior-identical to the old
  ``tools/perfscope.py`` implementation.
- **HLO op→site indexing** (:func:`op_site_index`,
  :func:`fold_site_events`): on CPU (and on device backends that emit
  bare HLO op names) trace events carry ``args.hlo_op`` — not the
  ``named_scope`` path. But the *compiled HLO text* keeps the full scope
  path in per-instruction ``metadata={op_name="..."}``. Indexing
  instruction names to sites at program-build time (fusions attributed to
  the dominant site of their called computation) lets the event fold
  recover genuinely measured per-site durations from traces whose event
  names alone carry no site information.
- **WorkloadProfile format** (:data:`PROFILE_FORMAT`,
  :func:`is_workload_profile`, :func:`load_workload_profile`,
  :func:`profile_sites`, :func:`validate_profile`): the durable ledger
  the profiler writes and ``schedule_search --profile`` /
  ``perfscope --sites`` consume. Format confusion (a ledger where a
  trace was expected, or vice versa) is a loud ``ValueError`` naming
  both formats — never a silent empty table.

Stdlib-only on purpose: tools import it without pulling jax.
"""

from __future__ import annotations

import gzip
import json
import re
from collections import Counter
from typing import Dict, List, Optional, Tuple

#: Format sentinel every WorkloadProfile ledger carries under ``format``.
PROFILE_FORMAT = "p2p-workload-profile/v1"

#: An attention site name as it appears inside named_scope paths and HLO
#: op metadata: ``cross_attn/down3``, ``self_attn/mid0``, ...
SITE_RE = re.compile(r"(cross_attn|self_attn)/(?:down|mid|up)\d+")

# HLO-text structure: a computation header opens a ``{`` block, each
# instruction line is ``%name = ... metadata={op_name="scope/path" ...}``,
# and fusion instructions name their called computation via ``calls=``.
_COMP_RE = re.compile(
    r"^(?:ENTRY\s+)?%?([A-Za-z0-9_.\-]+)\s*(?:\([^)]*\))?\s*->.*\{\s*$")
_INSTR_RE = re.compile(
    r'^\s*(?:ROOT\s+)?%([A-Za-z0-9_.\-]+)\s*=\s.*'
    r'metadata=\{[^}]*op_name="([^"]+)"')
_FUSION_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%([A-Za-z0-9_.\-]+)\s*=\s.*fusion\(.*"
    r"calls=%?([A-Za-z0-9_.\-]+)")

#: Top-level keys a v1 ledger must carry (schema table in
#: docs/OBSERVABILITY.md mirrors this).
PROFILE_REQUIRED_KEYS = (
    "format", "version", "tags", "window", "captures", "sites",
    "programs", "phases", "kernels", "schedule_segments",
    "stage_histograms", "device_memory", "drift", "overhead",
)


def _load_json(path: str):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        return json.load(f)


def load_trace_events(path: str) -> list:
    """Chrome-trace events from ``path`` (``traceEvents`` object or bare
    event list, ``.gz``-compressed or not). Loud on format confusion:
    handing it a WorkloadProfile ledger is a ``ValueError`` naming the
    right flag, never an empty fold."""
    data = _load_json(path)
    if isinstance(data, dict) and is_workload_profile(data):
        raise ValueError(
            f"{path}: this is a WorkloadProfile ledger "
            f"({PROFILE_FORMAT}), not a chrome trace — pass it where a "
            "profile is accepted (perfscope --sites auto-detects it; "
            "schedule_search takes --profile)")
    events = data.get("traceEvents", data) if isinstance(data, dict) \
        else data
    if not isinstance(events, list):
        raise ValueError(f"{path}: not a chrome-trace (no traceEvents "
                         "list)")
    return events


def fold_site_events(events: list, op_index: Optional[Dict[str, str]]
                     = None) -> list:
    """Sum per-site durations over chrome-trace ``events``.

    Sites are resolved from the event name via :data:`SITE_RE`
    (named_scope-instrumented device traces), falling back to
    ``op_index`` — an ``{hlo instruction name: site}`` map built by
    :func:`op_site_index` — keyed by ``args.hlo_op`` (or the bare event
    name) for backends whose trace events carry only HLO op names.
    Returns ``[{"site", "dur_us", "slices", "share"}]`` sorted hottest
    first; empty when nothing matched (callers decide how loud that is).
    """
    durs: Dict[str, float] = {}
    counts: Dict[str, int] = {}
    for e in events:
        if not isinstance(e, dict):
            continue
        name = e.get("name")
        dur = e.get("dur")
        if not name or dur is None:
            continue
        site = None
        m = SITE_RE.search(str(name))
        if m:
            site = m.group(0)
        elif op_index:
            args = e.get("args") or {}
            op = args.get("hlo_op") or name
            site = op_index.get(str(op))
        if site is None:
            continue
        durs[site] = durs.get(site, 0.0) + float(dur)
        counts[site] = counts.get(site, 0) + 1
    total = sum(durs.values())
    return [{"site": s, "dur_us": durs[s], "slices": counts[s],
             "share": (durs[s] / total) if total else 0.0}
            for s in sorted(durs, key=lambda s: -durs[s])]


def parse_site_trace(path: str, op_index: Optional[Dict[str, str]]
                     = None) -> list:
    """Aggregate per-attention-site device time from a Perfetto/Chrome
    trace (ISSUE 15, the schedule search's seed input).

    Every attention site is wrapped in a ``jax.named_scope`` whose name
    (``cross_attn/down3``) lands in the HLO op metadata, so device slices
    in a ``jax.profiler`` / ``serve --trace-out`` export carry the site
    name inside the op name; ``op_index`` (see :func:`op_site_index`)
    additionally recovers sites on backends whose events carry only bare
    HLO op names. Durations are summed per site, shares normalized over
    all matched sites. Raises ``ValueError`` when no site slice matched
    — and, loudly, when handed a WorkloadProfile ledger instead of a
    trace."""
    entries = fold_site_events(load_trace_events(path), op_index)
    if not entries:
        raise ValueError(
            f"{path}: no attention-site slices found — is this a DEVICE "
            "trace of a named_scope-instrumented program? (site names "
            "look like 'cross_attn/down3')")
    return entries


def op_site_index(hlo_text: str) -> Dict[str, str]:
    """``{HLO instruction name: attention site}`` from compiled HLO text.

    Instructions whose ``metadata.op_name`` scope path contains a site
    name map directly; fusion instructions (whose own metadata names only
    one member op) are attributed to the *dominant* site of their called
    computation — the site owning the most member instructions. This is
    the join key that makes CPU traces (bare ``dot.596`` event names,
    ``args.hlo_op``) yield measured per-site shares."""
    instr_site: Dict[str, str] = {}
    comp_sites: Dict[str, Counter] = {}
    fusions: List[Tuple[str, str]] = []
    current = None
    for line in hlo_text.splitlines():
        cm = _COMP_RE.match(line)
        if cm:
            current = cm.group(1)
            continue
        im = _INSTR_RE.match(line)
        if im:
            sm = SITE_RE.search(im.group(2))
            if sm:
                instr_site[im.group(1)] = sm.group(0)
                if current is not None:
                    comp_sites.setdefault(
                        current, Counter())[sm.group(0)] += 1
        fm = _FUSION_RE.match(line)
        if fm:
            fusions.append((fm.group(1), fm.group(2)))
    for instr, comp in fusions:
        if instr in instr_site:
            continue
        ctr = comp_sites.get(comp)
        if ctr:
            instr_site[instr] = ctr.most_common(1)[0][0]
    return instr_site


# -- WorkloadProfile format ----------------------------------------------


def is_workload_profile(doc) -> bool:
    return (isinstance(doc, dict)
            and doc.get("format") == PROFILE_FORMAT)


def load_workload_profile(path: str) -> dict:
    """A WorkloadProfile ledger from ``path``, loud on confusion: a
    chrome trace (or anything else) raises ``ValueError`` naming what was
    found and what was expected."""
    doc = _load_json(path)
    if isinstance(doc, dict) and not is_workload_profile(doc) \
            and isinstance(doc.get("traceEvents"), list):
        raise ValueError(
            f"{path}: this is a chrome trace, not a WorkloadProfile "
            f"ledger ({PROFILE_FORMAT}) — pass it where a trace is "
            "accepted (perfscope --sites TRACE, or fold it with "
            "serve --profile first)")
    if not is_workload_profile(doc):
        raise ValueError(
            f"{path}: not a WorkloadProfile ledger — expected a JSON "
            f"object with format={PROFILE_FORMAT!r}, got "
            f"{type(doc).__name__} with format="
            f"{doc.get('format')!r}" if isinstance(doc, dict) else
            f"{path}: not a WorkloadProfile ledger — expected a JSON "
            f"object with format={PROFILE_FORMAT!r}")
    return doc


def profile_sites(doc: dict) -> list:
    """The ledger's per-site table in the exact ``--sites-json`` /
    ``parse_site_trace`` entry shape. Loud when the ledger carries no
    measured sites (a profile captured before any dispatch folded)."""
    sites = doc.get("sites")
    if not isinstance(sites, list) or not sites:
        raise ValueError(
            "workload profile carries no measured sites — was any "
            "dispatch sampled? (captures: "
            f"{(doc.get('captures') or {}).get('count', 0)})")
    bad = [e for e in sites
           if not isinstance(e, dict) or "site" not in e
           or "share" not in e]
    if bad:
        raise ValueError(f"workload profile sites entries malformed: "
                         f"{bad[:2]!r}")
    return sites


def validate_profile(doc: dict) -> List[str]:
    """Schema problems in a ledger, empty when valid (the quality-gate
    ``profile_parity`` leg's validation unit)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return [f"not an object: {type(doc).__name__}"]
    if doc.get("format") != PROFILE_FORMAT:
        problems.append(f"format is {doc.get('format')!r}, "
                        f"expected {PROFILE_FORMAT!r}")
    for key in PROFILE_REQUIRED_KEYS:
        if key not in doc:
            problems.append(f"missing key {key!r}")
    sites = doc.get("sites")
    if isinstance(sites, list):
        for e in sites:
            if not isinstance(e, dict) or not {"site", "dur_us",
                                               "slices", "share"} <= set(e):
                problems.append(f"malformed sites entry: {e!r}")
                break
        total = sum(float(e.get("share", 0.0)) for e in sites
                    if isinstance(e, dict))
        if sites and not (0.999 <= total <= 1.001):
            problems.append(f"site shares sum to {total:.4f}, not 1")
    elif "sites" in doc:
        problems.append("sites is not a list")
    progs = doc.get("programs")
    if isinstance(progs, list):
        for p in progs:
            if not isinstance(p, dict) or "program" not in p:
                problems.append(f"malformed programs entry: {p!r}")
                break
    over = doc.get("overhead")
    if isinstance(over, dict):
        pct = over.get("overhead_pct")
        if pct is not None and (not isinstance(pct, (int, float))
                                or pct < 0):
            problems.append(f"overhead_pct invalid: {pct!r}")
    return problems


def parse_sites_any(path: str) -> Tuple[list, str]:
    """Site entries from either a chrome trace or a WorkloadProfile
    ledger — sniffed by content, with each format's loud errors intact.
    Returns ``(entries, kind)`` with kind ``"trace"`` or ``"profile"``.
    """
    doc = _load_json(path)
    if is_workload_profile(doc):
        return profile_sites(doc), "profile"
    return parse_site_trace(path), "trace"
