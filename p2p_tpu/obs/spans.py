"""Nested wall-clock spans with a bounded ring buffer of structured events.

``with span("serve.batch", lanes=4): ...`` records a start and an end event
(name, span id, parent id, nesting depth, relative timestamp, attributes,
duration) into a fixed-capacity ring buffer — old events are evicted, never
buffered unboundedly — and mirrors the block into
``jax.profiler.TraceAnnotation`` so the same named region shows up on the
host rows of an xplane/Perfetto trace captured with ``utils.progress.trace``
(docs/OBSERVABILITY.md shows how to line the two up). Span durations are
additionally observed into the ``span_duration_ms`` histogram of the default
metrics registry, so the Prometheus snapshot carries the per-span-name
distribution even after the ring has evicted the events.

Host-side only: entering a span never traces anything into an XLA program
(``TraceAnnotation`` is a profiler marker, not an op), so the
telemetry-disabled jaxpr-identity guarantee is unaffected by spans entirely.
``set_enabled(False)`` turns :func:`span` into a pure pass-through for
callers who want zero event traffic.

Timestamps are milliseconds on a module-local ``perf_counter`` epoch —
monotonic and comparable across events of one process, not wall-clock.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import time
from collections import deque
from typing import List, Optional

from . import metrics as metrics_mod

DEFAULT_CAPACITY = 4096

_EPOCH = time.perf_counter()


def _now_ms() -> float:
    return (time.perf_counter() - _EPOCH) * 1000.0


class SpanRecorder:
    """Bounded event sink. ``dropped`` counts ring-evicted events so an
    export can say it is a suffix, not the whole run."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._ring: deque = deque(maxlen=capacity)
        self.total = 0

    @property
    def capacity(self) -> int:
        return self._ring.maxlen

    @property
    def dropped(self) -> int:
        return self.total - len(self._ring)

    def resize(self, capacity: int) -> None:
        """Change the ring capacity in place, keeping the most recent
        events. ``total`` is preserved, so the ``dropped`` count stays
        honest across a resize: shrinking evicts (and counts) the oldest
        events exactly as organic eviction would."""
        if capacity < 1:
            raise ValueError(f"ring capacity must be >= 1, got {capacity}")
        self._ring = deque(self._ring, maxlen=capacity)

    def emit(self, event: dict) -> None:
        self._ring.append(event)
        self.total += 1

    def events(self) -> List[dict]:
        return list(self._ring)

    def clear(self) -> None:
        self._ring.clear()
        self.total = 0


_recorder = SpanRecorder()
_stack: List[int] = []           # active span ids, innermost last
_attached: List[dict] = []       # attach() contexts, innermost last
_ids = itertools.count(1)
_enabled = True


def set_enabled(flag: bool) -> None:
    global _enabled
    _enabled = bool(flag)


def set_capacity(capacity: int) -> None:
    """Resize the process ring (``serve --events-ring`` /
    ``P2P_OBS_EVENTS_RING``). Two-pool serving roughly doubles event
    volume over the single-pool engine, and a too-small ring silently
    evicts mid-trace — the meta line's ``dropped`` count stays honest
    across any resize (see :meth:`SpanRecorder.resize`)."""
    _recorder.resize(capacity)


def capacity() -> int:
    return _recorder.capacity


@contextlib.contextmanager
def attach(**attrs):
    """Attach context attributes (request identity, trace ids) to every
    span opened inside the block — how the flight-tracing layer stamps
    dispatch spans with the requests they carry without every call site
    threading ids by hand. Nested attaches merge, innermost winning; the
    attributes ride both the start and end events."""
    _attached.append(attrs)
    try:
        yield
    finally:
        _attached.pop()


def _attached_attrs() -> dict:
    out: dict = {}
    for d in _attached:
        out.update(d)
    return out


def recorder() -> SpanRecorder:
    return _recorder


def events() -> List[dict]:
    return _recorder.events()


def clear() -> None:
    _recorder.clear()


def _trace_annotation(name: str):
    """A ``jax.profiler.TraceAnnotation`` for ``name``, or None when jax (or
    its profiler) is unavailable — spans must not *require* jax."""
    try:
        import jax

        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return None


@contextlib.contextmanager
def span(name: str, **attrs):
    """Record a nested wall-clock span around the block.

    ``attrs`` must be JSON-serializable scalars (lane counts, step counts,
    cache-hit flags); they ride both the start and end events."""
    if not _enabled:
        yield None
        return
    sid = next(_ids)
    parent = _stack[-1] if _stack else None
    depth = len(_stack)
    if _attached:
        attrs = {**_attached_attrs(), **attrs}
    t0 = time.perf_counter()
    _recorder.emit({"event": "span_start", "span": sid, "name": name,
                    "parent": parent, "depth": depth, "ts_ms": _now_ms(),
                    **attrs})
    _stack.append(sid)
    ann = _trace_annotation(name)
    if ann is not None:
        ann.__enter__()
    try:
        yield sid
    finally:
        if ann is not None:
            ann.__exit__(None, None, None)
        _stack.pop()
        dur_ms = (time.perf_counter() - t0) * 1000.0
        _recorder.emit({"event": "span_end", "span": sid, "name": name,
                        "parent": parent, "depth": depth, "ts_ms": _now_ms(),
                        "dur_ms": dur_ms, **attrs})
        metrics_mod.registry().histogram(
            "span_duration_ms", "wall-clock span durations by span name",
            labels=("name",),
            buckets=metrics_mod.LATENCY_MS_BUCKETS,
        ).labels(name=name).observe(dur_ms)


def write_jsonl(fp) -> int:
    """Dump the ring buffer as JSONL to an open file; returns lines written.
    A leading meta line records capacity/total/dropped so consumers know
    whether the log is complete."""
    fp.write(json.dumps({"event": "meta", "total": _recorder.total,
                         "dropped": _recorder.dropped}) + "\n")
    n = 1
    for ev in _recorder.events():
        fp.write(json.dumps(ev) + "\n")
        n += 1
    return n


def active_depth() -> int:
    return len(_stack)


def active_span() -> Optional[int]:
    return _stack[-1] if _stack else None
