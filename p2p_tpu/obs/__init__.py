"""Unified telemetry: metrics registry, span tracing, device instrumentation.

Dependency-free modules every other subsystem reports through (see
docs/OBSERVABILITY.md for the metric catalog and span taxonomy):

- :mod:`.metrics` — process-global registry of counters, gauges and
  fixed-bucket histograms with labeled families, snapshot/reset semantics,
  Prometheus text exposition and JSONL export.
- :mod:`.spans` — nested wall-clock spans in a bounded (configurable)
  ring buffer, mirrored into ``jax.profiler.TraceAnnotation`` so host
  spans line up with device xplane traces; ``attach()`` stamps spans
  with request identity.
- :mod:`.flight` — request-scoped flight tracing for the serve engine:
  per-request stage timelines across the two program pools (stitched
  across crash-replay), a Chrome-trace/Perfetto export, and the blackbox
  post-mortem recorder.
- :mod:`.device` — the host half of the compiled-loop callback channel
  (``utils.progress.emit_step``/``emit_event``): per-phase step timing,
  compile-time recording, per-device ``memory_stats()`` gauges. Imported
  explicitly (``from p2p_tpu.obs import device``) because it pulls jax;
  this package root stays jax-free so CLI parsing and the serve data
  structures can import metrics/spans without a backend.
- :mod:`.costmodel` — the cost observatory (ISSUE 14): XLA cost cards
  (``cost_analysis``/``memory_analysis``), the per-platform peak table
  (datasheet on chip, calibrated microbenchmarks on a CPU rehearsal
  host), roofline/MFU arithmetic, the frozen canonical budgets behind
  the ``cost_regression`` gate, and the serve engine's ``CostScope``
  hook. Imported explicitly for the same jax-at-import reason as
  ``device`` (jax only inside functions, but its consumers are all
  jax-side).
- :mod:`.traceparse` — shared chrome-trace / WorkloadProfile parsing
  (ISSUE 18): the ``perfscope --sites`` named_scope fold, the HLO
  op→site index that recovers measured per-site shares from bare-op
  traces, and the ledger format helpers. Stdlib-only; safe from tools.
- :mod:`.prodscope` — in-engine sampled device profiling (ISSUE 18):
  the deterministic sampling plan, the bounded on-disk trace ring, the
  mergeable WorkloadProfile ledger and the EWMA drift sentinels behind
  ``serve --profile``. Imported explicitly (``from p2p_tpu.obs import
  prodscope``) — module import is jax-free, but capture methods pull
  jax, and its only consumer is the serve engine.

The TPU-native discipline: disabling telemetry traces *nothing* into any
XLA program (the ``emit_step(enabled=False)`` contract, pinned by jaxpr
identity tests), and everything here is host-side — enabling it changes
wall-clock overhead only, never numerics.
"""

from . import flight, metrics, spans  # noqa: F401  (device is explicit)
from .metrics import registry  # noqa: F401
from .spans import span  # noqa: F401

__all__ = ["flight", "metrics", "spans", "registry", "span"]
