"""prodscope — in-engine sampled device profiling (ISSUE 18).

The production half of the observability story: ``perfscope`` (ISSUE 14)
prices programs *analytically* and the schedule search (ISSUE 15) wants
*measured* per-site tables — but until now those came only from a
hand-collected Chrome trace. This module closes the loop inside the
serve engine:

- :class:`SamplingPlan` — deterministic, seeded per-pool dispatch
  sampling (a stable hash of ``(seed, pool, ordinal)``; same seed ⇒ the
  same sampled dispatch set, independent of wall clock or arrival
  jitter).
- :class:`TraceRing` — a bounded on-disk ring of ``jax.profiler``
  capture artifacts: size- and count-capped, written atomically
  (tmp dir → ``os.replace``), orphans from a crash mid-capture swept at
  startup and GC'd like carry spills. Each committed capture carries a
  ``meta.json`` tagging the dispatch's program label, pool, bucket,
  schedule table, kernel config, mesh spec and the device-memory gauges
  at the capture point.
- :class:`ProdScope` — the engine sidecar: ``begin``/``stop`` bracket
  every dispatch (sampled ones run under a programmatic profiler
  capture), ``finalize`` folds stopped captures — at the batch-boundary
  sync, never inside the dispatch ``try`` (a fold error must not be
  classified as a dispatch fault) — through the shared
  :mod:`.traceparse` parser into a durable, mergeable
  :data:`~p2p_tpu.obs.traceparse.PROFILE_FORMAT` WorkloadProfile
  ledger, and runs the EWMA drift sentinels over each capture.
- :func:`fold_profiles` — the ledger merge (commutative and
  associative; pinned by tests/test_prodscope.py), which is also how a
  restart extends the previous incarnation's ledger instead of
  clobbering it.

Disabled-mode discipline (PR-3/7/14): with ``prodscope=None`` the
engine's record stream, journal bytes, compiled programs and metric
families are byte-identical — every metric family here registers in
``__init__``, overhead accounting uses the scope's own
``time.perf_counter`` (never the engine's injected timer), and profile
facts live only in the ledger, the summary ``profile`` block and
journaled ``profile_drift`` events.

jax is imported lazily inside capture methods only, so the module (and
its fold/plan/ring units) stays importable backend-free.
"""

from __future__ import annotations

import glob as glob_mod
import hashlib
import json
import os
import shutil
import time
from typing import Dict, List, Optional, Tuple

from . import metrics as metrics_mod
from . import traceparse

PROFILE_FORMAT = traceparse.PROFILE_FORMAT

#: The ledger file a scope maintains under its output directory.
LEDGER_NAME = "workload_profile.json"

#: Registry histogram families snapshotted into the ledger (the
#: queue/batcher stage timings the autotuner correlates site shares
#: against).
STAGE_FAMILIES = ("serve_queue_wait_ms", "serve_run_ms",
                  "serve_compile_ms", "serve_request_total_ms",
                  "serve_batch_occupancy")


class SamplingPlan:
    """Deterministic per-pool dispatch sampling: dispatch ``ordinal`` of
    ``pool`` is sampled iff ``sha1(seed:pool:ordinal) % period == 0`` —
    seeded, independent of wall time, and stable across restarts (the
    determinism contract the ledger's provenance rests on)."""

    def __init__(self, seed: int = 0, period: int = 8):
        if period < 1:
            raise ValueError(f"sampling period must be >= 1, got {period}")
        self.seed = int(seed)
        self.period = int(period)

    def sampled(self, pool: str, ordinal: int) -> bool:
        if self.period == 1:
            return True
        h = hashlib.sha1(
            f"{self.seed}:{pool}:{ordinal}".encode()).digest()
        return int.from_bytes(h[:8], "big") % self.period == 0

    def describe(self) -> dict:
        return {"kind": "hash-mod", "seed": self.seed,
                "period": self.period}


class TraceRing:
    """Bounded on-disk ring of committed capture directories.

    Layout: ``<root>/cap-<seq:06d>/`` per committed capture (profiler
    output + ``meta.json``), ``<root>/tmp-cap-<seq:06d>/`` while a
    capture is in flight. Commit is a single ``os.replace`` — a crash
    mid-capture leaves only a ``tmp-cap-*`` orphan, swept (and counted)
    on the next startup, exactly the carry-spill GC discipline. GC
    evicts oldest-first past either cap but always keeps the newest
    committed capture."""

    TMP_PREFIX = "tmp-cap-"
    CAP_PREFIX = "cap-"

    def __init__(self, root: str, max_bytes: int = 256 << 20,
                 max_count: int = 16):
        if max_count < 1:
            raise ValueError(f"ring max_count must be >= 1, "
                             f"got {max_count}")
        if max_bytes < 1:
            raise ValueError(f"ring max_bytes must be >= 1, "
                             f"got {max_bytes}")
        self.root = root
        self.max_bytes = int(max_bytes)
        self.max_count = int(max_count)
        os.makedirs(root, exist_ok=True)

    def sweep_orphans(self) -> int:
        """Delete crash-orphaned tmp capture dirs; returns the count."""
        n = 0
        for d in sorted(glob_mod.glob(
                os.path.join(self.root, self.TMP_PREFIX + "*"))):
            shutil.rmtree(d, ignore_errors=True)
            n += 1
        return n

    def next_seq(self) -> int:
        seqs = [0]
        for d in self.captures():
            name = os.path.basename(d)[len(self.CAP_PREFIX):]
            try:
                seqs.append(int(name.split("-")[0]) + 1)
            except ValueError:
                pass
        return max(seqs)

    def tmp_dir(self, seq: int) -> str:
        path = os.path.join(self.root, f"{self.TMP_PREFIX}{seq:06d}")
        os.makedirs(path, exist_ok=True)
        return path

    def commit(self, tmpdir: str, seq: int) -> str:
        """Atomically promote a finished tmp capture into the ring."""
        final = os.path.join(self.root, f"{self.CAP_PREFIX}{seq:06d}")
        os.replace(tmpdir, final)
        return final

    def captures(self) -> List[str]:
        return sorted(glob_mod.glob(
            os.path.join(self.root, self.CAP_PREFIX + "*")))

    @staticmethod
    def _dir_bytes(d: str) -> int:
        total = 0
        for base, _, files in os.walk(d):
            for f in files:
                try:
                    total += os.path.getsize(os.path.join(base, f))
                except OSError:
                    pass
        return total

    def gc(self) -> Tuple[int, int]:
        """Evict oldest captures past either cap (the newest always
        survives, even when one capture alone exceeds ``max_bytes``).
        Returns ``(evicted, bytes_freed)``."""
        caps = self.captures()
        sizes = {d: self._dir_bytes(d) for d in caps}
        evicted = freed = 0
        while len(caps) > 1 and (
                len(caps) > self.max_count
                or sum(sizes[d] for d in caps) > self.max_bytes):
            victim = caps.pop(0)
            shutil.rmtree(victim, ignore_errors=True)
            evicted += 1
            freed += sizes.pop(victim)
        return evicted, freed

    def stats(self) -> dict:
        caps = self.captures()
        return {"count": len(caps),
                "bytes": sum(self._dir_bytes(d) for d in caps),
                "max_count": self.max_count,
                "max_bytes": self.max_bytes}


class DriftSentinel:
    """EWMA drift detector over one signal family, keyed by program or
    site. An observation fires an event when it deviates from the
    pre-update EWMA by more than ``threshold`` (relative) — but only
    after ``min_samples`` observations of that key, so short parity runs
    never emit journal lines (the byte-identical-off contract's quiet
    half)."""

    def __init__(self, kind: str, alpha: float = 0.3,
                 threshold: float = 0.25, min_samples: int = 3):
        self.kind = kind
        self.alpha = float(alpha)
        self.threshold = float(threshold)
        self.min_samples = int(min_samples)
        self._state: Dict[str, dict] = {}
        self.last_deviation = 0.0

    def observe(self, key: str, value: float) -> Optional[dict]:
        st = self._state.get(key)
        if st is None:
            self._state[key] = {"ewma": float(value), "n": 1}
            return None
        ewma = st["ewma"]
        st["n"] += 1
        deviation = abs(value - ewma) / max(abs(ewma), 1e-9)
        st["ewma"] = ewma + self.alpha * (value - ewma)
        self.last_deviation = deviation
        if st["n"] > self.min_samples and deviation > self.threshold:
            return {"drift": self.kind, "key": key,
                    "value": round(float(value), 4),
                    "ewma": round(ewma, 4),
                    "deviation": round(deviation, 4),
                    "threshold": self.threshold}
        return None


# -- the WorkloadProfile ledger ------------------------------------------


def empty_profile(tags: Optional[dict] = None) -> dict:
    return {
        "format": PROFILE_FORMAT,
        "version": 1,
        "tags": dict(tags or {}),
        "window": {"first_vnow_ms": None, "last_vnow_ms": None, "runs": 0},
        "captures": {"count": 0, "dispatches_seen": 0, "events_folded": 0},
        "sites": [],
        "programs": [],
        "phases": {},
        "kernels": [],
        "schedule_segments": [],
        "stage_histograms": {},
        "device_memory": {},
        "drift": {"events": 0, "by_kind": {}},
        "overhead": {"capture_ms": 0.0, "base_wall_ms": 0.0,
                     "overhead_pct": 0.0},
    }


def _fold_tags(a: dict, b: dict) -> dict:
    """Key-wise merge; conflicting values collapse to a sorted
    ``{"mixed": [...]}`` set so the fold stays commutative AND
    associative (mixed sets union, never nest)."""
    def variants(v) -> List[str]:
        if isinstance(v, dict) and set(v) == {"mixed"}:
            return list(v["mixed"])
        return [json.dumps(v, sort_keys=True)]

    out = {}
    for key in sorted(set(a) | set(b)):
        if key in a and key in b:
            vs = sorted(set(variants(a[key])) | set(variants(b[key])))
            out[key] = (json.loads(vs[0]) if len(vs) == 1
                        else {"mixed": vs})
        else:
            out[key] = a.get(key, b.get(key))
    return out


def _sum_keyed(a: List[dict], b: List[dict], key_fields: Tuple[str, ...],
               sum_fields: Tuple[str, ...],
               keep_fields: Tuple[str, ...] = ()) -> List[dict]:
    """Merge two entry lists by a key tuple, summing the numeric fields.
    ``keep_fields`` resolve conflicts by max (they are expected equal —
    e.g. a program's flops — and max is commutative/associative)."""
    merged: Dict[tuple, dict] = {}
    for entry in list(a) + list(b):
        k = tuple(entry.get(f) for f in key_fields)
        cur = merged.get(k)
        if cur is None:
            merged[k] = {f: entry.get(f) for f in
                         key_fields + sum_fields + keep_fields}
            continue
        for f in sum_fields:
            cur[f] = (cur.get(f) or 0) + (entry.get(f) or 0)
        for f in keep_fields:
            x, y = cur.get(f), entry.get(f)
            if y is not None and (x is None or y > x):
                cur[f] = y
    return [merged[k] for k in sorted(merged, key=lambda t: tuple(
        str(x) for x in t))]


def _fold_hist_samples(a: List[dict], b: List[dict]) -> List[dict]:
    """Sum histogram samples label-wise (buckets carry cumulative
    counts: the elementwise sum of two cumulative series is the
    cumulative series of the sum)."""
    merged: Dict[str, dict] = {}
    for s in list(a) + list(b):
        key = json.dumps(s.get("labels", {}), sort_keys=True)
        cur = merged.get(key)
        if cur is None:
            merged[key] = json.loads(json.dumps(s))  # deep copy
            continue
        cur["count"] = cur.get("count", 0) + s.get("count", 0)
        cur["sum"] = cur.get("sum", 0) + s.get("sum", 0)
        cb, sb = cur.get("buckets"), s.get("buckets")
        if isinstance(cb, list) and isinstance(sb, list) \
                and [x[0] for x in cb] == [x[0] for x in sb]:
            cur["buckets"] = [[x[0], x[1] + y[1]]
                              for x, y in zip(cb, sb)]
    return [merged[k] for k in sorted(merged)]


def _latest(a: dict, b: dict, stamp: str) -> dict:
    """Pick the later snapshot (max ``stamp``, JSON-string tie-break) —
    a commutative, associative selection for point-in-time blocks."""
    if not a:
        return b
    if not b:
        return a
    ka = (a.get(stamp) if a.get(stamp) is not None else -1,
          json.dumps(a, sort_keys=True))
    kb = (b.get(stamp) if b.get(stamp) is not None else -1,
          json.dumps(b, sort_keys=True))
    return a if ka >= kb else b


def derive_profile(doc: dict) -> dict:
    """Recompute every derived field (shares, means, ratios) from the
    raw sums in place. Folds carry raw sums; callers see a ledger whose
    derived fields are always consistent with them."""
    total = sum(e.get("dur_us", 0.0) for e in doc["sites"])
    for e in doc["sites"]:
        e["share"] = (e["dur_us"] / total) if total else 0.0
    doc["sites"].sort(key=lambda e: (-e["dur_us"], e["site"]))
    for p in doc["programs"]:
        n = p.get("captures", 0)
        p["run_ms_mean"] = (p["run_ms_sum"] / n) if n else 0.0
        mfu_n = p.get("mfu_samples", 0)
        p["mfu_pct_mean"] = ((p["mfu_pct_sum"] / mfu_n)
                             if mfu_n else None)
        pred = p.get("predicted_ms")
        p["measured_vs_predicted"] = (
            round(p["run_ms_mean"] / pred, 4)
            if pred and p["run_ms_mean"] else None)
    for pool, ph in doc["phases"].items():
        n = ph.get("captures", 0)
        ph["run_ms_mean"] = (ph["run_ms_sum"] / n) if n else 0.0
    ktotal = sum(k.get("ms", 0.0) for k in doc["kernels"])
    for k in doc["kernels"]:
        k["share"] = (k["ms"] / ktotal) if ktotal else 0.0
    doc["kernels"].sort(key=lambda k: (-k["ms"], k["variant"]))
    stotal = sum(s.get("measured_ms", 0.0)
                 for s in doc["schedule_segments"])
    for s in doc["schedule_segments"]:
        s["share"] = (s["measured_ms"] / stotal) if stotal else 0.0
    doc["schedule_segments"].sort(
        key=lambda s: (-s["measured_ms"], s["site"]))
    over = doc["overhead"]
    over["overhead_pct"] = (
        round(100.0 * over["capture_ms"] / over["base_wall_ms"], 3)
        if over.get("base_wall_ms") else 0.0)
    return doc


def fold_profiles(a: Optional[dict], b: Optional[dict]) -> dict:
    """Merge two WorkloadProfile ledgers. Commutative and associative
    (pinned by tests/test_prodscope.py): sums for accumulated blocks,
    later-snapshot-wins for point-in-time blocks, set-union for
    conflicting tags. Sentinel EWMA state is deliberately NOT in the
    ledger — it is order-dependent and lives in the scope instance."""
    if not a:
        return derive_profile(json.loads(json.dumps(b or
                                                    empty_profile())))
    if not b:
        return derive_profile(json.loads(json.dumps(a)))
    for doc in (a, b):
        if doc.get("format") != PROFILE_FORMAT:
            raise ValueError(f"fold_profiles: not a {PROFILE_FORMAT} "
                             f"ledger (format={doc.get('format')!r})")
    out = empty_profile(_fold_tags(a.get("tags", {}), b.get("tags", {})))
    wa, wb = a["window"], b["window"]
    firsts = [w["first_vnow_ms"] for w in (wa, wb)
              if w.get("first_vnow_ms") is not None]
    lasts = [w["last_vnow_ms"] for w in (wa, wb)
             if w.get("last_vnow_ms") is not None]
    out["window"] = {
        "first_vnow_ms": min(firsts) if firsts else None,
        "last_vnow_ms": max(lasts) if lasts else None,
        "runs": wa.get("runs", 0) + wb.get("runs", 0)}
    out["captures"] = {
        k: a["captures"].get(k, 0) + b["captures"].get(k, 0)
        for k in ("count", "dispatches_seen", "events_folded")}
    out["sites"] = _sum_keyed(a["sites"], b["sites"], ("site",),
                              ("dur_us", "slices"))
    out["programs"] = _sum_keyed(
        a["programs"], b["programs"], ("program", "pool", "bucket"),
        ("captures", "run_ms_sum", "mfu_pct_sum", "mfu_samples"),
        keep_fields=("flops", "predicted_ms"))
    pools = set(a["phases"]) | set(b["phases"])
    out["phases"] = {
        pool: {k: (a["phases"].get(pool, {}).get(k, 0)
                   + b["phases"].get(pool, {}).get(k, 0))
               for k in ("captures", "run_ms_sum")}
        for pool in sorted(pools)}
    out["kernels"] = _sum_keyed(a["kernels"], b["kernels"],
                                ("variant",), ("ms",))
    out["schedule_segments"] = _sum_keyed(
        a["schedule_segments"], b["schedule_segments"],
        ("site", "reuse"), ("measured_ms",))
    fams = set(a["stage_histograms"]) | set(b["stage_histograms"])
    out["stage_histograms"] = {
        fam: _fold_hist_samples(a["stage_histograms"].get(fam, []),
                                b["stage_histograms"].get(fam, []))
        for fam in sorted(fams)}
    out["device_memory"] = _latest(a["device_memory"],
                                   b["device_memory"], "sampled_at_ms")
    out["drift"] = {
        "events": a["drift"].get("events", 0) + b["drift"].get(
            "events", 0),
        "by_kind": {k: (a["drift"].get("by_kind", {}).get(k, 0)
                        + b["drift"].get("by_kind", {}).get(k, 0))
                    for k in sorted(set(a["drift"].get("by_kind", {}))
                                    | set(b["drift"].get("by_kind",
                                                         {})))}}
    last = _latest(a["drift"].get("last", {}), b["drift"].get(
        "last", {}), "vnow_ms")
    if last:
        out["drift"]["last"] = last
    out["overhead"] = {
        k: a["overhead"].get(k, 0.0) + b["overhead"].get(k, 0.0)
        for k in ("capture_ms", "base_wall_ms")}
    out["overhead"]["overhead_pct"] = 0.0
    return derive_profile(out)


def _schedule_reuse(schedule: Optional[dict], site: str) -> float:
    """The committed schedule's implied reuse fraction for ``site``.

    Schedule-spec table values (the tools/schedules artifact shape:
    per-family tables with a ``"*"`` default, falling back to
    ``cfg_gate``) are FLIP points — the fraction of the run at which the
    site switches to cached reuse — so the reused share of steps is
    ``1 - flip``. A fractional flip converts exactly; ``"auto"``
    approximates as the half-run gate it resolves to; absolute-step and
    ``null`` specs contribute 0 (no steps attributable to "use" without
    the run's step count). 0.0 without a schedule: every step runs the
    compute variant."""
    if not isinstance(schedule, dict):
        return 0.0
    family = "cross" if site.startswith("cross_attn/") else "self"
    table = schedule.get(family)
    if not isinstance(table, dict):
        table = {}
    flip = table.get(site, table.get("*", schedule.get("cfg_gate")))
    if flip == "auto":
        return 0.5
    if isinstance(flip, float) and 0.0 <= flip <= 1.0:
        return 1.0 - flip
    return 0.0


class ProdScope:
    """The serve engine's production-profiling sidecar (see the module
    docstring). One scope covers one ``serve_forever`` run; pointing a
    new run at the same directory folds the new session into the
    on-disk ledger (restart-mergeable, like the journal)."""

    def __init__(self, out_dir: str, *, seed: int = 0, period: int = 8,
                 ring_max_bytes: int = 256 << 20, ring_max_count: int = 16,
                 tags: Optional[dict] = None, registry=None,
                 devices: int = 1, ewma_alpha: float = 0.3,
                 drift_threshold: float = 0.25,
                 drift_min_samples: int = 3):
        self.out_dir = out_dir
        os.makedirs(out_dir, exist_ok=True)
        self.ledger_path = os.path.join(out_dir, LEDGER_NAME)
        self.ring = TraceRing(os.path.join(out_dir, "ring"),
                              max_bytes=ring_max_bytes,
                              max_count=ring_max_count)
        self.orphans_swept = self.ring.sweep_orphans()
        self.plan = SamplingPlan(seed=seed, period=period)
        self.devices = max(1, int(devices))
        self.tags = dict(tags or {})
        self._registry = registry or metrics_mod.registry()
        # Restart continuity: the previous incarnation's ledger becomes
        # the fold base; a corrupt/foreign file starts fresh (and is
        # overwritten at the first persist — the orphan-GC discipline).
        self._base: Optional[dict] = None
        if os.path.exists(self.ledger_path):
            try:
                self._base = traceparse.load_workload_profile(
                    self.ledger_path)
            except (ValueError, OSError):
                self._base = None
        self._session = empty_profile(self.tags)
        self._session["window"]["runs"] = 1
        self._cards: Dict[tuple, dict] = {}
        self._peaks = None
        self._ordinals: Dict[str, int] = {}
        self._seq = self.ring.next_seq()
        self._active: Optional[dict] = None
        self._pending: List[dict] = []
        self._capture_ms = 0.0
        self._base_wall_ms = 0.0
        self._gc_evicted = 0
        self._sentinels = {
            kind: DriftSentinel(kind, alpha=ewma_alpha,
                                threshold=drift_threshold,
                                min_samples=drift_min_samples)
            for kind in ("predicted_ratio", "site_share", "mfu")}
        # Families register only under an active scope — a profile-less
        # serve run's registry snapshot stays byte-identical (the
        # disabled-mode discipline shared with CostScope).
        reg = self._registry
        self._m_captures = reg.counter(
            "serve_profile_captures_total",
            "sampled device-trace captures folded into the ledger")
        self._m_sampled = reg.counter(
            "serve_profile_sampled_dispatches_total",
            "dispatches selected by the sampling plan")
        self._m_drift = reg.gauge(
            "serve_profile_drift",
            "latest relative EWMA deviation per drift-sentinel kind",
            labels=("kind",))
        self._m_drift_events = reg.counter(
            "serve_profile_drift_events_total",
            "journaled profile_drift events", labels=("kind",))
        self._m_ring_bytes = reg.gauge(
            "serve_profile_ring_bytes", "trace-ring bytes on disk")
        self._m_ring_count = reg.gauge(
            "serve_profile_ring_captures",
            "trace-ring committed captures on disk")

    # -- build-time ------------------------------------------------------

    def _get_peaks(self):
        if self._peaks is None:
            from . import costmodel
            self._peaks = costmodel.detect_peaks()
        return self._peaks

    def record_program(self, key, bucket: int, compiled) -> None:
        """Index one compiled program at build time: the HLO-text
        op→site index (the trace join key) plus the minimal cost-card
        facts (flops, predicted ms) the drift sentinels compare measured
        dispatches against."""
        from . import costmodel

        label = costmodel._program_label(key, bucket)
        entry = {"label": label, "op_index": {}, "flops": 0.0,
                 "predicted_ms": None}
        try:
            text = compiled.as_text()
        except Exception:
            text = ""
        if text:
            entry["op_index"] = traceparse.op_site_index(text)
        try:
            card = costmodel.card_from_compiled(compiled, label)
            if card.flops > 0 or card.bytes_accessed > 0:
                roof = costmodel.roofline(card.flops,
                                          card.bytes_accessed,
                                          self._get_peaks(),
                                          devices=self.devices)
                entry["flops"] = card.flops
                entry["predicted_ms"] = roof["predicted_ms"]
        except Exception:
            pass  # a card-less program still profiles (sites only)
        self._cards[(key, bucket)] = entry

    # -- dispatch-time ---------------------------------------------------

    def begin(self, pool: str, key, bucket: int, lanes: int) -> dict:
        """Bracket-open for one dispatch. Counts the pool ordinal
        against the sampling plan; a sampled dispatch (at most one
        capture in flight — jax profiler sessions don't nest) starts a
        programmatic trace into a ring tmp dir. Always returns a handle
        for :meth:`stop`/:meth:`abort`."""
        ordinal = self._ordinals[pool] = self._ordinals.get(pool, 0) + 1
        self._session["captures"]["dispatches_seen"] += 1
        handle = {"pool": pool, "key": key, "bucket": bucket,
                  "lanes": lanes, "ordinal": ordinal, "sampled": False,
                  "t0": time.perf_counter()}
        if self._active is None and self.plan.sampled(pool, ordinal):
            seq = self._seq
            self._seq += 1
            tmp = self.ring.tmp_dir(seq)
            t0 = time.perf_counter()
            try:
                import jax

                jax.profiler.start_trace(tmp)
            except Exception:
                shutil.rmtree(tmp, ignore_errors=True)
                handle["t0"] = time.perf_counter()
                return handle
            self._capture_ms += (time.perf_counter() - t0) * 1e3
            handle.update(sampled=True, seq=seq, tmp=tmp)
            self._active = handle
            self._m_sampled.inc()
            handle["t0"] = time.perf_counter()
        return handle

    def stop(self, handle: dict, run_ms: float, vnow: float) -> None:
        """Bracket-close after a successful run: the profiler stops (tmp
        trace files are durable on disk from here — the crash window the
        ``kill_during_capture`` chaos drill aims at) and the capture
        queues for :meth:`finalize` at the batch-boundary sync."""
        self._base_wall_ms += (time.perf_counter() - handle["t0"]) * 1e3
        if not handle["sampled"]:
            return
        t0 = time.perf_counter()
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:
            shutil.rmtree(handle["tmp"], ignore_errors=True)
            self._active = None
            return
        self._capture_ms += (time.perf_counter() - t0) * 1e3
        handle["run_ms"] = float(run_ms)
        handle["vnow_ms"] = round(float(vnow), 3)
        self._pending.append(handle)
        self._active = None

    def abort(self, handle: dict) -> None:
        """Bracket-close for a dispatch that raised: the profiler stops
        and the tmp capture is discarded (a faulted run's trace would
        poison the ledger with fault-path timings)."""
        self._base_wall_ms += (time.perf_counter() - handle["t0"]) * 1e3
        if not handle["sampled"]:
            return
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:
            pass
        shutil.rmtree(handle["tmp"], ignore_errors=True)
        self._active = None

    def pending(self) -> bool:
        return bool(self._pending)

    # -- batch-boundary fold ---------------------------------------------

    def finalize(self, kill_hook=None) -> dict:
        """Fold every stopped capture: parse its trace through the
        op→site index, tag + atomically commit the artifact into the
        ring, GC past the caps, update the session ledger + drift
        sentinels, persist the merged ledger. ``kill_hook`` (the chaos
        ``kill_during_capture`` window) runs after the tmp trace is
        durable and before the commit rename — dying there leaves
        exactly the orphan the startup sweep must collect. Returns
        ``{"captures": n, "drift_events": [...]}``."""
        if not self._pending:
            return {"captures": 0, "drift_events": []}
        pending, self._pending = self._pending, []
        drift_events: List[dict] = []
        n_folded = 0
        t0 = time.perf_counter()
        for h in pending:
            if kill_hook is not None:
                kill_hook()
            card = self._cards.get((h["key"], h["bucket"]))
            entries: List[dict] = []
            events_n = 0
            for tf in sorted(glob_mod.glob(
                    os.path.join(h["tmp"], "**", "*.trace.json.gz"),
                    recursive=True)):
                try:
                    evs = traceparse.load_trace_events(tf)
                except (ValueError, OSError):
                    continue
                events_n += len(evs)
                entries = _sum_keyed(
                    entries,
                    traceparse.fold_site_events(
                        evs, card["op_index"] if card else None),
                    ("site",), ("dur_us", "slices"))
            mem = self._device_memory()
            meta = {"seq": h["seq"], "pool": h["pool"],
                    "program": card["label"] if card else None,
                    "bucket": h["bucket"], "lanes": h["lanes"],
                    "ordinal": h["ordinal"], "run_ms": h["run_ms"],
                    "vnow_ms": h["vnow_ms"], "events": events_n,
                    "sampling": self.plan.describe(),
                    "tags": self.tags,
                    "sites": entries, "device_memory": mem}
            with open(os.path.join(h["tmp"], "meta.json"), "w") as f:
                json.dump(meta, f, indent=1)
                f.write("\n")
            self.ring.commit(h["tmp"], h["seq"])
            evicted, _ = self.ring.gc()
            self._gc_evicted += evicted
            self._fold_capture(h, entries, card, mem, events_n)
            drift_events += self._observe_drift(h, entries, card)
            n_folded += 1
            self._m_captures.inc()
        self._capture_ms += (time.perf_counter() - t0) * 1e3
        for ev in drift_events:
            kind = ev["drift"]
            by = self._session["drift"]["by_kind"]
            by[kind] = by.get(kind, 0) + 1
            self._session["drift"]["events"] += 1
            self._session["drift"]["last"] = ev
            self._m_drift_events.labels(kind=kind).inc()
        for kind, s in self._sentinels.items():
            self._m_drift.labels(kind=kind).set(
                round(s.last_deviation, 4))
        self.write_ledger()
        stats = self.ring.stats()
        self._m_ring_bytes.set(stats["bytes"])
        self._m_ring_count.set(stats["count"])
        return {"captures": n_folded, "drift_events": drift_events}

    def _device_memory(self) -> dict:
        """Satellite fix (ISSUE 18): the live ``device_memory_bytes``
        gauges, snapshotted at the capture point so trace artifacts and
        memory headroom line up post-hoc."""
        try:
            from . import device as obs_device

            return obs_device.sample_device_memory(self._registry)
        except Exception:
            return {}

    def _fold_capture(self, h: dict, entries: List[dict],
                      card: Optional[dict], mem: dict,
                      events_n: int) -> None:
        s = self._session
        s["captures"]["count"] += 1
        s["captures"]["events_folded"] += events_n
        w = s["window"]
        if w["first_vnow_ms"] is None or h["vnow_ms"] < w["first_vnow_ms"]:
            w["first_vnow_ms"] = h["vnow_ms"]
        if w["last_vnow_ms"] is None or h["vnow_ms"] > w["last_vnow_ms"]:
            w["last_vnow_ms"] = h["vnow_ms"]
        s["sites"] = _sum_keyed(s["sites"], entries, ("site",),
                                ("dur_us", "slices"))
        prog = {"program": card["label"] if card else
                f"uncarded@b{h['bucket']}",
                "pool": h["pool"], "bucket": h["bucket"], "captures": 1,
                "run_ms_sum": h["run_ms"], "mfu_pct_sum": 0.0,
                "mfu_samples": 0,
                "flops": card["flops"] if card else 0.0,
                "predicted_ms": card["predicted_ms"] if card else None}
        if card and card["flops"] > 0 and h["run_ms"] > 0:
            from . import costmodel

            mfu = costmodel.mfu_pct(card["flops"], h["run_ms"],
                                    self._get_peaks(),
                                    devices=self.devices)
            if mfu is not None:
                prog["mfu_pct_sum"] = mfu
                prog["mfu_samples"] = 1
        s["programs"] = _sum_keyed(
            s["programs"], [prog], ("program", "pool", "bucket"),
            ("captures", "run_ms_sum", "mfu_pct_sum", "mfu_samples"),
            keep_fields=("flops", "predicted_ms"))
        pool = s["phases"].setdefault(h["pool"],
                                      {"captures": 0, "run_ms_sum": 0.0})
        pool["captures"] += 1
        pool["run_ms_sum"] += h["run_ms"]
        schedule = self.tags.get("schedule")
        kernel_sites = self.tags.get("kernel_sites")
        kernels: List[dict] = []
        segments: List[dict] = []
        for e in entries:
            site = e["site"]
            ms = e["dur_us"] / 1e3
            reuse = _schedule_reuse(schedule, site)
            if isinstance(schedule, dict):
                segments.append({"site": site, "reuse": round(reuse, 4),
                                 "measured_ms": ms})
            # Variant attribution: the schedule's reuse fraction of the
            # run executes the cached "use" path; the rest runs the
            # site's compute variant (fused-edit when the kernel config
            # covers it, materialized otherwise — the dispatch.py
            # taxonomy).
            base = ("fused-edit" if kernel_sites == "*"
                    or (isinstance(kernel_sites, (list, tuple))
                        and site in kernel_sites) else "materialized")
            if reuse > 0:
                kernels.append({"variant": "use", "ms": ms * reuse})
            kernels.append({"variant": base, "ms": ms * (1.0 - reuse)})
        s["kernels"] = _sum_keyed(s["kernels"], kernels, ("variant",),
                                  ("ms",))
        s["schedule_segments"] = _sum_keyed(
            s["schedule_segments"], segments, ("site", "reuse"),
            ("measured_ms",))
        snap = self._registry.snapshot()
        s["stage_histograms"] = {
            fam: snap[fam]["samples"] for fam in STAGE_FAMILIES
            if fam in snap}
        if mem:
            s["device_memory"] = {"sampled_at_ms": h["vnow_ms"],
                                  "seq": h["seq"], "devices": mem}

    def _observe_drift(self, h: dict, entries: List[dict],
                       card: Optional[dict]) -> List[dict]:
        events: List[dict] = []

        def emit(ev):
            if ev is not None:
                ev["pool"] = h["pool"]
                ev["vnow_ms"] = h["vnow_ms"]
                events.append(ev)

        if card and card["predicted_ms"]:
            emit(self._sentinels["predicted_ratio"].observe(
                card["label"], h["run_ms"] / card["predicted_ms"]))
            if card["flops"] > 0 and h["run_ms"] > 0:
                from . import costmodel

                mfu = costmodel.mfu_pct(card["flops"], h["run_ms"],
                                        self._get_peaks(),
                                        devices=self.devices)
                if mfu is not None:
                    emit(self._sentinels["mfu"].observe(card["label"],
                                                        mfu))
        total = sum(e.get("dur_us", 0.0) for e in entries)
        for e in entries:
            emit(self._sentinels["site_share"].observe(
                e["site"], (e["dur_us"] / total) if total else 0.0))
        return events

    # -- artifacts -------------------------------------------------------

    def ledger(self) -> dict:
        """The merged (base ⊕ session) WorkloadProfile."""
        session = json.loads(json.dumps(self._session))
        over = session["overhead"]
        over["capture_ms"] = round(self._capture_ms, 3)
        over["base_wall_ms"] = round(self._base_wall_ms, 3)
        return fold_profiles(self._base, session)

    def write_ledger(self) -> str:
        """Persist the merged ledger atomically (tmp + rename)."""
        doc = self.ledger()
        tmp = self.ledger_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        os.replace(tmp, self.ledger_path)
        return self.ledger_path

    def ledger_bytes(self) -> int:
        try:
            return os.path.getsize(self.ledger_path)
        except OSError:
            return 0

    def blackbox_snapshot(self) -> dict:
        """What the flight recorder ships with a FATAL bundle: the
        active sampling plan and the latest merged ledger (the
        performance context that preceded the impact)."""
        return {"sampling_plan": self.plan.describe(),
                "ring": self.ring.stats(),
                "workload_profile": self.ledger()}

    def summary(self) -> dict:
        """The serve summary's ``profile`` block."""
        doc = self.ledger()
        return {
            "captures": doc["captures"]["count"],
            "dispatches_seen":
                self._session["captures"]["dispatches_seen"],
            "sampling": self.plan.describe(),
            "ring": self.ring.stats(),
            "ring_evicted": self._gc_evicted,
            "orphans_swept": self.orphans_swept,
            "ledger_path": self.ledger_path,
            "ledger_bytes": self.ledger_bytes(),
            "sites_measured": len(doc["sites"]),
            "drift_events": self._session["drift"]["events"],
            "overhead_pct": doc["overhead"]["overhead_pct"],
        }
