"""Step-level device-side instrumentation: the host end of the callback
channel ``utils.progress`` traces into compiled programs.

``utils.progress.emit_step``/``emit_event`` are the *trace-time* half: when
(and only when) a program was compiled with telemetry or progress enabled,
each scan step fires an async ``jax.debug.callback`` carrying the step index
(tagged with its phase) or a (tag, value) pair. This module is the host
half: :func:`instrument` installs a :class:`StepCollector` as the progress
module's obs sink for the duration of a block, timestamping step boundaries
as the callbacks land and folding them into the default metrics registry:

- ``sampler_step_ms{phase=...}`` — host-observed ms/step per phase
  (``phase1``/``phase2`` for the gated sampler, ``invert``/``null_text``
  for the inversion programs). Async callbacks arrive unordered; deltas
  are only taken between increasing step indices, the same monotonic
  discipline as ``progress.StepReporter``.
- ``sampler_steps_total{phase=...}`` — callback count (a liveness check:
  zero events under an enabled run means the channel is mis-wired).
- ``host_event_value{tag=...}`` — generic traced-value events
  (e.g. ``invert.inner_steps``, the per-outer-step null-text inner
  iteration count).

:func:`sample_device_memory` reads every local device's ``memory_stats()``
into ``device_memory_bytes{device=...,stat=...}`` gauges (one timeline per
mesh shard, PR 9's per-device convention) — present on TPU backends,
silently absent on CPU (the method returns None there), never an error.

:func:`record_compile` is the shared counter for compile/build time hits —
``serve.programs.ProgramCache`` reports each miss's build wall time here so
the registry can answer "how much of this window went to compiles".
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional

from ..utils import progress as progress_mod
from . import metrics as metrics_mod


class StepCollector:
    """Host sink for compiled-loop step/event callbacks (see module doc)."""

    def __init__(self, registry: Optional[metrics_mod.Registry] = None):
        reg = registry or metrics_mod.registry()
        self._step_ms = reg.histogram(
            "sampler_step_ms", "host-observed sampling step time by phase",
            labels=("phase",), buckets=metrics_mod.STEP_MS_BUCKETS)
        self._steps = reg.counter(
            "sampler_steps_total", "step callbacks received by phase",
            labels=("phase",))
        self._events = reg.histogram(
            "host_event_value", "traced host-event values by tag",
            labels=("tag",), buckets=metrics_mod.COUNT_BUCKETS)
        # phase -> (last step index, host perf_counter at that step)
        self._last = {}

    # The progress-module sink protocol: ("step", index, phase) for step
    # callbacks, (tag, value, None) for generic events.
    def __call__(self, tag: str, value, phase=None) -> None:
        if tag == "step":
            self.on_step(int(value), phase)
        else:
            self._events.labels(tag=str(tag)).observe(float(value))

    def on_step(self, step: int, phase) -> None:
        label = str(phase) if phase is not None else "main"
        now = time.perf_counter()
        self._steps.labels(phase=label).inc()
        last = self._last.get(label)
        if last is None:
            self._last[label] = (step, now)
        elif step > last[0]:
            dt_ms = (now - last[1]) / (step - last[0]) * 1000.0
            self._step_ms.labels(phase=label).observe(dt_ms)
            self._last[label] = (step, now)
        elif step < last[0]:
            # Step index went backwards: a NEW run started under the same
            # collector (multi-seed CLI loop, bench repeats) — re-arm the
            # timeline without observing, or every run after the first
            # would be silently dropped from the histogram. (A same-run
            # async late arrival can land here too; the reset only skews
            # the one next delta, bounded, vs losing whole runs.)
            self._last[label] = (step, now)
        # step == last[0]: duplicate delivery — ignore.


@contextlib.contextmanager
def instrument(registry: Optional[metrics_mod.Registry] = None):
    """Install a :class:`StepCollector` as the progress obs sink for the
    block. On exit the in-flight callback stream is drained
    (``jax.effects_barrier`` — dispatch is async) before the sink is
    removed, so late steps land in the collector instead of vanishing."""
    collector = StepCollector(registry)
    progress_mod.set_obs_sink(collector)
    try:
        yield collector
    finally:
        try:
            import jax

            jax.effects_barrier()
        except Exception:
            pass
        progress_mod.set_obs_sink(None)


def sample_device_memory(
        registry: Optional[metrics_mod.Registry] = None) -> dict:
    """Sample EVERY local device's ``memory_stats()`` into gauges with a
    ``device`` label (PR 9's per-device metric convention — under
    ``--mesh`` each shard's HBM pressure is its own timeline, exactly
    what the eviction/degradation ladder needs to see per device).
    Returns ``{device_id: {stat: value}}`` — {} when the backend exposes
    nothing (CPU returns no memory_stats; never an error)."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return {}
    reg = registry or metrics_mod.registry()
    gauge = reg.gauge("device_memory_bytes",
                      "jax device memory_stats() samples per local device",
                      labels=("device", "stat"))
    out: dict = {}
    for d in devices:
        try:
            stats = d.memory_stats() or {}
        except Exception:
            stats = {}
        sampled = {}
        for key, val in stats.items():
            if isinstance(val, (int, float)):
                gauge.labels(device=str(getattr(d, "id", "?")),
                             stat=str(key)).set(float(val))
                sampled[str(key)] = val
        if sampled:
            out[str(getattr(d, "id", "?"))] = sampled
    return out


def record_compile(ms: float, what: str = "program",
                   registry: Optional[metrics_mod.Registry] = None) -> None:
    """One compile/build observation. ``what``: 'program' (a whole
    ProgramCache miss, build+warm lump) — decomposed under the cost
    observatory into 'build' (lowering + XLA compile) vs 'warm' (warm-up
    execution), so cost cards can attribute the two separately."""
    reg = registry or metrics_mod.registry()
    reg.counter("compiles_total", "program builds recorded",
                labels=("what",)).labels(what=what).inc()
    reg.histogram("compile_ms", "program build/warm wall time",
                  labels=("what",),
                  buckets=metrics_mod.LATENCY_MS_BUCKETS
                  ).labels(what=what).observe(float(ms))
