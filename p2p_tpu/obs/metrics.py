"""Process-global metrics registry: counters, gauges, bucket histograms.

The serving loop and the sampler need numbers that survive past a single
record stream — queue depth over time, per-phase step-time distributions,
program-cache hit rates — without dragging a metrics dependency into the
image. This module is that substrate: dependency-free (stdlib only),
single-process (the serve loop is single-threaded by design; a lock guards
only family registration for safety), and cheap enough to leave on.

Design points, in the Prometheus idiom but trimmed to what this repo uses:

- **Families, not bare metrics.** ``registry().counter(name, help,
  labels=("status",))`` returns a :class:`Family`; ``family.labels(
  status="ok")`` returns the child :class:`Counter`. Registration is
  get-or-create and idempotent — re-declaring the same family from another
  module returns the existing one; a kind/label mismatch raises (two
  subsystems silently sharing a name with different shapes is a bug).
- **Histograms store buckets, never samples.** A fixed, monotonically
  increasing bound tuple; ``observe`` bumps one cumulative-style bucket
  count plus sum/count. p50/p95/p99 come from :meth:`Histogram.quantile`
  by linear interpolation inside the owning bucket — bounded memory no
  matter how many requests flow through, at bucket-width resolution (the
  acceptance contract everywhere is "agrees within one bucket").
- **snapshot/reset.** :meth:`Registry.snapshot` returns plain dicts (the
  JSONL export unit); :meth:`Registry.reset` zeroes every child *in place*
  so long-lived references (e.g. a ``ProgramCache``'s counters) stay live
  across serve runs.
- **Exposition.** :meth:`Registry.to_prometheus` renders the text format
  (``# HELP``/``# TYPE``, ``_bucket{le=...}``/``_sum``/``_count``);
  :meth:`Registry.write_jsonl` writes one JSON line per sample.
"""

from __future__ import annotations

import json
import threading
from typing import Dict, Iterable, Optional, Tuple

# Shared bound sets. Milli­second latencies span queue waits (sub-ms on the
# virtual clock) to cold compiles (minutes); step times are tighter.
LATENCY_MS_BUCKETS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
                      1000.0, 2500.0, 5000.0, 10000.0, 30000.0, 60000.0,
                      180000.0)
STEP_MS_BUCKETS = (0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                   500.0, 1000.0, 2500.0, 5000.0, 15000.0)
# Small-integer distributions: batch occupancy, inner-iteration counts.
COUNT_BUCKETS = (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 24.0, 32.0,
                 64.0, 128.0)


class Counter:
    """Monotonic accumulator (``inc`` only)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter increments must be >= 0, got {n}")
        self.value += n

    def _zero(self) -> None:
        self.value = 0.0

    def _sample(self) -> dict:
        return {"value": self.value}


class Gauge:
    """Point-in-time value (``set``/``add``)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def add(self, n: float = 1.0) -> None:
        self.value += n

    def _zero(self) -> None:
        self.value = 0.0

    def _sample(self) -> dict:
        return {"value": self.value}


class Histogram:
    """Fixed-bound bucket histogram; quantiles from buckets, no samples.

    ``bounds`` are the finite upper bounds (ascending); an implicit +Inf
    bucket catches the tail. ``counts[i]`` is the number of observations
    ``<= bounds[i]`` exclusive of lower buckets (per-bucket, cumulated only
    at exposition time, which keeps ``observe`` one index + two adds)."""

    __slots__ = ("bounds", "counts", "sum", "count")

    def __init__(self, bounds: Tuple[float, ...]):
        if not bounds or list(bounds) != sorted(set(bounds)):
            raise ValueError(f"histogram bounds must be ascending and "
                             f"non-empty, got {bounds!r}")
        self.bounds = tuple(float(b) for b in bounds)
        self.counts = [0] * (len(self.bounds) + 1)  # +1: the +Inf bucket
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        self.counts[self.bucket_index(v)] += 1
        self.sum += v
        self.count += 1

    def bucket_index(self, v: float) -> int:
        """Index of the bucket ``v`` falls into (len(bounds) = the +Inf
        tail). Exposed so tests can assert 'within one bucket'."""
        for i, b in enumerate(self.bounds):
            if v <= b:
                return i
        return len(self.bounds)

    def quantile(self, q: float) -> float:
        """Estimate the ``q``-quantile (q in [0, 1]) from bucket counts:
        linear interpolation between the owning bucket's bounds (lower bound
        0 for the first bucket; the +Inf bucket reports its finite floor —
        the honest answer bounded storage can give)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        if self.count == 0:
            return 0.0
        rank = q * self.count
        cum = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if cum + c >= rank:
                if i >= len(self.bounds):       # +Inf tail
                    return self.bounds[-1]
                lo = self.bounds[i - 1] if i else 0.0
                hi = self.bounds[i]
                frac = (rank - cum) / c
                return lo + (hi - lo) * max(0.0, min(1.0, frac))
            cum += c
        return self.bounds[-1]

    def _zero(self) -> None:
        self.counts = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0

    def _sample(self) -> dict:
        cum, buckets = 0, []
        for b, c in zip(self.bounds, self.counts):
            cum += c
            buckets.append([b, cum])
        return {"count": self.count, "sum": self.sum, "buckets": buckets}


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class Family:
    """One named metric family: a kind, label names, and labeled children."""

    def __init__(self, name: str, kind: str, help: str,
                 label_names: Tuple[str, ...],
                 buckets: Optional[Tuple[float, ...]] = None):
        self.name = name
        self.kind = kind
        self.help = help
        self.label_names = tuple(label_names)
        self.buckets = tuple(buckets) if buckets else None
        self._children: Dict[Tuple[str, ...], object] = {}

    def labels(self, **kv):
        """The child at these label values (created on first use)."""
        if set(kv) != set(self.label_names):
            raise ValueError(
                f"{self.name}: labels {sorted(kv)} != declared "
                f"{sorted(self.label_names)}")
        key = tuple(str(kv[n]) for n in self.label_names)
        child = self._children.get(key)
        if child is None:
            child = (Histogram(self.buckets) if self.kind == "histogram"
                     else _KINDS[self.kind]())
            self._children[key] = child
        return child

    # Unlabeled families act as the metric itself (the common case).
    def _default(self):
        return self.labels()

    def inc(self, n: float = 1.0) -> None:
        self._default().inc(n)

    def set(self, v: float) -> None:
        self._default().set(v)

    def add(self, n: float = 1.0) -> None:
        self._default().add(n)

    def observe(self, v: float) -> None:
        self._default().observe(v)

    @property
    def value(self) -> float:
        return self._default().value

    def quantile(self, q: float):
        return self._default().quantile(q)

    def bucket_index(self, v: float):
        return self._default().bucket_index(v)

    @property
    def count(self):
        return self._default().count

    @property
    def sum(self):
        return self._default().sum

    def samples(self) -> Iterable[Tuple[Dict[str, str], object]]:
        for key, child in sorted(self._children.items()):
            yield dict(zip(self.label_names, key)), child

    def _zero(self) -> None:
        for child in self._children.values():
            child._zero()


def _escape(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _label_str(labels: Dict[str, str], extra: str = "") -> str:
    parts = [f'{k}="{_escape(v)}"' for k, v in labels.items()]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt(v: float) -> str:
    return repr(int(v)) if float(v).is_integer() else repr(float(v))


class Registry:
    """Named families, get-or-create. One process-global default instance
    (:func:`registry`); tests may build private ones."""

    def __init__(self):
        self._families: Dict[str, Family] = {}
        self._lock = threading.Lock()

    def _family(self, name: str, kind: str, help: str,
                labels: Tuple[str, ...],
                buckets: Optional[Tuple[float, ...]] = None) -> Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.label_names != tuple(labels) or (
                        kind == "histogram" and buckets
                        and fam.buckets != tuple(buckets)):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}{fam.label_names} — cannot re-register "
                        f"as {kind}{tuple(labels)}")
                return fam
            fam = Family(name, kind, help, labels, buckets)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labels: Tuple[str, ...] = ()) -> Family:
        return self._family(name, "counter", help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Tuple[str, ...] = ()) -> Family:
        return self._family(name, "gauge", help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Tuple[str, ...] = (),
                  buckets: Tuple[float, ...] = LATENCY_MS_BUCKETS) -> Family:
        return self._family(name, "histogram", help, labels, buckets)

    def get(self, name: str) -> Optional[Family]:
        return self._families.get(name)

    def snapshot(self) -> dict:
        """Plain-dict view of every family (the JSONL export unit)."""
        out = {}
        for name, fam in sorted(self._families.items()):
            out[name] = {
                "type": fam.kind, "help": fam.help,
                "samples": [{"labels": labels, **child._sample()}
                            for labels, child in fam.samples()],
            }
        return out

    def reset(self) -> None:
        """Zero every child in place: families (and references to their
        children) survive, values restart — the between-runs semantics the
        CLI uses so one snapshot covers one run."""
        for fam in self._families.values():
            fam._zero()

    def to_prometheus(self) -> str:
        """Text exposition format (the ``--metrics-out`` artifact)."""
        lines = []
        for name, fam in sorted(self._families.items()):
            if fam.help:
                lines.append(f"# HELP {name} {fam.help}")
            lines.append(f"# TYPE {name} {fam.kind}")
            for labels, child in fam.samples():
                if fam.kind == "histogram":
                    cum = 0
                    for b, c in zip(child.bounds, child.counts):
                        cum += c
                        le = 'le="%g"' % b
                        lines.append(f"{name}_bucket"
                                     f"{_label_str(labels, le)} {cum}")
                    inf = 'le="+Inf"'
                    lines.append(f"{name}_bucket{_label_str(labels, inf)}"
                                 f" {child.count}")
                    lines.append(f"{name}_sum{_label_str(labels)}"
                                 f" {_fmt(child.sum)}")
                    lines.append(f"{name}_count{_label_str(labels)}"
                                 f" {child.count}")
                else:
                    lines.append(f"{name}{_label_str(labels)}"
                                 f" {_fmt(child.value)}")
        return "\n".join(lines) + ("\n" if lines else "")

    def write_jsonl(self, fp) -> int:
        """One JSON line per sample to an open file; returns lines written."""
        n = 0
        for name, fam in sorted(self._families.items()):
            for labels, child in fam.samples():
                fp.write(json.dumps({"metric": name, "type": fam.kind,
                                     "labels": labels, **child._sample()})
                         + "\n")
                n += 1
        return n


_default = Registry()


def registry() -> Registry:
    """The process-global registry every instrumented subsystem shares."""
    return _default
