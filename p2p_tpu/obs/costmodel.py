"""The cost observatory: XLA cost cards, platform peaks, roofline, MFU.

The R5 perf verdict ("40.6 ms/step ≈ 45% MFU is the XLA ceiling") was
hand-computed arithmetic in PERF.md plus a one-off ``cost_analysis()``
call in a profiling scratch script — no serve program, bench round or CI
leg could state its own FLOPs, bytes or MFU. This module makes that
arithmetic a first-class, testable data path:

- **Cost cards** (:class:`CostCard`): the XLA ``cost_analysis()`` scalars
  (flops, bytes accessed, transcendentals — behind the dict-vs-list
  API-drift guard :func:`cost_analysis_dict`, the one shared parser every
  driver now uses) plus the ``memory_analysis()`` byte budget (argument /
  output / temp / generated-code), extracted from any compiled program at
  build time.
- **Peaks** (:class:`Peaks`): per-platform peak FLOP/s + memory bytes/s.
  Known accelerators come from the datasheet table
  (:data:`PLATFORM_PEAKS` — v5e is the chip every PERF.md number was
  measured on); a CPU rehearsal host gets *calibrated microbenchmark*
  peaks (:func:`calibrated_cpu_peaks`) so the MFU/roofline arithmetic is
  exercised end to end everywhere, not only on chip.
- **Roofline + MFU** (:func:`roofline`, :func:`mfu_pct`): arithmetic
  intensity vs the ridge point classifies a program compute- vs
  bandwidth-bound and predicts its ms; measured MFU is
  ``flops ÷ measured_seconds ÷ peak`` — the exact PERF.md headline
  formula, now tool-derived (``tools/perfscope.py --headline`` reproduces
  89 TF/s ≈ 45% MFU at 40.75 ms/step from the recorded artifacts alone).
- **Frozen budgets** (:func:`load_budgets` / :func:`check_budgets`): the
  canonical programs' flops/bytes are committed in
  ``tools/cost_budgets.json`` and diffed by the default-on
  ``cost_regression`` quality-gate leg — a refactor that silently doubles
  the phase-2 program's bytes accessed fails CI *by program name*, the
  same discipline jaxcheck applies to compile keys and collectives.
- **CostScope**: the serve engine's hook. Every ``ProgramCache`` miss
  records its program's cost card (``serve --cost`` / ``--programs-out``);
  every dispatch contributes a measured-MFU observation; the serve
  summary gains a ``cost`` block and flight ``run`` segments gain
  predicted-vs-measured attribution. ``costscope=None`` (the default)
  changes nothing — not a record byte, a journal line, a compiled
  program or a metric family (the same disabled-mode discipline as
  flight/slo/semcache).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional

from . import metrics as metrics_mod

#: Default location of the frozen per-canonical-program budgets, relative
#: to the repo root (tools/perfscope.py --update-budgets rewrites it).
DEFAULT_BUDGETS = os.path.join("tools", "cost_budgets.json")

#: Budget-frozen cost-card fields: program *shape* facts (deterministic
#: for a given HLO), never timings.
BUDGET_FIELDS = ("flops", "bytes_accessed")

#: Relative drift tolerance for the budget diff: generous enough that
#: XLA-version jitter and fusion-order noise never flap the gate, tight
#: enough that a structural regression (a 2x bytes blow-up, a vanished
#: cache) cannot hide.
DEFAULT_RTOL = 0.25

#: MFU percentage histogram bounds (CostScope's dispatch observations).
MFU_PCT_BUCKETS = (1.0, 2.0, 5.0, 10.0, 15.0, 20.0, 30.0, 40.0, 50.0,
                   60.0, 70.0, 80.0, 90.0, 100.0)


# ---------------------------------------------------------------------------
# cost_analysis / memory_analysis extraction (the shared API-drift guard)
# ---------------------------------------------------------------------------


def cost_analysis_dict(compiled) -> dict:
    """The ``cost_analysis()`` properties of a compiled program as one flat
    dict — the shared parser behind every driver (this module,
    ``tools/profiling/prof_breakdown.py``).

    Guards the known jax API drift: older releases return a *list* of
    per-computation dicts, newer ones a plain dict; some backends return
    None or raise. Always returns a dict ({} when nothing is available),
    never raises."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(ca, dict):
        return dict(ca)
    if isinstance(ca, (list, tuple)) and ca and isinstance(ca[0], dict):
        return dict(ca[0])
    return {}


def memory_analysis_dict(compiled) -> dict:
    """The scalar byte counters of ``memory_analysis()`` as a plain dict
    ({} when the backend exposes nothing). Only the stable numeric
    attributes are read — the stats object also carries a serialized HLO
    proto that must never leak into a JSON artifact."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return {}
    if ma is None:
        return {}
    out = {}
    for attr in ("argument_size_in_bytes", "output_size_in_bytes",
                 "temp_size_in_bytes", "alias_size_in_bytes",
                 "generated_code_size_in_bytes"):
        val = getattr(ma, attr, None)
        if isinstance(val, (int, float)):
            out[attr] = int(val)
    return out


@dataclasses.dataclass
class CostCard:
    """One program's build-time cost facts (see the module docstring)."""

    program: str
    flops: float = 0.0
    bytes_accessed: float = 0.0
    transcendentals: float = 0.0
    argument_bytes: int = 0
    output_bytes: int = 0
    temp_bytes: int = 0
    generated_code_bytes: int = 0
    build_ms: float = 0.0          # lowering + XLA compile wall time
    warm_ms: float = 0.0           # warm-up execution wall time

    @property
    def peak_bytes(self) -> int:
        """The resident-byte budget the executable needs at once
        (arguments + outputs + temporaries + code)."""
        return (self.argument_bytes + self.output_bytes + self.temp_bytes
                + self.generated_code_bytes)

    @property
    def arith_intensity(self) -> float:
        """FLOPs per byte accessed (0 when bytes are unknown)."""
        return (self.flops / self.bytes_accessed
                if self.bytes_accessed else 0.0)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["peak_bytes"] = self.peak_bytes
        d["arith_intensity"] = self.arith_intensity
        return d


def card_from_compiled(compiled, program: str, build_ms: float = 0.0,
                       warm_ms: float = 0.0) -> CostCard:
    """Extract a :class:`CostCard` from a ``jax.stages.Compiled``."""
    ca = cost_analysis_dict(compiled)
    ma = memory_analysis_dict(compiled)
    return CostCard(
        program=program,
        flops=float(ca.get("flops", 0.0)),
        bytes_accessed=float(ca.get("bytes accessed", 0.0)),
        transcendentals=float(ca.get("transcendentals", 0.0)),
        argument_bytes=ma.get("argument_size_in_bytes", 0),
        output_bytes=ma.get("output_size_in_bytes", 0),
        temp_bytes=ma.get("temp_size_in_bytes", 0),
        generated_code_bytes=ma.get("generated_code_size_in_bytes", 0),
        build_ms=float(build_ms), warm_ms=float(warm_ms))


# ---------------------------------------------------------------------------
# Platform peaks
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Peaks:
    """Peak FLOP/s and memory bytes/s of one device, with provenance."""

    flops_per_s: float
    bytes_per_s: float
    platform: str = "unknown"
    source: str = "fake"          # "datasheet" | "calibrated" | "fake"

    @property
    def ridge(self) -> float:
        """Arithmetic intensity (flops/byte) at the roofline ridge point:
        programs above it are compute-bound, below it bandwidth-bound."""
        return (self.flops_per_s / self.bytes_per_s
                if self.bytes_per_s else 0.0)

    def to_dict(self) -> dict:
        return {**dataclasses.asdict(self), "ridge": self.ridge}


#: Datasheet peaks by ``device_kind`` substring (lower-cased match). The
#: v5e row is the chip every PERF.md number was measured on (bf16 matmul
#: ≈ 197 TF/s, HBM ≈ 819 GB/s — PERF.md "Hardware & workload").
PLATFORM_PEAKS = {
    "v5 lite": Peaks(197e12, 819e9, "tpu v5e", "datasheet"),
    "v5e": Peaks(197e12, 819e9, "tpu v5e", "datasheet"),
    "v5p": Peaks(459e12, 2765e9, "tpu v5p", "datasheet"),
    "v4": Peaks(275e12, 1228e9, "tpu v4", "datasheet"),
}

_CPU_PEAKS_CACHE: List[Optional[Peaks]] = [None]


def calibrated_cpu_peaks(refresh: bool = False) -> Peaks:
    """Microbenchmark-calibrated peaks for the rehearsal host, cached per
    process: a jitted f32 matmul for FLOP/s, a jitted add-copy for
    bytes/s (best-of-3 each, so a scheduler hiccup cannot deflate the
    peak and inflate every MFU computed against it). CPU MFU numbers are
    *relative to this calibration*, which is exactly what makes the
    roofline arithmetic testable off-chip — they are not comparable to
    datasheet-peak MFU on an accelerator and are labeled
    ``source="calibrated"`` so no artifact can confuse the two."""
    if _CPU_PEAKS_CACHE[0] is not None and not refresh:
        return _CPU_PEAKS_CACHE[0]
    import jax
    import jax.numpy as jnp
    import numpy as np

    n = 512
    a = jnp.asarray(np.random.RandomState(0).rand(n, n), jnp.float32)
    mm = jax.jit(lambda x, y: x @ y)
    jax.block_until_ready(mm(a, a))              # compile
    t_mm = min(_timed(lambda: jax.block_until_ready(mm(a, a)))
               for _ in range(3))
    flops_per_s = 2.0 * n ** 3 / max(t_mm, 1e-9)

    big = jnp.zeros((8 * 1024 * 1024,), jnp.float32)      # 32 MiB
    add = jax.jit(lambda x: x + 1.0)
    jax.block_until_ready(add(big))              # compile
    t_add = min(_timed(lambda: jax.block_until_ready(add(big)))
                for _ in range(3))
    bytes_per_s = 2.0 * big.size * 4 / max(t_add, 1e-9)   # read + write

    peaks = Peaks(flops_per_s, bytes_per_s, "cpu", "calibrated")
    _CPU_PEAKS_CACHE[0] = peaks
    return peaks


def _timed(fn) -> float:
    import time

    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def detect_peaks(device=None) -> Peaks:
    """Peaks for ``device`` (default: the first local device): datasheet
    numbers for known accelerators, calibrated microbenchmarks for the
    CPU rehearsal host, and a calibration fallback for unknown hardware
    (honest measured numbers beat a guessed table row). The fallback
    keeps the device's real platform label — a microbenchmark run on an
    unlisted accelerator is still *that* device's calibration, and
    labeling it "cpu" would be exactly the provenance confusion the
    ``source`` field exists to prevent (a tiny matmul cannot saturate a
    big accelerator, so treat fallback MFU as an upper bound there)."""
    import jax

    if device is None:
        device = jax.local_devices()[0]
    if device.platform != "cpu":
        peaks = lookup_peaks(getattr(device, "device_kind", ""))
        if peaks is not None:
            return peaks
        return dataclasses.replace(
            calibrated_cpu_peaks(),
            platform=(getattr(device, "device_kind", "")
                      or device.platform))
    return calibrated_cpu_peaks()


def lookup_peaks(device_kind: str) -> Optional[Peaks]:
    """Datasheet peaks by device-kind substring, or None when unknown."""
    kind = (device_kind or "").lower()
    for key, peaks in PLATFORM_PEAKS.items():
        if key in kind:
            return peaks
    return None


# ---------------------------------------------------------------------------
# Roofline / MFU arithmetic
# ---------------------------------------------------------------------------


def roofline(flops: float, bytes_accessed: float, peaks: Peaks,
             devices: int = 1) -> dict:
    """Roofline verdict for one program on ``devices`` copies of
    ``peaks``: which resource bounds it, and the model-predicted ms."""
    pf = peaks.flops_per_s * max(1, devices)
    pb = peaks.bytes_per_s * max(1, devices)
    compute_s = flops / pf if pf else 0.0
    memory_s = bytes_accessed / pb if pb else 0.0
    bound = "compute" if compute_s >= memory_s else "bandwidth"
    intensity = flops / bytes_accessed if bytes_accessed else 0.0
    return {"arith_intensity": intensity,
            "ridge": peaks.ridge,
            "bound": bound,
            "compute_ms": compute_s * 1e3,
            "memory_ms": memory_s * 1e3,
            "predicted_ms": max(compute_s, memory_s) * 1e3}


def mfu_pct(flops: float, run_ms: float, peaks: Peaks,
            devices: int = 1) -> Optional[float]:
    """Measured model-FLOPs utilization: ``flops / seconds / peak`` as a
    percentage — the PERF.md headline formula. None when the run time is
    unusable (a zero-timer rehearsal run measures control flow, not
    compute)."""
    if run_ms <= 0.0 or flops <= 0.0 or peaks.flops_per_s <= 0.0:
        return None
    return (flops / (run_ms / 1e3)
            / (peaks.flops_per_s * max(1, devices))) * 100.0


# ---------------------------------------------------------------------------
# Frozen budgets
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class BudgetVerdict:
    """One (program, field) budget comparison."""

    program: str
    field: str
    frozen: Optional[float]
    measured: Optional[float]
    ok: bool
    problem: str = ""

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def format(self) -> str:
        ratio = ("-" if not (self.frozen and self.measured)
                 else f"{self.measured / self.frozen:.3f}x")
        return (f"{'ok  ' if self.ok else 'FAIL'} cost_budget "
                f"{self.program:18s} {self.field:14s} {ratio:>8s} "
                f"{self.problem}")


def load_budgets(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def check_budgets(cards: Dict[str, dict], budgets: dict,
                  rtol: Optional[float] = None) -> List[BudgetVerdict]:
    """Diff measured canonical cost cards against the frozen budgets.

    Failures name the program (the acceptance contract: a perturbed
    phase-2 bytes budget must fail ``cost_regression`` *by name*). Both
    directions are covered: a frozen program with no card means the
    canonical set silently lost a program; a card with no frozen entry
    means a new canonical program shipped without freezing its budget."""
    if rtol is None:
        rtol = float(budgets.get("rtol", DEFAULT_RTOL))
    frozen_programs = budgets.get("programs", {})
    out: List[BudgetVerdict] = []
    for name in sorted(frozen_programs):
        frozen = frozen_programs[name]
        card = cards.get(name)
        if card is None:
            out.append(BudgetVerdict(
                name, "presence", None, None, False,
                "canonical program missing from the cost pass"))
            continue
        for field in BUDGET_FIELDS:
            want = frozen.get(field)
            got = float(card.get(field, 0.0))
            if want is None:
                continue
            if want <= 0:
                ok = got <= 0
                problem = "" if ok else "frozen 0 but program now costs"
            else:
                ratio = got / want
                ok = abs(ratio - 1.0) <= rtol
                problem = ("" if ok else
                           f"drifted {ratio:.2f}x past the ±{rtol:.0%} "
                           f"budget (frozen {want:.4g}, measured "
                           f"{got:.4g})")
            out.append(BudgetVerdict(name, field, want, got, ok, problem))
    for name in sorted(set(cards) - set(frozen_programs)):
        out.append(BudgetVerdict(
            name, "presence", None,
            float(cards[name].get("flops", 0.0)), False,
            "program has no frozen budget (freeze it: "
            "python tools/perfscope.py --update-budgets)"))
    return out


# ---------------------------------------------------------------------------
# Canonical cost pass (the jaxcheck `cost` section / budget source)
# ---------------------------------------------------------------------------


def canonical_cost_cards(pipe=None, bucket: int = 1) -> Dict[str, dict]:
    """Cost cards for the canonical serve programs at one lane bucket:
    the monolithic sweep and the two phase-pool programs (the same
    canonical set the jaxpr contracts trace, compiled here because cost
    analysis needs the optimized executable, not the jaxpr). Input
    construction mirrors ``analysis.contracts`` exactly — the cards must
    describe the programs the contracts certify."""
    import warnings

    import jax
    import jax.numpy as jnp

    from ..analysis import contracts as contracts_mod
    from ..engine.sampler import encode_prompts, phase2_controller
    from ..parallel.sweep import sweep, sweep_phase1, sweep_phase2

    if pipe is None:
        pipe = contracts_mod.tiny_pipeline()
    steps, gate = contracts_mod.STEPS, contracts_mod.GATE
    ctrl = contracts_mod._edit_controller(pipe)
    ctx, lats, _ = contracts_mod._scan_inputs(pipe)

    def lead(x):
        return jnp.broadcast_to(x[None], (bucket,) + x.shape)

    ctx_g, lat_g = lead(ctx), lead(lats)
    ctrl_g = jax.tree_util.tree_map(lead, ctrl)

    cards: Dict[str, dict] = {}

    def compiled_card(name, lowered):
        card = card_from_compiled(lowered.compile(), name)
        cards[name] = card.to_dict()

    # The canonical gate=2-of-3 deliberately truncates the controller's
    # 0.8T edit window (same constants as the contract traces) — the
    # engine's surfaced-truncation warning is expected here, not news.
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", UserWarning)
        compiled_card(
            f"sweep/b{bucket}",
            sweep(pipe, ctx_g, lat_g, ctrl_g, num_steps=steps,
                  lower_only=True))
        compiled_card(
            f"sweep/phase1/b{bucket}",
            sweep_phase1(pipe, ctx_g, lat_g, ctrl_g, num_steps=steps,
                         gate=gate, lower_only=True))
        cond = encode_prompts(pipe, list(contracts_mod.PROMPTS))
        carry = contracts_mod._zero_carry(pipe, ctrl)
        p2 = phase2_controller(ctrl)
        ctx2 = lead(cond)
        carry_g = jax.tree_util.tree_map(lead, carry)
        p2_g = (None if p2 is None
                else jax.tree_util.tree_map(lead, p2))
        compiled_card(
            f"sweep/phase2/b{bucket}",
            sweep_phase2(pipe, ctx2, carry_g, p2_g, num_steps=steps,
                         gate=gate, lower_only=True))
        # Kernel-bearing twin (ISSUE 16): the monolithic sweep dispatched
        # through the fused-edit kernel config, under the full-coverage
        # store=False kernel controller the contracts trace. Compiled via
        # the pallas interpreter (the CPU-compilable rehearsal of the same
        # program structure), so its frozen budget pins the fused program's
        # logical footprint next to its materialized sibling's.
        from ..kernels import KernelConfig

        kctrl = contracts_mod._kernel_controller(pipe)
        kctrl_g = jax.tree_util.tree_map(lead, kctrl)
        compiled_card(
            f"sweep/kernel/b{bucket}",
            sweep(pipe, ctx_g, lat_g, kctrl_g, num_steps=steps,
                  lower_only=True, kernels=KernelConfig(interpret=True)))
    return cards


# ---------------------------------------------------------------------------
# CostScope: the serve engine's observatory hook
# ---------------------------------------------------------------------------


def _program_label(key, bucket: int) -> str:
    """Compact human label for a program-cache key: the compile key's
    parts joined, suffixed with the lane bucket. Long parts (controller
    treedef reprs) collapse to a stable short hash so the label stays
    readable while distinct programs stay distinct."""
    import hashlib

    def short(p) -> str:
        s = str(p)
        if len(s) <= 24:
            return s
        return s[:10] + "~" + hashlib.sha1(s.encode()).hexdigest()[:8]

    if isinstance(key, tuple):
        parts = "/".join(short(p) for p in key)
    else:
        parts = short(key)
    return f"{parts}@b{bucket}"


class CostScope:
    """Per-serve-run cost observatory (see the module docstring).

    One scope covers one ``serve_forever`` run: the engine records a cost
    card at every ``ProgramCache`` miss (:meth:`record_program`) and an
    observation at every dispatch (:meth:`dispatch`). The scope owns the
    peak table, the per-program aggregation, the ``--programs-out``
    artifact and the summary's ``cost`` block. Everything is host-side:
    enabling a scope never changes a compiled program, a per-request
    record or a journal byte (the per-request JSONL stream stays
    byte-identical; only the *summary* gains a ``cost`` block)."""

    def __init__(self, peaks: Optional[Peaks] = None,
                 registry: Optional[metrics_mod.Registry] = None,
                 devices: int = 1):
        self.peaks = peaks if peaks is not None else detect_peaks()
        self.devices = max(1, int(devices))
        self._programs: Dict = {}          # (key, bucket) -> program dict
        reg = registry or metrics_mod.registry()
        # Families register only when a scope exists: a cost-less serve
        # run's registry snapshot stays byte-identical to the pre-cost
        # engine's (the disabled-mode discipline).
        self._m_cards = reg.counter(
            "cost_cards_total", "program cost cards recorded at build")
        self._m_flops = reg.gauge(
            "cost_program_flops", "XLA cost_analysis flops per program",
            labels=("program",))
        self._m_bytes = reg.gauge(
            "cost_program_bytes_accessed",
            "XLA cost_analysis bytes accessed per program",
            labels=("program",))
        self._m_mfu = reg.histogram(
            "cost_dispatch_mfu_pct",
            "measured model-FLOPs utilization per dispatch",
            labels=("program",), buckets=MFU_PCT_BUCKETS)

    # -- build-time ------------------------------------------------------

    def record_program(self, key, bucket: int, compiled,
                       build_ms: float = 0.0,
                       warm_ms: float = 0.0) -> Optional[dict]:
        """Record one program's cost card at build time (a cache miss).
        Returns the program entry, or None when the executable exposes
        no cost analysis."""
        label = _program_label(key, bucket)
        card = card_from_compiled(compiled, label, build_ms=build_ms,
                                  warm_ms=warm_ms)
        if card.flops <= 0 and card.bytes_accessed <= 0:
            # Backend exposes no cost analysis: no card beats a zero-cost
            # card (a flops=0 entry would ride flight segments and the
            # summary as a confidently-measured free program).
            return None
        roof = roofline(card.flops, card.bytes_accessed, self.peaks,
                        devices=self.devices)
        entry = {**card.to_dict(), **roof,
                 "bucket": bucket,
                 "devices": self.devices,
                 "dispatches": 0, "run_ms_sum": 0.0,
                 "mfu_pct_sum": 0.0, "mfu_samples": 0}
        self._programs[(key, bucket)] = entry
        self._m_cards.inc()
        self._m_flops.labels(program=label).set(card.flops)
        self._m_bytes.labels(program=label).set(card.bytes_accessed)
        return entry

    # -- dispatch-time ---------------------------------------------------

    def dispatch(self, key, bucket: int, run_ms: float,
                 lanes: int = 0) -> dict:
        """One dispatch observation against the program's card. Returns
        the flight-segment attribution attrs ({} when the program has no
        card — e.g. a fake-runner test harness, or a zero-timer run where
        measured MFU is meaningless)."""
        entry = self._programs.get((key, bucket))
        if entry is None:
            return {}
        entry["dispatches"] += 1
        entry["run_ms_sum"] += float(run_ms)
        attrs = {"predicted_ms": round(entry["predicted_ms"], 3)}
        mfu = mfu_pct(entry["flops"], run_ms, self.peaks,
                      devices=self.devices)
        if mfu is not None:
            entry["mfu_pct_sum"] += mfu
            entry["mfu_samples"] += 1
            self._m_mfu.labels(program=entry["program"]).observe(mfu)
            attrs["mfu_pct"] = round(mfu, 2)
        return attrs

    # -- artifacts -------------------------------------------------------

    def programs(self) -> List[dict]:
        """Per-program entries in build order, with derived means."""
        out = []
        for entry in self._programs.values():
            d = dict(entry)
            n = d.pop("dispatches")
            run_sum = d.pop("run_ms_sum")
            mfu_sum = d.pop("mfu_pct_sum")
            mfu_n = d.pop("mfu_samples")
            d["dispatches"] = n
            d["mean_run_ms"] = (run_sum / n) if n else 0.0
            d["mean_mfu_pct"] = (mfu_sum / mfu_n) if mfu_n else None
            out.append(d)
        return out

    def write_programs_jsonl(self, fp) -> int:
        """One JSON line per recorded program (``serve --programs-out``);
        returns lines written."""
        n = 0
        for entry in self.programs():
            fp.write(json.dumps(entry) + "\n")
            n += 1
        return n

    def summary(self) -> dict:
        """The serve summary's ``cost`` block."""
        progs = self.programs()
        dispatched = [p for p in progs if p["dispatches"]]
        mfus = [p["mean_mfu_pct"] for p in dispatched
                if p["mean_mfu_pct"] is not None]
        return {
            "peaks": self.peaks.to_dict(),
            "devices": self.devices,
            "n_programs": len(progs),
            "n_dispatches": sum(p["dispatches"] for p in progs),
            "mean_mfu_pct": (sum(mfus) / len(mfus)) if mfus else None,
            "programs": [
                {k: p[k] for k in
                 ("program", "bucket", "flops", "bytes_accessed",
                  "arith_intensity", "bound", "predicted_ms", "build_ms",
                  "warm_ms", "dispatches", "mean_run_ms", "mean_mfu_pct")}
                for p in progs],
        }
