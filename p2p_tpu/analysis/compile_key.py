"""Compile-key completeness — the ``ProgramCache`` poisoning/churn guard.

``serve.request.prepare`` derives ``compile_key`` by hand: the fields that
change the XLA program must be in it (two requests sharing a key MUST mean
the same program — a missing field silently *poisons* the cache: request B
runs request A's program), and fields that don't change the program must be
absent (a superfluous field splits one program across many keys — retracing
churn, and the dynamic batcher can then never co-batch the two requests).

This checker stops trusting the hand-derivation: it sweeps **every**
``Request`` field, perturbs it against a base request, traces the serve
batch program each variant would compile (``jax.make_jaxpr`` — structural
tracing only, no XLA), and asserts both directions per field:

- program changed  ⟹  ``compile_key`` changed   (else: cache poisoning)
- program unchanged ⟹ ``compile_key`` unchanged (else: retracing churn)

The sweep also fails on any ``Request`` field it has no variant for — a
*new* field added to the schema cannot dodge the checker by omission.

The program fingerprint is the jaxpr's printed structure: op sequence,
shapes, dtypes, scan lengths, sub-jaxprs. Constant *values* (e.g. a
scheduler's sigma table) don't print — a field that changed only trained
constants of identical shape would be invisible — but every field that can
change the program today does it structurally (steps → scan length,
scheduler → different step ops, gate → second scan, controller structure →
different edit ops).

``key_fn`` swaps the key derivation under test; the regression test masks
a jaxpr-affecting component through it and asserts the sweep catches the
seeded omission (the acceptance criterion for this checker).
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Callable, Dict, List, Optional, Tuple

#: Base request every field perturbs against: a 2-prompt replace edit (so
#: controller-shaping fields are live) with no blend/equalizer (so adding
#: them is a structure change). Word counts match across prompt variants —
#: 'replace' requires aligned token counts.
BASE = dict(
    request_id="ck-base",
    prompt="a cat riding a bike",
    target="a dog riding a bike",
    mode="replace",
    steps=3,
    scheduler="ddim",
    seed=11,
    guidance=7.5,
)

#: field -> (variant value, extra overrides applied to BOTH sides of the
#: comparison — context a field needs to be meaningful). The extras may
#: also override the field's own base value (``blend_resolution`` defaults
#: to 16, which no TINY attention site stores). Every Request field MUST
#: appear here — the sweep errors on gaps, so extending the schema forces
#: a decision about program identity.
VARIANTS: Dict[str, Tuple[object, dict]] = {
    "request_id": ("ck-other", {}),
    "prompt": ("a pig riding a bike", {}),
    "target": ("a fox riding a bike", {}),
    "mode": ("refine", {}),
    "cross_steps": (0.5, {}),
    "self_steps": (0.7, {}),
    "blend_words": ("bike", {"blend_resolution": 8}),
    "equalizer": ("bike=2.0", {}),
    # blend_resolution shapes the LocalBlend mask pooling, so its own
    # comparison needs a blend in the base — and a base resolution TINY
    # actually stores (8, not the schema default 16).
    "blend_resolution": (4, {"blend_words": "bike",
                             "blend_resolution": 8}),
    "seed": (7, {}),
    "steps": (4, {}),
    "scheduler": ("dpm", {}),
    "guidance": (3.0, {}),
    "negative_prompt": ("blurry", {}),
    "gate": (0.5, {}),
    # ISSUE 15: a NON-uniform reuse schedule (a uniform one would
    # normalize onto the plain gate and be a deliberate no-op). At the
    # base's steps=3 this resolves to cfg_gate=2 with one early cross
    # flip and the self sites inherited from step 2 — segmented
    # programs, so the jaxpr fingerprint moves with the key.
    "schedule": ({"cfg_gate": 2,
                  "cross": {"*": 2, "cross_attn/down1": 1},
                  "self": {"*": 2}}, {}),
    "arrival_ms": (125.0, {}),
    "deadline_ms": (5000.0, {}),
    "priority": (3, {}),
    # SLO scheduling metadata (ISSUE 12): pure scheduler inputs — they
    # must change neither the program nor any compile key (tiers must
    # not fragment programs; the tier joins the *batch* key only, and
    # only under an active SloConfig).
    "tenant": ("acme", {}),
    "tier": ("premium", {}),
}


@dataclasses.dataclass
class FieldVerdict:
    field: str
    program_changed: bool
    key_changed: bool

    @property
    def ok(self) -> bool:
        return self.program_changed == self.key_changed

    @property
    def problem(self) -> str:
        if self.ok:
            return ""
        if self.program_changed:
            return ("changes the traced program but NOT compile_key — "
                    "ProgramCache poisoning: two requests differing only "
                    "in this field would share one compiled program")
        return ("changes compile_key but NOT the traced program — "
                "retracing churn: identical programs split across cache "
                "keys and batching buckets")

    def format(self) -> str:
        marks = (f"program={'Δ' if self.program_changed else '='} "
                 f"key={'Δ' if self.key_changed else '='}")
        return (f"{'ok  ' if self.ok else 'FAIL'} {self.field:18s} {marks}"
                + (f"  {self.problem}" if not self.ok else ""))


def _request(overrides: dict):
    from ..serve.request import Request

    return Request(**{**BASE, **overrides})


def _overrides_key(overrides: dict) -> Tuple:
    """Hashable fingerprint-cache key for an override set — JSON-object
    values (the ``schedule`` spec) canonicalize through a sorted dump."""
    import json

    def canon(v):
        return (json.dumps(v, sort_keys=True)
                if isinstance(v, (dict, list)) else v)

    return tuple(sorted((k, canon(v)) for k, v in overrides.items()))


def _program_fingerprint(pipe, prep) -> str:
    """Hash of the serve batch program this prepared request would compile
    (bucket 1 — bucket only scales the group axis, per-field identity is
    bucket-independent). Mirrors ``serve.programs.SweepRunner``: same
    encode calls, same ``_sweep_jit`` entry, same static arguments."""
    import jax
    import jax.numpy as jnp

    from ..engine.sampler import encode_prompts, init_latent
    from ..models.config import unet_layout
    from ..ops import schedulers as sched_mod
    from ..parallel.sweep import _sweep_jit

    req = prep.request
    cfg = pipe.config
    layout = unet_layout(cfg.unet)
    schedule = sched_mod.schedule_from_config(req.steps, cfg.scheduler,
                                              kind=req.scheduler)
    cond = encode_prompts(pipe, list(req.prompts))
    uncond = encode_prompts(pipe,
                            [req.negative_prompt or ""] * len(req.prompts))
    ctx = jnp.concatenate([uncond, cond], axis=0)[None]
    _, lat = init_latent(None, pipe.latent_shape,
                         jax.random.PRNGKey(req.seed), len(req.prompts))
    lat = lat[None]
    ctrl = (None if prep.controller is None else jax.tree_util.tree_map(
        lambda x: jnp.stack([x]), prep.controller))
    gs = jnp.float32(req.guidance)

    def run(up, vp, ctx, lat, ctrl, gs):
        return _sweep_jit(up, vp, cfg, layout, schedule, req.scheduler,
                          ctx, lat, ctrl, gs, None, progress=False,
                          gate=prep.gate_step, metrics=False,
                          reuse=prep.schedule)

    jaxpr = jax.make_jaxpr(run)(pipe.unet_params, pipe.vae_params, ctx,
                                lat, ctrl, gs)
    return hashlib.sha256(str(jaxpr).encode()).hexdigest()


#: The phase-key sweep's base: the same request GATED (steps=4 so
#: gate=0.5 → step 2 leaves both phases ≥ 2 steps) — the disaggregated
#: pool keys only exist for gated requests. Field variants that need a
#: different value under this base override VARIANTS here.
PHASE_EXTRA = {"gate": 0.5, "steps": 4}
PHASE_VARIANT_OVERRIDES: Dict[str, Tuple[object, dict]] = {
    # The gated base pins steps=4 and gate=0.5, so the plain variants
    # (steps=4, gate=0.5) would be no-ops; these move them instead:
    # steps 4→5 changes both pool scan lengths, gate 0.5→0.75 moves the
    # boundary (phase-1 grows, phase-2 shrinks) — THE hand-off regression
    # this sweep exists for: a gate change that altered a phase program
    # but not its key would poison the pool cache.
    "steps": (5, {}),
    "gate": (0.75, {}),
    # ISSUE 15: under the gated phase base the schedule comparison runs
    # schedule-vs-schedule (gate and schedule are mutually exclusive, so
    # the extras swap the base's gate for an equivalent-boundary
    # schedule). Base and variant differ ONLY in WHICH cross site flips
    # early — a phase-1-only cell: the phase-1 program and key must both
    # move, while the phase-2 view of both collapses to the uniform
    # table (key component None) and the phase-2 program stays put —
    # the projection-correctness regression for the split keys.
    "schedule": ({"cfg_gate": 2,
                  "cross": {"*": 2, "cross_attn/down3": 1},
                  "self": {"*": None}},
                 {"gate": None,
                  "schedule": {"cfg_gate": 2,
                               "cross": {"*": 2, "cross_attn/down1": 1},
                               "self": {"*": None}}}),
}


def _phase_fingerprints(pipe, prep) -> Tuple[str, str]:
    """Hashes of the two POOL programs this gated prepared request would
    compile (bucket 1). Mirrors ``serve.programs.Phase1Runner`` /
    ``Phase2Runner``: same input construction, same jitted entries, same
    static arguments — including the phase-2 controller reduction."""
    import jax
    import jax.numpy as jnp

    from ..engine.sampler import (encode_prompts, init_latent,
                                  phase2_controller)
    from ..models.config import unet_layout
    from ..ops import schedulers as sched_mod
    from ..parallel.sweep import _sweep_phase1_jit, _sweep_phase2_jit
    from ..serve.handoff import carry_template

    req = prep.request
    cfg = pipe.config
    layout = unet_layout(cfg.unet)
    schedule = sched_mod.schedule_from_config(req.steps, cfg.scheduler,
                                              kind=req.scheduler)
    cond = encode_prompts(pipe, list(req.prompts))
    uncond = encode_prompts(pipe,
                            [req.negative_prompt or ""] * len(req.prompts))
    ctx = jnp.concatenate([uncond, cond], axis=0)[None]
    _, lat = init_latent(None, pipe.latent_shape,
                         jax.random.PRNGKey(req.seed), len(req.prompts))
    lat = lat[None]
    ctrl = (None if prep.controller is None else jax.tree_util.tree_map(
        lambda x: jnp.stack([x]), prep.controller))
    gs = jnp.float32(req.guidance)

    # Mirror the pool runners exactly: each phase program is keyed (and
    # traced) with its PROJECTED schedule component from the split key —
    # None (plain gate) when the view collapsed to the uniform table.
    from ..engine.reuse import ReuseSchedule

    def view_sched(phase_key):
        skey = phase_key[-1]
        return None if skey is None else ReuseSchedule.from_key(skey)

    reuse1 = view_sched(prep.phase1_key)
    reuse2 = view_sched(prep.phase2_key)

    def run1(up, ctx, lat, ctrl, gs):
        return _sweep_phase1_jit(up, cfg, layout, schedule, req.scheduler,
                                 ctx, lat, ctrl, gs, progress=False,
                                 gate=prep.gate_step, metrics=False,
                                 reuse=reuse1)

    fp1 = jax.make_jaxpr(run1)(pipe.unet_params, ctx, lat, ctrl, gs)

    # carry_template returns the hand-off unit {"carry", "ctx"}; the jit
    # takes the sampler carry and the cond context as separate arguments
    # (mirroring Phase2Runner's unpack).
    carry = jax.tree_util.tree_map(lambda x: jnp.stack([x]),
                                   carry_template(pipe, prep)["carry"])
    p2 = phase2_controller(prep.controller)
    p2_g = (None if p2 is None else jax.tree_util.tree_map(
        lambda x: jnp.stack([x]), p2))

    def run2(up, vp, ctx_c, carry, ctrl, gs):
        return _sweep_phase2_jit(up, vp, cfg, layout, schedule,
                                 req.scheduler, ctx_c, carry, ctrl, gs,
                                 progress=False, gate=prep.gate_step,
                                 metrics=False, reuse=reuse2)

    fp2 = jax.make_jaxpr(run2)(pipe.unet_params, pipe.vae_params,
                               cond[None], carry, p2_g, gs)
    return (hashlib.sha256(str(fp1).encode()).hexdigest(),
            hashlib.sha256(str(fp2).encode()).hexdigest())


def check_phase_keys(pipe=None,
                     key1_fn: Optional[Callable] = None,
                     key2_fn: Optional[Callable] = None,
                     fields: Optional[List[str]] = None
                     ) -> List[FieldVerdict]:
    """The completeness sweep over the SPLIT per-phase pool keys: every
    Request field is perturbed against a *gated* base, the two pool
    programs each variant would compile are traced, and both directions
    must hold per field per pool — a field that changes a pool program
    must change that pool's compile key (else: pool-cache poisoning, the
    hand-off serving requests a mismatched program), and one that doesn't
    must not (else: retracing churn and lost phase-2 packing). Verdicts
    come back as ``<field>@phase1`` / ``<field>@phase2``.

    ``key1_fn``/``key2_fn`` override the keys under test (the regression
    hook: masking the gate from ``phase2_key`` must be caught as
    poisoning for exactly the ``gate`` field)."""
    from ..serve.request import Request, prepare

    if pipe is None:
        from .contracts import tiny_pipeline

        pipe = tiny_pipeline()
    key1_fn = key1_fn or (lambda prep: prep.phase1_key)
    key2_fn = key2_fn or (lambda prep: prep.phase2_key)

    declared = {f.name for f in dataclasses.fields(Request)}
    missing = declared - set(VARIANTS)
    if missing:
        raise ValueError(
            f"Request field(s) {sorted(missing)} have no compile-key sweep "
            "variant: add them to analysis.compile_key.VARIANTS so the "
            "completeness check covers the new schema")

    todo = fields if fields is not None else sorted(VARIANTS)
    fp_cache: Dict[Tuple, Tuple[str, str]] = {}

    def fingerprint(overrides: dict):
        prep = prepare(_request({**PHASE_EXTRA, **overrides}), pipe)
        assert prep.gated, ("phase-key sweep base must stay gated; "
                            f"overrides {overrides} ungated it")
        cache_key = _overrides_key(overrides)
        if cache_key not in fp_cache:
            fp_cache[cache_key] = _phase_fingerprints(pipe, prep)
        return fp_cache[cache_key], key1_fn(prep), key2_fn(prep)

    verdicts = []
    for field in todo:
        variant, extra = PHASE_VARIANT_OVERRIDES.get(field, VARIANTS[field])
        (base1, base2), bk1, bk2 = fingerprint(dict(extra))
        (var1, var2), vk1, vk2 = fingerprint({**extra, field: variant})
        verdicts.append(FieldVerdict(field=f"{field}@phase1",
                                     program_changed=var1 != base1,
                                     key_changed=vk1 != bk1))
        verdicts.append(FieldVerdict(field=f"{field}@phase2",
                                     program_changed=var2 != base2,
                                     key_changed=vk2 != bk2))
    return verdicts


def check_compile_key(pipe=None,
                      key_fn: Optional[Callable] = None,
                      fields: Optional[List[str]] = None
                      ) -> List[FieldVerdict]:
    """Sweep every Request field; returns one :class:`FieldVerdict` each.

    ``key_fn(prepared) -> hashable`` overrides the key under test (default:
    the real ``prepared.compile_key``) — the masking hook the regression
    test uses. ``fields`` narrows the sweep. Raises ``ValueError`` when a
    Request field has no sweep variant (schema grew past the checker)."""
    from ..serve.request import Request, prepare

    if pipe is None:
        from .contracts import tiny_pipeline

        pipe = tiny_pipeline()
    key_fn = key_fn or (lambda prep: prep.compile_key)

    declared = {f.name for f in dataclasses.fields(Request)}
    missing = declared - set(VARIANTS)
    if missing:
        raise ValueError(
            f"Request field(s) {sorted(missing)} have no compile-key sweep "
            "variant: add them to analysis.compile_key.VARIANTS so the "
            "completeness check covers the new schema")
    unknown = set(VARIANTS) - declared
    if unknown:
        raise ValueError(f"sweep variant(s) {sorted(unknown)} no longer "
                         "exist on Request: prune VARIANTS")

    todo = fields if fields is not None else sorted(VARIANTS)
    fp_cache: Dict[Tuple, str] = {}

    def fingerprint(overrides: dict):
        prep = prepare(_request(overrides), pipe)
        cache_key = _overrides_key(overrides)
        if cache_key not in fp_cache:
            fp_cache[cache_key] = _program_fingerprint(pipe, prep)
        return fp_cache[cache_key], key_fn(prep)

    verdicts = []
    for field in todo:
        variant, extra = VARIANTS[field]
        base_fp, base_key = fingerprint(dict(extra))
        var_fp, var_key = fingerprint({**extra, field: variant})
        verdicts.append(FieldVerdict(
            field=field,
            program_changed=var_fp != base_fp,
            key_changed=var_key != base_key))
    return verdicts


# ---------------------------------------------------------------------------
# Content-key completeness (ISSUE 13) — the semantic-cache poisoning guard
# ---------------------------------------------------------------------------

#: Which Request fields determine the request's OUTPUT IMAGES — the
#: checker's own declaration, independent of the hand partition in
#: ``serve.request`` (CONTENT_FIELDS/SCHEDULING_FIELDS), so the two
#: derivations cross-check each other. A field marked True must perturb
#: ``content_key`` (missing ⇒ cache *poisoning*: a hit serves wrong
#: images); a field marked False must not (superfluous ⇒ identical
#: traffic split across cache lines: lost hits). The sweep also fails on
#: any Request field absent from this map — a new schema field cannot
#: dodge the cache-identity decision by omission.
OUTPUT_DETERMINING: Dict[str, bool] = {
    "prompt": True,
    "target": True,
    "mode": True,
    "cross_steps": True,
    "self_steps": True,
    "blend_words": True,
    "equalizer": True,
    "blend_resolution": True,
    "seed": True,
    "steps": True,
    "scheduler": True,
    "guidance": True,
    "negative_prompt": True,
    "gate": True,
    # ISSUE 15: a (non-uniform) reuse schedule changes which site-steps
    # compute — different images. Keyed on the RESOLVED table, so specs
    # resolving identically (and the uniform table vs plain gate=g)
    # share a cache line.
    "schedule": True,
    "request_id": False,
    "arrival_ms": False,
    "deadline_ms": False,
    "priority": False,
    "tenant": False,
    "tier": False,
}


@dataclasses.dataclass
class ContentVerdict:
    field: str
    output_determining: bool
    key_changed: bool

    @property
    def ok(self) -> bool:
        return self.output_determining == self.key_changed

    @property
    def problem(self) -> str:
        if self.ok:
            return ""
        if self.output_determining:
            return ("determines the output images but NOT content_key — "
                    "cache poisoning: a request differing only in this "
                    "field would be served another request's images")
        return ("changes content_key but NOT the output — lost hits: "
                "identical traffic split across cache lines by pure "
                "scheduling metadata")

    def format(self) -> str:
        marks = (f"output={'Δ' if self.output_determining else '='} "
                 f"key={'Δ' if self.key_changed else '='}")
        return (f"{'ok  ' if self.ok else 'FAIL'} {self.field:18s} {marks}"
                + (f"  {self.problem}" if not self.ok else ""))


def check_content_key(pipe=None,
                      key_fn: Optional[Callable] = None,
                      fields: Optional[List[str]] = None
                      ) -> List[ContentVerdict]:
    """The completeness sweep over the semantic cache's ``content_key``
    (ISSUE 13), same idiom as :func:`check_compile_key`: every Request
    field is perturbed against the edit base (so controller-shaping
    fields are live) and both directions must hold per field —
    output-determining fields (:data:`OUTPUT_DETERMINING`) must perturb
    the key, scheduling metadata must not.

    The oracle is the declared map rather than a traced program: seed,
    guidance and prompt change output *values* invisible to any jaxpr
    structure, so there is nothing cheaper than real execution to trace —
    the bitwise half is pinned empirically by the cache-parity drill
    (every cached serve bitwise-identical to its uncached twin) and by
    the value-only field test in tests/test_semcache.py. What this sweep
    stops trusting is the hand *derivation*: the checker's own field map
    is cross-checked against ``serve.request``'s CONTENT/SCHEDULING
    partition, and a schema field missing from either raises.

    ``key_fn(prepared) -> hashable`` overrides the key under test (the
    masking hook: hiding ``seed`` from the key must be caught as
    poisoning for exactly the ``seed`` field)."""
    from ..serve.request import (CONTENT_FIELDS, Request, SCHEDULING_FIELDS,
                                 prepare)

    if pipe is None:
        from .contracts import tiny_pipeline

        pipe = tiny_pipeline()
    key_fn = key_fn or (lambda prep: prep.content_key)

    declared = {f.name for f in dataclasses.fields(Request)}
    for name, covered in (("OUTPUT_DETERMINING map", set(OUTPUT_DETERMINING)),
                          ("compile-key sweep VARIANTS", set(VARIANTS))):
        missing = declared - covered
        if missing:
            raise ValueError(
                f"Request field(s) {sorted(missing)} are missing from the "
                f"{name}: extend analysis.compile_key so the content-key "
                "completeness check covers the new schema")
    # Cross-check the independent derivations: the checker's map vs the
    # serve schema's CONTENT/SCHEDULING partition.
    ours = {f for f, v in OUTPUT_DETERMINING.items() if v}
    theirs = set(CONTENT_FIELDS)
    if ours != theirs or (declared - ours) != set(SCHEDULING_FIELDS):
        raise ValueError(
            f"analysis.compile_key.OUTPUT_DETERMINING disagrees with "
            f"serve.request's CONTENT_FIELDS/SCHEDULING_FIELDS partition "
            f"on {sorted(ours ^ theirs)}: resolve which derivation is "
            "wrong before caching can serve this schema")

    todo = fields if fields is not None else sorted(OUTPUT_DETERMINING)
    verdicts = []
    for field in todo:
        variant, extra = VARIANTS[field]
        base_key = key_fn(prepare(_request(dict(extra)), pipe))
        var_key = key_fn(prepare(_request({**extra, field: variant}), pipe))
        verdicts.append(ContentVerdict(
            field=field,
            output_determining=OUTPUT_DETERMINING[field],
            key_changed=var_key != base_key))
    return verdicts
