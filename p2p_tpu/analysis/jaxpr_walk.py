"""Reusable jaxpr walkers — the contract pass's vocabulary.

Generalizes the ad-hoc walker ``tests/test_phase_cache.py`` grew for the
phase-2 "no 2B tensors" proof into the shared helpers every contract (and
that test) now uses: flatten a jaxpr recursively, pull shapes, find scans,
find callbacks, find dtype conversions. Everything here operates on
``jax.core`` data structures only — no tracing, no compilation.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple


def all_eqns(jaxpr) -> list:
    """Every equation in ``jaxpr``, recursing into sub-jaxprs (scan / cond /
    pjit / while bodies), so nothing hides one nesting level down. Accepts
    a ``ClosedJaxpr`` or a raw ``Jaxpr``."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    eqns = []
    for eqn in jaxpr.eqns:
        eqns.append(eqn)
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                eqns.extend(all_eqns(sub))
    return eqns


def _sub_jaxprs(param) -> Iterable:
    """Jaxprs embedded in one eqn param: a ClosedJaxpr, or a list/tuple of
    them (cond/switch carry `branches`)."""
    if hasattr(param, "jaxpr"):
        yield param
    elif isinstance(param, (list, tuple)):
        for item in param:
            if hasattr(item, "jaxpr"):
                yield item


def eqn_shapes(eqns) -> List[Tuple[int, ...]]:
    """Shapes of every in/out var across ``eqns`` (duplicates preserved —
    footprint questions care about how often a shape appears)."""
    out = []
    for eqn in eqns:
        for v in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(v, "aval", None)
            if aval is not None and hasattr(aval, "shape"):
                out.append(tuple(aval.shape))
    return out


def top_level_scans(jaxpr) -> list:
    """The outermost ``scan`` eqns of ``jaxpr`` in program order, looking
    through a single wrapping ``pjit``/``custom_*`` level (tracing a jitted
    entry point wraps the whole body in one pjit eqn)."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    scans = [e for e in jaxpr.eqns if e.primitive.name == "scan"]
    if scans:
        return scans
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in ("pjit", "custom_vjp_call_jaxpr",
                                  "custom_jvp_call", "remat"):
            for sub in _sub_jaxprs(eqn.params.get("jaxpr")):
                inner = top_level_scans(sub)
                if inner:
                    return inner
            # pjit stores it under 'jaxpr'; vmap-of-jit under nothing else.
    return scans


def scan_body(scan_eqn) -> list:
    """All eqns (recursive) of one scan eqn's body."""
    return all_eqns(scan_eqn.params["jaxpr"])


def callback_eqns(eqns) -> list:
    """Host-callback equations: ``debug_callback`` (the progress/obs sink
    channel), ``io_callback``, ``pure_callback`` — anything that escapes to
    the host mid-program."""
    return [e for e in eqns if "callback" in e.primitive.name]


def f64_eqns(eqns) -> list:
    """Equations producing (or converting to) float64 — the dtype-promotion
    contract. Catches both explicit ``convert_element_type`` to f64 and any
    op whose output aval is f64 (a promotion that skipped an explicit
    convert)."""
    import numpy as np

    bad = []
    for eqn in eqns:
        if eqn.primitive.name == "convert_element_type" and \
                np.dtype(eqn.params.get("new_dtype")) == np.float64:
            bad.append(eqn)
            continue
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            dt = getattr(aval, "dtype", None)
            if dt is not None and np.dtype(dt) == np.float64:
                bad.append(eqn)
                break
    return bad


def doubled_batch_shapes(shapes: Sequence[Tuple[int, ...]], group_batch: int,
                         max_tokens: Optional[int] = None,
                         lead_dims: Tuple[int, ...] = ()) -> list:
    """Shapes carrying the CFG-doubled batch ``2B`` — the phase-2 footprint
    detector (from tests/test_phase_cache.py, generalized).

    A hit is a ≥3-D tensor whose batch axis equals ``2 * group_batch``:
    4-D feature maps ``(2B, h, w, c)`` or 3-D token-major tensors
    ``(2B, P, C)`` with ``P ≤ max_tokens`` (so tiny coincidental dims don't
    count). ``lead_dims`` prefixes the expected batch position — a vmapped
    serve program carries a leading group axis, so its doubled tensors look
    like ``(G, 2B, ...)``: pass ``lead_dims=(G,)``.
    """
    two_b = 2 * group_batch
    k = len(lead_dims)
    hits = []
    for s in shapes:
        if len(s) < 3 + k or tuple(s[:k]) != tuple(lead_dims):
            continue
        body = s[k:]
        if body[0] != two_b:
            continue
        if len(body) == 4 or (
                len(body) == 3 and (max_tokens is None
                                    or body[1] <= max_tokens)):
            hits.append(s)
    return hits


def folded_batch_shapes(shapes: Sequence[Tuple[int, ...]],
                        batch: int) -> list:
    """4-D feature maps whose leading dim equals ``batch`` — the form a
    vmapped program's activations take after vmap folds the mapped group
    axis into the conv batch axis: a serve bucket's phase-1 CFG tensors are
    ``(G·2B, h, w, c)``. Only 4-D counts: weight tensors (conv kernels are
    ``(kh, kw, cin, cout)``, projections ≤ 3-D) can't collide."""
    return [s for s in shapes if len(s) == 4 and s[0] == batch]
