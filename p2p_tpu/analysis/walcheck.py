"""walcheck — exhaustive small-scope crash-consistency checking (ISSUE 20).

Pass 5's dynamic half. The chaos drills sample the WAL's crash windows one
kill at a time; this module *enumerates* them: every bounded-depth
interleaving of protocol records for K small requests, a crash injected at
every record boundary, every torn tail, and each of ``compact()``'s three
documented snapshot windows — each prefix folded through the REAL
``serve/journal.replay`` (loaded by path, no jax) and machine-checked
against an independent pure-Python oracle. Small-scope hypothesis: a
protocol bug that loses a request or double-serves one almost always has a
counterexample within 2–3 requests and a handful of records, so an
exhaustive sweep at that scope is worth more than any number of random
fuzz seeds — and tier-1 runs it on every commit (:data:`TIER1_SCOPE`,
also the report/gate default; the wider :data:`FULL_SCOPE` K=3 sweep is
the ``slow``-marked test in tests/test_walcheck.py).

Traces are generated FROM :data:`protocol.DECLARED_PROTOCOL` — a record
kind cannot be declared without being crash-tested (the coverage check
hard-errors if any declared kind or any ``protocol.CRASH_WINDOWS`` entry
goes unexercised). Seeded verdict-flips (:data:`SEEDED_BUGS`) prove the
checker can see: three planted protocol bugs — a dropped spill-fsync
ordering, a terminal-before-cache reorder, a hand-off retained past its
compact — must each flip the verdict with a failure naming the violated
invariant and the minimal counterexample trace.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import os
import shutil
import tempfile
from typing import Callable, Dict, List, Optional, Tuple

from . import protocol
from .protocol import DECLARED_EVENTS, DECLARED_PROTOCOL, GLOBAL

#: Short op labels for trace/counterexample strings.
_LABEL = {"admitted": "a", "dispatched": "d", "handoff": "h",
          "preempted": "p", "cache": "c", "terminal": "t", "event": "e",
          "compact": "C"}

#: Deterministic event payloads the executor writes and the oracle folds —
#: one entry per declared EVENT kind (validated at run start, so declaring
#: an event without teaching the model its payload is a hard error).
EVENT_PAYLOADS: Dict[str, dict] = {
    "degrade": {"level": 1},
    "restore": {"level": 0},
    "resize": {"new_dp": 2},
    "snapshot": {"seq": 7},
    "cache_shed": {},
    "drain": {"reason": "drill"},
    "drain_timeout": {"pending": 1},
    "fatal": {"reason": "drill"},
    "profile_drift": {},
}

_STATUSES = ("ok", "rejected", "expired", "timeout", "error",
             "invalid_output", "cancelled", "shed")


@dataclasses.dataclass(frozen=True)
class Op:
    """One protocol operation in a model trace."""

    kind: str                       # record kind, or "compact"
    rid: Optional[str] = None       # per-request records
    status: Optional[str] = None    # terminal
    event_kind: Optional[str] = None
    payload: Optional[Tuple[Tuple[str, object], ...]] = None

    def label(self) -> str:
        tag = _LABEL.get(self.kind, self.kind)
        if self.kind == "terminal":
            return f"{tag}({self.rid}:{self.status})"
        if self.kind == "event":
            return f"{tag}({self.event_kind})"
        if self.rid is not None:
            return f"{tag}({self.rid})"
        return tag

    def payload_dict(self) -> dict:
        return dict(self.payload or ())


@dataclasses.dataclass(frozen=True)
class Scope:
    """Enumeration bounds — the 'small scope' the sweep is exhaustive in."""

    name: str
    #: K: traces interleave up to this many concurrent request lifecycles.
    max_requests: int
    #: Per-request lifecycle path length bound (records for ONE request).
    max_path_ops: int
    #: Total trace length bound (sum over interleaved requests).
    max_depth: int
    #: Terminal statuses cycled across the enumeration (all of them get
    #: exercised as long as enough terminals are enumerated).
    statuses: Tuple[str, ...] = _STATUSES
    #: EVENT sub-kinds inserted (at every position) into K=1 traces.
    event_kinds: Tuple[str, ...] = tuple(DECLARED_EVENTS)
    #: Inject torn-tail crashes (mid-``write``) at every record.
    torn_tails: bool = True
    #: Run the compact sweep (snapshot∪tail ≡ full fold, at every cut) on
    #: traces with at most this many requests.
    compact_max_requests: int = 1
    #: Inject the three snapshot crash windows at every compact cut.
    compact_windows: bool = True


#: Runs inside tier-1 on every commit: K≤2, tiny depth, all statuses, all
#: event kinds, compact + all snapshot windows on K=1 traces.
TIER1_SCOPE = Scope("tier1", max_requests=2, max_path_ops=4, max_depth=6)

#: The quality-gate / jaxcheck scope: K≤3 interleavings, longer lifecycle
#: paths (re-dispatch after hand-off/preemption), compact on K≤2.
FULL_SCOPE = Scope("full", max_requests=3, max_path_ops=5, max_depth=7,
                   compact_max_requests=2)

#: Minimal scope the seeded verdict-flips run at: single request, "ok"
#: terminals, no events — the smallest box each planted bug is visible in,
#: so the reported counterexample is the minimal one.
BUG_SCOPE = Scope("seeded-bug", max_requests=1, max_path_ops=5, max_depth=5,
                  statuses=("ok",), event_kinds=(), torn_tails=True,
                  compact_max_requests=1, compact_windows=False)


@dataclasses.dataclass
class Violation:
    """One invariant violation at one crash point of one trace."""

    invariant: str
    window: str
    trace: str
    point: str
    detail: str

    def describe(self) -> str:
        return (f"{self.invariant} violated at {self.point} ({self.window})"
                f" of trace [{self.trace}]: {self.detail}")

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# Trace enumeration (from the declared protocol)
# ---------------------------------------------------------------------------

def request_paths(scope: Scope) -> List[Tuple[str, ...]]:
    """All per-request record-kind paths ``absent -> done`` the declared
    state machine admits within ``scope.max_path_ops``, shortest first."""
    paths: List[Tuple[str, ...]] = []

    def step(state: str, path: List[str]) -> None:
        if state == "done":
            paths.append(tuple(path))
            return
        if len(path) >= scope.max_path_ops:
            return
        for kind, d in DECLARED_PROTOCOL.items():
            if d.from_states == (GLOBAL,) or state not in d.from_states:
                continue
            if d.max_per_request is not None \
                    and path.count(kind) >= d.max_per_request:
                continue
            path.append(kind)
            step(d.to_state or state, path)
            path.pop()

    step("absent", [])
    return sorted(paths, key=lambda p: (len(p), p))


def _instantiate(path: Tuple[str, ...], rid: str,
                 statuses: "itertools.cycle") -> Tuple[Op, ...]:
    ops = []
    for kind in path:
        if kind == "terminal":
            ops.append(Op(kind, rid=rid, status=next(statuses)))
        else:
            ops.append(Op(kind, rid=rid))
    return tuple(ops)


def _merges(seqs: List[Tuple[Op, ...]]):
    """All order-preserving interleavings of the given op sequences."""
    total = sum(len(s) for s in seqs)
    idxs = [0] * len(seqs)
    acc: List[Op] = []

    def rec():
        if len(acc) == total:
            yield tuple(acc)
            return
        for k, seq in enumerate(seqs):
            if idxs[k] < len(seq):
                acc.append(seq[idxs[k]])
                idxs[k] += 1
                yield from rec()
                idxs[k] -= 1
                acc.pop()

    yield from rec()


def enumerate_traces(scope: Scope) -> List[Tuple[Op, ...]]:
    """Every bounded trace of the declared protocol at this scope, minimal
    (shortest) first: all K-way interleavings of complete request
    lifecycles, plus each declared EVENT kind inserted at every position
    of every single-request trace. Incomplete lifecycles need no separate
    enumeration — every crash prefix of a complete trace IS one."""
    paths = request_paths(scope)
    statuses = itertools.cycle(scope.statuses)
    traces: List[Tuple[Op, ...]] = []

    for k in range(1, scope.max_requests + 1):
        for combo in itertools.combinations_with_replacement(paths, k):
            if sum(len(p) for p in combo) > scope.max_depth:
                continue
            seqs = [_instantiate(p, f"r{i + 1}", statuses)
                    for i, p in enumerate(combo)]
            if k == 1:
                traces.append(seqs[0])
            else:
                traces.extend(_merges(seqs))

    # EVENT coverage: each declared kind inserted into every K=1 trace
    # (loop-level records interleave with one lifecycle; the compact sweep
    # below adds the event×snapshot interaction). Fold-bearing kinds
    # (degrade/restore/resize) go at EVERY position — their placement
    # changes the folded state. Informational kinds are no-ops to both the
    # oracle and replay, so one position per trace already proves the
    # reader reads past them (boundary + torn + compact included).
    for path in paths:
        base = _instantiate(path, "r1", statuses)
        for ek in scope.event_kinds:
            payload = tuple(sorted(EVENT_PAYLOADS[ek].items()))
            if DECLARED_EVENTS[ek].folds is not None:
                positions = range(len(base) + 1)
            else:
                positions = (len(base) // 2,)
            for pos in positions:
                traces.append(base[:pos]
                              + (Op("event", event_kind=ek,
                                    payload=payload),)
                              + base[pos:])
    traces.sort(key=len)
    return traces


def _trace_requests(ops: Tuple[Op, ...]) -> int:
    return len({op.rid for op in ops if op.rid is not None})


# ---------------------------------------------------------------------------
# The oracle: an independent pure fold of a trace prefix
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Expected:
    """What a correct restart must reconstruct from a durable prefix."""

    order: List[str] = dataclasses.field(default_factory=list)
    terminal: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: rid -> carry spill path of its LAST hand-off/preemption (includes
    #: terminal'd rids; liveness is filtered at check time).
    handoffs: Dict[str, str] = dataclasses.field(default_factory=dict)
    cache: Dict[str, str] = dataclasses.field(default_factory=dict)
    degrade_level: int = 0
    mesh_dp: int = 0

    @property
    def pending_ids(self) -> List[str]:
        return [r for r in self.order if r not in self.terminal]


def fold_expected(ops: Tuple[Op, ...], paths: Dict[str, Dict[str, str]]
                  ) -> Expected:
    """The oracle fold. ``paths``: rid -> {"carry": .., "cache": ..} spill
    paths the executor will use (so oracle and WAL agree byte-for-byte)."""
    exp = Expected()
    for op in ops:
        if op.kind == "admitted":
            if op.rid not in exp.order:
                exp.order.append(op.rid)
        elif op.kind == "terminal":
            exp.terminal.setdefault(op.rid, op.status)
        elif op.kind in ("handoff", "preempted"):
            exp.handoffs[op.rid] = paths[op.rid]["carry"]
        elif op.kind == "cache":
            exp.cache[f"key-{op.rid}"] = paths[op.rid]["cache"]
        elif op.kind == "event":
            decl = DECLARED_EVENTS[op.event_kind]
            if decl.folds is not None:
                val = int(op.payload_dict()[decl.payload])
                setattr(exp, decl.folds, val)
        # "dispatched" and "compact" fold to nothing.
    return exp


# ---------------------------------------------------------------------------
# Seeded verdict-flips
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class SeededBug:
    """One planted protocol bug the checker must catch (verdict flip)."""

    name: str
    #: Invariant name(s) an acceptable flip may report.
    expected_invariants: Tuple[str, ...]
    description: str
    #: The executor appends spill-bearing records BEFORE their spill file
    #: is durable (the file lands one op late) — the dropped-fsync bug.
    defer_spills: bool = False
    #: Trace rewrite applied before checking (protocol reorder bugs).
    transform: Optional[Callable] = None
    #: Applied to the snapshot file right after each compact (retention
    #: bugs that corrupt the compactor's output).
    snapshot_mutator: Optional[Callable] = None


def _reorder_cache_after_terminal(ops: Tuple[Op, ...]) -> Tuple[Op, ...]:
    out = list(ops)
    for rid in {op.rid for op in ops if op.kind == "cache"}:
        ci = next(i for i, op in enumerate(out)
                  if op.kind == "cache" and op.rid == rid)
        ti = next((i for i, op in enumerate(out)
                   if op.kind == "terminal" and op.rid == rid), None)
        if ti is not None and ti > ci:
            cache_op = out.pop(ci)
            out.insert(ti, cache_op)  # ti shifted down by the pop: lands
            # immediately AFTER the terminal — the reordered write.
    return tuple(out)


def _retain_handoffs_past_compact(spath: str, exp_cut: Expected) -> None:
    with open(spath, "r", encoding="utf-8") as f:
        snap = json.load(f)
    for rid in exp_cut.terminal:
        if rid in exp_cut.handoffs:
            snap.setdefault("handoffs", {})[rid] = {
                "type": "handoff", "id": rid,
                "carry_path": exp_cut.handoffs[rid], "spec": "spec-v1"}
    with open(spath, "w", encoding="utf-8") as f:
        json.dump(snap, f)


SEEDED_BUGS: Tuple[SeededBug, ...] = (
    SeededBug(
        "dropped-fsync",
        ("cache-spill-durable", "no-lost-handoff"),
        "spill files become durable one op AFTER their WAL record instead "
        "of before — a crash in between leaves a record pointing at "
        "nothing",
        defer_spills=True),
    SeededBug(
        "terminal-before-cache",
        ("cache-before-terminal",),
        "the semantic-cache insert record is appended after its leader's "
        "terminal instead of before — a crash in between makes the "
        "followers' cache hit unrecoverable",
        transform=_reorder_cache_after_terminal),
    SeededBug(
        "handoff-retained-past-compact",
        ("compact-hygiene",),
        "compact retains hand-off records of already-terminal requests in "
        "the snapshot — the restart would resume (re-run) finished work",
        snapshot_mutator=_retain_handoffs_past_compact),
)


# ---------------------------------------------------------------------------
# Trace execution through the real Journal
# ---------------------------------------------------------------------------

class _Boom(Exception):
    """The simulated crash ``on_durable`` raises in the overlap window."""


class _Executor:
    """Drives the REAL journal writers for a trace prefix in ``workdir``,
    honoring the spill-before-record discipline (or violating it, under
    the dropped-fsync seeded bug)."""

    def __init__(self, journal_mod, workdir: str,
                 bug: Optional[SeededBug] = None):
        self.jm = journal_mod
        self.workdir = workdir
        self.bug = bug
        self.wal = os.path.join(workdir, "wal")
        self.j = journal_mod.Journal(self.wal)
        self._deferred: List[str] = []
        self._vnow = 0.0
        self._batch = 0

    def spill_paths(self, rid: str) -> Dict[str, str]:
        return {"carry": self.j.carry_path(rid),
                "cache": os.path.join(self.workdir, f"cache-{rid}.bin")}

    def _write_spill(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "wb") as f:
            f.write(b"spill-bytes")

    def _spill_before(self, path: str) -> None:
        if self.bug is not None and self.bug.defer_spills:
            self._deferred.append(path)  # durable one op too late
        else:
            self._write_spill(path)

    def apply(self, op: Op, torn: bool = False) -> None:
        """Apply one op. ``torn=True`` models the crash landing mid-write
        of THIS op's record: the bytes are (partially) in the file but the
        writer never returned, so post-append side effects (the engine's
        post-terminal ``discard_carry`` hygiene) never ran."""
        # Flush spills the seeded dropped-fsync bug deferred: they become
        # durable only now, one op after their record — exactly the
        # ordering violation a crash in between exposes.
        for path in self._deferred:
            self._write_spill(path)
        self._deferred.clear()
        self._vnow += 1.0
        j, rid = self.j, op.rid
        if op.kind == "admitted":
            j.admitted({"request_id": rid, "prompt": f"prompt-{rid}"},
                       self._vnow)
        elif op.kind == "dispatched":
            self._batch += 1
            j.dispatched([rid], self._batch, self._vnow)
        elif op.kind in ("handoff", "preempted"):
            carry = self.spill_paths(rid)["carry"]
            self._spill_before(carry)
            if op.kind == "handoff":
                j.handoff(rid, self._vnow, carry, "spec-v1")
            else:
                j.preempted(rid, self._vnow, carry, "spec-v1", tier="batch")
        elif op.kind == "cache":
            cpath = self.spill_paths(rid)["cache"]
            self._spill_before(cpath)
            j.cache_insert(f"key-{rid}", rid, cpath, self._vnow)
        elif op.kind == "terminal":
            j.terminal(rid, op.status, self._vnow)
            if not torn:
                j.discard_carry(rid)  # the engine's post-terminal hygiene
        elif op.kind == "event":
            j.event(op.event_kind, **op.payload_dict())
        else:
            raise ValueError(f"unknown model op kind {op.kind!r}")
        j._f.flush()  # modeled durability: bytes visible to the reader

    def run(self, ops) -> None:
        for op in ops:
            self.apply(op)

    def crash(self) -> None:
        """Simulated kill: the file handle dies, deferred spills never
        land, no sync/close hygiene runs."""
        try:
            self.j._f.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Invariant checks
# ---------------------------------------------------------------------------

#: Every invariant the checker names in a failure (the docs table).
INVARIANTS = ("exactly-once-terminals", "pending-complete",
              "no-lost-handoff", "cache-index-complete",
              "cache-spill-durable", "cache-before-terminal",
              "degrade-resume", "resize-target-restart",
              "snapshot-tail-equivalence", "compact-hygiene")


def _check_state(st, exp: Expected, full_ops: Tuple[Op, ...],
                 trace_label: str, point: str, window: str,
                 out: List[Violation]) -> None:
    """Machine-check a replayed state against the oracle's expectation."""

    def viol(inv: str, detail: str) -> None:
        out.append(Violation(inv, window, trace_label, point, detail))

    if dict(st.terminal) != exp.terminal:
        viol("exactly-once-terminals",
             f"replay terminal map {dict(st.terminal)!r} != expected "
             f"{exp.terminal!r}")
    if list(st.pending_ids) != exp.pending_ids:
        viol("pending-complete",
             f"replay pending {list(st.pending_ids)!r} != expected "
             f"{exp.pending_ids!r} (a restart would lose or re-run work)")
    for rid, carry in exp.handoffs.items():
        if rid in exp.terminal:
            continue  # liveness: terminal'd spills are GC'd by design
        rec = st.handoffs.get(rid)
        if not isinstance(rec, dict) or rec.get("carry_path") != carry:
            viol("no-lost-handoff",
                 f"non-terminal {rid}'s durable hand-off record is gone "
                 f"after replay (record was appended before the crash)")
        elif not os.path.exists(carry):
            viol("no-lost-handoff",
                 f"non-terminal {rid}'s carry spill {carry} is missing "
                 f"after the replay sweep — phase-2 resume is impossible")
    for key, cpath in exp.cache.items():
        rec = st.cache_entries.get(key)
        if not isinstance(rec, dict) or rec.get("path") != cpath:
            viol("cache-index-complete",
                 f"durable cache insert {key!r} absent from the replayed "
                 f"cache index")
        elif not os.path.exists(cpath):
            viol("cache-spill-durable",
                 f"cache entry {key!r} points at missing spill {cpath} — "
                 f"the record outlived the bytes it references")
    for op in full_ops:
        if op.kind == "cache" and op.rid in st.terminal \
                and f"key-{op.rid}" not in st.cache_entries:
            viol("cache-before-terminal",
                 f"leader {op.rid}'s terminal is durable but its cache "
                 f"insert is not — the insert must be appended first")
    if int(st.degrade_level) != exp.degrade_level:
        viol("degrade-resume",
             f"replay degrade_level {st.degrade_level} != expected "
             f"{exp.degrade_level}")
    if int(st.mesh_dp) != exp.mesh_dp:
        viol("resize-target-restart",
             f"replay mesh_dp {st.mesh_dp} != committed resize target "
             f"{exp.mesh_dp} (restart would come up on the wrong mesh)")


def _check_snapshot_hygiene(spath: str, exp_cut: Expected,
                            trace_label: str, point: str,
                            out: List[Violation]) -> None:
    with open(spath, "r", encoding="utf-8") as f:
        snap = json.load(f)
    live = {rid for rid in exp_cut.handoffs if rid not in exp_cut.terminal}
    stale = sorted(set(snap.get("handoffs", {})) - live)
    if stale:
        out.append(Violation(
            "compact-hygiene", "compact-cut", trace_label, point,
            f"snapshot retains hand-off record(s) {stale} for requests "
            f"already terminal at compact time — a restart would resume "
            f"(re-run) finished work"))


# ---------------------------------------------------------------------------
# Crash-point drivers
# ---------------------------------------------------------------------------

def _torn_truncate(wal: str) -> bool:
    """Cut the WAL's last record mid-``write`` (keep half its bytes).
    Returns False when there is nothing to tear."""
    with open(wal, "rb") as f:
        data = f.read()
    body = data.rstrip(b"\n")
    if not body:
        return False
    cut = body.rfind(b"\n") + 1
    last = body[cut:]
    if len(last) < 2:
        return False
    with open(wal, "wb") as f:
        f.write(body[:cut] + last[:len(last) // 2])
    return True


class _Run:
    """One walcheck sweep: enumerate, execute, crash, fold, check."""

    def __init__(self, scope: Scope, root: Optional[str],
                 bug: Optional[SeededBug], workdir: str,
                 max_violations: int):
        self.scope = scope
        self.root = root
        self.bug = bug
        self.workdir = workdir
        self.max_violations = max_violations
        self.jm = protocol.load_journal(root)
        self.violations: List[Violation] = []
        self.windows_hit: set = set()
        self.kinds_hit: set = set()
        self.crash_points = 0
        self.traces = 0
        self._dir_seq = 0

    def _full(self) -> bool:
        return len(self.violations) >= self.max_violations

    def _fresh_dir(self) -> str:
        self._dir_seq += 1
        d = os.path.join(self.workdir, f"cp{self._dir_seq}")
        os.makedirs(d)
        return d

    def _start(self, ops: Tuple[Op, ...], n: int):
        """Fresh dir + executor with the first ``n`` ops applied; returns
        ``(ex, exps)`` where exps[i] is the oracle after i ops."""
        d = self._fresh_dir()
        ex = _Executor(self.jm, d, bug=self.bug)
        paths = {op.rid: ex.spill_paths(op.rid)
                 for op in ops if op.rid is not None}
        exps = [fold_expected(ops[:i], paths)
                for i in range(len(ops) + 1)]
        ex.run(ops[:n])
        return ex, exps

    def _fold(self, ex: _Executor):
        self.crash_points += 1
        return self.jm.replay(ex.wal)

    def _finish(self, ex: _Executor) -> None:
        shutil.rmtree(ex.workdir, ignore_errors=True)

    def check_trace(self, ops: Tuple[Op, ...]) -> None:
        if self.bug is not None and self.bug.transform is not None:
            ops = self.bug.transform(ops)
        self.traces += 1
        label = " ".join(op.label() for op in ops)
        for op in ops:
            self.kinds_hit.add(op.event_kind if op.kind == "event"
                               else op.kind)
            if op.kind == "event":
                self.kinds_hit.add("event")

        # -- crash at every record boundary --------------------------------
        for i in range(len(ops) + 1):
            if self._full():
                return
            ex, exps = self._start(ops, i)
            ex.crash()
            self.windows_hit.add("record-boundary")
            st = self._fold(ex)
            _check_state(st, exps[i], ops, label, f"boundary:{i}",
                         "record-boundary", self.violations)
            self._finish(ex)

        # -- torn tail at every record -------------------------------------
        if self.scope.torn_tails:
            for i in range(len(ops)):
                if self._full():
                    return
                ex, exps = self._start(ops, i)
                ex.apply(ops[i], torn=True)
                ex.crash()
                if _torn_truncate(ex.wal):
                    self.windows_hit.add("torn-tail")
                    st = self._fold(ex)
                    # The torn record must fold away: expected = prefix i.
                    _check_state(st, exps[i], ops, label, f"torn:{i}",
                                 "torn-tail", self.violations)
                self._finish(ex)

        # -- compact at every cut + the three snapshot windows -------------
        if _trace_requests(ops) > self.scope.compact_max_requests:
            return
        # The three snapshot windows replay only fold-relevant WAL content
        # at the cut; traces whose one event is informational add nothing
        # the base trace's windows don't cover, so they get the cut-mode
        # equivalence check but skip the (compact-heavy) window replays.
        windows = self.scope.compact_windows and not any(
            op.kind == "event"
            and DECLARED_EVENTS[op.event_kind].folds is None
            for op in ops)
        for c in range(len(ops) + 1):
            if self._full():
                return
            self._compact_cut(ops, c, label)
            if windows:
                self._snapshot_windows(ops, c, label)

    def _compact_cut(self, ops, c: int, label: str) -> None:
        """snapshot∪tail ≡ full-WAL fold: compact mid-trace at cut ``c``,
        run the rest, and the restart must see exactly the full fold."""
        ex, exps = self._start(ops, c)
        extra = {"degrade_level": exps[c].degrade_level,
                 "mesh_dp": exps[c].mesh_dp}
        ex.j.compact(extra=extra)
        spath = ex.wal + self.jm.SNAPSHOT_SUFFIX
        if self.bug is not None and self.bug.snapshot_mutator is not None:
            self.bug.snapshot_mutator(spath, exps[c])
        self.crash_points += 1
        _check_snapshot_hygiene(spath, exps[c], label, f"compact:{c}",
                                self.violations)
        ex.run(ops[c:])
        ex.crash()
        st = self.jm.replay(ex.wal)
        before = len(self.violations)
        _check_state(st, exps[len(ops)], ops, label, f"compact:{c}",
                     "record-boundary", self.violations)
        # Any divergence here IS the equivalence failure — name it too.
        if len(self.violations) > before:
            self.violations.append(Violation(
                "snapshot-tail-equivalence", "record-boundary", label,
                f"compact:{c}",
                "snapshot∪tail fold diverges from the full-WAL fold "
                "(see the preceding violation for the divergent field)"))
        self._finish(ex)

    def _snapshot_windows(self, ops, c: int, label: str) -> None:
        jm = self.jm
        # (1) crash mid-snapshot-write: only a torn .tmp exists; the WAL
        # is untouched and the restart must fold it fully + sweep the tmp.
        ex, exps = self._start(ops, c)
        with open(ex.wal + jm.SNAPSHOT_SUFFIX + ".tmp", "w",
                  encoding="utf-8") as f:
            f.write('{"version": 1, "torn')
        ex.crash()
        self.windows_hit.add("snapshot-torn-tmp")
        st = self._fold(ex)
        _check_state(st, exps[c], ops, label, f"snap-tmp:{c}",
                     "snapshot-torn-tmp", self.violations)
        if not os.path.exists(ex.wal + jm.SNAPSHOT_SUFFIX + ".tmp"):
            pass  # swept, as documented
        else:
            self.violations.append(Violation(
                "snapshot-tail-equivalence", "snapshot-torn-tmp", label,
                f"snap-tmp:{c}", "torn snapshot .tmp survived the sweep"))
        self._finish(ex)

        # (2) crash between the snapshot rename and the WAL rotation: the
        # snapshot and the full WAL overlap; folding both must be exact
        # (idempotent: first admission wins, duplicate terminals collapse).
        ex, exps = self._start(ops, c)

        def _die():
            raise _Boom()

        try:
            ex.j.compact(extra={"degrade_level": exps[c].degrade_level,
                                "mesh_dp": exps[c].mesh_dp},
                         on_durable=_die)
        except _Boom:
            pass
        if self.bug is not None and self.bug.snapshot_mutator is not None:
            self.bug.snapshot_mutator(ex.wal + jm.SNAPSHOT_SUFFIX, exps[c])
        ex.crash()
        self.windows_hit.add("snapshot-overlap")
        st = self._fold(ex)
        _check_state(st, exps[c], ops, label, f"snap-overlap:{c}",
                     "snapshot-overlap", self.violations)
        self._finish(ex)

        # (3) crash between rotation and old-segment removal: a stale
        # .old whose content the snapshot subsumes; replay must sweep it
        # and still fold exactly.
        ex, exps = self._start(ops, c)
        with open(ex.wal, "rb") as f:
            pre_bytes = f.read()
        ex.j.compact(extra={"degrade_level": exps[c].degrade_level,
                            "mesh_dp": exps[c].mesh_dp})
        with open(ex.wal + jm.OLD_SEGMENT_SUFFIX, "wb") as f:
            f.write(pre_bytes)
        ex.crash()
        self.windows_hit.add("snapshot-stale-old")
        st = self._fold(ex)
        _check_state(st, exps[c], ops, label, f"snap-old:{c}",
                     "snapshot-stale-old", self.violations)
        if os.path.exists(ex.wal + jm.OLD_SEGMENT_SUFFIX):
            self.violations.append(Violation(
                "snapshot-tail-equivalence", "snapshot-stale-old", label,
                f"snap-old:{c}",
                "stale rotated segment survived the replay sweep"))
        self._finish(ex)


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------

def run_walcheck(scope: Scope = TIER1_SCOPE, root: Optional[str] = None,
                 bug: Optional[SeededBug] = None,
                 workdir: Optional[str] = None,
                 max_violations: int = 25) -> dict:
    """The exhaustive sweep at ``scope``. Returns a summary dict:
    ``ok`` (no violations AND full kind/window coverage), the enumerated
    trace / crash-point counts, the violations (minimal-counterexample
    first: traces are checked shortest-first and each trace's earliest
    crash point first), and the coverage sets. ``bug`` plants one of
    :data:`SEEDED_BUGS` — the verdict must flip."""
    jm = protocol.load_journal(root)
    bad_status = set(scope.statuses) - set(jm.TERMINAL_STATUSES)
    if bad_status:
        raise ValueError(f"scope statuses {sorted(bad_status)} not in "
                         f"journal.TERMINAL_STATUSES")
    missing_payload = set(DECLARED_EVENTS) - set(EVENT_PAYLOADS)
    if missing_payload:
        raise ValueError(
            f"declared event kind(s) {sorted(missing_payload)} have no "
            f"EVENT_PAYLOADS entry — the model cannot exercise them")

    own_tmp = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="walcheck-")
    try:
        run = _Run(scope, root, bug, workdir, max_violations)
        for ops in enumerate_traces(scope):
            run.check_trace(ops)
            if run._full() or (bug is not None and run.violations):
                break
    finally:
        if own_tmp:
            shutil.rmtree(workdir, ignore_errors=True)

    required_kinds = ((set(DECLARED_PROTOCOL) - {"event"})
                      | set(scope.event_kinds)
                      | ({"event"} if scope.event_kinds else set()))
    kinds_missing = sorted(required_kinds - run.kinds_hit)
    required_windows = set(protocol.CRASH_WINDOWS)
    if not scope.torn_tails:
        required_windows.discard("torn-tail")
    if not scope.compact_windows:
        required_windows -= {"snapshot-torn-tmp", "snapshot-overlap",
                             "snapshot-stale-old"}
    windows_missing = sorted(required_windows - run.windows_hit)
    complete = bug is None  # a flipped run stops early by design
    return {
        "scope": scope.name,
        "traces": run.traces,
        "crash_points": run.crash_points,
        "violations": [v.to_dict() for v in run.violations],
        "kinds": sorted(run.kinds_hit),
        "kinds_missing": kinds_missing if complete else [],
        "windows": sorted(run.windows_hit),
        "windows_missing": windows_missing if complete else [],
        "ok": (not run.violations
               and (not complete
                    or (not kinds_missing and not windows_missing))),
    }


def run_seeded_bugs(root: Optional[str] = None,
                    scope: Scope = BUG_SCOPE) -> List[dict]:
    """Run every seeded protocol bug at the minimal scope; each MUST flip
    the verdict with a violation naming an expected invariant. Returns one
    summary per bug with ``flipped`` and the minimal counterexample."""
    out = []
    for bug in SEEDED_BUGS:
        res = run_walcheck(scope=scope, root=root, bug=bug,
                           max_violations=5)
        first = res["violations"][0] if res["violations"] else None
        flipped = (first is not None
                   and first["invariant"] in bug.expected_invariants)
        out.append({
            "bug": bug.name,
            "description": bug.description,
            "expected_invariants": list(bug.expected_invariants),
            "flipped": flipped,
            "violation": first,
            "counterexample": (
                f"trace [{first['trace']}] at {first['point']} "
                f"({first['window']})" if first else None),
        })
    return out

