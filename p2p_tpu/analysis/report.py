"""Assemble both passes into one structured report.

The report is the analyzer's single output contract — ``tools/jaxcheck.py``
prints/serializes it, ``tools/quality_gate.py``'s ``static_analysis`` check
consumes it, and ``p2p-tpu check --static`` wraps it. Shape:

.. code-block:: json

    {"version": 1,
     "ok": true,
     "ast": {"findings": [...], "summary": {"new": 0, ...}},
     "contracts": {"results": [...], "ok": true},
     "compile_key": {"fields": [...], "ok": true}}

``ok`` is the gate verdict: no *new* AST findings (suppressed/baselined
don't count) and every contract + compile-key field verdict holding.
"""

from __future__ import annotations

import os
from typing import Iterable, List, Optional

from . import astlint
from .findings import apply_baseline, load_baseline, summarize

REPORT_VERSION = 1

#: Default lint targets, relative to the repo root: the package plus the
#: drivers that embed repo invariants. tests/ is deliberately out — tests
#: exercise anti-patterns on purpose (fixture snippets for these very
#: rules would self-flag). tools/profiling/ is out too: those are
#: standalone on-accelerator scratch harnesses whose module scope *is*
#: their main() — import-time jax is their point, not a hazard.
DEFAULT_LINT_PATHS = ("p2p_tpu", "tools/quality_gate.py",
                      "tools/jaxcheck.py", "tools/loadgen.py",
                      "tools/chaos_drill.py", "tools/check_checkpoint.py",
                      "tools/parity_real_weights.py",
                      "bench.py", "__graft_entry__.py")

DEFAULT_BASELINE = os.path.join("tools", "jaxcheck_baseline.json")


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def run_ast_pass(paths: Optional[Iterable[str]] = None,
                 baseline_path: Optional[str] = None,
                 root: Optional[str] = None) -> dict:
    """Pass 1 over ``paths`` (default: the package + drivers), baselined
    against ``baseline_path`` (default: the committed baseline; pass "" to
    skip baselining)."""
    root = root or repo_root()
    abs_paths = [p if os.path.isabs(p) else os.path.join(root, p)
                 for p in (paths if paths is not None else
                           DEFAULT_LINT_PATHS)]
    # A missing target is an error, never a silent skip: a typo'd CI path
    # (or a renamed default) would otherwise report clean forever.
    missing = [p for p in abs_paths if not os.path.exists(p)]
    if missing:
        raise FileNotFoundError(
            f"lint target(s) do not exist: {missing}")
    findings = astlint.lint_paths(abs_paths, repo_root=root)
    if baseline_path is None:
        baseline_path = os.path.join(root, DEFAULT_BASELINE)
    if baseline_path:
        apply_baseline(findings, load_baseline(baseline_path))
    return {"findings": findings, "summary": summarize(findings)}


def run_contract_pass(pipe=None, buckets=(1, 2, 4, 8),
                      compile_key_fields: Optional[List[str]] = None) -> dict:
    """Pass 2: jaxpr contracts + the compile-key completeness sweep. Built
    lazily so the AST-only path never imports jax."""
    from . import compile_key as ck_mod
    from . import contracts as contracts_mod

    if pipe is None:
        pipe = contracts_mod.tiny_pipeline()
    results = contracts_mod.run_contracts(pipe, buckets=buckets)
    verdicts = ck_mod.check_compile_key(pipe, fields=compile_key_fields)
    # The split per-phase pool keys sweep the same schema against a gated
    # base (verdicts land as <field>@phase1 / <field>@phase2): the
    # hand-off's cache-poisoning guard rides the same report gate.
    verdicts += ck_mod.check_phase_keys(pipe, fields=compile_key_fields)
    return {
        "contracts": {"results": results,
                      "ok": all(r.ok for r in results)},
        "compile_key": {"fields": verdicts,
                        "ok": all(v.ok for v in verdicts)},
    }


def run_all(paths: Optional[Iterable[str]] = None,
            baseline_path: Optional[str] = None,
            root: Optional[str] = None,
            ast_only: bool = False,
            buckets=(1, 2, 4, 8)) -> dict:
    ast = run_ast_pass(paths, baseline_path=baseline_path, root=root)
    report = {"version": REPORT_VERSION, "ast": ast}
    if ast_only:
        report["ok"] = ast["summary"]["new"] == 0
        return report
    passes = run_contract_pass(buckets=buckets)
    report.update(passes)
    report["ok"] = (ast["summary"]["new"] == 0
                    and passes["contracts"]["ok"]
                    and passes["compile_key"]["ok"])
    return report


def to_json_dict(report: dict) -> dict:
    """The report with dataclasses rendered to plain dicts (the JSON file
    quality_gate and CI artifacts consume)."""
    out = {"version": report["version"], "ok": report["ok"],
           "ast": {"findings": [f.to_dict()
                                for f in report["ast"]["findings"]],
                   "summary": report["ast"]["summary"]}}
    if "contracts" in report:
        out["contracts"] = {
            "ok": report["contracts"]["ok"],
            "results": [r.to_dict()
                        for r in report["contracts"]["results"]]}
    if "compile_key" in report:
        out["compile_key"] = {
            "ok": report["compile_key"]["ok"],
            "fields": [{"field": v.field,
                        "program_changed": v.program_changed,
                        "key_changed": v.key_changed,
                        "ok": v.ok, "problem": v.problem}
                       for v in report["compile_key"]["fields"]]}
    return out


def render_text(report: dict, verbose: bool = False) -> str:
    """Human-readable rendering (the CLI's default output)."""
    lines: List[str] = []
    s = report["ast"]["summary"]
    lines.append(f"AST pass: {s['new']} new finding(s) "
                 f"({s['suppressed']} suppressed, {s['baselined']} "
                 f"baselined, {s['total']} total)")
    for f in report["ast"]["findings"]:
        if f.is_new or verbose:
            lines.append("  " + f.format())
    if "contracts" in report:
        c = report["contracts"]
        lines.append(f"Contract pass: "
                     f"{sum(1 for r in c['results'] if not r.ok)} "
                     f"failure(s) across {len(c['results'])} check(s)")
        for r in c["results"]:
            if not r.ok or verbose:
                lines.append("  " + r.format())
    if "compile_key" in report:
        k = report["compile_key"]
        lines.append(f"Compile-key sweep: "
                     f"{sum(1 for v in k['fields'] if not v.ok)} "
                     f"violation(s) across {len(k['fields'])} field(s)")
        for v in k["fields"]:
            if not v.ok or verbose:
                lines.append("  " + v.format())
    lines.append("static analysis " + ("PASSED" if report["ok"]
                                       else "FAILED"))
    return "\n".join(lines)
