"""Assemble the analyzer passes into one structured report.

The report is the analyzer's single output contract — ``tools/jaxcheck.py``
prints/serializes it, ``tools/quality_gate.py``'s ``static_analysis`` check
consumes it, and ``p2p-tpu check --static`` wraps it. Shape:

.. code-block:: json

    {"version": 3,
     "ok": true,
     "ast": {"findings": [...], "summary": {"new": 0, ...}},
     "contracts": {"results": [...], "ok": true},
     "compile_key": {"fields": [...], "ok": true},
     "collectives": {"results": [...], "ok": true,
                     "table": {"serve/mesh-dp2": {"ops": {},
                               "bytes_per_step": 0, ...}}},
     "wal": {"protocol": [...], "model": {"crash_points": 3722, ...},
             "seeded": [...], "ok": true}}

``ok`` is the gate verdict over the sections that ran: no *new* AST
findings (suppressed/baselined don't count) and every contract,
compile-key and shardcheck verdict holding. ``collectives.table`` is the
per-program bytes-per-step comms budget (:mod:`.collectives`) downstream
mesh work designs against. Sections are selectable (``only=`` /
``tools/jaxcheck.py --only collectives``) for fast local iteration; the
default runs everything.
"""

from __future__ import annotations

import os
from typing import Iterable, List, Optional

from . import astlint
from .findings import apply_baseline, load_baseline, summarize

REPORT_VERSION = 3

#: Selectable report sections (the ``only=`` vocabulary). ``ast`` is pass
#: 1; ``contracts`` bundles the jaxpr contracts with the compile-key sweep
#: (they share the traced canonical set); ``collectives`` is shardcheck;
#: ``cost`` is the cost observatory's canonical pass (XLA cost cards for
#: the canonical serve programs, diffed against the frozen budgets in
#: ``tools/cost_budgets.json`` — ISSUE 14); ``wal`` is pass 5 (ISSUE 20):
#: the WAL protocol completeness sweep + the exhaustive small-scope crash
#: model checker + the seeded verdict-flips (jax-free, like ``ast``).
SECTIONS = ("ast", "contracts", "collectives", "cost", "wal")

#: Default lint targets, relative to the repo root: the package plus the
#: drivers that embed repo invariants. tests/ is deliberately out — tests
#: exercise anti-patterns on purpose (fixture snippets for these very
#: rules would self-flag). tools/profiling/ is out too: those are
#: standalone on-accelerator scratch harnesses whose module scope *is*
#: their main() — import-time jax is their point, not a hazard.
DEFAULT_LINT_PATHS = ("p2p_tpu", "tools/quality_gate.py",
                      "tools/jaxcheck.py", "tools/loadgen.py",
                      "tools/chaos_drill.py", "tools/check_checkpoint.py",
                      "tools/parity_real_weights.py", "tools/perfscope.py",
                      "bench.py", "__graft_entry__.py")

DEFAULT_BASELINE = os.path.join("tools", "jaxcheck_baseline.json")


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def run_ast_pass(paths: Optional[Iterable[str]] = None,
                 baseline_path: Optional[str] = None,
                 root: Optional[str] = None) -> dict:
    """Pass 1 over ``paths`` (default: the package + drivers), baselined
    against ``baseline_path`` (default: the committed baseline; pass "" to
    skip baselining)."""
    root = root or repo_root()
    abs_paths = [p if os.path.isabs(p) else os.path.join(root, p)
                 for p in (paths if paths is not None else
                           DEFAULT_LINT_PATHS)]
    # A missing target is an error, never a silent skip: a typo'd CI path
    # (or a renamed default) would otherwise report clean forever.
    missing = [p for p in abs_paths if not os.path.exists(p)]
    if missing:
        raise FileNotFoundError(
            f"lint target(s) do not exist: {missing}")
    findings = astlint.lint_paths(abs_paths, repo_root=root)
    if baseline_path is None:
        baseline_path = os.path.join(root, DEFAULT_BASELINE)
    if baseline_path:
        apply_baseline(findings, load_baseline(baseline_path))
    return {"findings": findings, "summary": summarize(findings)}


def run_contract_pass(pipe=None, buckets=(1, 2, 4, 8),
                      compile_key_fields: Optional[List[str]] = None) -> dict:
    """Pass 2: jaxpr contracts + the compile-key completeness sweep. Built
    lazily so the AST-only path never imports jax."""
    from . import compile_key as ck_mod
    from . import contracts as contracts_mod

    if pipe is None:
        pipe = contracts_mod.tiny_pipeline()
    results = contracts_mod.run_contracts(pipe, buckets=buckets)
    verdicts = ck_mod.check_compile_key(pipe, fields=compile_key_fields)
    # The split per-phase pool keys sweep the same schema against a gated
    # base (verdicts land as <field>@phase1 / <field>@phase2): the
    # hand-off's cache-poisoning guard rides the same report gate.
    verdicts += ck_mod.check_phase_keys(pipe, fields=compile_key_fields)
    # The semantic cache's content_key sweeps the same schema against the
    # declared OUTPUT_DETERMINING map (ISSUE 13): a field that determines
    # the output images but not the key is cache poisoning — wrong images
    # served bitwise-confidently — so it rides the same report gate.
    content = ck_mod.check_content_key(pipe, fields=compile_key_fields)
    return {
        "contracts": {"results": results,
                      "ok": all(r.ok for r in results)},
        "compile_key": {"fields": verdicts,
                        "ok": all(v.ok for v in verdicts)},
        "content_key": {"fields": content,
                        "ok": all(v.ok for v in content)},
    }


def run_collectives_pass(pipe=None, collective_dps=None) -> dict:
    """Pass 3: shardcheck — the declared-collective / no-hidden-resharding
    / no-host-boundary contracts over the compiled mesh serve programs,
    plus the per-program bytes-per-step comms table (:mod:`.collectives`).
    Lazy-imported for the same reason as pass 2 (and because this pass
    additionally pays an XLA compile per program)."""
    from . import collectives as coll_mod

    dps = (coll_mod.SHARDCHECK_DPS if collective_dps is None
           else tuple(collective_dps))
    results, table = coll_mod.check_collectives(pipe, dps=dps)
    return {"collectives": {"results": results,
                            "ok": all(r.ok for r in results),
                            "table": table}}


def run_cost_pass(pipe=None, budgets_path: Optional[str] = None,
                  root: Optional[str] = None) -> dict:
    """Pass 4: the cost observatory's canonical pass (ISSUE 14) — compile
    the canonical serve programs, extract their XLA cost cards
    (``obs.costmodel``), and diff the budget-frozen fields against
    ``tools/cost_budgets.json``. Lazy-imported like the other traced
    passes (this one additionally pays an XLA compile per program)."""
    from ..obs import costmodel

    cards = costmodel.canonical_cost_cards(pipe)
    if budgets_path is None:
        budgets_path = os.path.join(root or repo_root(),
                                    costmodel.DEFAULT_BUDGETS)
    budget = costmodel.load_budgets(budgets_path)
    verdicts = costmodel.check_budgets(cards, budget)
    return {"cost": {"programs": cards,
                     "budget": verdicts,
                     "ok": all(v.ok for v in verdicts)}}


def run_wal_pass(root: Optional[str] = None, scope=None,
                 seeded: bool = True) -> dict:
    """Pass 5 (ISSUE 20): the WAL protocol checker — (a) the completeness
    sweep (declaration ↔ write-time registry ↔ append sites ↔ replay fold
    branches ↔ chaos crash windows), (b) the exhaustive small-scope crash
    model check through the real ``replay()`` (default
    :data:`walcheck.TIER1_SCOPE`; the pass fails on any invariant
    violation OR on incomplete kind/window coverage), and (c) the seeded
    verdict-flips — the three planted protocol bugs must each flip, so a
    checker that has gone blind fails its own report. Pure Python + the
    journal loaded by path: no jax import."""
    from . import protocol as protocol_mod
    from . import walcheck as walcheck_mod

    verdicts = protocol_mod.check_protocol(root)
    model = walcheck_mod.run_walcheck(
        scope=scope or walcheck_mod.TIER1_SCOPE, root=root)
    section = {"protocol": verdicts, "model": model,
               "ok": all(v.ok for v in verdicts) and model["ok"]}
    if seeded:
        flips = walcheck_mod.run_seeded_bugs(root)
        section["seeded"] = flips
        section["ok"] = section["ok"] and all(f["flipped"] for f in flips)
    return {"wal": section}


def run_all(paths: Optional[Iterable[str]] = None,
            baseline_path: Optional[str] = None,
            root: Optional[str] = None,
            ast_only: bool = False,
            buckets=(1, 2, 4, 8),
            only: Optional[str] = None,
            collective_dps=None,
            sections: Optional[Iterable[str]] = None) -> dict:
    """Run the selected sections (default: all). ``ast_only`` is the
    historical spelling of ``only="ast"``; ``only`` narrows to one section
    (``tools/jaxcheck.py --only``); ``sections`` picks an explicit subset
    (the quality gate's ``static_analysis`` check runs the three analyzer
    passes here and the ``cost`` pass in its own ``cost_regression`` leg,
    so the canonical programs compile once per gate run, not twice);
    ``collective_dps`` narrows the shardcheck dp sweep (the quality gate
    runs one dp for speed, the analyzer's own tests sweep the axis)."""
    if only is not None and only not in SECTIONS:
        raise ValueError(f"only must be one of {SECTIONS}, got {only!r}")
    if ast_only:
        only = "ast"
    if only is not None:
        sections = (only,)
    elif sections is None:
        sections = SECTIONS
    else:
        sections = tuple(sections)
        unknown = set(sections) - set(SECTIONS)
        if unknown:
            raise ValueError(f"sections must be from {SECTIONS}, "
                             f"got {sorted(unknown)}")
    report: dict = {"version": REPORT_VERSION}
    oks = []
    if "ast" in sections:
        ast = run_ast_pass(paths, baseline_path=baseline_path, root=root)
        report["ast"] = ast
        oks.append(ast["summary"]["new"] == 0)
    pipe = None
    if ("contracts" in sections or "collectives" in sections
            or "cost" in sections):
        # The traced passes share one tiny pipeline (same construction,
        # no reason to re-init weights per pass).
        from . import contracts as contracts_mod

        pipe = contracts_mod.tiny_pipeline()
    if "contracts" in sections:
        passes = run_contract_pass(pipe, buckets=buckets)
        report.update(passes)
        oks += [passes["contracts"]["ok"], passes["compile_key"]["ok"],
                passes["content_key"]["ok"]]
    if "collectives" in sections:
        coll = run_collectives_pass(pipe, collective_dps=collective_dps)
        report.update(coll)
        oks.append(coll["collectives"]["ok"])
    if "cost" in sections:
        cost = run_cost_pass(pipe, root=root)
        report.update(cost)
        oks.append(cost["cost"]["ok"])
    if "wal" in sections:
        wal = run_wal_pass(root=root)
        report.update(wal)
        oks.append(wal["wal"]["ok"])
    report["ok"] = all(oks)
    return report


def to_json_dict(report: dict) -> dict:
    """The report with dataclasses rendered to plain dicts (the JSON file
    quality_gate and CI artifacts consume)."""
    out = {"version": report["version"], "ok": report["ok"]}
    if "ast" in report:
        out["ast"] = {"findings": [f.to_dict()
                                   for f in report["ast"]["findings"]],
                      "summary": report["ast"]["summary"]}
    if "contracts" in report:
        out["contracts"] = {
            "ok": report["contracts"]["ok"],
            "results": [r.to_dict()
                        for r in report["contracts"]["results"]]}
    if "compile_key" in report:
        out["compile_key"] = {
            "ok": report["compile_key"]["ok"],
            "fields": [{"field": v.field,
                        "program_changed": v.program_changed,
                        "key_changed": v.key_changed,
                        "ok": v.ok, "problem": v.problem}
                       for v in report["compile_key"]["fields"]]}
    if "content_key" in report:
        out["content_key"] = {
            "ok": report["content_key"]["ok"],
            "fields": [{"field": v.field,
                        "output_determining": v.output_determining,
                        "key_changed": v.key_changed,
                        "ok": v.ok, "problem": v.problem}
                       for v in report["content_key"]["fields"]]}
    if "collectives" in report:
        out["collectives"] = {
            "ok": report["collectives"]["ok"],
            "results": [r.to_dict()
                        for r in report["collectives"]["results"]],
            "table": report["collectives"]["table"]}
    if "cost" in report:
        out["cost"] = {
            "ok": report["cost"]["ok"],
            "programs": report["cost"]["programs"],
            "budget": [v.to_dict() for v in report["cost"]["budget"]]}
    if "wal" in report:
        w = report["wal"]
        out["wal"] = {
            "ok": w["ok"],
            "protocol": [v.to_dict() for v in w["protocol"]],
            "model": w["model"]}
        if "seeded" in w:
            out["wal"]["seeded"] = w["seeded"]
    return out


def render_text(report: dict, verbose: bool = False) -> str:
    """Human-readable rendering (the CLI's default output)."""
    lines: List[str] = []
    if "ast" in report:
        s = report["ast"]["summary"]
        lines.append(f"AST pass: {s['new']} new finding(s) "
                     f"({s['suppressed']} suppressed, {s['baselined']} "
                     f"baselined, {s['total']} total)")
        for f in report["ast"]["findings"]:
            if f.is_new or verbose:
                lines.append("  " + f.format())
    if "contracts" in report:
        c = report["contracts"]
        lines.append(f"Contract pass: "
                     f"{sum(1 for r in c['results'] if not r.ok)} "
                     f"failure(s) across {len(c['results'])} check(s)")
        for r in c["results"]:
            if not r.ok or verbose:
                lines.append("  " + r.format())
    if "compile_key" in report:
        k = report["compile_key"]
        lines.append(f"Compile-key sweep: "
                     f"{sum(1 for v in k['fields'] if not v.ok)} "
                     f"violation(s) across {len(k['fields'])} field(s)")
        for v in k["fields"]:
            if not v.ok or verbose:
                lines.append("  " + v.format())
    if "content_key" in report:
        k = report["content_key"]
        lines.append(f"Content-key sweep: "
                     f"{sum(1 for v in k['fields'] if not v.ok)} "
                     f"violation(s) across {len(k['fields'])} field(s)")
        for v in k["fields"]:
            if not v.ok or verbose:
                lines.append("  " + v.format())
    if "collectives" in report:
        c = report["collectives"]
        lines.append(f"Shardcheck pass: "
                     f"{sum(1 for r in c['results'] if not r.ok)} "
                     f"failure(s) across {len(c['results'])} check(s)")
        for r in c["results"]:
            if not r.ok or verbose:
                lines.append("  " + r.format())
        lines.append("  collective budget (bytes/step | bytes once | ops):")
        for name in sorted(c["table"]):
            row = c["table"][name]
            lines.append(f"    {name:26s} {row['bytes_per_step']:>10d} | "
                         f"{row['bytes_once']:>10d} | {row['ops'] or '{}'}")
    if "cost" in report:
        c = report["cost"]
        lines.append(f"Cost pass: "
                     f"{sum(1 for v in c['budget'] if not v.ok)} budget "
                     f"violation(s) across {len(c['budget'])} check(s)")
        for v in c["budget"]:
            if not v.ok or verbose:
                lines.append("  " + v.format())
        lines.append("  cost cards (flops | bytes accessed | intensity):")
        for name in sorted(c["programs"]):
            card = c["programs"][name]
            lines.append(f"    {name:26s} {card['flops']:>14.4g} | "
                         f"{card['bytes_accessed']:>14.4g} | "
                         f"{card['arith_intensity']:>7.2f}")
    if "wal" in report:
        w = report["wal"]
        m = w["model"]
        lines.append(f"WAL protocol pass: "
                     f"{sum(1 for v in w['protocol'] if not v.ok)} sweep "
                     f"failure(s) across {len(w['protocol'])} check(s)")
        for v in w["protocol"]:
            if not v.ok or verbose:
                lines.append("  " + v.format())
        lines.append(f"  model check [{m['scope']}]: {m['traces']} "
                     f"trace(s), {m['crash_points']} crash point(s), "
                     f"{len(m['violations'])} violation(s)")
        for viol in m["violations"]:
            lines.append(f"    {viol['invariant']} at {viol['point']} "
                         f"({viol['window']}) of [{viol['trace']}]: "
                         f"{viol['detail']}")
        for missing, what in ((m["kinds_missing"], "record/event kind(s)"),
                              (m["windows_missing"], "crash window(s)")):
            if missing:
                lines.append(f"    COVERAGE: {what} never exercised: "
                             f"{missing}")
        for flip in w.get("seeded", ()):
            status = "flips" if flip["flipped"] else "DOES NOT FLIP"
            lines.append(f"  seeded bug {flip['bug']}: {status}"
                         + (f" — {flip['violation']['invariant']} at "
                            f"{flip['counterexample']}"
                            if flip["flipped"] else ""))
    lines.append("static analysis " + ("PASSED" if report["ok"]
                                       else "FAILED"))
    return "\n".join(lines)
