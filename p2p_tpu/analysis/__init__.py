"""jaxcheck — static analysis for the whole stack (docs/STATIC_ANALYSIS.md).

Two passes, one structured report:

- **Pass 1 (AST lints)** — :mod:`.astlint`: repo-specific TPU/JAX rules
  over the package source, with inline ``# jaxcheck: disable=<rule>``
  suppressions and a committed baseline (:mod:`.findings`). Pure Python,
  no jax import — runs in milliseconds on every PR.
- **Pass 2 (traced-program contracts)** — :mod:`.contracts` +
  :mod:`.compile_key`: trace the canonical programs (text2image baseline,
  gated phase 1/2, serve batch programs across lane buckets, inversion) on
  a tiny pipeline and assert jaxpr-level contracts: no f64, no callbacks
  in hot scans beyond the registered obs sinks, no CFG-doubled tensors in
  phase 2, donation as declared, and ``compile_key`` completeness over the
  full ``Request`` schema.

Drivers: ``tools/jaxcheck.py`` (CLI, ``--fix``, ``--update-baseline``),
``p2p-tpu check --static``, and the ``static_analysis`` check in
``tools/quality_gate.py``.
"""

from .astlint import RULES, lint_file, lint_paths, lint_source  # noqa: F401
from .findings import (  # noqa: F401
    Finding,
    apply_baseline,
    load_baseline,
    save_baseline,
    summarize,
)
from .report import run_all, run_ast_pass, run_contract_pass  # noqa: F401
