"""jaxcheck — static analysis for the whole stack (docs/STATIC_ANALYSIS.md).

The passes, one structured report:

- **Pass 1 (AST lints)** — :mod:`.astlint`: repo-specific TPU/JAX rules
  over the package source, with inline ``# jaxcheck: disable=<rule>``
  suppressions and a committed baseline (:mod:`.findings`). Pure Python,
  no jax import — runs in milliseconds on every PR.
- **Pass 2 (traced-program contracts)** — :mod:`.contracts` +
  :mod:`.compile_key`: trace the canonical programs (text2image baseline,
  gated phase 1/2, serve batch programs across lane buckets, inversion) on
  a tiny pipeline and assert jaxpr-level contracts: no f64, no callbacks
  in hot scans beyond the registered obs sinks, no CFG-doubled tensors in
  phase 2, donation as declared, and ``compile_key`` completeness over the
  full ``Request`` schema.
- **Pass 3 (shardcheck)** — :mod:`.collectives` + :mod:`.shlo_walk`:
  lower AND compile the canonical mesh serve programs
  (``serve/{mesh,phase1-mesh,phase2-mesh}-dpN``, dp ∈ {1, 2, 4}) and
  check the post-SPMD HLO against :data:`.collectives
  .DECLARED_COLLECTIVES` in both directions (undeclared collective /
  stale declaration), plus no-hidden-resharding and no-host-boundary —
  emitting the per-program bytes-per-step comms table into the report.
- **Pass 5 (walcheck)** — :mod:`.protocol` + :mod:`.walcheck`: the serve
  WAL protocol, declared and exhaustively crash-checked (ISSUE 20): a
  completeness sweep over the declared record/event grammar vs the
  write-time registry, every append site and every replay fold branch,
  plus an exhaustive small-scope model check — a crash injected at every
  record boundary, torn tail, and snapshot window of every bounded trace,
  folded through the real ``serve/journal.replay`` — and three seeded
  protocol bugs that must flip the verdict. Pure Python, no jax import.

Drivers: ``tools/jaxcheck.py`` (CLI, ``--fix``, ``--update-baseline``,
``--only collectives``), ``p2p-tpu check --static``, and the
``static_analysis`` check in ``tools/quality_gate.py``.
"""

from .astlint import RULES, lint_file, lint_paths, lint_source  # noqa: F401
from .findings import (  # noqa: F401
    Finding,
    apply_baseline,
    load_baseline,
    save_baseline,
    summarize,
)
from .report import (  # noqa: F401
    run_all,
    run_ast_pass,
    run_collectives_pass,
    run_contract_pass,
    run_wal_pass,
)
