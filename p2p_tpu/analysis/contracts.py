"""Pass 2 — traced-program contracts.

Trace the canonical programs of the stack on a tiny pipeline (abstract
tracing only — ``jax.make_jaxpr``, no XLA compile) and assert jaxpr-level
contracts that hand-written review keeps re-checking:

- ``no-f64`` — no ``convert_element_type`` to float64 and no f64-dtyped
  value anywhere in any canonical program. Under the default x64-off
  config this can only fire on an explicit promotion; it is the tripwire
  for the day someone enables x64 "just for one test".
- ``hot-scan-callbacks`` — the phase-2 scan and the serve batch programs
  carry **zero** host callbacks when telemetry is off (the disabled-mode
  program-identity discipline), and with telemetry on, the only callback
  primitive in a hot scan is ``debug_callback`` — the registered obs-sink
  channel (``utils.progress``). ``io_callback``/``pure_callback`` in a hot
  scan would serialize the device against the host every step.
- ``phase2-footprint`` — the phase-2 scan body carries no CFG-doubled
  ``2B``-batch tensors (the ISSUE 1 jaxpr proof from
  ``tests/test_phase_cache.py``, generalized to every gated surface
  including the vmapped serve programs) and is strictly smaller than the
  phase-1 body.
- ``donation-as-declared`` — each canonical jitted entry point's buffer
  donation matches :data:`DECLARED_DONATION`. Today every program declares
  *no* donation (``_sweep_jit`` spells ``donate_argnums=()`` explicitly —
  sweep inputs are caller-reused); a future PR that donates must update
  the declaration, and one that declares without the lowering actually
  aliasing (or vice versa) fails here.
- ``trace-invisible`` — re-tracing every canonical program under a *live*
  request-scoped flight tracer (``obs.flight``: open context, attached
  spans) yields byte-identical jaxpr fingerprints: flipping flight
  tracing on/off can never change a compiled program.
- ``no-materialized-probs`` — a canonical program dispatched through the
  fused-edit kernel config (:func:`kernel_programs`) carries no
  CFG-doubled ``(2B, heads, P, K)`` attention-probability softmax
  anywhere: the prompt-to-prompt edit runs inside the attention tile, so
  the probability tensor never exists as a program-level value. Each
  fused program is paired with its ``kernels=None`` twin, which must trip
  the detector (non-vacuity witness).

Programs traced (:func:`canonical_programs`): text2image ungated + gated
(phase 1/2), serve batch programs across every lane bucket (1/2/4/8, the
``BUCKET_SIZES`` padding contract), the disaggregated phase-1/phase-2
POOL programs at the same buckets (phase-disaggregated continuous
batching — ``phase2-footprint`` pairs each phase-2 pool program with its
phase-1 twin, since each pool compiles a single scan), the SHARDED serve
programs (mesh-parallel serving: the same three serve tracers with their
group-axis inputs placed under a ``NamedSharding(P("dp"))`` on a live
``dp`` mesh — ``dp=2`` when the process has the devices, degrading to a
one-device mesh otherwise, so the sweep always runs; the behavioral mesh
legs live in tests/test_serve_mesh.py and the ``mesh_parity`` quality
gate), and the two inversion programs. The tiny pipeline is the same
construction the golden tests use (random weights; contracts are
shape/structure properties, weights never matter).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from . import jaxpr_walk

#: Steps/gate the canonical programs trace with — small (tracing cost is
#: linear in scan length only at the python level; the jaxpr scan body is
#: length-independent) but ≥ 3 so gate=2 leaves both phases non-trivial.
STEPS = 3
GATE = 2
PROMPTS = ("a squirrel eating a burger", "a squirrel eating a lasagna")

#: program name -> donated argument indices the code *declares*. The
#: contract checks the lowering agrees in both directions, over every
#: jitted entry point the serve stack dispatches: the monolithic sweep,
#: the disaggregated phase-1/phase-2 pool programs, and all three again
#: as MESH programs (dp-sharded group inputs — donation lowers through
#: the partitioner, so the mesh twins are checked in their own right).
#: Today every program declares *no* donation (sweep inputs are
#: caller-reused; a hand-off carry outlives its phase-2 dispatch via the
#: journal spill path).
DECLARED_DONATION: Dict[str, Tuple[int, ...]] = {
    "text2image": (),
    "sweep": (),
    "sweep/phase1": (),
    "sweep/phase2": (),
    "sweep/mesh": (),
    "sweep/phase1-mesh": (),
    "sweep/phase2-mesh": (),
}


@dataclasses.dataclass
class ContractResult:
    contract: str
    program: str
    ok: bool
    detail: str = ""

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def format(self) -> str:
        return (f"{'ok  ' if self.ok else 'FAIL'} {self.contract:22s} "
                f"{self.program:18s} {self.detail}")


def tiny_pipeline():
    """The TINY random-weight pipeline (the golden tests' construction,
    package-local so the analyzer has no test dependency)."""
    import jax

    from ..engine.sampler import Pipeline
    from ..models import TINY, init_text_encoder, init_unet
    from ..models import vae as vae_mod
    from ..utils.tokenizer import HashWordTokenizer

    tok = HashWordTokenizer(vocab_size=TINY.text.vocab_size,
                            model_max_length=TINY.text.max_length)
    return Pipeline(
        config=TINY,
        unet_params=init_unet(jax.random.PRNGKey(0), TINY.unet),
        text_params=init_text_encoder(jax.random.PRNGKey(1), TINY.text),
        vae_params=vae_mod.init_vae(jax.random.PRNGKey(2), TINY.vae),
        tokenizer=tok,
    )


@dataclasses.dataclass
class Program:
    """One traced canonical program plus the metadata contracts key on."""

    name: str
    jaxpr: object                 # ClosedJaxpr
    group_batch: int              # B (prompts per edit group)
    gate: Optional[int]           # phase-2 start, None = ungated
    metrics: bool                 # telemetry traced in?
    lead_dims: Tuple[int, ...] = ()   # vmap prefix (G,) for serve programs
    max_tokens: Optional[int] = None  # token-major detector bound


def _edit_controller(pipe):
    from ..cli import controller_from_opts

    return controller_from_opts(list(PROMPTS), pipe.tokenizer, STEPS,
                                mode="replace", cross_steps=0.8,
                                self_steps=0.4)


def _scan_inputs(pipe):
    import jax.numpy as jnp

    from ..engine.sampler import encode_prompts

    b = len(PROMPTS)
    cond = encode_prompts(pipe, list(PROMPTS))
    uncond = encode_prompts(pipe, [""] * b)
    ctx = jnp.concatenate([uncond, cond], axis=0)
    lats = jnp.zeros((b,) + pipe.latent_shape)
    return ctx, lats, jnp.float32(7.5)


def _trace_denoise(pipe, ctrl, gate, metrics, kernels=None):
    import jax

    from ..engine.sampler import _denoise_scan
    from ..models.config import unet_layout
    from ..ops import schedulers as sched_mod

    cfg = pipe.config
    layout = unet_layout(cfg.unet)
    schedule = sched_mod.schedule_from_config(STEPS, cfg.scheduler,
                                              kind="ddim")
    ctx, lats, gs = _scan_inputs(pipe)

    def run(up, ctx, lats, gs):
        return _denoise_scan(up, cfg, layout, schedule, "ddim", ctx, lats,
                             ctrl, gs, gate=gate, metrics=metrics,
                             kernels=kernels)

    return jax.make_jaxpr(run)(pipe.unet_params, ctx, lats, gs)


def _mesh_dp() -> int:
    """The dp width the sharded canonical programs trace at: 2 when the
    process has at least two devices, else a one-device mesh — the sweep
    must run everywhere the analyzer does (a bare ``p2p-tpu check
    --static`` sees one CPU device; the test/gate environments force a
    virtual 8-device platform)."""
    import jax

    return 2 if len(jax.devices()) >= 2 else 1


def _stage_dp(x, mesh):
    """Place a group-axis value under the serve mesh's data sharding —
    exactly what the engine's dispatch staging does."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.device_put(x, NamedSharding(mesh, P("dp")))


def _trace_sweep(pipe, ctrl, bucket, gate, metrics, mesh=None, reuse=None,
                 kernels=None):
    import jax
    import jax.numpy as jnp

    from ..models.config import unet_layout
    from ..ops import schedulers as sched_mod
    from ..parallel.sweep import _sweep_jit

    cfg = pipe.config
    layout = unet_layout(cfg.unet)
    schedule = sched_mod.schedule_from_config(STEPS, cfg.scheduler,
                                              kind="ddim")
    ctx, lats, gs = _scan_inputs(pipe)
    ctx_g = jnp.broadcast_to(ctx[None], (bucket,) + ctx.shape)
    lat_g = jnp.broadcast_to(lats[None], (bucket,) + lats.shape)
    ctrl_g = (None if ctrl is None else jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x[None], (bucket,) + x.shape), ctrl))
    if mesh is not None:
        ctx_g, lat_g = _stage_dp(ctx_g, mesh), _stage_dp(lat_g, mesh)
        ctrl_g = (None if ctrl_g is None else jax.tree_util.tree_map(
            lambda x: _stage_dp(x, mesh), ctrl_g))

    def run(up, vp, ctx_g, lat_g, ctrl_g, gs):
        return _sweep_jit(up, vp, cfg, layout, schedule, "ddim", ctx_g,
                          lat_g, ctrl_g, gs, None, progress=False,
                          gate=gate, metrics=metrics, reuse=reuse,
                          kernels=kernels)

    return jax.make_jaxpr(run)(pipe.unet_params, pipe.vae_params, ctx_g,
                               lat_g, ctrl_g, gs)


def _zero_carry(pipe, ctrl, reuse=None):
    """A zero-valued per-group PhaseCarry with the shapes the phase-1 pool
    program produces for this controller — the phase-2 pool trace input.
    ``reuse`` (a resolved reuse schedule, ISSUE 15) swaps the all-cross
    AttnCache for the schedule's ever-cached leaf set."""
    import jax.numpy as jnp

    from ..controllers.base import init_store_state
    from ..engine.sampler import PhaseCarry
    from ..models.config import unet_layout
    from ..models.unet import init_attn_cache
    from ..ops import schedulers as sched_mod

    layout = unet_layout(pipe.config.unet)
    b = len(PROMPTS)
    lat = jnp.zeros((b,) + pipe.latent_shape)
    state = (init_store_state(layout, b)
             if (ctrl is not None and ctrl.needs_store) else ())
    if reuse is not None:
        from ..engine import reuse as reuse_mod

        cache = reuse_mod.init_schedule_cache(layout, reuse, b, phase=2,
                                              dtype=lat.dtype)
    else:
        cache = init_attn_cache(layout, b, dtype=lat.dtype)
    return PhaseCarry(
        latents=lat, resid=jnp.zeros_like(lat),
        cache=cache,
        ms=sched_mod.init_multistep_state("ddim", lat.shape, lat.dtype),
        state=state)


def _trace_sweep_phase1(pipe, ctrl, bucket, gate, metrics, mesh=None,
                        reuse=None):
    import jax
    import jax.numpy as jnp

    from ..models.config import unet_layout
    from ..ops import schedulers as sched_mod
    from ..parallel.sweep import _sweep_phase1_jit

    cfg = pipe.config
    layout = unet_layout(cfg.unet)
    schedule = sched_mod.schedule_from_config(STEPS, cfg.scheduler,
                                              kind="ddim")
    ctx, lats, gs = _scan_inputs(pipe)
    ctx_g = jnp.broadcast_to(ctx[None], (bucket,) + ctx.shape)
    lat_g = jnp.broadcast_to(lats[None], (bucket,) + lats.shape)
    ctrl_g = (None if ctrl is None else jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x[None], (bucket,) + x.shape), ctrl))
    if mesh is not None:
        ctx_g, lat_g = _stage_dp(ctx_g, mesh), _stage_dp(lat_g, mesh)
        ctrl_g = (None if ctrl_g is None else jax.tree_util.tree_map(
            lambda x: _stage_dp(x, mesh), ctrl_g))

    def run(up, ctx_g, lat_g, ctrl_g, gs):
        return _sweep_phase1_jit(up, cfg, layout, schedule, "ddim", ctx_g,
                                 lat_g, ctrl_g, gs, progress=False,
                                 gate=gate, metrics=metrics, reuse=reuse)

    return jax.make_jaxpr(run)(pipe.unet_params, ctx_g, lat_g, ctrl_g, gs)


def _trace_sweep_phase2(pipe, ctrl, bucket, gate, metrics, mesh=None,
                        reuse=None):
    import jax
    import jax.numpy as jnp

    from ..engine.sampler import encode_prompts, phase2_controller
    from ..models.config import unet_layout
    from ..ops import schedulers as sched_mod
    from ..parallel.sweep import _sweep_phase2_jit

    cfg = pipe.config
    layout = unet_layout(cfg.unet)
    schedule = sched_mod.schedule_from_config(STEPS, cfg.scheduler,
                                              kind="ddim")
    cond = encode_prompts(pipe, list(PROMPTS))
    carry = _zero_carry(pipe, ctrl, reuse=reuse)
    p2 = phase2_controller(ctrl)

    def lead(x):
        return jnp.broadcast_to(x[None], (bucket,) + x.shape)

    ctx_g = lead(cond)
    carry_g = jax.tree_util.tree_map(lead, carry)
    ctrl_g = None if p2 is None else jax.tree_util.tree_map(lead, p2)
    if mesh is not None:
        ctx_g = _stage_dp(ctx_g, mesh)
        carry_g = jax.tree_util.tree_map(lambda x: _stage_dp(x, mesh),
                                         carry_g)
        ctrl_g = (None if ctrl_g is None else jax.tree_util.tree_map(
            lambda x: _stage_dp(x, mesh), ctrl_g))
    gs = jnp.float32(7.5)

    def run(up, vp, ctx_g, carry_g, ctrl_g, gs):
        return _sweep_phase2_jit(up, vp, cfg, layout, schedule, "ddim",
                                 ctx_g, carry_g, ctrl_g, gs, progress=False,
                                 gate=gate, metrics=metrics, reuse=reuse)

    return jax.make_jaxpr(run)(pipe.unet_params, pipe.vae_params, ctx_g,
                               carry_g, ctrl_g, gs)


def _trace_invert(pipe, metrics):
    """The two inversion programs: DDIM forward-invert and the null-text
    optimizer outer scan."""
    import jax
    import jax.numpy as jnp

    from ..engine.inversion import _ddim_invert_jit, _null_optimize_jit
    from ..ops import schedulers as sched_mod

    cfg = pipe.config
    schedule = sched_mod.schedule_from_config(STEPS, cfg.scheduler,
                                              kind="ddim")
    img = jnp.zeros((1, cfg.image_size, cfg.image_size, 3), jnp.float32)
    cond = jnp.zeros((1, cfg.unet.context_len, cfg.unet.context_dim))
    uncond = jnp.zeros_like(cond)

    def run_inv(up, vp, img, cond):
        return _ddim_invert_jit(up, vp, cfg, schedule, img, cond,
                                progress=False, sp=None, metrics=metrics)

    inv = jax.make_jaxpr(run_inv)(pipe.unet_params, pipe.vae_params, img,
                                  cond)

    lat_shape = (STEPS + 1, 1) + pipe.latent_shape
    lats = jnp.zeros(lat_shape)

    def run_null(up, lats, cond, uncond):
        return _null_optimize_jit(up, cfg, schedule, lats, uncond, cond,
                                  jnp.float32(7.5), 2, jnp.float32(1e-5),
                                  progress=False, sp=None, metrics=metrics)

    null = jax.make_jaxpr(run_null)(pipe.unet_params, lats, cond, uncond)
    return inv, null


def canonical_programs(pipe=None, buckets=(1, 2, 4, 8),
                       metrics=False) -> List[Program]:
    """Trace every canonical program of the stack. ``metrics`` traces the
    telemetry variant (used by the hot-scan-callback contract's
    only-debug-callback half)."""
    if pipe is None:
        pipe = tiny_pipeline()
    b = len(PROMPTS)
    ctrl = _edit_controller(pipe)
    programs = [
        Program("text2image/ungated",
                _trace_denoise(pipe, ctrl, gate=None, metrics=metrics),
                group_batch=b, gate=None, metrics=metrics),
        Program("text2image/gated",
                _trace_denoise(pipe, ctrl, gate=GATE, metrics=metrics),
                group_batch=b, gate=GATE, metrics=metrics),
    ]
    for g in buckets:
        programs.append(Program(
            f"serve/bucket{g}",
            _trace_sweep(pipe, ctrl, bucket=g, gate=GATE, metrics=metrics),
            group_batch=b, gate=GATE, metrics=metrics, lead_dims=(g,)))
    for g in buckets:
        # The disaggregated pool programs (phase-disaggregated continuous
        # batching): phase 1 and phase 2 compile separately; the
        # phase2-footprint contract pairs them by bucket.
        programs.append(Program(
            f"serve/phase1-bucket{g}",
            _trace_sweep_phase1(pipe, ctrl, bucket=g, gate=GATE,
                                metrics=metrics),
            group_batch=b, gate=GATE, metrics=metrics, lead_dims=(g,)))
        programs.append(Program(
            f"serve/phase2-bucket{g}",
            _trace_sweep_phase2(pipe, ctrl, bucket=g, gate=GATE,
                                metrics=metrics),
            group_batch=b, gate=GATE, metrics=metrics, lead_dims=(g,)))
    # Sharded serve programs (mesh-parallel serving): the same three serve
    # tracers with group-axis inputs placed under NamedSharding(P("dp")) on
    # a live dp mesh — the engine's `--mesh` dispatch shape. One bucket of
    # dp whole per-device lanes keeps the sweep cheap; the footprint pair
    # uses the same phase1-/phase2- naming so it pairs like the rest.
    from ..parallel.mesh import make_mesh

    dp = _mesh_dp()
    mesh = make_mesh(dp, tp=1)
    g = dp * 2  # two lanes per device: the doubled-batch detector stays
    #             non-vacuous and the per-device sub-batch is a real batch
    programs.append(Program(
        f"serve/mesh-dp{dp}x{g}",
        _trace_sweep(pipe, ctrl, bucket=g, gate=GATE, metrics=metrics,
                     mesh=mesh),
        group_batch=b, gate=GATE, metrics=metrics, lead_dims=(g,)))
    programs.append(Program(
        f"serve/phase1-mesh-dp{dp}x{g}",
        _trace_sweep_phase1(pipe, ctrl, bucket=g, gate=GATE,
                            metrics=metrics, mesh=mesh),
        group_batch=b, gate=GATE, metrics=metrics, lead_dims=(g,)))
    programs.append(Program(
        f"serve/phase2-mesh-dp{dp}x{g}",
        _trace_sweep_phase2(pipe, ctrl, bucket=g, gate=GATE,
                            metrics=metrics, mesh=mesh),
        group_batch=b, gate=GATE, metrics=metrics, lead_dims=(g,)))
    inv, null = _trace_invert(pipe, metrics=metrics)
    programs.append(Program("invert/ddim", inv, group_batch=1, gate=None,
                            metrics=metrics))
    programs.append(Program("invert/null_text", null, group_batch=1,
                            gate=None, metrics=metrics))
    return programs


def scheduled_programs(pipe=None, spec=None, buckets=(1,),
                       metrics=False) -> List[Program]:
    """Scheduled canonical programs (ISSUE 15): the committed default
    reuse-schedule artifact (or ``spec``) resolved at the canonical
    STEPS, traced as the monolithic serve program and the two pool
    programs — the quality gate's ``schedule`` leg runs the no-f64 and
    hot-scan-callback contracts over these, so a schedule that sneaks a
    host callback or an f64 promotion into a segment fails CI exactly
    like a canonical program would. The spec is resolved with a
    NON-uniform fallback: if the artifact happens to normalize to the
    uniform gate at this scan length, the trace would silently collapse
    onto already-covered programs, so that case raises instead."""
    import jax

    from ..engine import reuse as reuse_mod
    from ..models.config import unet_layout

    if pipe is None:
        pipe = tiny_pipeline()
    if spec is None:
        import json
        import os

        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))),
            "tools", "schedules", "default_v1.json")
        with open(path) as f:
            spec = json.load(f)
    b = len(PROMPTS)
    ctrl = _edit_controller(pipe)
    layout = unet_layout(pipe.config.unet)
    sched = reuse_mod.resolve_schedule(spec, layout, STEPS, ctrl)
    if sched.uniform_gate is not None:
        raise ValueError(
            f"schedule spec resolves to the uniform gate at {STEPS} scan "
            "steps — the scheduled contract sweep would trace nothing new")
    gate = sched.cfg_gate
    programs = []
    import warnings

    with warnings.catch_warnings():
        # Window-conflict warnings are the workload's business (the tiny
        # contract controller has a long edit window on purpose); the
        # contract sweep only cares about program structure.
        warnings.simplefilter("ignore")
        for g in buckets:
            programs.append(Program(
                f"serve/sched-bucket{g}",
                _trace_sweep(pipe, ctrl, bucket=g, gate=gate,
                             metrics=metrics, reuse=sched),
                group_batch=b, gate=gate, metrics=metrics, lead_dims=(g,)))
            programs.append(Program(
                f"serve/sched-phase1-bucket{g}",
                _trace_sweep_phase1(pipe, ctrl, bucket=g, gate=gate,
                                    metrics=metrics,
                                    reuse=reuse_mod.phase1_view(sched)),
                group_batch=b, gate=gate, metrics=metrics, lead_dims=(g,)))
            programs.append(Program(
                f"serve/sched-phase2-bucket{g}",
                _trace_sweep_phase2(pipe, ctrl, bucket=g, gate=gate,
                                    metrics=metrics,
                                    reuse=reuse_mod.phase2_view(sched)),
                group_batch=b, gate=gate, metrics=metrics, lead_dims=(g,)))
    return programs


def _kernel_controller(pipe):
    """The kernel-twin controller: a replace edit whose window covers every
    TINY attention site (``self_max_pixels`` at the largest level) with
    ``store=False`` — no attention-store slots, so every controller-touched
    site is kernel-compilable and the fused twin has ZERO materialized
    CFG-doubled probability tensors by construction. The canonical
    ``_edit_controller`` keeps ``store=True`` (store sites stay materialized
    by design), which would make the no-materialized-probs detector
    trivially fail on sites the kernel deliberately does not claim."""
    from ..controllers import factory

    size = pipe.config.unet.sample_size
    return factory.attention_replace(
        list(PROMPTS), STEPS, cross_replace_steps=0.8,
        self_replace_steps=0.4, tokenizer=pipe.tokenizer,
        self_max_pixels=size * size, max_len=pipe.config.text.max_length,
        store=False)


def kernel_programs(pipe=None, metrics=False) -> List[Program]:
    """Kernel-bearing canonical program twins (fused-edit Pallas dispatch)
    plus their materialized counterparts under the SAME controller: the
    sequential sampler ungated + gated, and the monolithic serve program at
    one bucket. Each ``<name>-fused`` program traces with
    ``KernelConfig(interpret=True)`` (the CPU-traceable rehearsal config —
    the pallas_call program structure is identical to the compiled-TPU
    one); ``<name>`` traces the exact same program with ``kernels=None``,
    giving :func:`check_no_materialized_probs` its non-vacuity witness."""
    from ..kernels import KernelConfig

    if pipe is None:
        pipe = tiny_pipeline()
    b = len(PROMPTS)
    ctrl = _kernel_controller(pipe)
    kc = KernelConfig(interpret=True)
    programs = []
    for label, gate in (("ungated", None), ("gated", GATE)):
        programs.append(Program(
            f"kernel/{label}",
            _trace_denoise(pipe, ctrl, gate=gate, metrics=metrics),
            group_batch=b, gate=gate, metrics=metrics))
        programs.append(Program(
            f"kernel/{label}-fused",
            _trace_denoise(pipe, ctrl, gate=gate, metrics=metrics,
                           kernels=kc),
            group_batch=b, gate=gate, metrics=metrics))
    programs.append(Program(
        "kernel/serve-bucket1",
        _trace_sweep(pipe, ctrl, bucket=1, gate=GATE, metrics=metrics),
        group_batch=b, gate=GATE, metrics=metrics, lead_dims=(1,)))
    programs.append(Program(
        "kernel/serve-bucket1-fused",
        _trace_sweep(pipe, ctrl, bucket=1, gate=GATE, metrics=metrics,
                     kernels=kc),
        group_batch=b, gate=GATE, metrics=metrics, lead_dims=(1,)))
    return programs


# ---------------------------------------------------------------------------
# Contracts
# ---------------------------------------------------------------------------


def check_no_f64(programs: List[Program]) -> List[ContractResult]:
    out = []
    for p in programs:
        bad = jaxpr_walk.f64_eqns(jaxpr_walk.all_eqns(p.jaxpr))
        detail = (f"{len(bad)} f64 eqn(s), first: "
                  f"{bad[0].primitive.name}" if bad else "no f64 values")
        out.append(ContractResult("no-f64", p.name, not bad, detail))
    return out


def _hot_scans(p: Program) -> List[Tuple[str, list]]:
    """(label, body eqns) of the hot scans a program carries: for a gated
    program, the phase-2 scan (last top-level scan); serve programs are hot
    end to end, so every scan counts."""
    scans = jaxpr_walk.top_level_scans(p.jaxpr)
    if not scans:
        return []
    if p.name.startswith("serve/"):
        return [(f"scan{i}", jaxpr_walk.scan_body(s))
                for i, s in enumerate(scans)]
    if p.gate is not None:
        return [("phase2", jaxpr_walk.scan_body(scans[-1]))]
    return []


def check_hot_scan_callbacks(programs: List[Program]) -> List[ContractResult]:
    out = []
    for p in programs:
        for label, body in _hot_scans(p):
            cbs = jaxpr_walk.callback_eqns(body)
            if not p.metrics:
                ok = not cbs
                detail = (f"{label}: {len(cbs)} callback(s) with telemetry "
                          f"off" if cbs else f"{label}: no callbacks")
            else:
                alien = [e for e in cbs
                         if e.primitive.name != "debug_callback"]
                ok = not alien
                detail = (f"{label}: non-obs callback(s) "
                          f"{sorted({e.primitive.name for e in alien})}"
                          if alien else
                          f"{label}: {len(cbs)} debug_callback(s) only")
            out.append(ContractResult("hot-scan-callbacks", p.name, ok,
                                      detail))
    return out


def _doubled_detector(p: Program):
    """The CFG-doubled-batch detector for one program: plain ``(2B, ...)``
    shapes for unbatched programs; explicit ``(G, 2B, ...)`` prefixes plus
    vmap-folded ``(G·2B, h, w, c)`` conv activations for vmapped serve
    programs. Only these exact forms count: an unqualified leading-dim
    match would collide with G·B phase-2 activations whenever G·B == 2B
    (bucket 2 at B=2)."""

    def doubled(body):
        shapes = jaxpr_walk.eqn_shapes(body)
        if not p.lead_dims:
            return jaxpr_walk.doubled_batch_shapes(shapes, p.group_batch)
        g = p.lead_dims[0]
        return (jaxpr_walk.doubled_batch_shapes(
                    shapes, p.group_batch, lead_dims=p.lead_dims)
                + jaxpr_walk.folded_batch_shapes(
                    shapes, g * 2 * p.group_batch))

    return doubled


def check_pool_footprint(programs: List[Program]) -> List[ContractResult]:
    """phase2-footprint for the DISAGGREGATED pool programs: each pool
    compiles one scan, so the two-phase comparison pairs
    ``serve/phase1-bucketG`` with ``serve/phase2-bucketG`` — the phase-2
    pool program must carry no CFG-doubled tensors anywhere in its scan
    and its scan body must be strictly smaller than its phase-1 twin's."""
    out = []
    pool = {p.name: p for p in programs
            if p.name.startswith("serve/phase")}
    p1_names = sorted(n for n in pool if n.startswith("serve/phase1-"))
    for n1 in p1_names:
        n2 = n1.replace("phase1-", "phase2-")
        pair_name = n2
        if n2 not in pool:
            out.append(ContractResult(
                "phase2-footprint", pair_name, False,
                f"phase-1 pool program {n1} has no phase-2 twin"))
            continue
        p1, p2 = pool[n1], pool[n2]
        s1 = jaxpr_walk.top_level_scans(p1.jaxpr)
        s2 = jaxpr_walk.top_level_scans(p2.jaxpr)
        if len(s1) != 1 or len(s2) != 1:
            out.append(ContractResult(
                "phase2-footprint", pair_name, False,
                f"pool programs must carry exactly one scan each, found "
                f"{len(s1)}/{len(s2)}"))
            continue
        body1 = jaxpr_walk.scan_body(s1[0])
        body2 = jaxpr_walk.scan_body(s2[0])
        d1 = _doubled_detector(p1)(body1)
        d2 = _doubled_detector(p2)(body2)
        if not d1:
            out.append(ContractResult(
                "phase2-footprint", pair_name, False,
                "detector vacuous: the phase-1 pool scan carries no "
                "CFG-doubled batch"))
            continue
        ok = not d2 and len(body2) < len(body1)
        detail = (f"pool scan {len(body2)} eqns < phase1 {len(body1)}, "
                  f"no 2B tensors" if ok else
                  (f"phase-2 pool scan still carries 2B tensors: "
                   f"{sorted(set(d2))[:4]}" if d2 else
                   f"phase-2 pool scan ({len(body2)} eqns) not smaller "
                   f"than phase-1 ({len(body1)})"))
        out.append(ContractResult("phase2-footprint", pair_name, ok, detail))
    return out


def check_phase2_footprint(programs: List[Program]) -> List[ContractResult]:
    """The generalized ISSUE 1 proof: phase 2 carries no CFG-doubled batch
    and is strictly smaller than phase 1 — on every gated surface. The
    single-program (two-scan) surfaces are checked here; the disaggregated
    pool programs pair up in :func:`check_pool_footprint`."""
    out = []
    for p in programs:
        if p.gate is None or p.name.startswith("invert/") \
                or p.name.startswith("serve/phase"):
            continue
        scans = jaxpr_walk.top_level_scans(p.jaxpr)
        if len(scans) != 2:
            out.append(ContractResult(
                "phase2-footprint", p.name, False,
                f"expected a two-phase scan, found {len(scans)} top-level "
                "scan(s)"))
            continue
        body1 = jaxpr_walk.scan_body(scans[0])
        body2 = jaxpr_walk.scan_body(scans[1])
        doubled = _doubled_detector(p)
        d1, d2 = doubled(body1), doubled(body2)
        if not d1:
            out.append(ContractResult(
                "phase2-footprint", p.name, False,
                "detector vacuous: phase 1 carries no CFG-doubled batch"))
            continue
        ok = not d2 and len(body2) < len(body1)
        detail = (f"phase2 {len(body2)} eqns < phase1 {len(body1)}, "
                  f"no 2B tensors" if ok else
                  (f"phase2 still carries 2B tensors: "
                   f"{sorted(set(d2))[:4]}" if d2 else
                   f"phase2 body ({len(body2)} eqns) not smaller than "
                   f"phase1 ({len(body1)})"))
        out.append(ContractResult("phase2-footprint", p.name, ok, detail))
    return out


def _materialized_probs_eqns(p: Program) -> List[Tuple[int, ...]]:
    """Shapes of CFG-doubled attention-probability softmaxes a program
    materializes: ``exp`` equations over 4-D f32 operands (plus the vmap
    group prefix for serve programs) whose CFG batch dim is exactly ``2B``.
    In this stack the only 4-D f32 exp with a CFG-doubled leading dim is
    the attention softmax (``models.nn.attention_probs``); the fused-edit
    kernel's in-tile softmax runs on 2-D ``(block_q, K)`` tiles, so
    recursing into pallas_call bodies cannot false-positive, and the
    phase-2 single-branch path (batch ``B``) is out of scope by
    construction — the contract is about the ``(2B, heads, P, K)`` tensor
    the ISSUE's roofline names."""
    lead = len(p.lead_dims)
    hits = []
    for eqn in jaxpr_walk.all_eqns(p.jaxpr):
        if eqn.primitive.name != "exp":
            continue
        aval = eqn.invars[0].aval
        shape = tuple(getattr(aval, "shape", ()))
        if (len(shape) == 4 + lead and str(getattr(aval, "dtype", ""))
                == "float32" and shape[lead] == 2 * p.group_batch):
            hits.append(shape)
    return hits


def check_no_materialized_probs(
        programs: List[Program]) -> List[ContractResult]:
    """The kernel-bearing twin contract (ISSUE 16): a canonical program
    dispatched through the fused-edit kernel config materializes NO
    CFG-doubled ``(2B, heads, P, K)`` attention-probability tensor — the
    edit runs inside the attention tile, so the probs never exist as a
    program-level value (and therefore never reach HBM on chip). Each
    ``<name>-fused`` program is paired with its ``<name>`` materialized
    twin (same controller, ``kernels=None``), which must trip the detector
    — a vacuous detector (e.g. the probs shape drifting past the pattern)
    fails rather than silently passing."""
    out = []
    by_name = {p.name: p for p in programs}
    for name in sorted(by_name):
        if not name.endswith("-fused"):
            continue
        p = by_name[name]
        twin = by_name.get(name[:-len("-fused")])
        if twin is None:
            out.append(ContractResult(
                "no-materialized-probs", name, False,
                "fused program has no materialized twin in the sweep"))
            continue
        witness = _materialized_probs_eqns(twin)
        if not witness:
            out.append(ContractResult(
                "no-materialized-probs", name, False,
                f"detector vacuous: materialized twin {twin.name} shows no "
                "CFG-doubled softmax"))
            continue
        hits = _materialized_probs_eqns(p)
        ok = not hits
        detail = (f"0 materialized 2B-probs (twin shows "
                  f"{len(witness)})" if ok else
                  f"fused program still materializes CFG-doubled probs: "
                  f"{sorted(set(hits))[:4]}")
        out.append(ContractResult("no-materialized-probs", name, ok, detail))
    return out


def check_trace_invisible(pipe=None, buckets=(1,),
                          programs_fn=None) -> List[ContractResult]:
    """The flight-tracing half of the disabled-invisible discipline:
    flipping request-scoped tracing on/off must leave every canonical
    program fingerprint identical — a hard error otherwise.

    Flight tracing (``obs.flight``) is host-side by design; the day
    someone threads a tracer hook into a traced function, the retrace
    under a live tracer (open context, attached spans — the exact
    conditions the serve loop creates around every dispatch) diverges
    from the quiescent fingerprint and this contract names the program.
    ``programs_fn`` is an injection point for the verdict-flip proof in
    tests/test_jaxcheck.py."""
    import hashlib

    from ..obs import flight as flight_mod
    from ..obs import spans as spans_mod

    if pipe is None:
        pipe = tiny_pipeline()
    fn = programs_fn or canonical_programs

    def fingerprints() -> Dict[str, str]:
        return {p.name: hashlib.sha256(str(p.jaxpr).encode()).hexdigest()
                for p in fn(pipe, buckets=buckets, metrics=False)}

    base = fingerprints()
    tracer = flight_mod.FlightTracer()
    tracer.admit("jaxcheck-probe", 0.0, gated=True)
    tracer.segment("jaxcheck-probe", "run", 0.0, 1.0, pool="phase1")
    with spans_mod.attach(traces=tracer.current_trace_id("jaxcheck-probe")):
        live = fingerprints()
    tracer.finish("jaxcheck-probe", "ok", 1.0)
    out = []
    for name in sorted(base):
        if name not in live:
            out.append(ContractResult(
                "trace-invisible", name, False,
                "program missing from the tracer-live sweep"))
            continue
        ok = base[name] == live[name]
        detail = ("fingerprint identical with tracing on/off" if ok else
                  f"fingerprint changed under a live flight tracer: "
                  f"{base[name][:12]} != {live[name][:12]}")
        out.append(ContractResult("trace-invisible", name, ok, detail))
    return out


def _donated_params(lowered_text: str) -> int:
    """Count donated parameters in a lowering's StableHLO text: XLA marks
    them ``jax.buffer_donor`` (or legacy ``tf.aliasing_output``)."""
    return (lowered_text.count("jax.buffer_donor")
            + lowered_text.count("tf.aliasing_output"))


def _donation_lowerings(pipe) -> Dict[str, str]:
    """StableHLO text of every entry point :data:`DECLARED_DONATION`
    names: the two historical programs plus the pool programs and their
    mesh twins (group inputs staged under ``NamedSharding(P("dp"))`` on a
    :func:`_mesh_dp`-wide mesh, the engine's ``--mesh`` dispatch shape)."""
    import jax
    import jax.numpy as jnp

    from ..engine.sampler import (_text2image_jit, encode_prompts,
                                  phase2_controller)
    from ..models.config import unet_layout
    from ..ops import schedulers as sched_mod
    from ..parallel.mesh import make_mesh
    from ..parallel.sweep import (_sweep_jit, _sweep_phase1_jit,
                                  _sweep_phase2_jit)

    cfg = pipe.config
    layout = unet_layout(cfg.unet)
    schedule = sched_mod.schedule_from_config(STEPS, cfg.scheduler,
                                              kind="ddim")
    ctx, lats, gs = _scan_inputs(pipe)
    b = len(PROMPTS)
    cond, uncond = ctx[b:], ctx[:b]
    ctrl = _edit_controller(pipe)
    carry = _zero_carry(pipe, ctrl)
    p2 = phase2_controller(ctrl)
    cond_b = encode_prompts(pipe, list(PROMPTS))
    lead1 = lambda t: jax.tree_util.tree_map(  # noqa: E731
        lambda x: x[None], t)                  # one-lane group axis
    dp = _mesh_dp()
    mesh = make_mesh(dp, tp=1)

    def lead_dp(t):
        # dp whole lanes, staged under the engine's group-axis sharding
        # (a 1-lane group can't split over a dp>1 mesh).
        return jax.tree_util.tree_map(
            lambda x: _stage_dp(jnp.broadcast_to(x[None], (dp,) + x.shape),
                                mesh), t)

    lowerings = {
        "text2image": _text2image_jit.lower(
            pipe.unet_params, pipe.vae_params, cfg, layout, schedule,
            "ddim", cond, uncond, lats, None, gs, None, False,
            progress=False, sp=None, gate=None, metrics=False),
        "sweep": _sweep_jit.lower(
            pipe.unet_params, pipe.vae_params, cfg, layout, schedule,
            "ddim", ctx[None], lats[None], None, gs, None, progress=False,
            gate=None, metrics=False),
        "sweep/phase1": _sweep_phase1_jit.lower(
            pipe.unet_params, cfg, layout, schedule, "ddim", ctx[None],
            lats[None], lead1(ctrl), gs, progress=False, gate=GATE,
            metrics=False),
        "sweep/phase2": _sweep_phase2_jit.lower(
            pipe.unet_params, pipe.vae_params, cfg, layout, schedule,
            "ddim", cond_b[None], lead1(carry), lead1(p2), gs,
            progress=False, gate=GATE, metrics=False),
        "sweep/mesh": _sweep_jit.lower(
            pipe.unet_params, pipe.vae_params, cfg, layout, schedule,
            "ddim", lead_dp(ctx), lead_dp(lats), None, gs, None,
            progress=False, gate=None, metrics=False),
        "sweep/phase1-mesh": _sweep_phase1_jit.lower(
            pipe.unet_params, cfg, layout, schedule, "ddim",
            lead_dp(ctx), lead_dp(lats), lead_dp(ctrl), gs,
            progress=False, gate=GATE, metrics=False),
        "sweep/phase2-mesh": _sweep_phase2_jit.lower(
            pipe.unet_params, pipe.vae_params, cfg, layout, schedule,
            "ddim", lead_dp(cond_b), lead_dp(carry), lead_dp(p2), gs,
            progress=False, gate=GATE, metrics=False),
    }
    return {name: low.as_text() for name, low in lowerings.items()}


def check_donation(pipe=None,
                   declared: Optional[Dict[str, Tuple[int, ...]]] = None,
                   lowerings: Optional[Dict[str, str]] = None,
                   ) -> List[ContractResult]:
    """Lower every declared jitted entry point (monolithic, pool, and mesh
    programs) and check buffer donation against :data:`DECLARED_DONATION`
    — both directions (declared-but-absent and applied-but-undeclared
    fail). ``declared``/``lowerings`` are injection points for the seeded
    verdict-flip proofs in tests/test_jaxcheck.py."""
    if declared is None:
        declared = DECLARED_DONATION
    if lowerings is None:
        if pipe is None:
            pipe = tiny_pipeline()
        lowerings = _donation_lowerings(pipe)
    out = []
    for name, wants in declared.items():
        text = lowerings.get(name)
        if text is None:
            out.append(ContractResult(
                "donation-as-declared", name, False,
                "declared program has no lowering in the sweep (stale "
                "DECLARED_DONATION entry?)"))
            continue
        n = _donated_params(text)
        ok = (n > 0) == (len(wants) > 0)
        detail = (f"{n} donated param(s) in lowering, "
                  f"{len(wants)} declared")
        out.append(ContractResult("donation-as-declared", name, ok, detail))
    return out


def run_contracts(pipe=None, buckets=(1, 2, 4, 8)) -> List[ContractResult]:
    """All jaxpr contracts over all canonical programs (telemetry off and
    on), plus the donation check. The compile-key completeness sweep lives
    in :mod:`.compile_key` (it needs per-Request tracing, not the canonical
    set)."""
    if pipe is None:
        pipe = tiny_pipeline()
    plain = canonical_programs(pipe, buckets=buckets, metrics=False)
    instrumented = canonical_programs(pipe, buckets=buckets[:1],
                                      metrics=True)
    results: List[ContractResult] = []
    results += check_no_f64(plain)
    results += check_hot_scan_callbacks(plain)
    results += check_hot_scan_callbacks(instrumented)
    results += check_phase2_footprint(plain)
    results += check_pool_footprint(plain)
    results += check_donation(pipe)
    # Kernel-bearing twins (ISSUE 16): the fused-edit dispatch programs are
    # canonical too — they carry every structural contract the materialized
    # programs do, plus the no-materialized-probs proof against their
    # kernels=None twins.
    kpairs = kernel_programs(pipe)
    fused = [p for p in kpairs if p.name.endswith("-fused")]
    results += check_no_f64(kpairs)
    results += check_hot_scan_callbacks(fused)
    results += check_phase2_footprint(fused)
    results += check_no_materialized_probs(kpairs)
    # Flight tracing joins the disabled-invisible sweep at one bucket
    # (the check retraces the canonical set twice; the program identity
    # property is bucket-independent).
    results += check_trace_invisible(pipe, buckets=buckets[:1])
    return results
