"""``jaxcheck --fix`` — best-effort mechanical fixes.

Only rules whose fix is a pure text transformation with no behavioral
judgment are fixable:

- ``unused-import`` — remove the dead name from its import statement
  (dropping the whole statement when every name it binds is dead).
- suppression formatting — normalize ``#jaxcheck:disable = x`` spelling
  variants to the canonical ``# jaxcheck: disable=x`` so grep and the
  suppression scanner agree.

Everything else (traced branches, host syncs, mutable defaults) needs a
human: the fix changes semantics. The fixer re-lints after rewriting, so a
fix can never *introduce* a finding silently — if it would, the file is
left untouched and reported.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from . import astlint
# One regex for scanner and fixer: if they ever diverged, --fix could
# normalize to a spelling the suppression scanner parses differently.
from .findings import _SUPPRESS_RE, SUPPRESS_CANONICAL, comment_columns


def normalize_suppressions(source: str) -> Tuple[str, int]:
    """Rewrite suppression comments to the canonical spelling, preserving
    indentation and any trailing reason text after the rule list. Only real
    comments are touched (tokenize-verified) — directive-looking text
    inside string literals/docstrings is content, not a directive. Returns
    (new_source, n_changed)."""
    changed = 0
    out_lines: List[str] = []
    cols = comment_columns(source.splitlines())
    for i, line in enumerate(source.splitlines(keepends=True)):
        eol = line[len(line.rstrip("\r\n")):]
        body = line.rstrip("\r\n")
        col = cols.get(i + 1)
        m = _SUPPRESS_RE.search(body, col) if col is not None else None
        if m:
            rules = ",".join(r.strip() for r in m.group(1).split(",")
                             if r.strip())
            canonical = SUPPRESS_CANONICAL + rules
            if body[m.start():m.end()] != canonical:
                prefix = body[:m.start()]
                if prefix.strip():   # trailing-comment form: code + 2 sp
                    prefix = prefix.rstrip() + "  "
                # else: standalone comment — keep the indentation verbatim
                body = prefix + canonical + body[m.end():]
                changed += 1
        out_lines.append(body + eol)
    return "".join(out_lines), changed


def remove_unused_imports(source: str, path: str = "<string>"
                          ) -> Tuple[str, int]:
    """Drop dead imported names reported by the ``unused-import`` rule.
    Returns (new_source, n_removed). Only single-line import statements are
    rewritten (multi-line imports are rare in this repo and not worth the
    reconstruction risk in a best-effort tool)."""
    findings = [f for f in astlint.lint_source(source, path,
                                               rules=("unused-import",))
                if f.is_new]
    if not findings:
        return source, 0
    dead = {}  # line (1-based) -> set of dead names
    for f in findings:
        name = f.message.split("`")[1]
        dead.setdefault(f.line, set()).add(name)

    lines = source.splitlines(keepends=True)
    tree = ast.parse(source)
    removed = 0
    for stmt in list(ast.walk(tree)):
        if not isinstance(stmt, (ast.Import, ast.ImportFrom)):
            continue
        names = dead.get(stmt.lineno)
        if not names or stmt.end_lineno != stmt.lineno:
            continue
        keep = []
        for a in stmt.names:
            bound = (a.asname or a.name).split(".")[0]
            if bound in names:
                removed += 1
            else:
                keep.append(a)
        idx = stmt.lineno - 1
        eol = lines[idx][len(lines[idx].rstrip("\r\n")):]
        indent = lines[idx][:len(lines[idx]) - len(lines[idx].lstrip())]
        if not keep:
            lines[idx] = ""
        else:
            rendered = ", ".join(a.name + (f" as {a.asname}" if a.asname
                                           else "") for a in keep)
            if isinstance(stmt, ast.ImportFrom):
                dots = "." * stmt.level
                lines[idx] = (f"{indent}from {dots}{stmt.module or ''} "
                              f"import {rendered}{eol}")
            else:
                lines[idx] = f"{indent}import {rendered}{eol}"
    return "".join(lines), removed


def fix_source(source: str, path: str = "<string>") -> Tuple[str, dict]:
    """Apply every mechanical fix; returns (new_source, counts). Refuses a
    rewrite that fails to parse or that introduces new findings (returns
    the original source with ``counts['aborted']`` set)."""
    counts = {"unused_imports_removed": 0, "suppressions_normalized": 0}
    new, n = remove_unused_imports(source, path)
    counts["unused_imports_removed"] = n
    new, n = normalize_suppressions(new)
    counts["suppressions_normalized"] = n
    if new == source:
        return source, counts
    try:
        before = {f.fingerprint for f in astlint.lint_source(source, path)
                  if f.is_new}
        after = [f for f in astlint.lint_source(new, path) if f.is_new]
    except SyntaxError:
        return source, {**counts, "aborted": "rewrite failed to parse"}
    introduced = [f for f in after if f.fingerprint not in before]
    if introduced:
        return source, {**counts,
                        "aborted": f"rewrite would introduce "
                                   f"{len(introduced)} new finding(s)"}
    return new, counts


def fix_file(path: str, repo_root: Optional[str] = None) -> dict:
    import os

    with open(path) as f:
        source = f.read()
    rel = os.path.relpath(path, repo_root) if repo_root else path
    new, counts = fix_source(source, rel)
    counts["path"] = rel
    counts["changed"] = new != source
    if new != source:
        with open(path, "w") as f:
            f.write(new)
    return counts
