"""The serve WAL protocol, DECLARED — pass 5's contract surface (ISSUE 20).

Six PRs grew the journal grammar organically (admission, dispatch,
hand-off, preemption, cache inserts, terminals, and nine EVENT sub-kinds);
every durability claim the ROADMAP rests on is enforced by one hand-written
``replay()`` fold over that grammar. This module makes the grammar a
*declaration* — the ``DECLARED_COLLECTIVES``/``DECLARED_DONATION`` pattern
applied to the WAL:

- :data:`DECLARED_PROTOCOL` is a per-request lifecycle state machine over
  record kinds (``absent → pending → inflight ⇄ parked → done``), and
  :data:`DECLARED_EVENTS` declares every EVENT sub-kind with its replay
  fold target. The walcheck model checker (:mod:`.walcheck`) *generates
  its traces from these declarations*, so a record kind cannot be declared
  without being crash-tested.
- :func:`check_protocol` is the completeness sweep: the declaration, the
  write-time registry in ``serve/journal.py``, the journal append sites
  across the package, and ``replay()``'s fold branches must all agree —
  an undeclared kind, a stale declaration, a writer with no call site, or
  a fold branch for a kind nobody declared are each hard errors, in both
  directions. Extending the grammar (ROADMAP 2c multi-host leader WALs,
  ROADMAP 3 schedule-rollout records) starts here or fails CI.

Everything is pure Python over the AST plus an importlib-by-path load of
``serve/journal.py`` (stdlib-only by design) — no jax import, so the pass
runs in milliseconds next to the AST lints.
"""

from __future__ import annotations

import ast
import dataclasses
import importlib.util
import os
import sys
from typing import Dict, List, Optional, Tuple

#: Per-request lifecycle states the record state machine ranges over.
#: ``absent`` = never admitted; ``pending`` = admitted, not dispatched;
#: ``inflight`` = handed to a runner; ``parked`` = carry spilled at the
#: phase boundary (hand-off or preemption), waiting to resume;
#: ``done`` = a terminal record ended the request's life.
STATES = ("absent", "pending", "inflight", "parked", "done")

#: The marker ``from_states`` value for records that are not per-request
#: (EVENT: loop-level, no request id).
GLOBAL = "*"


@dataclasses.dataclass(frozen=True)
class RecordDecl:
    """One declared WAL record kind and its lifecycle transition."""

    kind: str
    #: Lifecycle states the writer may append this record from
    #: ((:data:`GLOBAL`,) for loop-level records).
    from_states: Tuple[str, ...]
    #: State the request moves to (``None`` = unchanged).
    to_state: Optional[str]
    #: ``replay()`` must fold this kind into :class:`ReplayState` (the
    #: fold-branch sweep checks the branch exists; the model checker
    #: checks it folds *correctly* at every crash point).
    replay_folds: bool
    #: The record references an on-disk spill that must be durable BEFORE
    #: the record is appended (hand-off carries, cache result spills) —
    #: the ordering the ``dropped-fsync`` seeded bug violates.
    spill: bool = False
    #: Enumeration bound: at most this many per request per trace
    #: (``None`` = bounded only by trace depth).
    max_per_request: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class EventDecl:
    """One declared EVENT sub-kind."""

    kind: str
    #: :class:`ReplayState` field the event folds into (``None`` =
    #: informational; replay reads past it). Must equal the write-time
    #: registry's entry in ``journal.EVENT_KINDS``.
    folds: Optional[str]
    #: The payload field the fold reads (model traces carry it).
    payload: Optional[str] = None


#: The declared record grammar. Every kind ``serve/journal.py`` registers,
#: every kind a serve-side call site appends, and every kind ``replay()``
#: branches on must appear here — and vice versa (:func:`check_protocol`).
DECLARED_PROTOCOL: Dict[str, RecordDecl] = {d.kind: d for d in (
    RecordDecl("admitted", ("absent",), "pending", replay_folds=True,
               max_per_request=1),
    RecordDecl("dispatched", ("pending", "parked"), "inflight",
               replay_folds=False),
    RecordDecl("handoff", ("inflight",), "parked", replay_folds=True,
               spill=True),
    RecordDecl("preempted", ("inflight",), "parked", replay_folds=True,
               spill=True),
    RecordDecl("cache", ("inflight",), None, replay_folds=True, spill=True,
               max_per_request=1),
    RecordDecl("terminal", ("pending", "inflight", "parked"), "done",
               replay_folds=True, max_per_request=1),
    RecordDecl("event", (GLOBAL,), None, replay_folds=True),
)}

#: The declared EVENT sub-kinds — the protocol-side twin of the write-time
#: registry ``journal.EVENT_KINDS`` (cross-checked both directions).
DECLARED_EVENTS: Dict[str, EventDecl] = {d.kind: d for d in (
    EventDecl("degrade", folds="degrade_level", payload="level"),
    EventDecl("restore", folds="degrade_level", payload="level"),
    EventDecl("resize", folds="mesh_dp", payload="new_dp"),
    EventDecl("snapshot", folds=None),
    EventDecl("cache_shed", folds=None),
    EventDecl("drain", folds=None),
    EventDecl("drain_timeout", folds=None),
    EventDecl("fatal", folds=None),
    EventDecl("profile_drift", folds=None),
)}

#: The crash-point catalog: every way the model checker kills the writer.
#: ``record-boundary`` — after every durable record prefix; ``torn-tail``
#: — a record cut mid-``write``; the three ``snapshot-*`` windows are
#: compact()'s documented crash windows (torn ``.tmp``, snapshot durable
#: but WAL unrotated, rotated-but-unremoved ``.old``). The chaos catalog
#: (``serve/chaos.py``) maps each lifecycle kill kind onto one of these,
#: and walcheck must exercise all of them or its own coverage check fails.
CRASH_WINDOWS = ("record-boundary", "torn-tail", "snapshot-torn-tmp",
                 "snapshot-overlap", "snapshot-stale-old")


# ---------------------------------------------------------------------------
# Loading the serve-side modules without importing the serve package
# ---------------------------------------------------------------------------

_MOD_CACHE: dict = {}


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _load_by_path(name: str, rel: str, root: Optional[str] = None):
    """Import a stdlib-only serve module by file path. ``p2p_tpu.serve``'s
    package ``__init__`` imports the engine (and with it jax); the files
    this pass needs (``journal.py``, ``chaos.py``) are deliberately
    stdlib-only, so loading them standalone keeps pass 5 jax-free —
    without a second copy of the code under test: the *source file* is the
    one the engine runs."""
    root = root or repo_root()
    key = (name, root)
    if key not in _MOD_CACHE:
        path = os.path.join(root, rel)
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        # dataclasses resolves ``cls.__module__`` through sys.modules at
        # class-creation time, so register before exec.
        sys.modules[name] = mod
        try:
            spec.loader.exec_module(mod)
        except BaseException:
            sys.modules.pop(name, None)
            raise
        _MOD_CACHE[key] = mod
    return _MOD_CACHE[key]


def load_journal(root: Optional[str] = None):
    """The real ``serve/journal.py`` module (real writers, real replay)."""
    return _load_by_path("_walcheck_journal",
                         os.path.join("p2p_tpu", "serve", "journal.py"),
                         root)


def load_chaos(root: Optional[str] = None):
    """The real ``serve/chaos.py`` module (the chaos-kind catalog)."""
    return _load_by_path("_walcheck_chaos",
                         os.path.join("p2p_tpu", "serve", "chaos.py"),
                         root)


# ---------------------------------------------------------------------------
# Static sweeps
# ---------------------------------------------------------------------------

#: Directories scanned for journal append sites (package code only; tests
#: construct raw records on purpose).
APPEND_SCAN_PATHS = (os.path.join("p2p_tpu", "serve"), "p2p_tpu")


def _is_journal_receiver(node: ast.AST) -> bool:
    """``journal.event(...)`` / ``self._journal.terminal(...)`` — the
    receiver's final name names a journal."""
    if isinstance(node, ast.Attribute):
        name = node.attr
    elif isinstance(node, ast.Name):
        name = node.id
    else:
        return False
    return name == "journal" or name.endswith("_journal")


def scan_append_sites(root: Optional[str] = None):
    """Walk the package AST for journal writer calls. Returns
    ``(record_sites, event_sites, dynamic_event_sites)``: record kind ->
    list of ``path:line`` sites (via ``journal.WRITER_KINDS``), EVENT
    literal sub-kind -> sites, and sites whose event kind is not a string
    literal (covered by the write-time raise, invisible to staleness)."""
    root = root or repo_root()
    journal = load_journal(root)
    record_sites: Dict[str, List[str]] = {}
    event_sites: Dict[str, List[str]] = {}
    dynamic: List[str] = []
    pkg = os.path.join(root, "p2p_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path, "r", encoding="utf-8") as f:
                try:
                    tree = ast.parse(f.read(), filename=path)
                except SyntaxError:
                    continue
            rel = os.path.relpath(path, root)
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in journal.WRITER_KINDS
                        and _is_journal_receiver(node.func.value)):
                    continue
                site = f"{rel}:{node.lineno}"
                kind = journal.WRITER_KINDS[node.func.attr]
                record_sites.setdefault(kind, []).append(site)
                if node.func.attr == "event":
                    arg = node.args[0] if node.args else None
                    if isinstance(arg, ast.Constant) and isinstance(
                            arg.value, str):
                        event_sites.setdefault(arg.value, []).append(site)
                    else:
                        dynamic.append(site)
    return record_sites, event_sites, dynamic


def scan_replay_branches(root: Optional[str] = None):
    """Record kinds ``replay()``'s fold branches on: the names compared
    against ``rec.get("type")`` inside ``fold_file``, resolved through the
    module-level constants (``ADMITTED`` -> ``"admitted"``). Returns the
    set of folded record kinds."""
    root = root or repo_root()
    path = os.path.join(root, "p2p_tpu", "serve", "journal.py")
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    consts: Dict[str, str] = {}
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            consts[node.targets[0].id] = node.value.value
    folded: set = set()

    def resolve(n: ast.AST) -> Optional[str]:
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            return n.value
        if isinstance(n, ast.Name):
            return consts.get(n.id)
        return None

    replay_fn = next((n for n in tree.body
                      if isinstance(n, ast.FunctionDef)
                      and n.name == "replay"), None)
    if replay_fn is None:
        return folded
    for node in ast.walk(replay_fn):
        if not (isinstance(node, ast.Compare) and len(node.ops) == 1
                and isinstance(node.left, ast.Name)
                and node.left.id == "kind"
                and isinstance(node.ops[0], (ast.Eq, ast.In))):
            continue
        comp = node.comparators[0]
        elts = comp.elts if isinstance(comp, (ast.Tuple, ast.List)) \
            else [comp]
        for elt in elts:
            val = resolve(elt)
            if val is not None:
                folded.add(val)
    return folded


@dataclasses.dataclass
class ProtocolVerdict:
    """One completeness-sweep verdict (the ``FieldVerdict`` shape)."""

    check: str
    ok: bool
    problem: str = ""

    def format(self) -> str:
        if self.ok:
            return f"{self.check}: ok"
        return f"{self.check}: {self.problem}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def check_protocol(root: Optional[str] = None) -> List[ProtocolVerdict]:
    """The completeness sweep: declaration ↔ registry ↔ append sites ↔
    replay fold branches, every edge in both directions, plus the chaos
    catalog's crash-window mapping. Any ``ok=False`` verdict is a hard
    error for the ``wal`` report section and the quality gate."""
    root = root or repo_root()
    journal = load_journal(root)
    chaos = load_chaos(root)
    out: List[ProtocolVerdict] = []

    def verdict(check: str, problems: List[str]) -> None:
        out.append(ProtocolVerdict(check, not problems,
                                   "; ".join(problems)))

    # 1. Declaration ↔ write-time registry (record kinds).
    probs = []
    declared = set(DECLARED_PROTOCOL)
    registered = set(journal.RECORD_KINDS)
    for k in sorted(registered - declared):
        probs.append(f"record kind {k!r} registered in journal.RECORD_KINDS"
                     f" but not declared in DECLARED_PROTOCOL")
    for k in sorted(declared - registered):
        probs.append(f"record kind {k!r} declared but not registered in "
                     f"journal.RECORD_KINDS (stale declaration)")
    for k, d in sorted(DECLARED_PROTOCOL.items()):
        bad_states = (set(d.from_states) | ({d.to_state} - {None})) \
            - set(STATES) - {GLOBAL}
        if bad_states:
            probs.append(f"record kind {k!r} names unknown lifecycle "
                         f"state(s) {sorted(bad_states)}")
    verdict("record-kinds-registered", probs)

    # 2. Declaration ↔ write-time registry (event kinds + fold targets).
    probs = []
    ev_declared = set(DECLARED_EVENTS)
    ev_registered = set(journal.EVENT_KINDS)
    for k in sorted(ev_registered - ev_declared):
        probs.append(f"event kind {k!r} registered in journal.EVENT_KINDS "
                     f"but not declared in DECLARED_EVENTS")
    for k in sorted(ev_declared - ev_registered):
        probs.append(f"event kind {k!r} declared but not registered "
                     f"(stale declaration)")
    for k in sorted(ev_declared & ev_registered):
        if DECLARED_EVENTS[k].folds != journal.EVENT_KINDS[k]:
            probs.append(
                f"event kind {k!r} fold disagrees: declared "
                f"{DECLARED_EVENTS[k].folds!r}, registry folds into "
                f"{journal.EVENT_KINDS[k]!r}")
    verdict("event-kinds-registered", probs)

    # 3. Append sites: every observed kind declared, every declared kind
    #    written somewhere (stale otherwise).
    record_sites, event_sites, _dynamic = scan_append_sites(root)
    probs = []
    for k in sorted(set(record_sites) - declared):
        probs.append(f"append site(s) {record_sites[k]} write undeclared "
                     f"record kind {k!r}")
    for k in sorted(declared - set(record_sites)):
        probs.append(f"declared record kind {k!r} has no journal append "
                     f"site in the package (stale declaration)")
    for k in sorted(set(event_sites) - ev_declared):
        probs.append(f"append site(s) {event_sites[k]} write undeclared "
                     f"event kind {k!r}")
    for k in sorted(ev_declared - set(event_sites)):
        probs.append(f"declared event kind {k!r} has no journal.event "
                     f"call site in the package (stale declaration)")
    verdict("append-sites-declared", probs)

    # 4. Replay fold branches: every branch kind declared; every declared
    #    record kind read by a branch (reader totality — an unbranched
    #    kind would fall through to skipped_corrupt).
    folded = scan_replay_branches(root)
    probs = []
    for k in sorted(folded - declared):
        probs.append(f"replay() folds undeclared record kind {k!r}")
    for k in sorted(declared - folded):
        probs.append(f"declared record kind {k!r} has no replay() fold "
                     f"branch (the reader would skip it as corrupt)")
    verdict("replay-branches-declared", probs)

    # 5. Chaos catalog ↔ crash-point catalog: every lifecycle kill kind's
    #    declared crash window is one walcheck injects.
    probs = []
    catalog = getattr(chaos, "CATALOG", None)
    if catalog is None:
        probs.append("serve/chaos.py has no CATALOG table")
    else:
        for name, entry in sorted(catalog.items()):
            win = entry.crash_window
            if win is not None and win not in CRASH_WINDOWS:
                probs.append(
                    f"chaos kind {name!r} names crash window {win!r} not "
                    f"in protocol.CRASH_WINDOWS {CRASH_WINDOWS}")
    verdict("chaos-windows-covered", probs)
    return out
