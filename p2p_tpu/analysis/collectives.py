"""Pass 3 — shardcheck: collective-budget contracts over the mesh programs.

PR 9's transfer-guard test caught two implicit transfers *at runtime*;
this pass catches the same bug class at review time, on the compiler's
own evidence. Every canonical mesh serve program
(``serve/{mesh,phase1-mesh,phase2-mesh}-dpN`` at dp ∈
:data:`SHARDCHECK_DPS`) is lowered AND compiled on the CPU backend, and
three contracts are checked over the emitted text
(:mod:`.shlo_walk`):

- ``collectives-as-declared`` — the program's collective signature (the
  op-kind multiset of its post-SPMD HLO) matches
  :data:`DECLARED_COLLECTIVES`, **both directions**: an undeclared
  collective is a hard error naming the op, shape and ring-cost bytes (an
  accidental all-gather — e.g. an unsharded operand the partitioner had
  to replicate mid-program); a declared-but-absent kind (or a declaration
  for a program the sweep no longer produces) is a stale-declaration
  error. Today every dp program declares the empty multiset: dp is
  embarrassingly parallel by design (``parallel/mesh.py`` — "Collective-
  free in the sampling loop"), replicated weights and dp-replicated host
  scalars are the *declared* baseline, and everything else is a finding.
- ``no-hidden-resharding`` — the lowered StableHLO carries no
  sharding-changing custom calls (``@Sharding`` constraints,
  ``@SPMDFullToShardShape``/``@SPMDShardToFullShape`` pairs): nothing in
  a canonical dp program may re-spec — least of all replicate — a
  dp-sharded tensor mid-program.
- ``no-host-boundary`` — neither text form carries infeed/outfeed or a
  host-callback custom call: the mesh dispatch path never round-trips
  the host (the static twin of the ``jax.transfer_guard("disallow")``
  dispatch tests).

The per-program :func:`~.shlo_walk.collective_signature` (op multiset +
bytes-per-step / bytes-once under the ring cost model) is returned as the
comms table the report JSON carries — the budget the mp-axis work will
design against (today: all zeros, and the contract keeps it that way
until a declaration says otherwise).

Unlike the jaxpr contracts this pass pays an XLA compile (the GSPMD
partitioner only runs there), ~7s per program at TINY scale; the
persistent compile cache makes repeats cheap. Like
:func:`.contracts._mesh_dp`, the dp sweep degrades to the dp values the
process has devices for — the test/CI environments force a virtual
8-device platform, a bare laptop run still checks dp=1.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from . import shlo_walk
from .contracts import ContractResult

#: The dp widths the shardcheck sweep covers when the process has the
#: devices (tools/jaxcheck.py and the test conftest force a virtual
#: 8-device CPU platform, so CI always sweeps all three).
SHARDCHECK_DPS: Tuple[int, ...] = (1, 2, 4)

#: program name -> declared collective op-kind multiset (op -> count in
#: the compiled post-SPMD HLO). The declared baseline for the dp-only
#: mesh is ZERO collectives everywhere: per-device lane buckets are
#: independent, weights are replicated once at engine start
#: (``serve.meshing.replicate_pipeline``) and host scalars stage
#: dp-replicated — so any collective the partitioner inserts is data
#: movement nobody designed. The mp-axis PR will declare its psums here
#: (and the check will then also fail if they *disappear* — a stale
#: declaration is as much a review lie as an undeclared op).
DECLARED_COLLECTIVES: Dict[str, Dict[str, int]] = {
    "serve/mesh-dp1": {},
    "serve/mesh-dp2": {},
    "serve/mesh-dp4": {},
    "serve/phase1-mesh-dp1": {},
    "serve/phase1-mesh-dp2": {},
    "serve/phase1-mesh-dp4": {},
    "serve/phase2-mesh-dp1": {},
    "serve/phase2-mesh-dp2": {},
    "serve/phase2-mesh-dp4": {},
}

_NAME_TEMPLATES = ("serve/mesh-dp{dp}", "serve/phase1-mesh-dp{dp}",
                   "serve/phase2-mesh-dp{dp}")


@dataclasses.dataclass
class MeshProgram:
    """One lowered+compiled canonical mesh program: both text forms plus
    the metadata the comms table keys on. ``steps`` is the scan length the
    per-step bytes are denominated in."""

    name: str
    dp: int
    lanes: int
    stablehlo: str
    hlo: str
    steps: int


def mesh_dps(dps: Tuple[int, ...] = SHARDCHECK_DPS) -> Tuple[int, ...]:
    """The subset of ``dps`` this process can actually mesh (same
    degradation rule as :func:`.contracts._mesh_dp`: the sweep must run
    everywhere the analyzer does)."""
    import jax

    n = len(jax.devices())
    return tuple(d for d in dps if d <= n)


def lower_mesh_programs(pipe=None,
                        dps: Tuple[int, ...] = SHARDCHECK_DPS
                        ) -> List[MeshProgram]:
    """Lower + compile the three mesh serve entry points at each dp in
    ``dps`` (one whole lane per device — shardcheck is about bytes over
    the interconnect, not batch-shape coverage, which the jaxpr contracts
    already sweep). Inputs are staged exactly as the engine dispatches:
    group axis under ``NamedSharding(P("dp"))``, weights replicated via
    ``serve.meshing.replicate_pipeline``, schedule tables and the
    guidance scalar mesh-replicated."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..engine.sampler import encode_prompts, phase2_controller, stage_host
    from ..models.config import unet_layout
    from ..ops import schedulers as sched_mod
    from ..parallel.mesh import make_mesh
    from ..parallel.sweep import (_stage_replicated, _stage_sharded,
                                  _sweep_jit, _sweep_phase1_jit,
                                  _sweep_phase2_jit)
    from ..serve.meshing import replicate_pipeline
    from ..utils.cache import ensure_persistent_cache
    from .contracts import (GATE, PROMPTS, STEPS, _edit_controller,
                            _scan_inputs, _zero_carry, tiny_pipeline)

    ensure_persistent_cache()   # the compile step is real XLA work
    if pipe is None:
        pipe = tiny_pipeline()
    ctrl = _edit_controller(pipe)
    cfg = pipe.config
    layout = unet_layout(cfg.unet)
    schedule = sched_mod.schedule_from_config(STEPS, cfg.scheduler,
                                              kind="ddim")
    ctx, lats, _ = _scan_inputs(pipe)
    cond = encode_prompts(pipe, list(PROMPTS))
    carry = _zero_carry(pipe, ctrl)
    p2 = phase2_controller(ctrl)

    out: List[MeshProgram] = []
    for dp in mesh_dps(dps):
        mesh = make_mesh(dp, tp=1)
        mpipe = replicate_pipeline(pipe, mesh)
        sch = _stage_replicated(schedule, mesh)
        gs = stage_host(np.float32(7.5), mesh=mesh)
        gspec = NamedSharding(mesh, P("dp"))
        g = dp   # one whole lane bucket per device

        def stage(x):
            return _stage_sharded(
                jnp.broadcast_to(x[None], (g,) + x.shape), gspec)

        ctx_g, lat_g = stage(ctx), stage(lats)
        ctrl_g = jax.tree_util.tree_map(stage, ctrl)
        lowered = {
            f"serve/mesh-dp{dp}": _sweep_jit.lower(
                mpipe.unet_params, mpipe.vae_params, cfg, layout, sch,
                "ddim", ctx_g, lat_g, ctrl_g, gs, None, progress=False,
                gate=GATE, metrics=False),
            f"serve/phase1-mesh-dp{dp}": _sweep_phase1_jit.lower(
                mpipe.unet_params, cfg, layout, sch, "ddim", ctx_g, lat_g,
                ctrl_g, gs, progress=False, gate=GATE, metrics=False),
            f"serve/phase2-mesh-dp{dp}": _sweep_phase2_jit.lower(
                mpipe.unet_params, mpipe.vae_params, cfg, layout, sch,
                "ddim", stage(cond),
                jax.tree_util.tree_map(stage, carry),
                jax.tree_util.tree_map(stage, p2), gs, progress=False,
                gate=GATE, metrics=False),
        }
        for name, low in lowered.items():
            out.append(MeshProgram(
                name=name, dp=dp, lanes=g, stablehlo=low.as_text(),
                hlo=low.compile().as_text(), steps=STEPS))
    return out


def check_collectives(pipe=None, dps: Tuple[int, ...] = SHARDCHECK_DPS,
                      programs: Optional[List[MeshProgram]] = None,
                      declared: Optional[Dict[str, Dict[str, int]]] = None,
                      ) -> Tuple[List[ContractResult], Dict[str, dict]]:
    """Run shardcheck: ``(results, comms table)``. ``programs`` and
    ``declared`` are injection points for the seeded verdict-flip tests
    (tests/test_shardcheck.py); production callers pass neither."""
    if declared is None:
        declared = DECLARED_COLLECTIVES
    if programs is None:
        programs = lower_mesh_programs(pipe, dps=dps)

    results: List[ContractResult] = []
    table: Dict[str, dict] = {}
    for prog in programs:
        ops = shlo_walk.collective_ops(prog.hlo)
        sig = shlo_walk.collective_signature(ops)
        table[prog.name] = {"dp": prog.dp, "lanes": prog.lanes,
                            "steps": prog.steps, **sig}

        # -- collectives-as-declared, both directions -------------------
        want = declared.get(prog.name)
        if want is None:
            results.append(ContractResult(
                "collectives-as-declared", prog.name, False,
                "no DECLARED_COLLECTIVES entry for this program — declare "
                "its collective multiset (empty means collective-free)"))
        else:
            got = sig["ops"]
            undeclared = {k: n - want.get(k, 0) for k, n in got.items()
                          if n > want.get(k, 0)}
            stale = {k: n - got.get(k, 0) for k, n in want.items()
                     if n > got.get(k, 0)}
            if undeclared:
                first = next(op for op in ops if op.kind in undeclared)
                results.append(ContractResult(
                    "collectives-as-declared", prog.name, False,
                    f"undeclared collective(s) {undeclared}: first is "
                    f"{first.describe()}"))
            elif stale:
                results.append(ContractResult(
                    "collectives-as-declared", prog.name, False,
                    f"stale declaration: declared {stale} absent from the "
                    "compiled program (update DECLARED_COLLECTIVES)"))
            else:
                results.append(ContractResult(
                    "collectives-as-declared", prog.name, True,
                    f"ops {got or '{}'} = declared, "
                    f"{sig['bytes_per_step']}B/step + "
                    f"{sig['bytes_once']}B once"))

        # -- no-hidden-resharding ---------------------------------------
        changes = shlo_walk.sharding_custom_calls(prog.stablehlo)
        if changes:
            worst = next((c for c in changes if c.forces_replication),
                         changes[0])
            results.append(ContractResult(
                "no-hidden-resharding", prog.name, False,
                f"{len(changes)} sharding-changing custom call(s): "
                f"{worst.describe()}"
                + (" — full replication of a sharded tensor"
                   if worst.forces_replication else "")))
        else:
            results.append(ContractResult(
                "no-hidden-resharding", prog.name, True,
                "no sharding-changing custom calls"))

        # -- no-host-boundary -------------------------------------------
        host = (shlo_walk.host_boundary_ops(prog.stablehlo)
                + shlo_walk.host_boundary_ops(prog.hlo))
        results.append(ContractResult(
            "no-host-boundary", prog.name, not host,
            (f"host-boundary op(s) in a mesh program: {sorted(set(host))}"
             if host else "no infeed/outfeed/host callbacks")))

    # -- stale program-level declarations -------------------------------
    swept = {p.name for p in programs}
    reachable = {t.format(dp=d) for d in SHARDCHECK_DPS
                 for t in _NAME_TEMPLATES}
    for name in sorted(declared):
        if name in swept:
            continue
        if name in reachable and name not in {
                t.format(dp=d) for d in mesh_dps(dps)
                for t in _NAME_TEMPLATES}:
            continue   # environment-limited (not enough devices): not stale
        results.append(ContractResult(
            "collectives-as-declared", name, False,
            "stale declaration: no canonical mesh program by this name "
            "was swept (remove or rename the DECLARED_COLLECTIVES entry)"))
    return results, table
