"""Pass 1 — AST lints over the package source (no jax import, no tracing).

A rule is ``(id, severity, docstring, checker)`` registered in
:data:`RULES`; a checker takes a :class:`ModuleContext` and yields
:class:`~p2p_tpu.analysis.findings.Finding`. The repo-specific rules
encode the TPU/JAX invariants this codebase keeps re-learning in review:

``traced-branch``   Python ``if``/``while`` on traced data inside a
                    jit/scan body — tracing picks ONE side forever (or
                    raises a ConcretizationTypeError at trace time).
``host-sync``       ``.item()`` / ``np.asarray`` / ``float()`` on traced
                    values inside a jit/scan body — a device sync in the
                    hot path (or a tracer leak).
``impure-jit``      ``time.time()`` / Python ``random`` / ``np.random``
                    inside jitted code — baked in at trace time, silently
                    constant across calls.
``f64-literal``     ``jnp.float64`` dtypes — silent downcast under default
                    x64-disabled config, 2× memory + no TPU support when
                    someone flips x64 on.
``mutable-default`` mutable default arguments — one shared instance across
                    calls; in pytree dataclasses it also breaks structural
                    equality of compile keys.
``import-time-jax`` array-creating ``jnp``/``jax.random`` calls at module
                    scope — forces backend init (and possibly device
                    memory) on *import*, before the CLI can pick a
                    platform.
``unguarded-transfer`` implicit host↔device transfers in the serve
                    dispatch-path modules: ``np.asarray``/``np.array`` on
                    a value that didn't land via ``jax.device_get`` (a
                    hidden d2h sync), or ``jnp.asarray``/``jnp.array``
                    staging host data outside ``stage_host``/
                    ``jax.device_put`` (a hidden h2d). The lint-time twin
                    of the runtime ``jax.transfer_guard("disallow")``
                    dispatch tests.
``unregistered-journal-record`` a ``journal.append``/``journal.event``
                    call site whose kind literal is missing from the
                    write-time WAL registry (``serve/journal.py``
                    ``RECORD_KINDS``/``EVENT_KINDS``) — the lint-time
                    twin of the write-time ``ValueError`` and the
                    walcheck protocol sweep (docs/STATIC_ANALYSIS.md
                    pass 5).
``unused-import``   dead imports (mechanical; ``--fix`` removes them).
``shadowed-name``   a binding that silently rebinds an imported name (or a
                    parameter that shadows a module-level import).

Traced regions are found statically: functions decorated with ``jax.jit``
(including ``partial(jax.jit, ...)``), functions passed to
``lax.scan``/``while_loop``/``fori_loop``/``cond``/``switch``/``jax.vmap``
/``jax.grad``/``jax.checkpoint`` (by name, through ``partial`` too), and
every function nested inside one. This is a lint, not a proof: it
over-approximates (a helper called from a traced body but defined at
module level is missed) and relies on the narrow idioms this repo actually
uses — which is exactly what makes it cheap enough to run on every PR.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from .findings import Finding, apply_suppressions

# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

RULES: "Dict[str, Tuple[str, str]]" = {}     # id -> (severity, summary)
_CHECKERS: "List[Tuple[str, object]]" = []   # (id, checker)


def rule(rule_id: str, severity: str, summary: str):
    """Decorator registering a checker under ``rule_id``."""

    def register(fn):
        RULES[rule_id] = (severity, summary)
        _CHECKERS.append((rule_id, fn))
        return fn

    return register


# ---------------------------------------------------------------------------
# Module context: one parse, shared derived tables
# ---------------------------------------------------------------------------

_TRACE_CONSUMERS = {
    # call roots whose function-valued argument(s) get traced
    "scan", "while_loop", "fori_loop", "cond", "switch",
    "vmap", "grad", "value_and_grad", "checkpoint", "remat", "jit",
    "custom_vjp", "custom_jvp", "pmap", "shard_map",
}


def _dotted(node: ast.AST) -> str:
    """'jax.lax.scan' for Attribute/Name chains, '' otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_partial(call: ast.Call) -> bool:
    d = _dotted(call.func)
    return d in ("partial", "functools.partial")


def _fn_refs(node: ast.AST) -> Iterator[str]:
    """Names of functions referenced by a call argument: a bare Name, or
    the first argument of a ``partial(...)``."""
    if isinstance(node, ast.Name):
        yield node.id
    elif isinstance(node, ast.Call) and _is_partial(node) and node.args:
        yield from _fn_refs(node.args[0])


class ModuleContext:
    """One parsed module plus the derived tables every rule shares."""

    def __init__(self, source: str, path: str):
        self.source = source
        self.path = path
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.is_init = os.path.basename(path) == "__init__.py"
        # name -> import node (module-level only)
        self.imports: Dict[str, ast.stmt] = {}
        # names bound by `import x as x` / listed in __all__: re-exports
        self.reexports: Set[str] = set()
        self._collect_imports()
        self.traced_fns = self._find_traced_functions()

    # -- imports ----------------------------------------------------------

    def _collect_imports(self) -> None:
        for node in self.tree.body:
            stmts = [node]
            # TYPE_CHECKING imports still bind names used in annotations.
            if isinstance(node, ast.If) and _dotted(node.test).endswith(
                    "TYPE_CHECKING"):
                stmts = list(node.body)
            for stmt in stmts:
                if isinstance(stmt, ast.Import):
                    for a in stmt.names:
                        name = (a.asname or a.name).split(".")[0]
                        self.imports[name] = stmt
                        if a.asname and a.asname == a.name:
                            self.reexports.add(name)
                elif isinstance(stmt, ast.ImportFrom):
                    if stmt.module == "__future__":
                        continue
                    for a in stmt.names:
                        if a.name == "*":
                            continue
                        name = a.asname or a.name
                        self.imports[name] = stmt
                        if a.asname and a.asname == a.name:
                            self.reexports.add(name)
        for node in ast.walk(self.tree):
            if (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name) and t.id == "__all__"
                            for t in node.targets)):
                for elt in ast.walk(node.value):
                    if isinstance(elt, ast.Constant) and isinstance(
                            elt.value, str):
                        self.reexports.add(elt.value)

    # -- traced regions ---------------------------------------------------

    def _find_traced_functions(self) -> List[ast.AST]:
        """FunctionDefs (and Lambdas) statically known to be traced."""
        by_name: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                by_name.setdefault(node.name, []).append(node)

        traced: List[ast.AST] = []
        seen: Set[int] = set()

        def mark(fn: ast.AST) -> None:
            if id(fn) in seen:
                return
            seen.add(id(fn))
            traced.append(fn)

        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    names = set()
                    if isinstance(dec, ast.Call):
                        names.add(_dotted(dec.func).rsplit(".", 1)[-1])
                        for a in dec.args:
                            names.add(_dotted(a).rsplit(".", 1)[-1])
                    else:
                        names.add(_dotted(dec).rsplit(".", 1)[-1])
                    if names & _TRACE_CONSUMERS:
                        mark(node)
            elif isinstance(node, ast.Call):
                tail = _dotted(node.func).rsplit(".", 1)[-1]
                if tail in _TRACE_CONSUMERS:
                    for arg in list(node.args) + [k.value
                                                  for k in node.keywords]:
                        for ref in _fn_refs(arg):
                            for fn in by_name.get(ref, []):
                                mark(fn)
                        if isinstance(arg, ast.Lambda):
                            mark(arg)

        # Nested defs inside a traced function are traced too.
        frontier = list(traced)
        while frontier:
            fn = frontier.pop()
            for sub in ast.walk(fn):
                if sub is not fn and isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)) and id(sub) not in seen:
                    seen.add(id(sub))
                    traced.append(sub)
                    frontier.append(sub)
        return traced

    # -- helpers ----------------------------------------------------------

    def finding(self, rule_id: str, node: ast.AST, message: str) -> Finding:
        sev, _ = RULES[rule_id]
        line = getattr(node, "lineno", 1)
        text = self.lines[line - 1].strip() if line <= len(self.lines) else ""
        return Finding(rule=rule_id, severity=sev, path=self.path,
                       line=line, message=message, source_line=text)


def _param_tainted(fn: ast.AST) -> Set[str]:
    """Parameter names plus names assigned (directly) from param-derived
    expressions — a one-pass forward taint, good enough for scan bodies."""
    args = fn.args
    names = {a.arg for a in (args.posonlyargs + args.args + args.kwonlyargs)}
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    names.discard("self")
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for node in body:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign):
                used = {n.id for n in ast.walk(sub.value)
                        if isinstance(n, ast.Name)}
                if used & names:
                    for tgt in sub.targets:
                        for n in ast.walk(tgt):
                            if isinstance(n, ast.Name):
                                names.add(n.id)
    return names


_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding"}


def _static_expr(node: ast.AST) -> bool:
    """Expressions that are static facts even about traced arrays."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in _STATIC_ATTRS:
            return True
        if isinstance(sub, ast.Call):
            tail = _dotted(sub.func).rsplit(".", 1)[-1]
            if tail in ("isinstance", "len", "hasattr", "getattr", "type"):
                return True
    return False


def _tainted_data_leaf(node: ast.AST, tainted: Set[str]) -> bool:
    """A Name or Subscript rooted at a tainted name (a traced value or a
    piece of one) — excluding static-fact expressions."""
    if _static_expr(node):
        return False
    root = node
    while isinstance(root, (ast.Subscript, ast.Starred)):
        root = root.value
    return isinstance(root, ast.Name) and root.id in tainted


# ---------------------------------------------------------------------------
# Rules — traced-region hazards
# ---------------------------------------------------------------------------


@rule("traced-branch", "error",
      "Python branch on traced data inside a jit/scan body")
def _check_traced_branch(ctx: ModuleContext) -> Iterator[Finding]:
    for fn in ctx.traced_fns:
        tainted = _param_tainted(fn)
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if not isinstance(node, (ast.If, ast.While, ast.IfExp)):
                    continue
                test = node.test
                # Bare flags (`if capture:`) and None checks are the static
                # idioms jit code legitimately branches on.
                if isinstance(test, ast.Name) or (
                        isinstance(test, ast.UnaryOp)
                        and isinstance(test.op, ast.Not)
                        and isinstance(test.operand, ast.Name)):
                    continue
                if isinstance(test, ast.Constant):
                    continue
                if isinstance(test, ast.Compare) and any(
                        isinstance(c, ast.Constant) and c.value is None
                        for c in [test.left] + list(test.comparators)):
                    continue
                if _static_expr(test):
                    continue
                hot = [leaf for leaf in ast.walk(test)
                       if isinstance(leaf, (ast.Name, ast.Subscript))
                       and _tainted_data_leaf(leaf, tainted)]
                # Only comparisons/arithmetic over traced data are a trap;
                # a bare tainted name as the whole test was skipped above.
                if hot and isinstance(test, (ast.Compare, ast.BoolOp,
                                             ast.BinOp)):
                    kind = ("if" if isinstance(node, (ast.If, ast.IfExp))
                            else "while")
                    yield ctx.finding(
                        "traced-branch", node,
                        f"`{kind}` on traced value(s) "
                        f"{sorted({_leaf_name(h) for h in hot})} inside a "
                        "traced function: tracing freezes one side (use "
                        "lax.cond/jnp.where, or hoist to a static arg)")


def _leaf_name(node: ast.AST) -> str:
    while isinstance(node, (ast.Subscript, ast.Starred)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else "<expr>"


_HOST_SYNC_METHODS = {"item", "tolist", "to_py", "block_until_ready"}
_HOST_SYNC_CALLS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
_HOST_CASTS = {"float", "int", "bool", "complex"}


@rule("host-sync", "error",
      "host-synchronizing call on traced data inside a jit/scan body")
def _check_host_sync(ctx: ModuleContext) -> Iterator[Finding]:
    for fn in ctx.traced_fns:
        tainted = _param_tainted(fn)
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr in _HOST_SYNC_METHODS
                        and _tainted_data_leaf(node.func.value, tainted)):
                    yield ctx.finding(
                        "host-sync", node,
                        f".{node.func.attr}() on a traced value inside a "
                        "traced function: device sync / tracer leak")
                    continue
                d = _dotted(node.func)
                if (d in _HOST_SYNC_CALLS or d in _HOST_CASTS) and node.args \
                        and _tainted_data_leaf(node.args[0], tainted):
                    yield ctx.finding(
                        "host-sync", node,
                        f"{d}() on a traced value inside a traced function: "
                        "forces a host round-trip (keep it jnp, or move it "
                        "outside the jit)")


_IMPURE_PREFIXES = ("time.", "random.", "np.random.", "numpy.random.",
                    "datetime.", "os.urandom", "secrets.")
_IMPURE_EXEMPT = {"np.random.default_rng"}  # host-side Generator *handle*


@rule("impure-jit", "error",
      "wall-clock / unseeded randomness inside a jit/scan body")
def _check_impure(ctx: ModuleContext) -> Iterator[Finding]:
    for fn in ctx.traced_fns:
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                d = _dotted(node.func)
                if d in _IMPURE_EXEMPT:
                    continue
                if d.startswith(_IMPURE_PREFIXES):
                    yield ctx.finding(
                        "impure-jit", node,
                        f"{d}() inside a traced function: evaluated ONCE at "
                        "trace time and baked into the program (use "
                        "jax.random with an explicit key, or hoist to the "
                        "host)")


# ---------------------------------------------------------------------------
# Rules — dtype / structure hazards (whole module)
# ---------------------------------------------------------------------------


def _names_float64(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and node.value == "float64":
        return True
    d = _dotted(node)
    return d.endswith(".float64") or d == "float64"


@rule("f64-literal", "warning",
      "explicit float64 dtype in jnp code (promotion / x64 hazard)")
def _check_f64(ctx: ModuleContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Attribute):
            d = _dotted(node)
            if d in ("jnp.float64", "jax.numpy.float64"):
                yield ctx.finding(
                    "f64-literal", node,
                    "jnp.float64: silently f32 under default config, 2x "
                    "memory and unsupported on TPU under x64 (compute in "
                    "f32/bf16; do f64 accumulation host-side with numpy)")
        elif isinstance(node, ast.Call):
            d = _dotted(node.func)
            rooted_jnp = d.startswith(("jnp.", "jax.numpy.")) or \
                (isinstance(node.func, ast.Attribute)
                 and node.func.attr == "astype"
                 and not d.startswith(("np.", "numpy.")))
            if not rooted_jnp:
                continue
            vals = [k.value for k in node.keywords if k.arg == "dtype"]
            if node.func.attr == "astype" if isinstance(
                    node.func, ast.Attribute) else False:
                vals += list(node.args[:1])
            for v in vals:
                if _dotted(v) in ("jnp.float64", "jax.numpy.float64"):
                    continue  # already reported at the Attribute site above
                if _names_float64(v) and not _dotted(v).startswith(
                        ("np.", "numpy.")):
                    yield ctx.finding(
                        "f64-literal", node,
                        f"float64 dtype in `{d}(...)`: silent downcast "
                        "under default x64-off config; hazard if x64 is "
                        "ever enabled")


_MUTABLE_CALLS = {"list", "dict", "set", "bytearray", "defaultdict",
                  "OrderedDict", "collections.defaultdict",
                  "collections.OrderedDict"}


def _is_mutable_literal(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        return _dotted(node.func) in _MUTABLE_CALLS
    return False


@rule("mutable-default", "error",
      "mutable default argument (shared across calls; breaks pytree "
      "dataclass key equality)")
def _check_mutable_default(ctx: ModuleContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            defaults = list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None]
            for d in defaults:
                if _is_mutable_literal(d):
                    name = getattr(node, "name", "<lambda>")
                    yield ctx.finding(
                        "mutable-default", d,
                        f"mutable default in `{name}(...)`: one instance "
                        "is shared across every call (use None + create "
                        "inside, or dataclasses.field(default_factory=...))")
        elif isinstance(node, ast.ClassDef):
            decorated = any("dataclass" in _dotted(
                d.func if isinstance(d, ast.Call) else d)
                for d in node.decorator_list)
            if not decorated:
                continue
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and stmt.value is not None \
                        and _is_mutable_literal(stmt.value):
                    yield ctx.finding(
                        "mutable-default", stmt,
                        f"mutable default on dataclass field "
                        f"`{getattr(stmt.target, 'id', '?')}`: shared "
                        "across instances (use field(default_factory=...))")


_IMPORT_TIME_ROOTS = ("jnp.", "jax.numpy.", "jax.random.")
_IMPORT_TIME_CALLS = {"jax.devices", "jax.local_devices", "jax.device_put",
                      "jax.device_count", "jax.local_device_count"}


def _walk_eager(node: ast.AST):
    """ast.walk, but skipping the interiors of lambdas and nested function
    definitions — their bodies run at call time, not import time."""
    yield node
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.Lambda, ast.FunctionDef,
                              ast.AsyncFunctionDef)):
            continue
        yield from _walk_eager(child)


@rule("import-time-jax", "warning",
      "array-creating jnp/jax call at module import time")
def _check_import_time(ctx: ModuleContext) -> Iterator[Finding]:
    def scan(stmts) -> Iterator[Finding]:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                # Decorators run at import, bodies don't.
                nodes: List[ast.AST] = list(stmt.decorator_list)
                if isinstance(stmt, ast.ClassDef):
                    yield from scan(stmt.body)  # class attrs run at import
            elif isinstance(stmt, ast.If):
                yield from scan(stmt.body)
                yield from scan(stmt.orelse)
                continue
            else:
                nodes = [stmt]
            for top in nodes:
                for node in _walk_eager(top):
                    if not isinstance(node, ast.Call):
                        continue
                    d = _dotted(node.func)
                    if d.startswith(_IMPORT_TIME_ROOTS) or \
                            d in _IMPORT_TIME_CALLS:
                        yield ctx.finding(
                            "import-time-jax", node,
                            f"{d}() at module import time: initializes the "
                            "backend (and may allocate device memory) "
                            "before any CLI/platform choice runs — build "
                            "lazily inside a function")

    yield from scan(ctx.tree.body)


# ---------------------------------------------------------------------------
# Rules — serve dispatch-path transfer hygiene
# ---------------------------------------------------------------------------

#: Repo-relative modules on the serve dispatch path: code that runs inside
#: (or feeds) the engine's per-batch dispatch, which executes under
#: ``jax.transfer_guard("disallow")``. Every host↔device crossing here must
#: be explicit — ``stage_host``/``jax.device_put`` in, ``jax.device_get``
#: out — so the rule below fires on the implicit spellings. Input-prep
#: modules (``parallel/sweep.py`` stages via its own ``_stage_sharded``)
#: keep the runtime guard only: the lint covers the modules whose implicit
#: transfers the PR 9 guard test actually caught.
DISPATCH_PATH_MODULES = (
    "p2p_tpu/serve/programs.py",
    "p2p_tpu/serve/handoff.py",
    "p2p_tpu/serve/engine_loop.py",
)

_D2H_CALLS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
_H2D_CALLS = {"jnp.asarray", "jnp.array", "jax.numpy.asarray",
              "jax.numpy.array"}
_STAGING_CALLS = {"stage_host", "device_put", "device_get"}


def _is_dispatch_module(path: str) -> bool:
    return path.replace(os.sep, "/").endswith(DISPATCH_PATH_MODULES)


@rule("unguarded-transfer", "error",
      "implicit host<->device transfer in a serve dispatch-path module "
      "(bypasses stage_host / jax.device_get)")
def _check_unguarded_transfer(ctx: ModuleContext) -> Iterator[Finding]:
    if not _is_dispatch_module(ctx.path):
        return
    # Calls appearing as a *direct argument* of an explicit staging call
    # are the sanctioned idiom (`stage_host(np.asarray(ids))`) — collect
    # them first so they don't fire below.
    staged: Set[int] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and _dotted(node.func).rsplit(
                ".", 1)[-1] in _STAGING_CALLS:
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(arg, ast.Call):
                    staged.add(id(arg))
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or id(node) in staged:
            continue
        d = _dotted(node.func)
        if d in _D2H_CALLS:
            arg = node.args[0] if node.args else None
            if isinstance(arg, ast.Call) and _dotted(arg.func) in (
                    "jax.device_get", "device_get"):
                continue   # the explicit d2h landing, host-copied: fine
            yield ctx.finding(
                "unguarded-transfer", node,
                f"{d}() in a dispatch-path module: an implicit d2h sync "
                "on a device value (land results via jax.device_get; "
                "wrap host staging in stage_host)")
        elif d in _H2D_CALLS:
            yield ctx.finding(
                "unguarded-transfer", node,
                f"{d}() in a dispatch-path module: an implicit h2d "
                "transfer the dispatch transfer guard would reject "
                "(stage host values via stage_host / jax.device_put)")


def _journal_registries():
    """The write-time WAL registries, loaded from the real
    ``serve/journal.py`` by path (jax-free — ISSUE 20). Cached: the lint
    runs per module."""
    global _JOURNAL_REGS
    if _JOURNAL_REGS is None:
        from . import protocol

        jm = protocol.load_journal()
        _JOURNAL_REGS = (tuple(jm.RECORD_KINDS),
                         tuple(sorted(jm.EVENT_KINDS)))
    return _JOURNAL_REGS


_JOURNAL_REGS = None


def _is_journal_recv(node: ast.AST) -> bool:
    name = node.attr if isinstance(node, ast.Attribute) else (
        node.id if isinstance(node, ast.Name) else "")
    return name == "journal" or name.endswith("_journal")


@rule("unregistered-journal-record", "error",
      "journal append/event call site writes a kind literal missing from "
      "the WAL registry (serve/journal.py RECORD_KINDS / EVENT_KINDS)")
def _check_unregistered_journal_record(ctx: ModuleContext
                                       ) -> Iterator[Finding]:
    # The write-time raise catches these at runtime; the lint catches them
    # at review time, before any engine runs the path. Receiver must NAME
    # a journal (``journal`` / ``*_journal``) — ``flight.event(...)`` and
    # other event-shaped APIs never match. Non-literal kinds are skipped:
    # the runtime validation owns them.
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and _is_journal_recv(node.func.value)):
            continue
        record_kinds, event_kinds = _journal_registries()
        if node.func.attr == "event":
            arg = node.args[0] if node.args else None
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
                    and arg.value not in event_kinds:
                yield ctx.finding(
                    "unregistered-journal-record", node,
                    f"journal.event({arg.value!r}) is not a registered "
                    f"EVENT kind (registered: {', '.join(event_kinds)}) — "
                    f"register it in serve/journal.py EVENT_KINDS and "
                    f"declare it in analysis/protocol.DECLARED_EVENTS")
        elif node.func.attr in ("append", "_append"):
            arg = node.args[0] if node.args else None
            if not isinstance(arg, ast.Dict):
                continue
            for k, v in zip(arg.keys, arg.values):
                if (isinstance(k, ast.Constant) and k.value == "type"
                        and isinstance(v, ast.Constant)
                        and isinstance(v.value, str)
                        and v.value not in record_kinds):
                    yield ctx.finding(
                        "unregistered-journal-record", node,
                        f"journal append of record type {v.value!r} is "
                        f"not a registered RECORD kind (registered: "
                        f"{', '.join(record_kinds)}) — register it in "
                        f"serve/journal.py and declare it in "
                        f"analysis/protocol.DECLARED_PROTOCOL")


# ---------------------------------------------------------------------------
# Rules — mechanical hygiene
# ---------------------------------------------------------------------------

_WORD_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")


@rule("unused-import", "warning", "imported name never used (dead import)")
def _check_unused_import(ctx: ModuleContext) -> Iterator[Finding]:
    if ctx.is_init:
        return  # __init__ imports are the package's public re-export surface
    used: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            root = node
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name):
                used.add(root.id)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            # String annotations / docstring references — conservative: a
            # word match anywhere in a string counts as a use.
            used |= set(_WORD_RE.findall(node.value))
    for name, stmt in ctx.imports.items():
        if name in used or name in ctx.reexports or name.startswith("_"):
            continue
        line_text = ctx.lines[stmt.lineno - 1] if stmt.lineno <= len(
            ctx.lines) else ""
        if "noqa" in line_text:
            continue
        yield ctx.finding(
            "unused-import", stmt,
            f"`{name}` imported but never used")


@rule("shadowed-name", "warning",
      "binding shadows an imported name")
def _check_shadowed(ctx: ModuleContext) -> Iterator[Finding]:
    if not ctx.imports:
        return
    import_lines = {s.lineno for s in ctx.imports.values()}
    # Module-level rebinding of an import.
    for stmt in ctx.tree.body:
        targets: List[str] = []
        if isinstance(stmt, ast.Assign):
            # Direct Name targets only: `os.environ[k] = v` mutates through
            # the import, it does not rebind it.
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    targets.append(t.id)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    targets.extend(n.id for n in t.elts
                                   if isinstance(n, ast.Name))
        elif isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name) and stmt.value is not None:
            targets.append(stmt.target.id)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            targets.append(stmt.name)
        for name in targets:
            imp = ctx.imports.get(name)
            if imp is not None and stmt.lineno not in import_lines \
                    and stmt.lineno > imp.lineno:
                yield ctx.finding(
                    "shadowed-name", stmt,
                    f"`{name}` rebinds the import from line {imp.lineno}: "
                    "the import is dead past here (rename one of them)")
    # Function parameters shadowing a module-level import.
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = node.args
            for arg in a.posonlyargs + a.args + a.kwonlyargs + \
                    [x for x in (a.vararg, a.kwarg) if x]:
                if arg.arg in ctx.imports:
                    yield ctx.finding(
                        "shadowed-name", arg,
                        f"parameter `{arg.arg}` of `{node.name}` shadows "
                        f"the module-level import (line "
                        f"{ctx.imports[arg.arg].lineno})")


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------


def lint_source(source: str, path: str = "<string>",
                rules: Optional[Iterable[str]] = None) -> List[Finding]:
    """Run the AST pass over one module's source. ``rules`` narrows to a
    subset of rule ids (default: all). Suppressions are applied; baseline
    is the caller's job (it is repo-level state)."""
    try:
        ctx = ModuleContext(source, path)
    except SyntaxError as e:
        return [Finding(rule="parse-error", severity="error", path=path,
                        line=e.lineno or 1,
                        message=f"syntax error: {e.msg}")]
    wanted = set(rules) if rules is not None else None
    out: List[Finding] = []
    for rule_id, checker in _CHECKERS:
        if wanted is not None and rule_id not in wanted:
            continue
        out.extend(checker(ctx))
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    apply_suppressions(out, ctx.lines)
    return out


def lint_file(path: str, repo_root: Optional[str] = None,
              rules: Optional[Iterable[str]] = None) -> List[Finding]:
    with open(path) as f:
        source = f.read()
    rel = os.path.relpath(path, repo_root) if repo_root else path
    return lint_source(source, rel, rules=rules)


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        else:
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def lint_paths(paths: Iterable[str], repo_root: Optional[str] = None,
               rules: Optional[Iterable[str]] = None) -> List[Finding]:
    out: List[Finding] = []
    for path in iter_python_files(paths):
        out.extend(lint_file(path, repo_root=repo_root, rules=rules))
    return out
