"""Text walkers over lowered mesh programs — shardcheck's vocabulary.

The jaxpr walkers (:mod:`.jaxpr_walk`) see the program *before* XLA does;
this module reads what XLA actually emits, at two stages:

- **Lowered StableHLO** (``jitted.lower(...).as_text()``) — where sharding
  *intent* lives: ``stablehlo.custom_call @Sharding`` /
  ``@SPMDFullToShardShape`` / ``@SPMDShardToFullShape`` annotations (a
  ``with_sharding_constraint``, a ``shard_map`` boundary) and explicit
  host-boundary ops. A resharding custom call in a canonical dp program is
  someone *asking* for data movement the dp design promises not to need.
- **Compiled post-SPMD HLO** (``.compile().as_text()``) — where sharding
  *consequence* lives: after the GSPMD partitioner runs, every implicit
  reshard has become a real collective (``all-reduce`` / ``all-gather`` /
  ``all-to-all`` / ``collective-permute`` / ``collective-broadcast``) with
  a concrete dtype, shape and replica grouping. This is the ground truth
  the declared-collective contract (:mod:`.collectives`) checks against —
  the compile-time twin of the runtime ``jax.transfer_guard`` tests.

Everything here is string parsing over the textual HLO forms jax 0.4.x
emits — deliberately: no MLIR bindings, no XLA internals, and the parsed
shapes are cross-checked by seeded-violation tests
(tests/test_shardcheck.py) so a silent format drift breaks loudly.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Tuple

#: Collective op mnemonics as the post-partitioning HLO text spells them.
#: ``reduce-scatter`` matters even on a dp-only mesh: XLA rewrites an
#: all-reduce whose consumer is sharded into reduce-scatter, so omitting
#: it would blind the check to a whole class of partitioner-inserted
#: traffic. Async spellings (``all-gather-start``/``-done``) are folded
#: onto their sync kind — the ``-start`` op carries the traffic, the
#: ``-done`` is a wait and is skipped.
COLLECTIVE_KINDS = ("all-reduce", "reduce-scatter", "all-gather",
                    "all-to-all", "collective-permute",
                    "collective-broadcast")

#: HLO element-type byte widths (tuple/token types are handled structurally).
DTYPE_BYTES: Dict[str, int] = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
    "f8e4m3fn": 1, "f8e5m2": 1,
}

_TENSOR_TYPE_RE = re.compile(r"\b([a-z][a-z0-9]*)\[([0-9,]*)\]")
_COLLECTIVE_RE = re.compile(
    r"%\S+\s*=\s*(?P<type>[^=]*?)\s*"
    r"(?P<kind>" + "|".join(COLLECTIVE_KINDS) + r")"
    r"(?P<start>-start)?(?:\.\d+)?\(")
# replica_groups={{0,1},{2,3}} (explicit), replica_groups=[2,2]<=[4]
# (iota), or replica_groups={} (ONE group of all partitions — sized from
# the HloModule header's num_partitions). collective-permute carries
# source_target_pairs instead; any non-self pair means real traffic.
_GROUPS_EXPLICIT_RE = re.compile(r"replica_groups=\{\{([0-9,{} ]*)\}\}")
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]<=")
_GROUPS_EMPTY_RE = re.compile(r"replica_groups=\{\}")
_PAIRS_RE = re.compile(r"source_target_pairs=\{(\{[0-9,{} ]*\})\}")
_NUM_PARTITIONS_RE = re.compile(r"\bnum_partitions=(\d+)")
# Computation headers carry nested parens for tuple-typed params
# (`%body (p: (s32[], f32[])) -> ...`), so the param blob is matched
# greedily; the `) -> ... {` tail anchors the header shape.
_COMPUTATION_RE = re.compile(
    r"^(?:ENTRY\s+)?%?([\w.\-]+)\s*\(.*\)\s*->.*\{$")
# Callee references: single-name attrs (`calls=%f`, `body=%b`) and brace
# lists (`branch_computations={%b0, %b1}` — every member counts, or a
# collective in a later conditional branch would lose its per-step
# attribution).
_CALLED_ONE_RE = re.compile(r"(?:calls|to_apply|body|condition|"
                            r"true_computation|false_computation)=%?"
                            r"([\w.\-]+)")
_CALLED_LIST_RE = re.compile(r"branch_computations=\{([^}]*)\}")
_WHILE_BODY_RE = re.compile(r"\bwhile\(.*body=%?([\w.\-]+)")


@dataclasses.dataclass
class CollectiveOp:
    """One collective in a compiled (post-SPMD) HLO module."""

    kind: str                 # one of COLLECTIVE_KINDS
    dtype: str                # element type of the (first) payload tensor
    shape: Tuple[int, ...]    # payload tensor shape
    payload_bytes: int        # sum over all result tensors
    group_size: int           # devices per replica group (1 = degenerate)
    per_step: bool            # inside a while (scan) body → paid every step
    computation: str          # HLO computation holding the op
    line: str                 # the (trimmed) HLO line, for error messages

    @property
    def bytes_moved(self) -> int:
        return cost_bytes(self.kind, self.payload_bytes, self.group_size)

    def describe(self) -> str:
        where = "per-step" if self.per_step else "once"
        return (f"{self.kind} {self.dtype}{list(self.shape)} "
                f"group={self.group_size} ~{self.bytes_moved}B {where}")


def cost_bytes(kind: str, payload_bytes: int, group_size: int) -> int:
    """Bytes each participant moves over the interconnect for one op — the
    standard ring-algorithm counts, the budget unit the comms table (and
    the upcoming mp-axis PR) is denominated in:

    - ``all-gather`` / ``all-to-all``: ``(g-1)/g`` of the full payload
      (every shard but your own crosses the wire).
    - ``all-reduce``: ``2(g-1)/g`` (reduce-scatter + all-gather phases).
    - ``reduce-scatter``: ``(g-1)``× the payload — the HLO result type is
      the *shard*, and each participant sends every shard but its own.
    - ``collective-permute`` / ``collective-broadcast``: the full payload
      (one explicit hop).

    A degenerate group (``g == 1``) moves nothing — dp=1 programs cost 0
    by construction, which is what keeps the dp=1 leg a real (non-vacuous)
    baseline row rather than a skipped one.
    """
    if group_size <= 1:
        return 0
    frac = (group_size - 1) / group_size
    if kind == "all-reduce":
        return int(2 * frac * payload_bytes)
    if kind == "reduce-scatter":
        return (group_size - 1) * payload_bytes
    if kind in ("all-gather", "all-to-all"):
        return int(frac * payload_bytes)
    return payload_bytes


def _parse_types(type_text: str) -> List[Tuple[str, Tuple[int, ...]]]:
    """Tensor (dtype, shape) list from an HLO result-type string —
    ``f32[4,8]{1,0}`` or a tuple ``(f32[4], u32[])``. Layout suffixes and
    ``token[]`` pseudo-types are ignored."""
    out = []
    for dtype, dims in _TENSOR_TYPE_RE.findall(type_text):
        if dtype not in DTYPE_BYTES:
            continue   # token[], opaque[] — no payload
        shape = tuple(int(d) for d in dims.split(",") if d != "")
        out.append((dtype, shape))
    return out


def _numel(shape: Tuple[int, ...]) -> int:
    n = 1
    for d in shape:
        n *= d
    return n


def _group_size(line: str, num_partitions: int = 1) -> int:
    """Effective replica-group size for one collective line. Degenerate
    (size-1) groups price to 0 in :func:`cost_bytes`, so every spelling
    that means "real traffic" must resolve to > 1 here: an empty
    ``replica_groups={}`` is ONE group of all ``num_partitions`` devices,
    and a ``collective-permute`` has no groups at all — any pair whose
    source differs from its target moves the full payload."""
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        # [G,S]<=[N]: G groups of S devices each.
        return int(m.group(2))
    m = _GROUPS_EXPLICIT_RE.search(line)
    if m:
        first = m.group(1).split("}")[0]
        return len([t for t in first.split(",") if t.strip() != ""])
    if _GROUPS_EMPTY_RE.search(line):
        return max(num_partitions, 1)
    m = _PAIRS_RE.search(line)
    if m:
        pairs = re.findall(r"\{\s*(\d+)\s*,\s*(\d+)\s*\}", m.group(0))
        moving = any(a != b for a, b in pairs)
        return 2 if moving else 1
    return 1


def _computation_spans(hlo_text: str) -> List[Tuple[str, List[str]]]:
    """(computation name, its lines) for every computation in an HLO
    module, in file order. HLO text opens a computation with
    ``[ENTRY] %name (params) -> type {`` at top level."""
    spans: List[Tuple[str, List[str]]] = []
    current: Optional[str] = None
    for raw in hlo_text.splitlines():
        line = raw.strip()
        m = _COMPUTATION_RE.match(line)
        if m:
            current = m.group(1)
            spans.append((current, []))
            continue
        if current is not None:
            spans[-1][1].append(line)
            if line == "}":
                current = None
    return spans


def _per_step_computations(spans: List[Tuple[str, List[str]]]) -> set:
    """Names of computations executed once per while-loop (scan) iteration:
    every while body plus the transitive closure of computations it calls
    (fusions via ``calls=``, reducers via ``to_apply=``, nested control
    flow via ``body=``/``condition=``)."""
    called: Dict[str, set] = {}
    bodies: set = set()
    for name, lines in spans:
        refs = set()
        for line in lines:
            refs.update(_CALLED_ONE_RE.findall(line))
            for blob in _CALLED_LIST_RE.findall(line):
                refs.update(t.strip().lstrip("%")
                            for t in blob.split(",") if t.strip())
            wb = _WHILE_BODY_RE.search(line)
            if wb:
                bodies.add(wb.group(1))
        called[name] = refs
    per_step = set()
    frontier = list(bodies)
    while frontier:
        name = frontier.pop()
        if name in per_step:
            continue
        per_step.add(name)
        frontier.extend(called.get(name, ()))
    return per_step


def collective_ops(hlo_text: str) -> List[CollectiveOp]:
    """Every collective in a compiled HLO module, with its payload cost and
    whether it sits inside a scan (while) body."""
    spans = _computation_spans(hlo_text)
    per_step = _per_step_computations(spans)
    np_m = _NUM_PARTITIONS_RE.search(hlo_text[:2000])   # HloModule header
    num_partitions = int(np_m.group(1)) if np_m else 1
    ops: List[CollectiveOp] = []
    for comp_name, lines in spans:
        for line in lines:
            m = _COLLECTIVE_RE.search(line)
            if not m:
                continue
            types = _parse_types(m.group("type"))
            if m.group("start") and len(types) > 1:
                # Async form: the result tuple aliases operands and may
                # trail context words (permute-start's u32[] pair); the
                # transferred payload is the LARGEST element, not the
                # last or the sum.
                types = [max(types,
                             key=lambda t: DTYPE_BYTES[t[0]] * _numel(t[1]))]
            payload = sum(DTYPE_BYTES[dt] * _numel(sh) for dt, sh in types)
            dtype, shape = types[0] if types else ("?", ())
            ops.append(CollectiveOp(
                kind=m.group("kind"), dtype=dtype, shape=shape,
                payload_bytes=payload,
                group_size=_group_size(line, num_partitions),
                per_step=comp_name in per_step, computation=comp_name,
                line=line[:160]))
    return ops


def collective_signature(ops: List[CollectiveOp]) -> dict:
    """The per-program comms summary the report JSON carries: an op-kind
    multiset plus the bytes-per-step / bytes-once split of the ring-cost
    model — the budget the mp-axis work designs against."""
    kinds: Dict[str, int] = {}
    per_step = once = 0
    for op in ops:
        kinds[op.kind] = kinds.get(op.kind, 0) + 1
        if op.per_step:
            per_step += op.bytes_moved
        else:
            once += op.bytes_moved
    return {"ops": dict(sorted(kinds.items())),
            "bytes_per_step": per_step, "bytes_once": once}


# ---------------------------------------------------------------------------
# StableHLO-side detectors (pre-partitioning intent)
# ---------------------------------------------------------------------------

_SHARDING_CALL_RE = re.compile(
    r"stablehlo\.custom_call\s+@(Sharding|SPMDFullToShardShape|"
    r"SPMDShardToFullShape)\b([^\n]*)")
_MHLO_SHARDING_RE = re.compile(r'mhlo\.sharding\s*=\s*"([^"]*)"')
_RESULT_TENSOR_RE = re.compile(r"->\s*tensor<([^>]*)>")


@dataclasses.dataclass
class ShardingChange:
    """One sharding-changing custom call in lowered StableHLO."""

    target: str        # Sharding | SPMDFullToShardShape | SPMDShardToFullShape
    sharding: str      # the mhlo.sharding attribute ("" when absent)
    result_type: str   # e.g. "4x8x8x16xf32"

    def describe(self) -> str:
        return (f"@{self.target} -> tensor<{self.result_type}> "
                f"sharding={self.sharding or '?'}")

    @property
    def forces_replication(self) -> bool:
        """A mid-program constraint that replicates a value — the "silent
        full replication of a dp-sharded tensor" shape of the bug."""
        return "replicated" in self.sharding


def sharding_custom_calls(stablehlo_text: str) -> List[ShardingChange]:
    """All sharding-changing custom calls in a lowered StableHLO module.
    Input-argument shardings (``mhlo.sharding`` on the entry params) are
    NOT included: staging inputs under a NamedSharding is the declared
    dispatch contract, not a mid-program reshard."""
    out = []
    for m in _SHARDING_CALL_RE.finditer(stablehlo_text):
        rest = m.group(2)
        sh = _MHLO_SHARDING_RE.search(rest)
        res = _RESULT_TENSOR_RE.search(rest)
        out.append(ShardingChange(
            target=m.group(1),
            sharding=sh.group(1) if sh else "",
            result_type=res.group(1) if res else "?"))
    return out


# ---------------------------------------------------------------------------
# Host-boundary ops (either text form)
# ---------------------------------------------------------------------------

_HOST_HLO_RE = re.compile(
    r"\b(infeed|outfeed)(?:\.\d+)?\(|"
    r'custom-call[^\n]*custom_call_target="([^"]*callback[^"]*)"')
_HOST_SHLO_RE = re.compile(
    r"stablehlo\.(infeed|outfeed)\b|"
    r'stablehlo\.custom_call\s+@([\w.]*callback[\w.]*)')


def host_boundary_ops(text: str) -> List[str]:
    """Host-crossing ops in either a StableHLO or a compiled HLO module:
    infeed/outfeed and host-callback custom calls. Each entry names the op
    (and callback target when present)."""
    out = []
    for m in _HOST_HLO_RE.finditer(text):
        out.append(m.group(1) or f"custom-call:{m.group(2)}")
    for m in _HOST_SHLO_RE.finditer(text):
        out.append(m.group(1) or f"custom_call:@{m.group(2)}")
    return out
