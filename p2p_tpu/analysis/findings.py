"""Finding model shared by both analysis passes, plus the two mechanisms
that keep the linter adoptable on a codebase with history:

- **Inline suppressions** — ``# jaxcheck: disable=<rule>[,<rule>...]`` on the
  flagged line or on the line directly above it. The code-review contract:
  an *intentional* pattern gets an inline disable (next to a reason), so the
  exemption lives where the code lives and travels with it in diffs.
- **A committed baseline** — a JSON file of fingerprints for pre-existing
  findings. Findings matching the baseline are reported but don't fail the
  gate; anything *new* does. Fingerprints are ``(rule, path, source line
  text)`` — deliberately line-number-free, so unrelated edits above a
  baselined finding don't resurrect it.

The repo ships an **empty** baseline (tools/jaxcheck_baseline.json): every
pre-existing finding was either fixed or inline-disabled with a reason when
the analyzer landed. The baseline mechanism exists for future rule
*additions*, where fixing the whole backlog in the rule-introducing PR may
not be reasonable.
"""

from __future__ import annotations

import dataclasses
import io
import json
import re
import tokenize
from typing import Dict, List, Optional, Tuple

#: Severities, in gate order. "error" findings are correctness hazards
#: (host sync in a hot scan); "warning" findings are hygiene (dead import).
#: Both fail the gate when new — severity is for human triage, not for
#: deciding what CI ignores.
SEVERITIES = ("error", "warning")

# The rule list is comma-separated rule tokens; the match stops at the
# first non-token text so a trailing reason ("... disable=host-sync --
# intentional: X") never swallows into the rule list.
_SUPPRESS_RE = re.compile(
    r"#\s*jaxcheck\s*:\s*disable\s*=\s*"
    r"([A-Za-z0-9_-]+(?:\s*,\s*[A-Za-z0-9_-]+)*)")

#: The canonical spelling `fixes.normalize_suppressions` rewrites to.
SUPPRESS_CANONICAL = "# jaxcheck: disable="


@dataclasses.dataclass
class Finding:
    """One analyzer hit. ``path`` is repo-relative wherever possible (the
    fingerprint must be stable across checkouts)."""

    rule: str
    severity: str
    path: str
    line: int            # 1-based
    message: str
    source_line: str = ""  # stripped text of the flagged line
    suppressed: bool = False
    baselined: bool = False

    @property
    def fingerprint(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.source_line)

    @property
    def is_new(self) -> bool:
        return not (self.suppressed or self.baselined)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def format(self) -> str:
        state = ("" if self.is_new
                 else (" [suppressed]" if self.suppressed else " [baseline]"))
        return (f"{self.path}:{self.line}: {self.severity} "
                f"[{self.rule}] {self.message}{state}")


def comment_columns(source_lines: List[str]) -> Dict[int, int]:
    """1-based line number -> column where that line's comment starts,
    tokenize-accurate: a ``#`` inside a string literal is NOT a comment, so
    directive-looking text in docstrings/strings can never suppress (or be
    rewritten by ``--fix``)."""
    cols: Dict[int, int] = {}
    src = "\n".join(source_lines) + "\n"
    try:
        for tok in tokenize.generate_tokens(io.StringIO(src).readline):
            if tok.type == tokenize.COMMENT:
                cols[tok.start[0]] = tok.start[1]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass  # partial/odd sources: keep whatever tokenized cleanly
    return cols


def suppressed_rules(source_lines: List[str], line: int,
                     cols: Optional[Dict[int, int]] = None) -> set:
    """Rules disabled for 1-based ``line``: a trailing ``# jaxcheck:
    disable=`` comment on the line itself, or a comment on the line
    directly above (the whole-line form, for when the flagged line has no
    room). Returns the union. ``cols`` is a precomputed
    :func:`comment_columns` table (recomputed here when absent)."""
    if cols is None:
        cols = comment_columns(source_lines)
    rules: set = set()
    for idx in (line - 1, line - 2):  # 0-based: the line, then the one above
        if not 0 <= idx < len(source_lines):
            continue
        col = cols.get(idx + 1)
        if col is None:
            continue  # no real comment on this line
        if idx == line - 2 and source_lines[idx][:col].strip():
            continue  # the above-line form must be a standalone comment
        m = _SUPPRESS_RE.search(source_lines[idx], col)
        if m:
            rules |= {r.strip() for r in m.group(1).split(",") if r.strip()}
    return rules


def apply_suppressions(findings: List[Finding],
                       source_lines: List[str]) -> None:
    """Mark findings whose line (or the line above) carries a matching
    inline disable."""
    cols = comment_columns(source_lines)
    for f in findings:
        if f.rule in suppressed_rules(source_lines, f.line, cols):
            f.suppressed = True


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------


def load_baseline(path: str) -> List[dict]:
    """Read a baseline file → list of fingerprint dicts (missing file =
    empty baseline: everything is new)."""
    import os

    if not os.path.exists(path):
        return []
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "findings" not in doc:
        raise ValueError(f"baseline {path}: expected "
                         '{"version": 1, "findings": [...]}')
    return list(doc["findings"])


def save_baseline(path: str, findings: List[Finding]) -> None:
    """Write the current *new* findings as the baseline (``--update-
    baseline``). Suppressed findings are excluded — an inline disable is
    already a durable exemption; baselining it too would hide a later
    removal of the comment."""
    entries = [{"rule": f.rule, "path": f.path, "code": f.source_line}
               for f in findings if not f.suppressed]
    entries.sort(key=lambda e: (e["path"], e["rule"], e["code"]))
    with open(path, "w") as fh:
        json.dump({"version": 1, "findings": entries}, fh, indent=1)
        fh.write("\n")


def apply_baseline(findings: List[Finding], baseline: List[dict]) -> None:
    """Mark findings matching a baseline fingerprint. Matching consumes
    entries (a multiset match): two identical offending lines need two
    baseline entries, so deleting one of them surfaces the other as
    still-baselined, not new."""
    pool: Dict[Tuple[str, str, str], int] = {}
    for e in baseline:
        key = (e.get("rule", ""), e.get("path", ""), e.get("code", ""))
        pool[key] = pool.get(key, 0) + 1
    for f in findings:
        if f.suppressed:
            continue
        n = pool.get(f.fingerprint, 0)
        if n > 0:
            pool[f.fingerprint] = n - 1
            f.baselined = True


def summarize(findings: List[Finding]) -> dict:
    new = [f for f in findings if f.is_new]
    return {
        "total": len(findings),
        "new": len(new),
        "suppressed": sum(1 for f in findings if f.suppressed),
        "baselined": sum(1 for f in findings if f.baselined),
        "by_rule": _count_by(new, "rule"),
        "by_severity": _count_by(new, "severity"),
    }


def _count_by(findings: List[Finding], attr: str) -> dict:
    out: Dict[str, int] = {}
    for f in findings:
        key = getattr(f, attr)
        out[key] = out.get(key, 0) + 1
    return dict(sorted(out.items()))
