"""Diffusion schedulers as pure JAX — scan-friendly, stateless where possible.

Three deterministic samplers — two covering the reference's paths, one going
beyond it:

- **DDIM** (η=0) — the null-text path's scheduler
  (`/root/reference/null_text.py:16-20`), whose closed-form ``prev_step`` /
  ``next_step`` updates (`/root/reference/null_text.py:471-489`) are the
  numeric spec here, including ``set_alpha_to_one=False`` semantics (the
  final step uses ``alphas_cumprod[0]``, not 1).
- **PLMS** (PNDM with ``skip_prk_steps``) — the scheduler the reference CLI
  inherits from the SD pipeline (`/root/reference/main.py:29` keeps the
  pipeline default; noted at SURVEY §2.14). Implemented from the published
  pseudo-linear-multistep method (Liu et al., arXiv 2202.09778): an
  Adams–Bashforth combination over a ring buffer of the last 4 ε-predictions,
  carried explicitly through the scan instead of Python-side lists/counters.
- **DPM-Solver++(2M)** (not in the reference) — a second-order multistep ODE
  solver reaching ~50-step-DDIM quality in ~20-25 steps: the cheapest 2×
  throughput available, since it changes only the integrator, not the model.

All share a :class:`DiffusionSchedule` of precomputed constants; per-step
updates index it with the traced timestep, so one compiled program serves any
step count with the same shapes.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct


def make_betas(
    num_train_timesteps: int = 1000,
    beta_start: float = 0.00085,
    beta_end: float = 0.012,
    schedule: str = "scaled_linear",
) -> np.ndarray:
    """The SD-1.x β schedule (defaults from `/root/reference/null_text.py:16-18`)."""
    if schedule == "scaled_linear":
        return np.linspace(beta_start ** 0.5, beta_end ** 0.5, num_train_timesteps,
                           dtype=np.float64) ** 2
    if schedule == "linear":
        return np.linspace(beta_start, beta_end, num_train_timesteps, dtype=np.float64)
    raise ValueError(f"unknown beta schedule: {schedule!r}")


@struct.dataclass
class DiffusionSchedule:
    """Precomputed constants shared by all samplers.

    ``timesteps`` descend (sampling order). ``final_alpha_cumprod`` encodes
    ``set_alpha_to_one``: for SD it is ``alphas_cumprod[0]``
    (`/root/reference/null_text.py:20` sets ``set_alpha_to_one=False``).
    """

    alphas_cumprod: jax.Array            # (num_train,)
    timesteps: jax.Array                 # (num_sampling_iters,) int32, descending
    final_alpha_cumprod: jax.Array       # scalar
    num_train_timesteps: int = struct.field(pytree_node=False, default=1000)
    num_inference_steps: int = struct.field(pytree_node=False, default=50)
    # Diffusers `clip_sample`: clamp pred_x0 to [-1, 1] inside the DDIM update.
    # False for both reference-relevant configs (SD DDIM sets it explicitly,
    # `/root/reference/null_text.py:19`); static so it costs nothing when off.
    clip_sample: bool = struct.field(pytree_node=False, default=False)
    # What the model predicts: 'epsilon' (SD-1.x, the reference's only mode)
    # or 'v_prediction' (SD-2.1 768-v). Static; converted to ε once per step.
    prediction_type: str = struct.field(pytree_node=False, default="epsilon")

    @property
    def step_size(self) -> int:
        return self.num_train_timesteps // self.num_inference_steps


def make_schedule(
    num_inference_steps: int,
    num_train_timesteps: int = 1000,
    beta_start: float = 0.00085,
    beta_end: float = 0.012,
    schedule: str = "scaled_linear",
    set_alpha_to_one: bool = False,
    steps_offset: int = 0,
    kind: str = "ddim",
    clip_sample: bool = False,
    prediction_type: str = "epsilon",
    dtype=jnp.float32,
) -> DiffusionSchedule:
    """Build a :class:`DiffusionSchedule`.

    ``kind='ddim'`` / ``'dpm'``: T timesteps ``[(T-1)·s, ..., 0] + offset``.
    ``kind='plms'``: T+1 timesteps with the second one repeated — the
    warm-up double-evaluation of the first step that PLMS needs to build its
    multistep history (so a 50-step PLMS run makes 51 U-Net calls, matching
    the reference pipeline's loop over ``scheduler.timesteps``).
    """
    betas = make_betas(num_train_timesteps, beta_start, beta_end, schedule)
    acp = np.cumprod(1.0 - betas)
    step = num_train_timesteps // num_inference_steps
    base = (np.arange(num_inference_steps) * step).round().astype(np.int64) + steps_offset
    if kind in ("ddim", "dpm"):
        ts = base[::-1].copy()
    elif kind == "plms":
        ts = np.concatenate([base[:-1], base[-2:-1], base[-1:]])[::-1].copy()
    else:
        raise ValueError(f"unknown schedule kind: {kind!r}")
    final = acp[0] if not set_alpha_to_one else 1.0
    return DiffusionSchedule(
        alphas_cumprod=jnp.asarray(acp, dtype=dtype),
        timesteps=jnp.asarray(ts, dtype=jnp.int32),
        final_alpha_cumprod=jnp.asarray(final, dtype=dtype),
        num_train_timesteps=num_train_timesteps,
        num_inference_steps=num_inference_steps,
        clip_sample=clip_sample,
        prediction_type=prediction_type,
    )


@functools.lru_cache(maxsize=64)
def schedule_from_config(num_inference_steps: int, sched_cfg, kind: Optional[str] = None,
                         dtype=jnp.float32) -> DiffusionSchedule:
    """Build the schedule a backend's :class:`SchedulerConfig` describes,
    optionally overriding the sampler kind (the reference uses PNDM for the
    CLI path and DDIM for null-text on the same SD backend).

    Cached per ``(steps, config, kind, dtype)``: the schedule is a pure
    function of its arguments, and rebuilding it per call re-transferred the
    (num_train,) constant tables host→device on *every* serve batch — the
    hot-path transfer the ``jax.transfer_guard("disallow")`` test pins away
    (the schedule is immutable — a frozen struct.dataclass of arrays — so
    sharing one instance across callers is safe)."""
    kind = kind or sched_cfg.kind
    return make_schedule(
        num_inference_steps,
        num_train_timesteps=sched_cfg.num_train_timesteps,
        beta_start=sched_cfg.beta_start,
        beta_end=sched_cfg.beta_end,
        schedule=sched_cfg.beta_schedule,
        set_alpha_to_one=sched_cfg.set_alpha_to_one,
        steps_offset=sched_cfg.steps_offset(kind),
        kind=kind,
        clip_sample=sched_cfg.clip_sample,
        prediction_type=sched_cfg.prediction_type,
        dtype=dtype,
    )


def to_epsilon(sched: DiffusionSchedule, model_out: jax.Array, t: jax.Array,
               sample: jax.Array) -> jax.Array:
    """Convert the model output to an ε-prediction under the schedule's
    ``prediction_type``. v-parameterization (Salimans & Ho, arXiv 2202.00512):
    v = α·ε − σ·x₀  ⇒  ε = α·v + σ·x_t (with α=√ā, σ=√(1−ā))."""
    if sched.prediction_type == "epsilon":
        return model_out
    if sched.prediction_type == "v_prediction":
        a_t = _alpha_at(sched, t)
        alpha, sigma = jnp.sqrt(a_t), jnp.sqrt(1.0 - a_t)
        return (alpha * model_out.astype(jnp.float32)
                + sigma * sample.astype(jnp.float32)).astype(model_out.dtype)
    raise ValueError(f"unknown prediction_type: {sched.prediction_type!r}")


def _alpha_at(sched: DiffusionSchedule, t: jax.Array) -> jax.Array:
    """``alphas_cumprod[t]`` with t<0 mapping to ``final_alpha_cumprod``
    (`/root/reference/null_text.py:474`)."""
    safe_t = jnp.clip(t, 0, sched.num_train_timesteps - 1)
    return jnp.where(t >= 0, sched.alphas_cumprod[safe_t], sched.final_alpha_cumprod)


# ---------------------------------------------------------------------------
# DDIM (η = 0)
# ---------------------------------------------------------------------------


def ddim_step(
    sched: DiffusionSchedule, eps: jax.Array, t: jax.Array, sample: jax.Array
) -> jax.Array:
    """One deterministic DDIM denoising step x_t → x_{t-Δ}
    (`/root/reference/null_text.py:471-479`)."""
    prev_t = t - sched.step_size
    a_t = _alpha_at(sched, t)
    a_prev = _alpha_at(sched, prev_t)
    x = sample.astype(jnp.float32)
    e = eps.astype(jnp.float32)
    pred_x0 = (x - jnp.sqrt(1.0 - a_t) * e) / jnp.sqrt(a_t)
    if sched.clip_sample:
        # diffusers 0.8.1 semantics (the reference's pin): clamp pred_x0 but
        # keep the raw ε in the direction term — no ε recompute.
        pred_x0 = jnp.clip(pred_x0, -1.0, 1.0)
    direction = jnp.sqrt(1.0 - a_prev) * e
    # Step math in f32 regardless of compute dtype (the constants span 4
    # orders of magnitude); carry dtype is preserved for the scan.
    return (jnp.sqrt(a_prev) * pred_x0 + direction).astype(sample.dtype)


def ddim_next_step(
    sched: DiffusionSchedule, eps: jax.Array, t: jax.Array, sample: jax.Array
) -> jax.Array:
    """One DDIM *inversion* step x_t → x_{t+Δ} — the forward closed-form
    ascent used by null-text inversion (`/root/reference/null_text.py:481-489`)."""
    cur_t = jnp.minimum(t - sched.step_size, sched.num_train_timesteps - 1)
    next_t = t
    a_t = _alpha_at(sched, cur_t)
    a_next = _alpha_at(sched, next_t)
    x = sample.astype(jnp.float32)
    e = eps.astype(jnp.float32)
    pred_x0 = (x - jnp.sqrt(1.0 - a_t) * e) / jnp.sqrt(a_t)
    direction = jnp.sqrt(1.0 - a_next) * e
    return (jnp.sqrt(a_next) * pred_x0 + direction).astype(sample.dtype)


# ---------------------------------------------------------------------------
# PLMS (pseudo linear multistep; PNDM with prk steps skipped)
# ---------------------------------------------------------------------------


@struct.dataclass
class PlmsState:
    """Scan-carried multistep history: ring buffer of the last 4 ε's, the
    evaluation counter, and the saved sample for the warm-up double-step."""

    ets: jax.Array        # (4, *sample_shape) — newest at index 0
    counter: jax.Array    # int32 scalar
    cur_sample: jax.Array  # sample saved at counter==0


def init_plms_state(sample_shape: Tuple[int, ...], dtype=jnp.float32) -> PlmsState:
    return PlmsState(
        ets=jnp.zeros((4,) + tuple(sample_shape), dtype=dtype),
        counter=jnp.int32(0),
        cur_sample=jnp.zeros(sample_shape, dtype=dtype),
    )


def _plms_prev_sample(sched, sample, t, prev_t, eps):
    """The PNDM transfer formula φ(x, t, t-Δ, ε) (Liu et al., eq. 11)."""
    a_t = _alpha_at(sched, t)
    a_prev = _alpha_at(sched, prev_t)
    b_t = 1.0 - a_t
    b_prev = 1.0 - a_prev
    sample_coeff = jnp.sqrt(a_prev / a_t)
    denom = a_t * jnp.sqrt(b_prev) + jnp.sqrt(a_t * b_t * a_prev)
    out = (sample_coeff * sample.astype(jnp.float32)
           - (a_prev - a_t) * eps.astype(jnp.float32) / denom)
    return out.astype(sample.dtype)


def plms_step(
    sched: DiffusionSchedule,
    state: PlmsState,
    eps: jax.Array,
    t: jax.Array,
    sample: jax.Array,
) -> Tuple[PlmsState, jax.Array]:
    """One PLMS step, branch-free over the warm-up phases.

    Evaluation counter c selects the ε combination (Adams–Bashforth orders
    1→4): c=0 raw ε (and the sample is saved for the re-evaluation), c=1
    average with the stored ε stepping from the *same* timestep, c=2/3/≥4
    the 2nd/3rd/4th-order combinations. History updates only when c≠1.
    """
    c = state.counter
    e1, e2, e3, e4 = state.ets[0], state.ets[1], state.ets[2], state.ets[3]

    # Timestep bookkeeping: at c==1 we re-evaluate the first step, stepping
    # from t+Δ to t+Δ-Δ = t's original position.
    prev_t = jnp.where(c == 1, t, t - sched.step_size)
    t_eff = jnp.where(c == 1, t + sched.step_size, t)

    # ε history push (skipped at c==1).
    new_ets = jnp.where(
        c == 1,
        state.ets,
        jnp.stack([eps, e1, e2, e3]),
    )
    ne1, ne2, ne3, ne4 = new_ets[0], new_ets[1], new_ets[2], new_ets[3]

    order = jnp.minimum(c, 4)
    eps_used = jax.lax.switch(
        order,
        [
            lambda: ne1,                                   # c=0: raw ε (just pushed)
            lambda: (eps + e1) / 2.0,                      # c=1: avg with stored ε
            lambda: (3.0 * ne1 - ne2) / 2.0,               # c=2
            lambda: (23.0 * ne1 - 16.0 * ne2 + 5.0 * ne3) / 12.0,   # c=3
            lambda: (55.0 * ne1 - 59.0 * ne2 + 37.0 * ne3 - 9.0 * ne4) / 24.0,
        ],
    )
    sample_used = jnp.where(c == 1, state.cur_sample, sample)
    new_cur = jnp.where(c == 0, sample, state.cur_sample)

    prev_sample = _plms_prev_sample(sched, sample_used, t_eff, prev_t, eps_used)
    return (
        PlmsState(ets=new_ets, counter=c + 1, cur_sample=new_cur),
        prev_sample,
    )


def init_multistep_state(kind: str, sample_shape: Tuple[int, ...],
                         dtype=jnp.float32):
    """The scan-carried multistep state for scheduler ``kind`` (None for the
    single-step DDIM). One constructor so phase-gated sampling initializes it
    once and hands the SAME carry across the phase boundary: the PLMS ε ring
    buffer / DPM x0 history holds CFG-combined ε-space values, which phase 2's
    extrapolated-guidance ε continues seamlessly — re-initializing at the gate
    would re-enter the low-order warm-up mid-trajectory and visibly kink the
    integration."""
    if kind == "plms":
        return init_plms_state(sample_shape, dtype)
    if kind == "dpm":
        return init_dpm_state(sample_shape, dtype)
    if kind == "ddim":
        return None
    raise ValueError(f"unknown scheduler kind: {kind!r}")


# ---------------------------------------------------------------------------
# DPM-Solver++(2M) — beyond the reference: a second-order multistep solver
# (Lu et al., arXiv 2211.01095) that reaches 50-step-DDIM quality in ~20-25
# steps, i.e. ~2× throughput at matched quality. Deterministic,
# data-prediction parameterization, scan-carried multistep state.
# ---------------------------------------------------------------------------


@struct.dataclass
class DpmState:
    """Scan-carried DPM-Solver++ history: previous x0 prediction, its
    log-SNR λ, and whether a previous step exists (order ramps 1→2)."""

    x0_prev: jax.Array
    lam_prev: jax.Array   # f32 scalar
    has_prev: jax.Array   # bool scalar


def init_dpm_state(sample_shape: Tuple[int, ...], dtype=jnp.float32) -> DpmState:
    return DpmState(
        x0_prev=jnp.zeros(sample_shape, dtype=dtype),
        lam_prev=jnp.float32(0.0),
        has_prev=jnp.asarray(False),
    )


def dpm_step(
    sched: DiffusionSchedule,
    state: DpmState,
    eps: jax.Array,
    t: jax.Array,
    sample: jax.Array,
) -> Tuple[DpmState, jax.Array]:
    """One DPM-Solver++(2M) step x_t → x_{t-Δ}.

    Data-prediction form: with α=√ā, σ=√(1−ā), λ=log(α/σ), h=λ_next−λ_t,
        x_next = (σ_next/σ_t)·x − α_next·(e^{−h}−1)·D,
    where D is x0 (first step / final step) or the second-order extrapolation
    (1+1/2r)·x0 − 1/(2r)·x0_prev with r = h_prev/h. The final step (t−Δ < 0)
    drops to first order (diffusers' ``lower_order_final``). Note: under
    set_alpha_to_one=True the final step has σ_next=0 ⇒ h=+inf; the update is
    still exact (expm1(-inf)=-1, σ-ratio term 0 ⇒ x_next = x0) but relies on
    IEEE inf semantics — don't replace expm1 with a series expansion or add
    h-magnitude guards without covering that case."""
    prev_t = t - sched.step_size
    a_t = _alpha_at(sched, t)
    a_next = _alpha_at(sched, prev_t)

    x = sample.astype(jnp.float32)
    e = eps.astype(jnp.float32)
    alpha_t, sigma_t = jnp.sqrt(a_t), jnp.sqrt(1.0 - a_t)
    alpha_n, sigma_n = jnp.sqrt(a_next), jnp.sqrt(1.0 - a_next)
    lam_t = jnp.log(alpha_t / sigma_t)
    lam_n = jnp.log(alpha_n / sigma_n)
    h = lam_n - lam_t

    x0 = (x - sigma_t * e) / alpha_t
    if sched.clip_sample:
        x0 = jnp.clip(x0, -1.0, 1.0)

    h_prev = lam_t - state.lam_prev
    r = h_prev / h
    d2 = (1.0 + 1.0 / (2.0 * r)) * x0 - (1.0 / (2.0 * r)) * state.x0_prev.astype(jnp.float32)
    use_second = jnp.logical_and(state.has_prev, prev_t >= 0)
    d = jnp.where(use_second, d2, x0)

    x_next = (sigma_n / sigma_t) * x - alpha_n * jnp.expm1(-h) * d
    new_state = DpmState(
        x0_prev=x0.astype(state.x0_prev.dtype),
        lam_prev=lam_t.astype(jnp.float32),
        has_prev=jnp.asarray(True),
    )
    return new_state, x_next.astype(sample.dtype)


# ---------------------------------------------------------------------------
# DDPM (ancestral) — completes the family; useful for training-time sampling
# ---------------------------------------------------------------------------


def ddpm_step(
    sched: DiffusionSchedule,
    eps: jax.Array,
    t: jax.Array,
    sample: jax.Array,
    rng: jax.Array,
) -> jax.Array:
    """One ancestral DDPM step with the ``fixed_small`` posterior variance."""
    prev_t = t - sched.step_size
    a_t = _alpha_at(sched, t)
    a_prev = _alpha_at(sched, prev_t)
    alpha_ratio = a_t / a_prev
    beta_t = 1.0 - alpha_ratio
    x = sample.astype(jnp.float32)
    e = eps.astype(jnp.float32)
    pred_x0 = (x - jnp.sqrt(1.0 - a_t) * e) / jnp.sqrt(a_t)
    x0_coeff = jnp.sqrt(a_prev) * beta_t / (1.0 - a_t)
    xt_coeff = jnp.sqrt(alpha_ratio) * (1.0 - a_prev) / (1.0 - a_t)
    mean = x0_coeff * pred_x0 + xt_coeff * x
    var = beta_t * (1.0 - a_prev) / (1.0 - a_t)
    noise = jax.random.normal(rng, sample.shape, dtype=jnp.float32)
    out = jnp.where(prev_t >= 0, mean + jnp.sqrt(jnp.maximum(var, 0.0)) * noise, mean)
    return out.astype(sample.dtype)


def add_noise(
    sched: DiffusionSchedule, x0: jax.Array, noise: jax.Array, t: jax.Array
) -> jax.Array:
    """Forward q(x_t | x_0) sample — the training-time corruption."""
    a_t = _alpha_at(sched, t)
    while a_t.ndim < x0.ndim:
        a_t = a_t[..., None]
    return jnp.sqrt(a_t) * x0 + jnp.sqrt(1.0 - a_t) * noise
