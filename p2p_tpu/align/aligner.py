"""Host-side prompt-alignment precompute (runs once per edit, in numpy).

Builds the token-mapping tensors that parameterize the cross-attention edits:

- **Replacement mapper** — a dense ``(L, L)`` matrix per edit prompt that
  projects the source prompt's attention columns onto the edit prompt's token
  grid (behavioral spec: `/root/reference/seq_aligner.py:152-195`; consumed by
  the einsum at `/root/reference/main.py:218`).
- **Refinement mapper** — an integer gather (edit-token → source-token index)
  plus a 0/1 ``alphas`` vector marking which edit tokens existed in the
  source, produced by Needleman–Wunsch global alignment over token ids
  (spec: `/root/reference/seq_aligner.py:61-128`).

These run on host exactly once per controller construction — O(77²) — so
there is nothing to accelerate; the TPU-side hot path consumes the resulting
fixed-shape arrays.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from ..utils.tokenizer import Tokenizer
from .words import get_word_inds

GAP, MATCH, MISMATCH = 0, 1, -1  # `/root/reference/seq_aligner.py:110`


def needleman_wunsch(x: Sequence[int], y: Sequence[int],
                     gap: int = GAP, match: int = MATCH, mismatch: int = MISMATCH
                     ) -> List[Tuple[int, int]]:
    """Global alignment of two id sequences; returns ``(y_pos, x_pos)`` pairs
    for every position of ``y``, with ``x_pos = -1`` where ``y`` inserted a
    token absent from ``x``.

    Tie-breaking matches the reference exactly (left-gap preferred over
    up-gap over diagonal when scores tie — `/root/reference/seq_aligner.py:70-75`),
    which matters for reproducing its mappers bit-for-bit. Implemented as a
    vectorized-row DP (numpy) rather than the reference's per-cell Python loop.
    """
    nx, ny = len(x), len(y)
    xa = np.asarray(x)
    ya = np.asarray(y)
    score = np.zeros((nx + 1, ny + 1), dtype=np.int32)
    score[0, 1:] = np.arange(1, ny + 1) * gap
    score[1:, 0] = np.arange(1, nx + 1) * gap
    # traceback codes: 1=left (gap in x), 2=up (gap in y), 3=diag, 4=origin
    trace = np.zeros((nx + 1, ny + 1), dtype=np.int8)
    trace[0, 1:] = 1
    trace[1:, 0] = 2
    trace[0, 0] = 4

    sub = np.where(xa[:, None] == ya[None, :], match, mismatch)  # (nx, ny)
    for i in range(1, nx + 1):
        up = score[i - 1, 1:] + gap
        diag = score[i - 1, :-1] + sub[i - 1]
        # The row has a left-to-right dependency; keep that one scalar loop.
        row = score[i]
        trow = trace[i]
        for j in range(1, ny + 1):
            left = row[j - 1] + gap
            best = max(left, up[j - 1], diag[j - 1])
            row[j] = best
            trow[j] = 1 if best == left else (2 if best == up[j - 1] else 3)

    pairs: List[Tuple[int, int]] = []
    i, j = nx, ny
    while i > 0 or j > 0:
        code = trace[i, j]
        if code == 3:
            i -= 1
            j -= 1
            pairs.append((j, i))
        elif code == 1:
            j -= 1
            pairs.append((j, -1))
        elif code == 2:
            i -= 1
        else:  # origin
            break
    pairs.reverse()
    return pairs


def refinement_mapper_single(src: str, tgt: str, tokenizer: Tokenizer,
                             max_len: int = 77) -> Tuple[np.ndarray, np.ndarray]:
    """Integer gather + alphas for one (source, edit) prompt pair.

    Output spec matches `/root/reference/seq_aligner.py:107-118`: positions
    past the aligned length continue as identity (``len(y), len(y)+1, ...``)
    and their alphas stay 1.
    """
    x_ids = tokenizer.encode(src)
    y_ids = tokenizer.encode(tgt)
    pairs = needleman_wunsch(x_ids, y_ids)
    n = len(pairs)
    mapper = np.zeros(max_len, dtype=np.int32)
    alphas = np.ones(max_len, dtype=np.float32)
    pa = np.asarray(pairs, dtype=np.int32)  # (n, 2) = (y_pos, x_pos)
    mapper[:n] = pa[:, 1]
    alphas[:n] = (pa[:, 1] != -1).astype(np.float32)
    mapper[n:] = len(y_ids) + np.arange(max_len - len(y_ids), dtype=np.int32)
    return mapper, alphas


def get_refinement_mapper(prompts: Sequence[str], tokenizer: Tokenizer,
                          max_len: int = 77) -> Tuple[np.ndarray, np.ndarray]:
    """Stacked refinement mappers for prompts[1:] against prompts[0].

    Returns ``mapper (E, L) int32`` and ``alphas (E, L) float32``
    (`/root/reference/seq_aligner.py:121-128`).
    """
    out = [refinement_mapper_single(prompts[0], p, tokenizer, max_len) for p in prompts[1:]]
    mappers = np.stack([m for m, _ in out])
    alphas = np.stack([a for _, a in out])
    return mappers, alphas


def replacement_mapper_single(src: str, tgt: str, tokenizer: Tokenizer,
                              max_len: int = 77) -> np.ndarray:
    """Dense ``(L, L)`` projection matrix for a word-swap edit.

    Word-level diff of two prompts with equal word counts; swapped words'
    token spans cross-connect (weight ``1/len(target_span)`` when span sizes
    differ), everything else is identity
    (`/root/reference/seq_aligner.py:152-185`). Rows index source tokens,
    columns index edit-prompt tokens; when every swapped word keeps its
    token count, each source-token ROW carries unit mass and ``attn @ m``
    preserves total attention mass. When a swapped word's token count
    CHANGES, the reference's trailing diagonal (noted below) misaligns the
    tail: shrinking spans double-count rows (mass > 1), growing spans skip
    rows (mass 0) — both reproduced bit-for-bit for pixel parity.
    """
    words_x = src.split(" ")
    words_y = tgt.split(" ")
    if len(words_x) != len(words_y):
        raise ValueError(
            "attention replacement edit requires prompts with the same word count, "
            f"got {len(words_x)} vs {len(words_y)} — use AttentionRefine for "
            "prompts of different lengths."
        )
    diff = [i for i in range(len(words_y)) if words_y[i] != words_x[i]]
    spans_src = [get_word_inds(src, i, tokenizer) for i in diff]
    spans_tgt = [get_word_inds(tgt, i, tokenizer) for i in diff]

    mapper = np.zeros((max_len, max_len), dtype=np.float32)
    i = j = 0
    k = 0
    while i < max_len and j < max_len:
        if k < len(spans_src) and len(spans_src[k]) > 0 and spans_src[k][0] == i:
            s, t = spans_src[k], spans_tgt[k]
            if len(s) == len(t):
                mapper[s, t] = 1.0
            else:
                mapper[np.ix_(s, t)] = 1.0 / len(t)
            k += 1
            i += len(s)
            j += len(t)
        elif k < len(spans_src):
            mapper[i, j] = 1.0
            i += 1
            j += 1
        else:
            # Past the last replaced span the reference switches to a pure
            # diagonal keyed by the *target* index (`seq_aligner.py:179-182`:
            # ``mapper[j, j] = 1``). NOTE: when a replaced source span is
            # longer than its target span this diagonal overlaps rows the
            # span block already used (row sums then exceed 1 and trailing
            # same-word tokens misalign by the length difference) — a quirk
            # of the reference we reproduce bit-for-bit for pixel parity;
            # it is pinned in tests/test_align_properties.py.
            mapper[j, j] = 1.0
            i += 1
            j += 1
    return mapper


def get_replacement_mapper(prompts: Sequence[str], tokenizer: Tokenizer,
                           max_len: int = 77) -> np.ndarray:
    """Stacked ``(E, L, L)`` replacement mappers for prompts[1:] vs prompts[0]
    (`/root/reference/seq_aligner.py:189-195`)."""
    return np.stack(
        [replacement_mapper_single(prompts[0], p, tokenizer, max_len) for p in prompts[1:]]
    )
