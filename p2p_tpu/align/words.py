"""Word→token indexing and the per-step/per-token edit schedules.

Host-side precompute producing the fixed-shape arrays the jitted sampling loop
indexes by step:

- ``get_word_inds`` — token indices of a whitespace word inside a prompt
  (spec: `/root/reference/ptp_utils.py:245-263`).
- ``get_time_words_attention_alpha`` — the ``(T+1, E, 1, 1, L)`` 0/1 schedule
  that turns ``cross_replace_steps`` (a float or a per-word dict) into a
  per-step/per-token blend weight (`/root/reference/ptp_utils.py:266-297`).
- ``get_equalizer`` — per-token scale vectors for AttentionReweight, in both
  the sweep form (`/root/reference/main.py:281-290`, one row per value) and the
  paired form (`/root/reference/null_text.py:340-349`, one row, word↔value).
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple, Union

import numpy as np

from ..utils.tokenizer import Tokenizer, token_strings

Bounds = Union[float, Tuple[float, float]]


def get_word_inds(text: str, word_place: Union[int, str, List[int]],
                  tokenizer: Tokenizer) -> np.ndarray:
    """Token indices (1-based, accounting for BOS) covering a word of ``text``.

    ``word_place`` is a whitespace-word position, a word string (all
    occurrences), or a list of positions. Sub-word tokens are attributed to
    words by accumulating decoded-token lengths until they cover the current
    word, exactly as `/root/reference/ptp_utils.py:245-263` does.
    """
    split_text = text.split(" ")
    if isinstance(word_place, str):
        places = [i for i, w in enumerate(split_text) if word_place == w]
    elif isinstance(word_place, int):
        places = [word_place]
    else:
        places = list(word_place)
    out: List[int] = []
    if places:
        pieces = token_strings(tokenizer, text)
        cur_len, ptr = 0, 0
        for i, piece in enumerate(pieces):
            cur_len += len(piece)
            if ptr in places:
                out.append(i + 1)
            if ptr < len(split_text) and cur_len >= len(split_text[ptr]):
                ptr += 1
                cur_len = 0
    return np.array(out, dtype=np.int64)


def update_alpha_time_word(alpha: np.ndarray, bounds: Bounds, prompt_ind: int,
                           word_inds: np.ndarray | None = None) -> np.ndarray:
    """Write a 0/1 step window into ``alpha[(step), prompt_ind, word_inds]``
    (`/root/reference/ptp_utils.py:266-276`). ``bounds`` as a float means
    ``(0, bounds)``; fractions index into the step axis."""
    if isinstance(bounds, (int, float)):
        bounds = (0.0, float(bounds))
    start, end = int(bounds[0] * alpha.shape[0]), int(bounds[1] * alpha.shape[0])
    if word_inds is None:
        word_inds = np.arange(alpha.shape[2])
    alpha[:start, prompt_ind, word_inds] = 0
    alpha[start:end, prompt_ind, word_inds] = 1
    alpha[end:, prompt_ind, word_inds] = 0
    return alpha


def get_time_words_attention_alpha(
    prompts: Sequence[str],
    num_steps: int,
    cross_replace_steps: Union[Bounds, Dict[str, Bounds]],
    tokenizer: Tokenizer,
    max_num_words: int = 77,
) -> np.ndarray:
    """Build the ``(num_steps+1, E, 1, 1, L)`` cross-replace schedule
    (`/root/reference/ptp_utils.py:279-297`).

    A plain float/tuple applies to every token; a dict maps words (of the edit
    prompts) to their own step windows, with ``"default_"`` as the fallback.
    """
    if not isinstance(cross_replace_steps, dict):
        cross_replace_steps = {"default_": cross_replace_steps}
    if "default_" not in cross_replace_steps:
        cross_replace_steps = {**cross_replace_steps, "default_": (0.0, 1.0)}
    n_edit = len(prompts) - 1
    alpha = np.zeros((num_steps + 1, n_edit, max_num_words), dtype=np.float32)
    for i in range(n_edit):
        update_alpha_time_word(alpha, cross_replace_steps["default_"], i)
    for key, bounds in cross_replace_steps.items():
        if key == "default_":
            continue
        for i in range(1, len(prompts)):
            inds = get_word_inds(prompts[i], key, tokenizer)
            if len(inds) > 0:
                update_alpha_time_word(alpha, bounds, i - 1, inds)
    return alpha.reshape(num_steps + 1, n_edit, 1, 1, max_num_words)


def get_equalizer(
    text: str,
    word_select: Union[int, str, Sequence[Union[int, str]]],
    values: Sequence[float],
    tokenizer: Tokenizer,
    mode: str = "sweep",
) -> np.ndarray:
    """Per-token attention scale vectors for AttentionReweight.

    - ``mode='sweep'``: ``(len(values), L)`` — every selected word gets scale
      ``values[v]`` in row ``v`` (the equalizer-sweep form,
      `/root/reference/main.py:281-290`).
    - ``mode='paired'``: ``(1, L)`` — ``word_select[k]`` gets ``values[k]``
      (`/root/reference/null_text.py:340-349`).
    """
    if isinstance(word_select, (int, str)):
        word_select = (word_select,)
    L = tokenizer.model_max_length
    if mode == "sweep":
        eq = np.ones((len(values), L), dtype=np.float32)
        vals = np.asarray(values, dtype=np.float32)
        for word in word_select:
            inds = get_word_inds(text, word, tokenizer)
            eq[:, inds] = vals[:, None]
    elif mode == "paired":
        eq = np.ones((1, L), dtype=np.float32)
        for word, val in zip(word_select, values):
            inds = get_word_inds(text, word, tokenizer)
            eq[:, inds] = float(val)
    else:
        raise ValueError(f"unknown equalizer mode: {mode!r}")
    return eq
