from .aligner import (
    get_refinement_mapper,
    get_replacement_mapper,
    needleman_wunsch,
    refinement_mapper_single,
    replacement_mapper_single,
)
from .words import (
    get_equalizer,
    get_time_words_attention_alpha,
    get_word_inds,
    update_alpha_time_word,
)

__all__ = [
    "get_refinement_mapper",
    "get_replacement_mapper",
    "needleman_wunsch",
    "refinement_mapper_single",
    "replacement_mapper_single",
    "get_equalizer",
    "get_time_words_attention_alpha",
    "get_word_inds",
    "update_alpha_time_word",
]
