"""p2p-tpu: a TPU-native (JAX/XLA/pallas/pjit) prompt-to-prompt image-editing framework.

Re-designs the capabilities of KIMGEONUNG/prompt-to-prompt (attention Replace /
Refine / Reweight edits, LocalBlend, attention-map storage/visualization, and
null-text inversion) as a functionally pure, jit-compiled pipeline: the
reference's runtime monkey-patching (`/root/reference/ptp_utils.py:175-242`)
becomes a pluggable attention-controller applied inside our own Flax U-Net, with
controller state threaded through a `lax.scan` sampling loop and data-parallel
sharding over TPU meshes for seed / equalizer sweeps.
"""

__version__ = "0.1.0"

MAX_NUM_WORDS = 77  # CLIP context length; the reference's `MAX_NUM_WORDS` (main.py:21)
