"""p2p-tpu: a TPU-native (JAX/XLA/pallas/pjit) prompt-to-prompt image-editing framework.

Re-designs the capabilities of KIMGEONUNG/prompt-to-prompt (attention Replace /
Refine / Reweight edits, LocalBlend, attention-map storage/visualization, and
null-text inversion) as a functionally pure, jit-compiled pipeline: the
reference's runtime monkey-patching (`/root/reference/ptp_utils.py:175-242`)
becomes a pluggable attention-controller applied inside our own Flax U-Net, with
controller state threaded through a `lax.scan` sampling loop and data-parallel
sharding over TPU meshes for seed / equalizer sweeps.
"""

__version__ = "0.1.0"

MAX_NUM_WORDS = 77  # CLIP context length; the reference's `MAX_NUM_WORDS` (main.py:21)

# Lazy top-level re-exports of the core user surface (PEP 562): keeps
# `import p2p_tpu` light (no jax/flax import) while letting users write
# `from p2p_tpu import text2image, Pipeline, make_controller, invert, ...`.
_EXPORTS = {
    "Pipeline": "p2p_tpu.engine.sampler",
    "text2image": "p2p_tpu.engine.sampler",
    "invert": "p2p_tpu.engine.inversion",
    "InversionArtifact": "p2p_tpu.engine.inversion",
    "load_image": "p2p_tpu.engine.inversion",
    "load_pipeline": "p2p_tpu.models.checkpoint",
    "make_controller": "p2p_tpu.controllers.factory",
    "SpConfig": "p2p_tpu.models.unet",
    "save_pipeline_native": "p2p_tpu.models.native",
    "load_pipeline_native": "p2p_tpu.models.native",
}

__all__ = ["MAX_NUM_WORDS", *_EXPORTS]


def __getattr__(name):
    if name in _EXPORTS:
        import importlib

        value = getattr(importlib.import_module(_EXPORTS[name]), name)
        globals()[name] = value  # cache: later accesses are plain dict hits
        return value
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
