"""Mesh-parallel serving: the spec, bucket scaling, and program-key rules.

``serve --mesh dp=N`` puts the engine's *batch dimension* on a device mesh
(ROADMAP open item 1; the hardware-co-optimization axis SD-Acc pairs with
its phase-aware sampling). The serve loop stays a single-threaded
virtual-clock scheduler — what changes is the shape of a dispatch:

- **Lane buckets become per-device sub-batches.** The fixed padding set
  (:data:`~p2p_tpu.serve.batcher.BUCKET_SIZES`) scales to
  ``(dp, 2·dp, 4·dp, 8·dp)``: a dispatched bucket of ``b·dp`` lanes lands
  as ``b`` whole lanes per device under a ``NamedSharding`` on the group
  axis (``PartitionSpec("dp")`` — the SNIPPETS [2]/[3] pattern via
  ``parallel.mesh.make_mesh``). ``--max-batch`` keeps its per-device
  meaning, so one operator knob describes one device's footprint on any
  mesh; the phase-2 pool's wider cap scales the same way
  (``phase2_max_batch · dp`` — the equal-footprint doubling now spans the
  whole mesh).
- **Program-cache entries become mesh programs.** The device count and
  mesh shape join the cache/compile key (:func:`mesh_key`), so a
  ``dp=4`` program can never be served to a ``dp=1`` dispatch (or
  vice versa) out of the LRU or the persistent compile cache. Prewarm
  builds the mesh programs ahead of traffic exactly like today.
- **Durability stays mesh-agnostic.** Nothing in this module touches the
  journal: the WAL, snapshots, hand-off spills, drain and crash-resume
  paths carry request state only, never device topology — a journal
  written at ``dp=4`` restarts cleanly at ``dp=1`` and the other way
  round (pinned by tests/test_serve_mesh.py).

``dp=1`` builds a real one-device mesh and dispatches through the sharded
staging path, bitwise-identical to the mesh-less engine (the
``mesh_parity`` quality-gate leg); ``dp>1`` matches at the repo's
documented vmap tolerance (±1 uint8 step, tests/test_parallel.py).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple, Union

from .batcher import BUCKET_SIZES


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """The serve engine's mesh request: a data-parallel width. Kept
    jax-free (CLI parsing and key derivation must not initialize a
    backend); :func:`build_mesh` turns it into a live ``jax.sharding.Mesh``
    when the engine starts."""

    dp: int = 1

    def __post_init__(self):
        if self.dp < 1:
            raise ValueError(f"mesh dp must be >= 1, got {self.dp}")
        if self.dp & (self.dp - 1):
            # Power-of-two dp keeps every scaled bucket divisible by dp
            # (and the per-device sub-batch a whole fixed bucket).
            raise ValueError(f"mesh dp must be a power of two, got {self.dp}")


def parse_mesh(spec: str) -> MeshSpec:
    """Parse the CLI ``--mesh`` value: ``dp=N`` (the only axis the serve
    engine shards today — tensor parallelism composes later via
    ``parallel.mesh`` tp rules)."""
    s = spec.strip()
    if not s.startswith("dp="):
        raise ValueError(f"--mesh expects 'dp=N', got {spec!r}")
    try:
        dp = int(s[3:])
    except ValueError:
        raise ValueError(f"--mesh expects an integer dp, got {spec!r}")
    return MeshSpec(dp=dp)


def as_spec(mesh: Union[None, str, MeshSpec]) -> Optional[MeshSpec]:
    """Normalize the engine's ``mesh=`` argument (None | 'dp=N' | MeshSpec)."""
    if mesh is None or isinstance(mesh, MeshSpec):
        return mesh
    if isinstance(mesh, str):
        return parse_mesh(mesh)
    raise TypeError(f"mesh must be None, 'dp=N' or MeshSpec, got {mesh!r}")


def build_mesh(spec: MeshSpec):
    """A live ``(dp, tp=1)`` mesh over the first ``spec.dp`` devices
    (``parallel.mesh.make_mesh``), validated against what the process
    actually has — a mesh wider than the machine is a configuration error
    at startup, never a shape failure mid-traffic."""
    import jax

    from ..parallel.mesh import make_mesh

    n = len(jax.devices())
    if spec.dp > n:
        raise ValueError(
            f"--mesh dp={spec.dp} needs {spec.dp} devices; this process "
            f"has {n} (virtual CPU meshes: "
            f"--xla_force_host_platform_device_count)")
    return make_mesh(spec.dp, tp=1)


def replicate_pipeline(pipe, mesh):
    """The mesh's weight residency: one explicit replication of the U-Net
    and VAE params onto every mesh device at engine start. Without it,
    every dispatch would *implicitly* reshard the device-0 weights onto
    the mesh — a per-batch transfer the
    ``jax.transfer_guard("disallow")`` contract exists to forbid (and the
    mesh transfer-guard test catches). The text encoder stays put: it
    runs host-side of the dispatch (admission-time prompt encoding), not
    inside the sharded programs."""
    import dataclasses as _dc

    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    rep = NamedSharding(mesh, PartitionSpec())
    put = lambda tree: jax.tree_util.tree_map(  # noqa: E731
        lambda x: jax.device_put(x, rep), tree)
    return _dc.replace(pipe, unet_params=put(pipe.unet_params),
                       vae_params=put(pipe.vae_params))


def scaled_bucket_sizes(dp: int) -> Tuple[int, ...]:
    """The global lane-bucket set on a ``dp``-wide mesh: each fixed bucket
    times ``dp``, so every padded batch splits into whole per-device
    sub-batches and the bounded-program-count contract holds per mesh
    shape (still exactly ``len(BUCKET_SIZES)`` buckets)."""
    return tuple(b * dp for b in BUCKET_SIZES)


#: Tag prefix of the mesh component appended to program-cache keys.
MESH_KEY_TAG = "mesh"


def mesh_key(compile_key: Tuple, spec: MeshSpec) -> Tuple:
    """Join the device count / mesh shape to a program key: a mesh program
    and its single-chip twin must never share a cache entry (LRU or the
    persistent XLA cache keyed off the traced call)."""
    return compile_key + ((MESH_KEY_TAG, "dp", spec.dp),)


def strip_mesh_key(compile_key: Tuple) -> Tuple:
    """Drop a trailing mesh component (no-op when absent) — runners parse
    the un-suffixed key layout."""
    if (compile_key and isinstance(compile_key[-1], tuple)
            and len(compile_key[-1]) == 3
            and compile_key[-1][0] == MESH_KEY_TAG):
        return compile_key[:-1]
    return compile_key
