"""Content-addressed semantic caching for the serve loop (ISSUE 13).

At millions of users traffic is Zipfian — identical and near-identical
requests dominate — so the cheapest request is the one the engine never
computes. A :class:`SemCache` sits *above* the two-pool engine and serves
three layers, addressed by the request's ``content_key``
(``serve.request.content_key``: every output-determining field, nothing
else):

- **L1 — text-encoder outputs.** Cond/uncond embeddings are pure functions
  of ``(model, prompt)``; the runners memoize them here (bounded LRU with
  bytes accounting), so a popular prompt pays the text encoder once per
  process instead of once per lane.
- **L2 — phase-1 carry prefix.** A gated request's hand-off carry is a
  pure function of its content key, and the engine already knows how to
  *resume* a request from a spilled carry (the journal's crash-replay
  path). Every hand-off spills a copy here (content-addressed ``.npz``
  via ``handoff.spill_carry``); a later request with the same content key
  loads it (template-validated via ``handoff.load_carry`` — a corrupt or
  mismatched spill is a **silent miss + recompute, never a fault**) and
  enters the engine directly in phase 2: a prefix hit IS a hand-off
  resume.
- **L3 — exact results.** The leader's terminal images, returned bitwise.
  Entries spill to content-addressed ``.npz`` files so they survive a
  crash: the engine journals a ``cache`` record per insert and replay
  reseeds the index (``SemCache.seed``), which is what lets a restart
  serve a killed leader's followers without recomputing (the
  ``kill_after_cache_insert`` chaos drill). The ordering that makes it
  sound — the ``cache`` record lands *before* the leader's terminal, so
  no follower can dedupe against a terminal whose result never became
  durable — is a declared invariant (``cache-before-terminal`` in
  ``p2p_tpu.analysis.walcheck``, ISSUE 20), model-checked at every
  crash point and guarded by the ``terminal-before-cache`` seeded bug.
  In-memory residency is bounded by ``l3_bytes`` (LRU; eviction deletes
  the spill file too).

Single-flight collapsing (identical in-flight requests ride one leader)
lives in the engine, not here — the cache is pure storage; the engine owns
the clock and the record stream.

Eviction joins the degradation ladder: under sustained pressure the engine
calls :meth:`shed_l2` *before* it sheds requests — spill disk is the
cheapest thing the server owns.

Everything is strictly opt-in: ``semcache=None`` (the default everywhere)
leaves the record stream, journal bytes, compiled programs and metric
families byte-identical to the pre-cache engine — the disabled-mode parity
discipline every serve subsystem pins.
"""

from __future__ import annotations

import functools
import hashlib
import os
import tempfile
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

from ..obs import metrics as obs_metrics

LAYERS = ("l1", "l2", "l3")


@functools.lru_cache(maxsize=65536)
def digest(key: Tuple) -> str:
    """Stable content address for any hashable key tuple. ``repr`` is the
    serialization: content keys are flat tuples of python scalars/strings,
    so equal keys repr identically across processes. Memoized: the engine
    digests the same key at admission, leader registration and hand-off
    spill — and popular traffic repeats keys by construction."""
    return hashlib.sha256(repr(key).encode()).hexdigest()[:32]


class SemCache:
    """Three-layer content-addressed cache. One instance covers one serve
    process; the engine consults it at admission (L3/L2) and the runners
    at encode time (L1).

    ``spill_dir`` holds the L2/L3 sidecar files (content-addressed names,
    written tmp+rename so a crash never leaves a torn file that parses);
    default: a fresh tempdir. ``layers`` opts layers in individually —
    a layer not listed never stores, never hits, never counts."""

    def __init__(self, spill_dir: Optional[str] = None,
                 l1_bytes: int = 32 << 20, l2_entries: int = 256,
                 l3_bytes: int = 256 << 20,
                 layers: Tuple[str, ...] = LAYERS):
        for layer in layers:
            if layer not in LAYERS:
                raise ValueError(f"unknown cache layer {layer!r}; "
                                 f"valid: {', '.join(LAYERS)}")
        if l1_bytes < 1 or l2_entries < 1 or l3_bytes < 1:
            raise ValueError("cache budgets must be >= 1")
        self.layers = tuple(layers)
        self.l1_bytes = l1_bytes
        self.l2_entries = l2_entries
        self.l3_bytes = l3_bytes
        self.spill_dir = spill_dir or tempfile.mkdtemp(prefix="p2p-semcache-")
        os.makedirs(self.spill_dir, exist_ok=True)
        # Open-time hygiene (the journal's carry-dir idiom): a crash
        # mid-spill leaves only a .tmp (the rename is atomic), and a
        # previous incarnation's L2 prefix spills are unreachable by
        # construction — the L2 index is memory-only, so a reused
        # --cache-dir would otherwise leak p1-* files forever. L3 r-*
        # spills are NOT swept here: the journal may reference them
        # (``seed`` is the authority — it sweeps what replay does not).
        for name in os.listdir(self.spill_dir):
            if name.endswith(".tmp") or (name.startswith("p1-")
                                         and name.endswith(".npz")):
                try:
                    os.remove(os.path.join(self.spill_dir, name))
                except OSError:
                    pass
        self._l1: "OrderedDict[Tuple, Tuple[Any, int]]" = OrderedDict()
        self._l1_used = 0
        self._l2: "OrderedDict[str, Dict]" = OrderedDict()
        self._l3: "OrderedDict[str, Dict]" = OrderedDict()
        self._l3_used = 0
        self.stats = {layer: {"hits": 0, "misses": 0, "inserts": 0,
                              "evictions": 0, "corrupt": 0}
                      for layer in LAYERS}
        reg = obs_metrics.registry()
        self._m_events = reg.counter(
            "serve_semcache_events_total",
            "semantic-cache lookups/inserts/evictions by layer and event",
            labels=("layer", "event"))
        self._m_bytes = reg.gauge(
            "serve_semcache_bytes",
            "bytes resident per semantic-cache layer (L2: spill disk)",
            labels=("layer",))

    def enabled(self, layer: str) -> bool:
        return layer in self.layers

    digest = staticmethod(digest)

    def _note(self, layer: str, event: str, n: int = 1) -> None:
        self.stats[layer][event] += n
        self._m_events.labels(layer=layer, event=event).inc(n)

    def note_miss(self, layer: str) -> None:
        """Count one lookup miss decided OUTSIDE the store: the engine
        tests presence first (``l3_has``/``l2_has``) so admission can
        reject a request before any cache counter moves, then records
        the miss only once the request is actually admitted — keeping
        hits+misses == lookups of admitted traffic."""
        if self.enabled(layer):
            self._note(layer, "misses")

    # -- L1: text-encoder outputs -----------------------------------------

    def l1_get_or_build(self, key: Tuple, build):
        """Memoized encode: returns the cached value for ``key`` or builds,
        stores (bytes-bounded LRU) and returns it. Values are the device
        arrays the encoder produced — reuse is bitwise by construction."""
        if not self.enabled("l1"):
            return build()
        if key in self._l1:
            self._l1.move_to_end(key)
            self._note("l1", "hits")
            return self._l1[key][0]
        self._note("l1", "misses")
        value = build()
        nbytes = int(getattr(value, "size", 0)) * int(
            getattr(getattr(value, "dtype", None), "itemsize", 0) or 0)
        self._l1[key] = (value, nbytes)
        self._l1_used += nbytes
        self._note("l1", "inserts")
        while self._l1_used > self.l1_bytes and len(self._l1) > 1:
            _, (_, freed) = self._l1.popitem(last=False)
            self._l1_used -= freed
            self._note("l1", "evictions")
        self._m_bytes.labels(layer="l1").set(self._l1_used)
        return value

    # -- L2: phase-1 carry prefix -----------------------------------------

    def _l2_path(self, key_digest: str) -> str:
        return os.path.join(self.spill_dir, f"p1-{key_digest}.npz")

    def l2_has(self, key_digest: str) -> bool:
        return self.enabled("l2") and key_digest in self._l2

    def l2_put(self, key_digest: str, carry: Any) -> None:
        """Spill one per-lane hand-off unit under its content address
        (``handoff.spill_carry``: tmp+rename+fsync). Entry-bounded LRU;
        eviction deletes the spill file."""
        if not self.enabled("l2"):
            return
        if key_digest in self._l2:
            self._l2.move_to_end(key_digest)
            return
        from .handoff import spill_carry

        path = self._l2_path(key_digest)
        spec = spill_carry(carry, path)
        self._l2[key_digest] = {"path": path, "spec": spec,
                                "bytes": os.path.getsize(path)}
        self._note("l2", "inserts")
        while len(self._l2) > self.l2_entries:
            self._evict_l2(next(iter(self._l2)), "evictions")
        self._update_l2_bytes()

    def l2_get(self, key_digest: str, template: Any) -> Optional[Any]:
        """Load a prefix carry, validated leaf-by-leaf against the treedef
        the *request* implies (``handoff.load_carry``). Any mismatch or
        unreadable file — a template refusal, a corrupt entry, operator
        damage — is a silent miss: the entry is dropped and the caller
        recomputes phase 1. A wrong-shaped carry must never reach a
        compiled program, and a bad cache entry must never fail a
        request."""
        if not self.enabled("l2"):
            return None
        entry = self._l2.get(key_digest)
        if entry is None:
            self._note("l2", "misses")
            return None
        from .handoff import load_carry

        try:
            carry = load_carry(entry["path"], template)
        except ValueError:
            self._note("l2", "corrupt")
            self._note("l2", "misses")
            self._evict_l2(key_digest, None)
            self._update_l2_bytes()
            return None
        self._l2.move_to_end(key_digest)
        self._note("l2", "hits")
        return carry

    def _evict_l2(self, key_digest: str, count_as: Optional[str]) -> None:
        entry = self._l2.pop(key_digest, None)
        if entry is None:
            return
        try:
            os.remove(entry["path"])
        except OSError:
            pass
        if count_as:
            self._note("l2", count_as)

    def _update_l2_bytes(self) -> None:
        self._m_bytes.labels(layer="l2").set(
            sum(e["bytes"] for e in self._l2.values()))

    def shed_l2(self) -> int:
        """Drop every L2 entry and its spill disk — the degradation
        ladder's cheapest rung, taken *before* any request is shed.
        Returns how many entries went."""
        n = len(self._l2)
        for key_digest in list(self._l2):
            self._evict_l2(key_digest, "evictions")
        self._update_l2_bytes()
        return n

    # -- L3: exact results -------------------------------------------------

    def _l3_path(self, key_digest: str) -> str:
        return os.path.join(self.spill_dir, f"r-{key_digest}.npz")

    def l3_has(self, key_digest: str) -> bool:
        """Presence only — no counters move (the engine's pre-admission
        test; a not-yet-lazy-loaded seeded entry counts as present)."""
        return self.enabled("l3") and key_digest in self._l3

    def l3_put(self, key_digest: str, images: Any) -> Optional[str]:
        """Store one terminal result under its content address; returns
        the spill path (for the journal's ``cache`` record) or None when
        the layer is off / the key is already present. The spill is
        durable before this returns (tmp+fsync+rename), so a journaled
        ``cache`` record never points at a file a crash can lose."""
        if not self.enabled("l3"):
            return None
        if key_digest in self._l3:
            self._l3.move_to_end(key_digest)
            return None
        import numpy as np

        arr = np.asarray(images)
        path = self._l3_path(key_digest)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, images=arr)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self._l3[key_digest] = {"path": path, "images": arr,
                                "bytes": int(arr.nbytes)}
        self._l3_used += int(arr.nbytes)
        self._note("l3", "inserts")
        while self._l3_used > self.l3_bytes and len(self._l3) > 1:
            self._evict_l3(next(iter(self._l3)), "evictions")
        self._m_bytes.labels(layer="l3").set(self._l3_used)
        return path

    def l3_get(self, key_digest: str):
        """The bitwise result for this content key, or None. A seeded
        (journal-replayed) entry loads lazily off its spill; a missing or
        corrupt spill is a silent miss + entry drop, never a fault."""
        if not self.enabled("l3"):
            return None
        entry = self._l3.get(key_digest)
        if entry is None:
            self._note("l3", "misses")
            return None
        if entry["images"] is None:
            import numpy as np

            try:
                with np.load(entry["path"]) as data:
                    entry["images"] = np.asarray(data["images"])
            except Exception:  # noqa: BLE001 — any unreadable spill: miss
                self._note("l3", "corrupt")
                self._note("l3", "misses")
                self._evict_l3(key_digest, None)
                return None
            entry["bytes"] = int(entry["images"].nbytes)
            self._l3_used += entry["bytes"]
            # Seeded loads charge the same budget as inserts: a restart
            # with many journaled entries must not grow residency
            # unbounded on a read-only (hit-heavy) workload. MRU first so
            # the entry being served cannot evict itself.
            self._l3.move_to_end(key_digest)
            while self._l3_used > self.l3_bytes and len(self._l3) > 1:
                self._evict_l3(next(iter(self._l3)), "evictions")
            self._m_bytes.labels(layer="l3").set(self._l3_used)
        self._l3.move_to_end(key_digest)
        self._note("l3", "hits")
        return entry["images"]

    def _evict_l3(self, key_digest: str, count_as: Optional[str]) -> None:
        entry = self._l3.pop(key_digest, None)
        if entry is None:
            return
        self._l3_used -= entry["bytes"]
        try:
            os.remove(entry["path"])
        except OSError:
            pass
        if count_as:
            self._note("l3", count_as)
        self._m_bytes.labels(layer="l3").set(self._l3_used)

    def seed(self, cache_entries: Dict[str, dict]) -> int:
        """Reseed the L3 index from journal-replayed ``cache`` records
        (``ReplayState.cache_entries``): each entry registers path-only
        (lazy load, validated at first hit), and spill files the journal
        does NOT reference are swept — after a crash between an insert's
        spill and its ``cache`` record, the unreferenced file is garbage,
        not evidence. Returns how many entries seeded."""
        if not self.enabled("l3"):
            return 0
        referenced = set()
        n = 0
        for key_digest, rec in cache_entries.items():
            path = rec.get("path")
            if not path or not os.path.exists(path):
                continue
            referenced.add(os.path.abspath(path))
            if key_digest not in self._l3:
                self._l3[key_digest] = {"path": path, "images": None,
                                        "bytes": 0}
                n += 1
        for name in sorted(os.listdir(self.spill_dir)):
            full = os.path.join(self.spill_dir, name)
            if name.startswith("r-") and name.endswith(".npz") and \
                    os.path.abspath(full) not in referenced:
                try:
                    os.remove(full)
                except OSError:
                    pass
        return n

    # -- reporting ---------------------------------------------------------

    def layer_stats(self) -> dict:
        """Per-layer counters + resident bytes — the summary's
        ``semcache.layers`` block and the bench/quality-gate source."""
        out = {}
        for layer in LAYERS:
            if not self.enabled(layer):
                continue
            s = dict(self.stats[layer])
            s["bytes"] = {"l1": self._l1_used,
                          "l2": sum(e["bytes"] for e in self._l2.values()),
                          "l3": self._l3_used}[layer]
            s["entries"] = {"l1": len(self._l1), "l2": len(self._l2),
                            "l3": len(self._l3)}[layer]
            out[layer] = s
        return out
