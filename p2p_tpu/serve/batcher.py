"""Dynamic batcher: bucket compatible requests, pad to fixed batch sizes.

Requests group by ``batch_key`` (compile key + traced-but-shared values —
see ``serve.request``), so a bucket never mixes work that couldn't ride one
``parallel.sweep`` call. A bucket flushes when it reaches ``max_batch`` or
when its oldest entry has waited ``max_wait_ms`` — the classic latency ⇄
occupancy trade, both knobs surfaced on the CLI.

Dispatched batches are padded up to a small fixed set of lane counts
(:data:`BUCKET_SIZES`, capped by ``max_batch``) so the number of distinct
XLA programs stays bounded no matter what sizes the traffic produces; the
padding lanes replicate a real request and are masked out of results by the
engine (``engine.sampler.lane_select``). The engine may also pad a partial
batch *up* to a larger already-compiled bucket (warm-preference) — trading
a few wasted lanes for keeping compiles off the request path entirely.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Tuple

from ..obs import metrics as obs_metrics
from .queue import Entry

BUCKET_SIZES = (1, 2, 4, 8)


def bucket_for(n: int, max_batch: int = BUCKET_SIZES[-1],
               sizes: Tuple[int, ...] = BUCKET_SIZES) -> int:
    """Smallest fixed bucket holding ``n`` lanes (≤ ``max_batch``).

    ``max_batch`` must itself be one of ``sizes``: a cap between buckets
    (say 5) would force a 5-entry flush into a 4-lane bucket, silently
    breaking the every-entry-gets-a-lane padding contract and the
    bounded-program-count guarantee built on it. ``sizes`` defaults to the
    single-device :data:`BUCKET_SIZES`; mesh serving passes the dp-scaled
    set (``serve.meshing.scaled_bucket_sizes``) so every bucket splits
    into whole per-device sub-batches.
    """
    if n < 1:
        raise ValueError(f"bucket_for needs n >= 1, got {n}")
    if max_batch not in sizes:
        raise ValueError(f"max_batch must be one of {sizes}, "
                         f"got {max_batch}")
    for b in sizes:
        if b >= min(n, max_batch):
            return b
    return sizes[-1]


@dataclasses.dataclass
class Batch:
    """A flush unit: compatible entries + the bucket they pad to."""

    batch_key: Tuple
    entries: List[Entry]
    created_ms: float
    urgent: bool = False        # flushed by the deadline jump (flush_key)

    @property
    def compile_key(self) -> Tuple:
        return self.entries[0].prepared.compile_key


def _default_key(entry: Entry) -> Tuple:
    return entry.prepared.batch_key


class DynamicBatcher:
    """Groups entries by ``key_fn`` (default: the monolithic ``batch_key``);
    flushes on max-batch or max-wait.

    The phase-disaggregated engine runs TWO of these: the admission-side
    pool (mono + phase-1 batches, default key) and the hand-off-side
    phase-2 pool (``key_fn`` selecting ``prepared.phase2_batch_key``,
    entries are ``handoff.HandoffEntry``). ``pool`` labels the shared
    metric families so the two pools' timelines stay distinguishable."""

    def __init__(self, max_batch: int = 8, max_wait_ms: float = 50.0,
                 key_fn: Optional[Callable[[Entry], Tuple]] = None,
                 pool: str = "main",
                 bucket_sizes: Tuple[int, ...] = BUCKET_SIZES):
        if max_batch not in bucket_sizes:
            raise ValueError(
                f"max_batch must be one of {bucket_sizes}, got {max_batch}")
        self.bucket_sizes = tuple(bucket_sizes)
        self.max_batch = max_batch
        self.max_wait_ms = float(max_wait_ms)
        self.key_fn = key_fn or _default_key
        self.pool = pool
        self._waiting: Dict[Tuple, List[Entry]] = {}
        self._oldest_ms: Dict[Tuple, float] = {}
        reg = obs_metrics.registry()
        # Flush cause tells the latency ⇄ occupancy story: mostly "full"
        # means traffic saturates max_batch; mostly "age" means max_wait_ms
        # is the binding constraint (docs/OBSERVABILITY.md).
        self._m_flush = reg.counter(
            "serve_batch_flushes_total", "batcher flushes by cause",
            labels=("cause", "pool"))
        self._m_waiting = reg.gauge(
            "serve_batcher_waiting", "entries held in batcher buckets",
            labels=("pool",))

    def __len__(self) -> int:
        return sum(len(v) for v in self._waiting.values())

    def add(self, entry: Entry, now_ms: float) -> None:
        key = self.key_fn(entry)
        group = self._waiting.setdefault(key, [])
        if not group:
            self._oldest_ms[key] = now_ms
        group.append(entry)
        self._m_waiting.labels(pool=self.pool).set(len(self))

    def next_flush_ms(self) -> Optional[float]:
        """Earliest future time a waiting bucket ages out (None when empty).
        Full buckets flush immediately via ``ready``, so only age matters."""
        if not self._oldest_ms:
            return None
        return min(self._oldest_ms.values()) + self.max_wait_ms

    # -- SLO-scheduler accessors (serve.scheduling) ------------------------
    # The engine's preemption and deadline-jump passes need to look inside
    # (and surgically edit) the waiting buckets; these keep the dict
    # private while exposing exactly what the scheduler reads.

    def entries(self):
        """Iterate every waiting entry (bucket order, arrival order)."""
        for group in self._waiting.values():
            yield from group

    def waiting_keys(self) -> List[Tuple]:
        return list(self._waiting)

    def group(self, key: Tuple) -> List[Entry]:
        return list(self._waiting.get(key, ()))

    def group_flush_at(self, key: Tuple) -> Optional[float]:
        """When this bucket would age out naturally (None if absent)."""
        if key not in self._oldest_ms:
            return None
        return self._oldest_ms[key] + self.max_wait_ms

    def remove_if(self, pred: Callable[[Entry], bool]) -> List[Entry]:
        """Remove (and return) every waiting entry matching ``pred`` —
        the phase-boundary preemption hook: parked entries leave the
        bucket; the survivors keep their bucket's age (a preemption must
        never *delay* the work it was meant to favor)."""
        removed: List[Entry] = []
        for key in list(self._waiting):
            keep: List[Entry] = []
            took: List[Entry] = []
            for e in self._waiting[key]:
                (took if pred(e) else keep).append(e)
            if not took:
                continue
            removed.extend(took)
            if keep:
                self._waiting[key] = keep
            else:
                del self._waiting[key]
                del self._oldest_ms[key]
        if removed:
            self._m_waiting.labels(pool=self.pool).set(len(self))
        return removed

    def flush_key(self, key: Tuple, now_ms: float) -> List[Batch]:
        """Flush one bucket immediately (the deadline-jump path): the
        engine decided its entries cannot afford to age out. Counted as
        its own flush cause (``urgent``)."""
        out: List[Batch] = []
        while key in self._waiting:
            b = self._pop(key, self.max_batch, now_ms)
            b.urgent = True
            out.append(b)
            self._m_flush.labels(cause="urgent", pool=self.pool).inc()
        return out

    def _pop(self, key: Tuple, n: int, now_ms: float) -> Batch:
        group = self._waiting[key]
        taken, rest = group[:n], group[n:]
        if rest:
            self._waiting[key] = rest
            self._oldest_ms[key] = now_ms  # age restarts for the remainder
        else:
            del self._waiting[key]
            del self._oldest_ms[key]
        self._m_waiting.labels(pool=self.pool).set(len(self))
        return Batch(batch_key=key, entries=taken, created_ms=now_ms)

    def ready(self, now_ms: float) -> List[Batch]:
        """Flush every bucket that is full or has aged past max-wait."""
        out: List[Batch] = []
        for key in list(self._waiting):
            while key in self._waiting and \
                    len(self._waiting[key]) >= self.max_batch:
                out.append(self._pop(key, self.max_batch, now_ms))
                self._m_flush.labels(cause="full", pool=self.pool).inc()
            # Same arithmetic as next_flush_ms (oldest + max_wait), NOT
            # `now - oldest >= max_wait`: the two can disagree in the last
            # float ulp, and the engine advances its virtual clock to
            # exactly next_flush_ms when idle — a mismatch leaves a bucket
            # forever "almost aged" and the loop spinning (surfaced by the
            # soak drill's long virtual horizons).
            if key in self._waiting and \
                    now_ms >= self._oldest_ms[key] + self.max_wait_ms:
                out.append(self._pop(key, self.max_batch, now_ms))
                self._m_flush.labels(cause="age", pool=self.pool).inc()
        out.sort(key=lambda b: min(e.seq for e in b.entries))
        return out

    def flush_all(self, now_ms: float) -> List[Batch]:
        """Drain everything (end of trace / shutdown)."""
        out: List[Batch] = []
        for key in list(self._waiting):
            while key in self._waiting:
                out.append(self._pop(key, self.max_batch, now_ms))
                self._m_flush.labels(cause="drain", pool=self.pool).inc()
        out.sort(key=lambda b: min(e.seq for e in b.entries))
        return out
