"""Request schema + admission-time validation for the serving layer.

A :class:`Request` is the JSONL unit of work the serve loop consumes: one
generation (``prompt``) or one prompt-to-prompt edit (``prompt`` +
``target``), with the same knobs the CLI exposes per run (mode, windows,
equalizer, seed, steps, scheduler, gate, negative prompt) plus the
request-level fields the one-shot CLI has no use for: arrival time, a
deadline, a priority, and a stable ``request_id``.

Validation happens at admission, not dispatch: a request that can never run
(bad mode/scheduler, a gate spec ``engine.sampler.resolve_gate`` rejects, a
controller the factory can't build) is rejected with a reason before it
costs queue capacity — the same controller factory and gate checks the CLI
path uses (``cli.controller_from_opts`` / ``resolve_gate``), so the serve
surface can never accept a spec the direct surface would refuse.

:func:`prepare` also derives the keys the batcher runs on:

- ``compile_key`` — everything that changes the XLA program: steps,
  scheduler kind, resolved gate step, group batch (1 or 2 prompts), and the
  controller's *structure* (pytree treedef + leaf shapes/dtypes — edit
  values are traced leaves and deliberately absent).
- ``batch_key`` — ``compile_key`` plus the values that are traced but
  *shared* across a sweep call (guidance scale): requests may share a
  compiled program yet not a batch.

- ``content_key`` — the *semantic cache* address (ISSUE 13): every field
  that determines the request's **output images** — prompts, edit values,
  seed, steps, scheduler, guidance, negative prompt, resolved gate step —
  and nothing that doesn't (``request_id``, arrival/deadline, priority,
  tenant, tier are pure scheduling metadata). Two requests sharing a
  content key produce bitwise-identical images, so one may be served the
  other's result; a field missing from the key would serve *wrong* images
  (cache poisoning), a superfluous one would split identical traffic
  (lost hits). The ``OUTPUT_DETERMINING`` sweep in
  ``analysis.compile_key`` guards both directions per field, the same
  completeness idiom that covers ``compile_key``.

Gated requests (resolved gate step < scan length) additionally carry the
**per-phase** keys of the disaggregated program pools:

- ``phase1_key`` — the phase-1 pool program (full CFG + controller hooks,
  steps ``[0, gate)``, returns the hand-off carry): the monolithic
  compile key behind a ``"phase1"`` tag — every component shapes phase 1.
- ``phase2_key`` — the phase-2 pool program (single-branch U-Net off the
  carry, steps ``[gate, S)`` + decode): the controller component is the
  *phase-2 slice* (``engine.sampler.phase2_controller``) — attention-edit
  structure is gone past the gate, so e.g. ``replace`` and ``refine``
  edits share ONE phase-2 program and their lanes pack together.
- ``phase2_batch_key`` — ``phase2_key`` + guidance, the phase-2 pool's
  batching key: lanes from *different requests* (different phase-1
  batches, even different edit modes) co-batch here.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Optional, Tuple

from . import scheduling

_SCHEDULERS = ("ddim", "plms", "dpm")
_MODES = ("replace", "refine")

#: The partition of Request fields by OUTPUT identity (ISSUE 13).
#: ``CONTENT_FIELDS`` determine the images a request produces and feed the
#: semantic-cache ``content_key``; ``SCHEDULING_FIELDS`` never do (they
#: decide *when/whether* a request runs, not *what* it computes). The two
#: tuples must cover the schema exactly — ``content_key`` errors on a
#: field in neither, so extending the schema forces a cache-identity
#: decision (the compile-key completeness discipline).
CONTENT_FIELDS = ("prompt", "target", "mode", "cross_steps", "self_steps",
                  "blend_words", "equalizer", "blend_resolution", "seed",
                  "steps", "scheduler", "guidance", "negative_prompt",
                  "gate", "schedule")
SCHEDULING_FIELDS = ("request_id", "arrival_ms", "deadline_ms", "priority",
                     "tenant", "tier")


@dataclasses.dataclass(frozen=True)
class Request:
    """One unit of serving work. ``target=None`` is pure generation; a
    ``target`` makes it a 2-prompt edit group (source lane + edited lane,
    the CLI ``edit`` semantics)."""

    request_id: str
    prompt: str
    target: Optional[str] = None
    mode: str = "refine"
    cross_steps: float = 0.8
    self_steps: float = 0.4
    blend_words: Optional[str] = None
    equalizer: Optional[str] = None
    blend_resolution: int = 16
    seed: int = 8191
    steps: int = 50
    scheduler: str = "ddim"
    guidance: float = 7.5
    negative_prompt: Optional[str] = None
    gate: Any = None            # None | 'auto' | float fraction | int step
    # Per-site per-step reuse schedule (ISSUE 15): a JSON spec object
    # (engine.reuse.validate_spec), the generalized gate — mutually
    # exclusive with ``gate``. The RESOLVED static table joins the
    # compile/content keys (identical tables from different files pool;
    # a one-cell difference splits); the uniform table normalizes onto
    # the plain gate path and pools with gate=g traffic.
    schedule: Any = None
    arrival_ms: float = 0.0     # virtual trace time (loadgen / replay)
    deadline_ms: Optional[float] = None  # relative to arrival; None = none
    priority: int = 0           # higher dispatches first (within a tier)
    # SLO scheduling metadata (serve.scheduling): who the request belongs
    # to and what latency class it bought. Pure scheduler inputs — they
    # never join a compile key (tiers must not fragment programs) and,
    # absent, the whole SLO layer is byte-invisible (to_dict drops None).
    tenant: Optional[str] = None   # quota/fair-share identity
    tier: Optional[str] = None     # one of scheduling.TIERS

    @property
    def prompts(self) -> Tuple[str, ...]:
        return (self.prompt,) if self.target is None else (self.prompt,
                                                           self.target)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        return {k: v for k, v in d.items() if v is not None}

    @classmethod
    def from_dict(cls, d: dict) -> "Request":
        """Build a Request from a JSONL record, rejecting unknown keys (the
        honored-flags discipline: a typo'd field must error, not silently
        do nothing)."""
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - fields
        if unknown:
            raise ValueError(f"unknown request field(s) {sorted(unknown)}; "
                             f"valid: {sorted(fields)}")
        if "request_id" not in d or "prompt" not in d:
            raise ValueError("request needs 'request_id' and 'prompt'")
        return cls(**d)


@dataclasses.dataclass(frozen=True)
class Cancel:
    """Control record: cancel a previously submitted request by id (only
    guaranteed before its batch dispatches)."""

    request_id: str


def parse_jsonl_line(line: str):
    """One serve-input line → :class:`Request` or :class:`Cancel` (a line of
    the form ``{"cancel": "<id>"}``), or ``None`` for a blank line."""
    line = line.strip()
    if not line:
        return None
    d = json.loads(line)
    if not isinstance(d, dict):
        raise ValueError(f"request line must be a JSON object, got {d!r}")
    if set(d) == {"cancel"}:
        return Cancel(request_id=str(d["cancel"]))
    return Request.from_dict(d)


def _structural_validate(req: Request) -> None:
    if not req.request_id:
        raise ValueError("empty request_id")
    if not req.prompt:
        raise ValueError("empty prompt")
    if req.steps < 1:
        raise ValueError(f"steps must be >= 1, got {req.steps}")
    if req.scheduler not in _SCHEDULERS:
        raise ValueError(f"unknown scheduler {req.scheduler!r}; "
                         f"valid: {', '.join(_SCHEDULERS)}")
    if req.mode not in _MODES:
        raise ValueError(f"unknown mode {req.mode!r}; valid: "
                         f"{', '.join(_MODES)}")
    if req.target is None and (req.blend_words or req.equalizer):
        raise ValueError("blend_words/equalizer need a 'target' edit prompt")
    if req.deadline_ms is not None and req.deadline_ms <= 0:
        raise ValueError(f"deadline_ms must be positive, got {req.deadline_ms}")
    if isinstance(req.gate, str) and req.gate != "auto":
        raise ValueError(f"gate must be null, 'auto', a fraction or a step "
                         f"index, got {req.gate!r}")
    if req.schedule is not None:
        if req.gate is not None:
            raise ValueError("gate and schedule are mutually exclusive: a "
                             "reuse schedule generalizes the gate")
        from ..engine.reuse import validate_spec

        # Structural (layout-free) validation at admission — resolution
        # against the model's site layout happens in prepare().
        validate_spec(req.schedule)
    # Scheduling metadata is validated HERE, at admission, so a bad value
    # is a clean schema reject — never a TypeError inside the queue's sort
    # comparator three stages later (bool is an int subclass and would
    # sort, but it is always a caller bug: rejected explicitly).
    if isinstance(req.priority, bool) or not isinstance(req.priority, int):
        raise ValueError(f"priority must be an int, "
                         f"got {type(req.priority).__name__} "
                         f"{req.priority!r}")
    if abs(req.priority) > scheduling.PRIORITY_BOUND:
        raise ValueError(f"priority must be within "
                         f"±{scheduling.PRIORITY_BOUND}, got {req.priority}")
    if req.tenant is not None:
        if not isinstance(req.tenant, str) or not req.tenant:
            raise ValueError(f"tenant must be a non-empty string, "
                             f"got {req.tenant!r}")
        if len(req.tenant) > scheduling.TENANT_MAX_LEN:
            raise ValueError(f"tenant id longer than "
                             f"{scheduling.TENANT_MAX_LEN} chars")
    if req.tier is not None and req.tier not in scheduling.TIERS:
        raise ValueError(f"unknown tier {req.tier!r}; valid: "
                         f"{', '.join(scheduling.TIERS)}")


def controller_signature(controller) -> Tuple:
    """The controller's *static* program identity: pytree structure + leaf
    shapes/dtypes. Edit values (equalizer scales, window schedules,
    thresholds) are traced leaves and must NOT appear here — two requests
    whose controllers differ only in values share one compiled program."""
    if controller is None:
        return ("none",)
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(controller)
    return (str(treedef),
            tuple((tuple(x.shape), str(getattr(x, "dtype", type(x).__name__)))
                  for x in leaves))


def content_key(req: Request, gate_step: int, model_name: str,
                sched_key: Optional[Tuple] = None) -> Tuple:
    """The semantic-cache address: every output-determining field, nothing
    else (ISSUE 13). Keyed on the *resolved* gate step, not the raw spec —
    ``gate=0.5`` and ``gate=2`` at ``steps=4`` run the identical
    trajectory and must share one cache line. Edit knobs (mode, windows,
    blend, equalizer) only shape the output when a ``target`` builds a
    controller, so a pure generation normalizes them away — two
    generations differing only in an ignored ``mode`` are the same
    traffic. Errors if the schema grew a field outside the declared
    CONTENT/SCHEDULING partition: a new field must decide its cache
    identity before it can ride a cached serve."""
    declared = set(CONTENT_FIELDS) | set(SCHEDULING_FIELDS)
    fields = {f.name for f in dataclasses.fields(Request)}
    if fields != declared:
        raise ValueError(
            f"Request fields {sorted(fields ^ declared)} are missing from "
            "the CONTENT_FIELDS/SCHEDULING_FIELDS partition: decide "
            "whether they determine the output before caching can serve "
            "this schema")
    edit = (None if req.target is None else
            (req.target, req.mode, float(req.cross_steps),
             float(req.self_steps), req.blend_words, req.equalizer,
             int(req.blend_resolution)))
    # ``sched_key`` is the RESOLVED reuse table (engine.reuse key form),
    # not the raw spec: specs that resolve identically (fraction vs step,
    # different files) share a cache line, and the uniform table (None
    # here) shares one with plain gate=g traffic.
    return ("content", model_name, req.prompt, edit, int(req.seed),
            int(req.steps), req.scheduler, float(req.guidance),
            req.negative_prompt, int(gate_step), sched_key)


@dataclasses.dataclass(frozen=True)
class PreparedRequest:
    """A validated request bound to a pipeline: controller built, gate
    resolved, batching keys derived (monolithic + per-phase pool keys),
    plus the semantic-cache ``content_key`` (always derived — a pure
    tuple — but only *read* when a ``SemCache`` is active)."""

    request: Request
    controller: Any
    gate_step: int
    scan_steps: int
    compile_key: Tuple
    batch_key: Tuple
    phase1_key: Optional[Tuple] = None      # None = ungated (single-pool)
    phase2_key: Optional[Tuple] = None
    phase2_batch_key: Optional[Tuple] = None
    content_key: Optional[Tuple] = None
    #: The resolved reuse table (engine.reuse.ReuseSchedule) — None when
    #: the request has no schedule or it normalized to the uniform gate.
    #: The hand-off carry template and the runners read the TABLE from
    #: here/the keys; the raw spec never leaves the Request.
    schedule: Any = None

    @property
    def gated(self) -> bool:
        """Does this request cross the phase gate (and therefore the
        hand-off) when served through the disaggregated pools?"""
        return self.gate_step < self.scan_steps


def prepare(req: Request, pipe) -> PreparedRequest:
    """Validate ``req`` against ``pipe`` and derive its batching keys.

    Raises ``ValueError`` with a human-readable reason on any spec the
    direct CLI path would also refuse — reusing the CLI's controller
    factory (``cli.controller_from_opts``) and the sampler's gate
    resolution/validation (``engine.sampler.resolve_gate``)."""
    _structural_validate(req)

    from ..cli import controller_from_opts
    from ..engine import reuse as reuse_mod
    from ..engine.sampler import resolve_reuse
    from ..models.config import unet_layout
    from ..ops import schedulers as sched_mod

    controller = None
    if req.target is not None:
        controller = controller_from_opts(
            list(req.prompts), pipe.tokenizer, req.steps,
            mode=req.mode, cross_steps=req.cross_steps,
            self_steps=req.self_steps, blend_words=req.blend_words,
            equalizer=req.equalizer, blend_resolution=req.blend_resolution)

    # Same scan length the sampler will run (PLMS warm-up adds one step).
    schedule = sched_mod.schedule_from_config(req.steps, pipe.config.scheduler,
                                              kind=req.scheduler)
    scan_steps = int(schedule.timesteps.shape[0])
    # ``resolve_reuse`` is the same gate/schedule resolution every sampling
    # surface uses: it rejects gate+schedule, resolves the spec against the
    # model's site layout, normalizes a UNIFORM table to the plain gate
    # (``reuse=None`` — pools with gate=g traffic) and fires the per-site
    # window-conflict warning for non-uniform tables.
    layout = unet_layout(pipe.config.unet)
    gate_step, reuse_sched = resolve_reuse(req.gate, req.schedule, layout,
                                           scan_steps, controller)
    sched_key = None if reuse_sched is None else reuse_sched.key()

    compile_key = (pipe.config.name, req.steps, req.scheduler, gate_step,
                   len(req.prompts), controller_signature(controller),
                   sched_key)
    batch_key = compile_key + (float(req.guidance),)
    phase1_key = phase2_key = phase2_batch_key = None
    if gate_step < scan_steps:
        from ..engine.sampler import phase2_controller

        # Phase 1 is shaped by everything the monolithic program is; phase 2
        # only by what survives the gate — the reduced controller slice.
        # Conservative components (steps AND gate) stay in both keys: the
        # compile-key completeness sweep (analysis.compile_key) guards both
        # directions per field, and a gate change that altered a phase
        # program without its key would be cache poisoning. The SCHEDULE
        # component is per-phase PROJECTED (engine.reuse.phase{1,2}_view):
        # a table cell that only moves a phase-1 flip must not split the
        # phase-2 pool — lanes from schedules differing only before the
        # boundary still pack into one phase-2 program.
        # A projection that collapses to the UNIFORM table is the plain
        # gate=g phase program — its key component normalizes to None so
        # e.g. a schedule whose only non-uniformity is a phase-1 flip
        # packs its phase-2 lanes with plain-gate traffic (the views
        # preserve the carry's leaf set, so the pooled program's hand-off
        # pytree matches structurally too).
        def view_key(view_fn):
            if reuse_sched is None:
                return None
            view = view_fn(reuse_sched)
            return None if view.uniform_gate is not None else view.key()

        key1 = view_key(reuse_mod.phase1_view)
        key2 = view_key(reuse_mod.phase2_view)
        phase1_key = ("phase1", pipe.config.name, req.steps, req.scheduler,
                      gate_step, len(req.prompts),
                      controller_signature(controller), key1)
        phase2_key = ("phase2", pipe.config.name, req.steps, req.scheduler,
                      gate_step, len(req.prompts),
                      controller_signature(phase2_controller(controller)),
                      key2)
        phase2_batch_key = phase2_key + (float(req.guidance),)
    return PreparedRequest(request=req, controller=controller,
                           gate_step=gate_step, scan_steps=scan_steps,
                           compile_key=compile_key, batch_key=batch_key,
                           phase1_key=phase1_key, phase2_key=phase2_key,
                           phase2_batch_key=phase2_batch_key,
                           content_key=content_key(req, gate_step,
                                                   pipe.config.name,
                                                   sched_key),
                           schedule=reuse_sched)
