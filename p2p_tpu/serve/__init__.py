"""Request-level serving: queue → dynamic batcher → program cache → worker.

The one-shot entry points (``engine.sampler.text2image``, ``parallel.sweep``)
serve a single caller; this package serves *traffic*: JSONL requests ride a
bounded admission queue, compatible requests batch by compile key (padded to
a fixed bucket set so the program count stays bounded), compiled programs
are cached and compiled ahead of traffic, and a single-threaded worker loop
drains batches while emitting one structured record per request. The
fault-tolerance layer — crash-safe journal + replay (``journal``), typed
failure classification with bounded retries (``faults``), a dispatch-time
watchdog, post-run output validation, graceful degradation under pressure,
and the deterministic fault-injection harness (``chaos``) — rides the same
loop and is fully off by default. The lifecycle layer (``lifecycle`` +
``journal.compact``) adds the orderly half: graceful drain on
SIGTERM/SIGINT, periodic journal snapshot/compaction, and warm restart
from snapshot + WAL tail. See docs/SERVING.md.
"""

from .batcher import BUCKET_SIZES, DynamicBatcher, bucket_for
from .chaos import FaultPlan, SimulatedKill
from .elastic import ElasticConfig, ElasticController, parse_elastic
from .engine_loop import DegradeConfig, serve_forever
from .faults import InjectedFault, RetryPolicy, WatchdogTimeout, classify
from .handoff import HandoffEntry
from .journal import Journal, ReplayState, replay
from .lifecycle import DrainController, signal_drain
from .meshing import MeshSpec, parse_mesh
from .programs import ProgramCache
from .queue import AdmissionQueue, Rejected
from .request import Cancel, Request, content_key, parse_jsonl_line, prepare
from .scheduling import TIERS, FairClock, SloConfig
from .semcache import SemCache

__all__ = [
    "AdmissionQueue",
    "BUCKET_SIZES",
    "Cancel",
    "DegradeConfig",
    "DrainController",
    "DynamicBatcher",
    "ElasticConfig",
    "ElasticController",
    "FairClock",
    "FaultPlan",
    "HandoffEntry",
    "InjectedFault",
    "Journal",
    "MeshSpec",
    "ProgramCache",
    "Rejected",
    "ReplayState",
    "Request",
    "RetryPolicy",
    "SemCache",
    "SimulatedKill",
    "SloConfig",
    "TIERS",
    "WatchdogTimeout",
    "bucket_for",
    "classify",
    "content_key",
    "parse_elastic",
    "parse_jsonl_line",
    "parse_mesh",
    "prepare",
    "replay",
    "serve_forever",
    "signal_drain",
]
