"""Request-level serving: queue → dynamic batcher → program cache → worker.

The one-shot entry points (``engine.sampler.text2image``, ``parallel.sweep``)
serve a single caller; this package serves *traffic*: JSONL requests ride a
bounded admission queue, compatible requests batch by compile key (padded to
a fixed bucket set so the program count stays bounded), compiled programs
are cached and compiled ahead of traffic, and a single-threaded worker loop
drains batches while emitting one structured record per request. See
docs/SERVING.md.
"""

from .batcher import BUCKET_SIZES, DynamicBatcher, bucket_for
from .engine_loop import serve_forever
from .programs import ProgramCache
from .queue import AdmissionQueue, Rejected
from .request import Cancel, Request, parse_jsonl_line, prepare

__all__ = [
    "AdmissionQueue",
    "BUCKET_SIZES",
    "Cancel",
    "DynamicBatcher",
    "ProgramCache",
    "Rejected",
    "Request",
    "bucket_for",
    "parse_jsonl_line",
    "prepare",
    "serve_forever",
]
