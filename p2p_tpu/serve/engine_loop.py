"""Single-threaded serve worker: admit → batch → dispatch → record.

The loop runs on a *virtual clock*: trace time (request ``arrival_ms``,
batcher age-out, deadlines) advances either to the next event (an arrival
or a bucket aging out) or by the measured wall time of each dispatched
batch. That makes the control flow — admission order, bucketing, deadline
expiry, backpressure — fully deterministic for a given trace and runner,
while latency numbers stay real measurements. A JSONL file replay, the
bench ``serve`` rehearsal, and the tests all ride the same loop.

Every submitted request resolves to exactly ONE structured record:

- ``ok`` — served; carries ``images`` (B, H, W, 3) uint8 plus the latency
  split: ``queue_wait_ms`` (arrival → dispatch), ``compile_ms`` (its
  batch's program build/warm cost, 0 on a program-cache hit), ``run_ms``
  (batch execution), ``total_ms``; plus ``batch_lanes`` (padded bucket),
  ``batch_occupancy`` (real lanes), ``cache_hit``.
- ``rejected`` — failed validation or backpressure; ``reason`` says why.
- ``expired`` — deadline passed before dispatch (never runs).
- ``cancelled`` — a ``{"cancel": id}`` record landed before dispatch.
- ``error`` — the request itself poisoned a program: its batch failed, the
  survivors were re-run without it (isolation retry), and only this lane
  failed again. One bad request can never take its batchmates down.

A final ``summary`` record aggregates the run: counts per status, batch
count, mean occupancy, program-cache stats, latency percentiles.

The loop also feeds the telemetry registry (``p2p_tpu.obs``): request
counters by status, reject kinds, stage-latency histograms, batch
occupancy, bucket upsizing, and ``serve.batch``/``serve.prewarm``/
``serve.isolate_retry`` spans — the registry is the cross-run Prometheus/
JSONL surface (``p2p-tpu serve --metrics-out/--events-out``), while the
record stream above stays the stable per-request contract; the summary's
p50/p95 (raw lists) and the registry histograms must agree within one
bucket (tests/test_obs.py pins this reconciliation).
"""

from __future__ import annotations

import time
from typing import Callable, Iterable, Iterator, List, Optional

from ..obs import metrics as obs_metrics
from ..obs.spans import span
from . import queue as queue_mod
from .batcher import BUCKET_SIZES, Batch, DynamicBatcher, bucket_for
from .programs import ProgramCache, default_runner_factory
from .queue import AdmissionQueue, Rejected
from .request import Cancel, PreparedRequest, Request, prepare


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list (0 when empty) —
    tiny and dependency-free; good enough for latency reporting."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, int(round(
        q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class _Trace:
    """Pull-parser over the request stream; enforces sorted arrivals."""

    def __init__(self, items: Iterable):
        self._it = iter(items)
        self._next = None
        self._last_arrival = float("-inf")
        self._advance()

    def _advance(self) -> None:
        try:
            item = next(self._it)
        except StopIteration:
            self._next = None
            return
        if isinstance(item, dict):
            item = (Cancel(str(item["cancel"])) if set(item) == {"cancel"}
                    else Request.from_dict(item))
        if isinstance(item, Request):
            if item.arrival_ms < self._last_arrival:
                raise ValueError(
                    f"request {item.request_id!r} arrives at "
                    f"{item.arrival_ms}ms, after a {self._last_arrival}ms "
                    "arrival — the trace must be sorted by arrival_ms")
            self._last_arrival = item.arrival_ms
        self._next = item

    def peek(self):
        return self._next

    def pop(self):
        item = self._next
        self._advance()
        return item

    @property
    def next_arrival_ms(self) -> Optional[float]:
        if self._next is None:
            return None
        return getattr(self._next, "arrival_ms", self._last_arrival)


def _pick_bucket(n: int, compile_key, max_batch: int,
                 cache: ProgramCache) -> int:
    """Smallest bucket that fits — unless a larger bucket for the same
    compile key is already warm, in which case pad up to it: a few wasted
    lanes beat compiling (and caching) one more program."""
    smallest = bucket_for(n, max_batch)
    for b in BUCKET_SIZES:
        if b >= smallest and b <= max_batch and (compile_key, b) in cache:
            return b
    return smallest


def serve_forever(
    pipe,
    requests: Iterable,
    *,
    max_batch: int = 8,
    max_wait_ms: float = 50.0,
    queue_cap: int = 64,
    program_cache_cap: int = 8,
    prewarm: Optional[Iterable[Request]] = None,
    progress: bool = False,
    runner_factory: Optional[Callable] = None,
    timer: Callable[[], float] = time.perf_counter,
) -> Iterator[dict]:
    """Drain ``requests`` (Request/Cancel objects or JSONL-shaped dicts,
    sorted by ``arrival_ms``) through the queue → batcher → program-cache →
    sweep pipeline; yield one record per request plus a final summary.

    ``prewarm``: representative requests whose ``(compile_key, max-bucket)``
    programs are built before the trace starts — compile-ahead, so steady
    traffic never pays a compile in-band. ``runner_factory(compile_key,
    bucket) -> runner`` and ``timer`` are injection points for tests and
    rehearsal; the defaults run real ``parallel.sweep`` batches and measure
    wall time.
    """
    from ..engine.sampler import lane_select
    from ..utils import progress as progress_mod

    make_runner = runner_factory or default_runner_factory(pipe,
                                                           progress=progress)
    queue = AdmissionQueue(queue_cap)
    batcher = DynamicBatcher(max_batch=max_batch, max_wait_ms=max_wait_ms)
    cache = ProgramCache(program_cache_cap)
    trace = _Trace(requests)

    counts = {"ok": 0, "rejected": 0, "expired": 0, "cancelled": 0,
              "error": 0}
    latencies: List[float] = []
    occupancies: List[int] = []
    batch_hits: List[bool] = []
    prewarm_ms = 0.0
    vnow = 0.0
    batch_index = 0

    # Registry-backed aggregation alongside (never instead of) the JSONL
    # records: the per-request record schema is the stable contract, the
    # registry is the cross-run timeline (docs/OBSERVABILITY.md). Stage
    # histograms bound memory — the summary still computes its percentiles
    # from the raw latency list, and the test contract is that the two
    # agree within one histogram bucket.
    reg = obs_metrics.registry()
    m_requests = reg.counter("serve_requests_total",
                             "terminal per-request records by status",
                             labels=("status",))
    m_rejects = reg.counter("serve_admission_rejects_total",
                            "admission rejections by kind", labels=("kind",))
    m_stage = {
        "queue_wait_ms": reg.histogram(
            "serve_queue_wait_ms", "arrival -> dispatch wait per request"),
        "compile_ms": reg.histogram(
            "serve_compile_ms",
            "in-band build time of the request's batch (0 on cache hit; "
            "observed once per ok lane, like the record field — sum over "
            "a batch overcounts by its occupancy)"),
        "run_ms": reg.histogram(
            "serve_run_ms", "batch execution wall time per request"),
        "total_ms": reg.histogram(
            "serve_request_total_ms", "arrival -> images latency"),
    }
    m_occupancy = reg.histogram(
        "serve_batch_occupancy", "real lanes per dispatched batch",
        buckets=tuple(float(b) for b in BUCKET_SIZES))
    m_upsized = reg.counter(
        "serve_bucket_upsized_total",
        "batches padded up to a larger warm bucket (warm-preference)")
    m_isolated = reg.counter(
        "serve_isolation_retries_total",
        "lanes re-run alone after a poisoned batch")

    def record(status: str, request_id: str, *, release: bool = True,
               **fields) -> dict:
        # release=False for admission rejections: a rejected submission was
        # never admitted, and its id may belong to a still-live earlier
        # request (duplicate-id rejection) whose capacity slot and cancel
        # marker must survive.
        counts[status] += 1
        m_requests.labels(status=status).inc()
        if status == "ok":
            for key, hist in m_stage.items():
                if key in fields:
                    hist.observe(float(fields[key]))
        if release:
            queue.release(request_id)
        return {"request_id": request_id, "status": status, **fields}

    def _build(factory, compile_key, bucket, entries):
        runner = factory(compile_key, bucket)
        warm = getattr(runner, "warm", None)
        if warm is not None:
            warm(entries)
        return runner

    if prewarm:
        t0 = timer()
        with span("serve.prewarm"):
            for req in prewarm:
                try:
                    prep = prepare(req, pipe)
                except ValueError:
                    # Prewarm is an optimization: an invalid spec here must
                    # not take the server down — the same request gets its
                    # proper 'rejected' record if/when it arrives in the
                    # trace.
                    continue
                bucket = bucket_for(max_batch, max_batch)
                entry = queue_mod.Entry(prepared=prep, arrival_ms=0.0)
                cache.get((prep.compile_key, bucket),
                          lambda p=prep, b=bucket, e=entry: _build(
                              make_runner, p.compile_key, b, [e]))
        prewarm_ms = (timer() - t0) * 1000.0

    def run_entries(entries, compile_key, guidance, bucket):
        """Run one padded batch; returns (images, compile_ms, run_ms, hit).
        The steps the compiled loop reports flow into per-request progress
        via the shared step hook."""
        runner, hit, _ = cache.get(
            (compile_key, bucket),
            lambda: _build(make_runner, compile_key, bucket, entries))
        # cache.get's build_ms times only the closure; re-derive compile_ms
        # from our own timer so injected timers see it too.
        t0 = timer()
        steps_seen = []
        if progress:
            progress_mod.set_step_hook(lambda s: steps_seen.append(int(s)))
        try:
            imgs = runner(entries, guidance)
        finally:
            if progress:
                progress_mod.set_step_hook(None)
        run_ms = (timer() - t0) * 1000.0
        return imgs, run_ms, hit, (max(steps_seen) + 1 if steps_seen else None)

    def dispatch(batch: Batch) -> Iterator[dict]:
        nonlocal vnow, batch_index
        live = []
        for e in batch.entries:
            if queue.is_cancelled(e.request_id):
                yield record("cancelled", e.request_id,
                             arrival_ms=e.arrival_ms,
                             queue_wait_ms=vnow - e.arrival_ms)
            elif queue_mod.expired(e, vnow):
                yield record(
                    "expired", e.request_id, arrival_ms=e.arrival_ms,
                    reason=(f"deadline {e.request.deadline_ms}ms passed "
                            f"before dispatch (waited "
                            f"{vnow - e.arrival_ms:.1f}ms)"))
            else:
                live.append(e)
        if not live:
            return
        batch_index += 1
        this_batch = batch_index
        guidance = live[0].request.guidance
        compile_key = live[0].prepared.compile_key
        bucket = _pick_bucket(len(live), compile_key, max_batch, cache)
        if bucket > bucket_for(len(live), max_batch):
            m_upsized.inc()  # warm-preference padded past the smallest fit
        dispatch_ms = vnow
        try:
            t0 = timer()
            with span("serve.batch", batch=this_batch, lanes=bucket,
                      occupancy=len(live)):
                imgs, run_ms, hit, steps_done = run_entries(
                    live, compile_key, guidance, bucket)
            total_ms = (timer() - t0) * 1000.0
            compile_ms = max(0.0, total_ms - run_ms)
        except Exception as exc:  # noqa: BLE001 — isolate, then re-raise per lane
            vnow += (timer() - t0) * 1000.0
            yield from isolate(live, compile_key, guidance, exc)
            return
        vnow += compile_ms + run_ms
        occupancies.append(len(live))
        # Observed only on success, next to the summary's list, so the
        # histogram and mean_batch_occupancy reconcile exactly (a poisoned
        # batch contributes to neither — its lanes re-dispatch via
        # isolate()).
        m_occupancy.observe(float(len(live)))
        batch_hits.append(hit)
        lanes = lane_select(imgs, range(len(live)))
        for i, e in enumerate(live):
            latency = vnow - e.arrival_ms
            latencies.append(latency)
            yield record(
                "ok", e.request_id, images=lanes[i],
                arrival_ms=e.arrival_ms,
                queue_wait_ms=dispatch_ms - e.arrival_ms,
                compile_ms=compile_ms, run_ms=run_ms, total_ms=latency,
                batch_id=this_batch, batch_lanes=bucket,
                batch_occupancy=len(live), cache_hit=hit,
                gate_step=e.prepared.gate_step,
                **({"steps_done": steps_done} if steps_done else {}))

    def isolate(entries, compile_key, guidance, batch_exc) -> Iterator[dict]:
        """A batch failed: re-run each lane alone so one poisoned request
        fails alone; survivors still get served (one retry each)."""
        nonlocal vnow, batch_index
        for e in entries:
            batch_index += 1
            m_isolated.inc()
            bucket = _pick_bucket(1, compile_key, max_batch, cache)
            dispatch_ms = vnow
            try:
                t0 = timer()
                with span("serve.isolate_retry", batch=batch_index,
                          lanes=bucket, request=e.request_id):
                    imgs, run_ms, hit, steps_done = run_entries(
                        [e], compile_key, guidance, bucket)
                compile_ms = max(0.0, (timer() - t0) * 1000.0 - run_ms)
            except Exception as exc:  # noqa: BLE001
                vnow += (timer() - t0) * 1000.0
                yield record(
                    "error", e.request_id, arrival_ms=e.arrival_ms,
                    reason=f"{type(exc).__name__}: {exc}",
                    batch_error=f"{type(batch_exc).__name__}: {batch_exc}")
                continue
            vnow += compile_ms + run_ms
            occupancies.append(1)
            m_occupancy.observe(1.0)  # success-only, mirroring dispatch()
            batch_hits.append(hit)
            lanes = lane_select(imgs, range(1))
            latency = vnow - e.arrival_ms
            latencies.append(latency)
            yield record(
                "ok", e.request_id, images=lanes[0],
                arrival_ms=e.arrival_ms,
                queue_wait_ms=dispatch_ms - e.arrival_ms,
                compile_ms=compile_ms, run_ms=run_ms, total_ms=latency,
                batch_id=batch_index, batch_lanes=bucket, batch_occupancy=1,
                cache_hit=hit, isolated_retry=True,
                gate_step=e.prepared.gate_step,
                **({"steps_done": steps_done} if steps_done else {}))

    while True:
        # 1. Admit everything that has arrived by now.
        while trace.peek() is not None and \
                getattr(trace.peek(), "arrival_ms", vnow) <= vnow:
            item = trace.pop()
            if isinstance(item, Cancel):
                queue.cancel(item.request_id)  # unknown id: benign no-op
                continue
            try:
                prep = prepare(item, pipe)
                queue.submit(prep, vnow)
            except (Rejected, ValueError) as e:
                reason = e.reason if isinstance(e, Rejected) else str(e)
                # Bounded-cardinality reject classification (reasons are
                # free text): backpressure kinds come off the exception,
                # spec validation is "invalid_spec".
                m_rejects.labels(
                    kind=getattr(e, "kind", "invalid_spec")).inc()
                yield record("rejected", item.request_id, release=False,
                             arrival_ms=item.arrival_ms, reason=reason)
        # 2. Feed the batcher.
        for entry in queue.drain():
            batcher.add(entry, vnow)
        # 3. Flush whatever is due.
        batches = batcher.ready(vnow)
        if not batches:
            events = [t for t in (trace.next_arrival_ms,
                                  batcher.next_flush_ms()) if t is not None]
            if events:
                vnow = max(vnow, min(events))
                continue
            batches = batcher.flush_all(vnow)  # trace done: drain the tail
            if not batches:
                break
        for batch in batches:
            yield from dispatch(batch)

    n_batches = len(occupancies)
    lat_sorted = sorted(latencies)
    yield {
        "request_id": None, "status": "summary",
        "counts": dict(counts),
        "n_batches": n_batches,
        "mean_batch_occupancy": (sum(occupancies) / n_batches
                                 if n_batches else 0.0),
        "dispatch_hit_rate": (sum(batch_hits) / len(batch_hits)
                              if batch_hits else 0.0),
        "program_cache": cache.stats(),
        "prewarm_ms": prewarm_ms,
        "p50_ms": _percentile(lat_sorted, 50),
        "p95_ms": _percentile(lat_sorted, 95),
        "makespan_ms": vnow,
    }
