"""Single-threaded serve worker: admit → batch → dispatch → record.

The loop runs on a *virtual clock*: trace time (request ``arrival_ms``,
batcher age-out, deadlines) advances either to the next event (an arrival
or a bucket aging out) or by the measured wall time of each dispatched
batch. That makes the control flow — admission order, bucketing, deadline
expiry, backpressure, retries, degradation — fully deterministic for a
given trace and runner, while latency numbers stay real measurements. A
JSONL file replay, the bench ``serve`` rehearsal, the chaos drill and the
tests all ride the same loop.

**Phase-disaggregated continuous batching** (``phase_pools``, on by
default): PR 1 made denoising steps heterogeneous — a phase-1 step (full
CFG + controller hooks) costs ~2× a phase-2 step (single-branch U-Net off
the ``AttnCache``) — so a *gated* request no longer holds one lane for its
whole trajectory. It runs as two separately scheduled program pools with
an explicit hand-off (``serve.handoff``, the vLLM continuous-batching idea
mapped onto diffusion's phase structure):

- the **phase-1 pool** batches by the monolithic batch key and runs steps
  ``[0, gate)`` through a ``("phase1", ...)``-keyed program that returns
  the per-lane :class:`~p2p_tpu.engine.sampler.PhaseCarry`;
- each carry enters the **phase-2 batcher**, keyed by the *reduced*
  ``phase2_batch_key`` (attention-edit structure is gone past the gate),
  where lanes from different requests — different arrival times, different
  phase-1 batches, even different edit modes — pack into wide cheap
  batches at the same {1,2,4,8} buckets (default cap: one bucket above
  ``max_batch`` — a phase-2 lane carries no uncond half, so 2× the lanes
  fit the same peak footprint);
- phase-1 lanes vacate at the gate, so new admissions fill them while
  earlier requests are still denoising in phase 2.

Phase-2 flushes dispatch *before* new phase-1 work each cycle (finish
nearly-done requests first: frees outstanding slots, bounds p95). Ungated
traffic (``gate`` absent / ``off``) never touches any of this: it takes
the single-pool monolithic path bitwise-unchanged, control flow included.

Every submitted request resolves to exactly ONE structured record:

- ``ok`` — served; carries ``images`` (B, H, W, 3) uint8 plus the latency
  split: ``queue_wait_ms`` (arrival → dispatch), ``compile_ms`` (its
  batch's program build/warm cost, 0 on a program-cache hit), ``run_ms``
  (batch execution), ``total_ms``; plus ``batch_lanes`` (padded bucket),
  ``batch_occupancy`` (real lanes), ``cache_hit``. Gated requests served
  through the disaggregated pools additionally carry a ``phases`` detail
  (phase-1 batch facts, ``handoff_wait_ms``, phase-2 batch facts);
  ``compile_ms``/``run_ms`` are then the summed per-phase components and
  the batch fields describe the completing (phase-2) batch.
- ``rejected`` — failed validation or backpressure; ``reason`` says why.
- ``expired`` — deadline passed before dispatch (never runs).
- ``cancelled`` — a ``{"cancel": id}`` record landed before dispatch.
- ``error`` — the request itself poisoned a program (its batch failed, the
  survivors were re-run without it, and only this lane failed again), or a
  transient fault outlived the retry budget, or the loop drained after a
  fatal fault. One bad request can never take its batchmates down.
- ``timeout`` — the dispatch-time watchdog (``watchdog_ms``) killed a hung
  compile/execute; the program-cache entry is quarantined.
- ``invalid_output`` — the post-run finite check (``validate_outputs``)
  found NaN/Inf in this lane's latents; the image is withheld.
- ``shed`` — dropped under sustained overload at the deepest degradation
  level (see below), with a reason — never a silent drop.

A final ``summary`` record aggregates the run: counts per status, batch
count, mean occupancy, program-cache stats, latency percentiles, fault/
retry/degradation tallies, and (when journaled) the replay outcome.

Fault tolerance (``serve.faults``): a failed batch is *classified* —
``transient`` failures re-run the same batch after bounded exponential
backoff with deterministic jitter, charged to the virtual clock and capped
by the lanes' own deadlines; ``poison`` takes the pre-existing lane-
isolation retry; ``fatal`` drains the loop cleanly, resolving everything
outstanding to ``error`` records. ``journal=`` (``serve.journal.Journal``)
adds a crash-safe JSONL WAL — admitted / dispatched / terminal transitions,
fsync'd at batch boundaries — whose replay on restart reconstructs the
queue from non-terminal entries and serves each exactly once (trace ids
already terminal are deduped, corrupt trailing records are skipped with a
counter). Every record kind and EVENT sub-kind this loop writes is part
of the **declared WAL protocol**
(``p2p_tpu.analysis.protocol.DECLARED_PROTOCOL`` /
``DECLARED_EVENTS``, ISSUE 20): the write-time registry raises on an
unregistered kind, and the walcheck pass crash-tests every declared
transition at every record boundary — a new kind here must be declared
there first, or jaxcheck's ``wal`` pass and the quality gate fail. ``chaos=`` (``serve.chaos.FaultPlan``) is the deterministic
fault-injection hook, ``None`` in production. Under sustained queue
pressure (``degrade=``), the loop degrades before it rejects: force
``gate='auto'`` on gate-less requests, then shrink the max lane bucket,
then shed — every transition (and its reversal) journaled and counted.

With no journal, no chaos plan, no watchdog, no validation and no
degradation, none of the above touches a single dispatch: the loop's
control flow, compiled programs and outputs are identical to the
pre-fault-tolerance engine (pinned by tests/test_faults.py's disabled-mode
parity proof, the PR 3 discipline).

The loop also feeds the telemetry registry (``p2p_tpu.obs``): request
counters by status, reject kinds, stage-latency histograms, batch
occupancy, bucket upsizing, fault/retry/shed/replay counters, and
``serve.batch``/``serve.prewarm``/``serve.isolate_retry``/``serve.retry``/
``serve.replay`` spans — the registry is the cross-run Prometheus/JSONL
surface (``p2p-tpu serve --metrics-out/--events-out``), while the record
stream above stays the stable per-request contract; the summary's p50/p95
(raw lists) and the registry histograms must agree within one bucket
(tests/test_obs.py pins this reconciliation).

``flight=`` (an :class:`~p2p_tpu.obs.flight.FlightTracer`) adds
*request-scoped* tracing on top: every admitted request gets a trace
context (``request_id#epoch``) whose stage segments — queue wait, per-pool
compile/run, transient fault + backoff, hand-off wait, isolation re-queue —
tile its whole virtual-clock lifetime, closed into one flight record per
terminal. The context rides the journal's ``handoff`` record, so a
crash-replayed request resumed in phase 2 stitches its timeline to the
pre-crash phase-1 segments (``handoff_resumed`` link); on a fatal drain or
a watchdog kill the tracer's blackbox dumps the span-ring tail, the
in-flight contexts and a pool/queue snapshot as a post-mortem bundle.
``flight=None`` (default) is byte-invisible: the record stream, the
journal bytes and the compiled programs are identical with the tracer off
(tests/test_flight.py pins the parity; the ``trace-invisible`` jaxpr
contract pins the program half).

**Lifecycle** (``serve.lifecycle`` + ``journal.compact``): the loop can
now *stop on purpose*. A drain request (SIGTERM/SIGINT via the CLI, a
drill trigger, or a chaos ``sigterm`` fault) latches at the next cycle
boundary: admissions stop (new arrivals — and, on exit, the not-yet-
arrived trace tail — resolve to ``rejected`` with the ``draining`` kind,
not journaled as terminal: backpressure, not a resolution), both
batchers flush, in-flight work completes — including
phase-2 hand-offs — bounded by ``drain_timeout_ms`` on the wall clock
(past it: journaled leftovers stay pending for the warm restart,
un-journaled ones resolve to draining rejections), then a final journal
snapshot is taken and the summary closes the stream. ``snapshot_every_ms``
additionally compacts the WAL periodically on the virtual clock, so a
restart replays O(traffic since the last snapshot) instead of O(process
history) and resumes the snapshot's degradation level. With all three
off (the default), not a record, journal byte or program changes.

**Mesh-parallel serving** (``mesh='dp=N'``, ``serve.meshing``): the
engine goes mesh-native without changing its control flow. Lane buckets
scale to per-device sub-batches (``BUCKET_SIZES · dp`` — a dispatched
bucket lands as whole lanes per device under a ``NamedSharding`` on the
group axis), the device count and mesh shape join every program-cache
key, both phase pools dispatch sharded (phase 2's wide cheap batches are
exactly the pool that spans devices: its cap scales to
``phase2_max_batch · dp``), and hand-off carries are staged to their
target shard device-to-device — the transfer-guard("disallow") contract
holds on mesh dispatch too. Durability and determinism are mesh-agnostic
by construction: the journal, snapshots, drain and crash-resume paths
carry no device topology, so every drill passes unchanged at any ``dp``
and a WAL written on one mesh shape restarts on another. ``dp=1`` is
bitwise-identical to ``mesh=None`` (quality-gate ``mesh_parity``); the
summary gains a ``mesh`` block and the registry per-device
``serve_mesh_lanes_total`` only when a mesh is active.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Callable, Iterable, Iterator, List, Optional

from ..obs import device as obs_device
from ..obs import metrics as obs_metrics
from ..obs import spans as obs_spans
from ..obs.spans import span
from . import chaos as chaos_mod
from . import elastic as elastic_mod
from . import faults as faults_mod
from . import handoff as handoff_mod
from . import lifecycle as lifecycle_mod
from . import meshing as meshing_mod
from . import queue as queue_mod
from . import scheduling as scheduling_mod
from .batcher import BUCKET_SIZES, Batch, DynamicBatcher, bucket_for
from .faults import RetryPolicy
from .handoff import HandoffEntry
from .programs import ProgramCache, default_runner_factory
from .queue import AdmissionQueue, Rejected
from .request import Cancel, Request, prepare

#: Every terminal status a request can resolve to. Single-sourced from the
#: WAL module: the journal is the durability contract, so the set of
#: statuses it recognises as terminal *is* the set the loop can emit.
from .journal import TERMINAL_STATUSES  # noqa: E402  (re-export)


@dataclasses.dataclass(frozen=True)
class DegradeConfig:
    """Graceful-degradation policy: when ``queue.outstanding`` stays above
    ``depth_threshold`` for ``window_ms`` of *virtual* time, the loop steps
    one level deeper (and one level back after an equally long calm spell):

    - level 1 — force ``gate='auto'`` on gate-less requests at admission
      (cheaper phase-2 sampling; approximate results beat rejections),
    - level 2 — shrink the max lane bucket one fixed-bucket step below
      the operator's cap, floored at ``min_bucket`` and never above the
      cap (smaller batches, shorter head-of-line blocking under deadline
      pressure),
    - level 3 — shed: newly drained entries beyond the threshold resolve
      to ``shed`` records, lowest priority and newest arrivals first."""

    depth_threshold: int = 16
    window_ms: float = 2000.0
    min_bucket: int = 2

    def __post_init__(self):
        if self.depth_threshold < 1:
            raise ValueError(f"depth_threshold must be >= 1, "
                             f"got {self.depth_threshold}")
        if self.window_ms <= 0:
            raise ValueError(f"window_ms must be positive, "
                             f"got {self.window_ms}")
        if self.min_bucket not in BUCKET_SIZES:
            raise ValueError(f"min_bucket must be one of {BUCKET_SIZES}, "
                             f"got {self.min_bucket}")


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list (0 when empty) —
    tiny and dependency-free; good enough for latency reporting."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, int(round(
        q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class _Trace:
    """Pull-parser over the request stream; enforces sorted arrivals."""

    def __init__(self, items: Iterable):
        self._it = iter(items)
        self._next = None
        self._last_arrival = float("-inf")
        self._advance()

    def _advance(self) -> None:
        try:
            item = next(self._it)
        except StopIteration:
            self._next = None
            return
        if isinstance(item, dict):
            item = (Cancel(str(item["cancel"])) if set(item) == {"cancel"}
                    else Request.from_dict(item))
        if isinstance(item, Request):
            if item.arrival_ms < self._last_arrival:
                raise ValueError(
                    f"request {item.request_id!r} arrives at "
                    f"{item.arrival_ms}ms, after a {self._last_arrival}ms "
                    "arrival — the trace must be sorted by arrival_ms")
            self._last_arrival = item.arrival_ms
        self._next = item

    def peek(self):
        return self._next

    def pop(self):
        item = self._next
        self._advance()
        return item

    @property
    def next_arrival_ms(self) -> Optional[float]:
        if self._next is None:
            return None
        return getattr(self._next, "arrival_ms", self._last_arrival)


def _warm_bucket(n: int, compile_key, max_batch: int, cache: ProgramCache,
                 sizes=BUCKET_SIZES) -> Optional[int]:
    """Smallest already-compiled bucket that holds ``n`` lanes for this
    compile key (≤ ``max_batch``), or None if no warm program fits — the
    single definition of "warm" shared by dispatch padding
    (:func:`_pick_bucket`) and the deadline jump (``jump_urgent``), so
    the two sites can never drift apart on warm-preference rules."""
    smallest = bucket_for(n, max_batch, sizes)
    for b in sizes:
        if b >= smallest and b <= max_batch and (compile_key, b) in cache:
            return b
    return None


def _pick_bucket(n: int, compile_key, max_batch: int, cache: ProgramCache,
                 sizes=BUCKET_SIZES) -> int:
    """Smallest bucket that fits — unless a larger bucket for the same
    compile key is already warm, in which case pad up to it: a few wasted
    lanes beat compiling (and caching) one more program. ``sizes`` is the
    engine's active bucket set (the dp-scaled one under a mesh)."""
    warm = _warm_bucket(n, compile_key, max_batch, cache, sizes)
    return warm if warm is not None else bucket_for(n, max_batch, sizes)


def _shrunken_bucket(max_batch: int, floor: int) -> int:
    """One fixed bucket below ``max_batch``, floored at ``floor`` — the
    level-2 degradation target. Degradation must never *raise* the
    operator's cap, so a floor above ``max_batch`` clamps back to it
    (level 2 becomes a no-op rather than a grow). Operates on the
    per-device :data:`BUCKET_SIZES`; the engine scales the result by the
    mesh width, so a mesh degrades per-device like a single chip."""
    idx = BUCKET_SIZES.index(max_batch)
    return min(max_batch, max(floor, BUCKET_SIZES[max(0, idx - 1)]))


def _wider_bucket(max_batch: int) -> int:
    """One fixed bucket above ``max_batch`` (capped at the largest) — the
    phase-2 pool's default cap: a phase-2 lane carries no CFG uncond half,
    so a bucket of 2N phase-2 lanes peaks at the same U-Net batch as N
    phase-1 lanes."""
    idx = BUCKET_SIZES.index(max_batch)
    return BUCKET_SIZES[min(idx + 1, len(BUCKET_SIZES) - 1)]


def serve_forever(
    pipe,
    requests: Iterable,
    *,
    max_batch: int = 8,
    max_wait_ms: float = 50.0,
    queue_cap: int = 64,
    program_cache_cap: int = 8,
    prewarm: Optional[Iterable[Request]] = None,
    progress: bool = False,
    runner_factory: Optional[Callable] = None,
    timer: Callable[[], float] = time.perf_counter,
    journal=None,
    chaos=None,
    retry_policy: Optional[RetryPolicy] = None,
    watchdog_ms: Optional[float] = None,
    validate_outputs: bool = False,
    degrade: Optional[DegradeConfig] = None,
    phase_pools: bool = True,
    phase2_max_batch: Optional[int] = None,
    flight=None,
    lifecycle=None,
    snapshot_every_ms: Optional[float] = None,
    drain_timeout_ms: Optional[float] = None,
    mesh=None,
    slo=None,
    semcache=None,
    costscope=None,
    prodscope=None,
    elastic=None,
) -> Iterator[dict]:
    """Drain ``requests`` (Request/Cancel objects or JSONL-shaped dicts,
    sorted by ``arrival_ms``) through the queue → batcher → program-cache →
    sweep pipeline; yield one record per request plus a final summary.

    ``prewarm``: representative requests whose ``(compile_key, max-bucket)``
    programs are built before the trace starts — compile-ahead, so steady
    traffic never pays a compile in-band. ``runner_factory(compile_key,
    bucket) -> runner`` and ``timer`` are injection points for tests and
    rehearsal; the defaults run real ``parallel.sweep`` batches and measure
    wall time.

    Fault tolerance (all off by default; see the module docstring):
    ``journal`` (a ``serve.journal.Journal``) enables the crash-safe WAL +
    replay; ``chaos`` (a ``serve.chaos.FaultPlan``) injects deterministic
    faults; ``retry_policy`` bounds transient same-batch retries (defaults
    to ``RetryPolicy()``); ``watchdog_ms`` arms a wall-clock per-batch
    deadline past dispatch; ``validate_outputs`` runs the post-run finite
    check per lane; ``degrade`` enables graceful degradation under
    sustained queue pressure.

    ``phase_pools`` enables phase-disaggregated continuous batching for
    *gated* requests (see the module docstring); ``phase_pools=False`` is
    the single-pool baseline (every request runs its monolithic program —
    the pre-disaggregation engine, kept for A/B benching). Ungated traffic
    is single-pool either way, bitwise-unchanged. ``phase2_max_batch``
    caps the phase-2 pool's lane bucket (default: one fixed bucket above
    ``max_batch`` — same peak U-Net footprint, since phase-2 lanes carry
    no CFG uncond half).

    ``flight`` (an ``obs.flight.FlightTracer``, default None = off) enables
    request-scoped flight tracing: per-request stage timelines, the
    Chrome-trace export and the blackbox post-mortem (see the module
    docstring). Tracing is a pure sidecar — it never changes a record, a
    journal byte, or a compiled program.

    Lifecycle (``serve.lifecycle``): ``lifecycle`` (a
    :class:`~p2p_tpu.serve.lifecycle.DrainController`) enables the
    graceful-drain protocol — once its flag latches (SIGTERM/SIGINT via
    the CLI, a drill's record-count trigger, or a chaos ``sigterm``
    fault), the loop stops admitting (new arrivals resolve to ``rejected``
    records with the ``draining`` kind, deliberately NOT journaled as
    terminal so a restart still serves a resubmission), flushes both
    batchers, completes in-flight work, takes a final journal snapshot
    and exits with its summary. ``drain_timeout_ms`` bounds the
    completion phase on the wall clock: past it the loop falls back to
    snapshot-and-exit (journaled leftovers stay pending for the warm
    restart; un-journaled ones resolve to draining rejections).
    ``snapshot_every_ms`` takes a periodic ``journal.compact`` snapshot
    on the virtual clock, keeping restart cost O(traffic since the last
    snapshot); a warm restart also resumes the snapshot's degradation
    level. All three default off and, off, change nothing (the
    disabled-mode parity contract).

    ``mesh`` (None | ``'dp=N'`` | ``serve.meshing.MeshSpec``) makes the
    engine mesh-native: lane buckets scale to per-device sub-batches
    (``BUCKET_SIZES · dp`` — ``max_batch``/``phase2_max_batch`` keep their
    per-device meaning), every dispatch runs the sharded sweep under a
    ``NamedSharding`` on the group axis, and the device count + mesh
    shape join the program-cache key (``meshing.mesh_key``). Durability
    and determinism are mesh-agnostic: the journal carries no device
    topology, so chaos/crash/drain/restart semantics are unchanged at any
    ``dp`` — and a journal written on one mesh shape restarts on another.
    ``dp=1`` is bitwise-identical to ``mesh=None``; ``dp>1`` matches at
    the repo's documented vmap tolerance (tests/test_serve_mesh.py,
    quality-gate ``mesh_parity``).

    ``slo`` (None | ``serve.scheduling.SloConfig``) enables SLO-tiered
    multi-tenant scheduling (docs/SERVING.md "SLO tiers and preemption"):
    weighted-fair admission ordering and per-tenant outstanding quotas on
    the queue (reject kind ``quota``); tier-pure batches (the tier joins
    the *batch* key only — compiled programs are shared across tiers) and
    tier-ordered dispatch; phase-boundary preemption (under pressure,
    lower-tier work parked between its phases spills its carry via the
    journal's hand-off path with a ``preempted`` WAL record and resumes
    when pressure clears — a preempted-then-killed request resumes off
    the spill exactly like a crashed hand-off); deadline-aware batching
    (urgent requests flush immediately onto an already-*warm* bucket
    instead of aging out); and per-tier degradation (the force-gate →
    bucket-shrink →
    shed ladder sheds best-effort before touching paid tiers, and
    ``protect_gate_tiers`` are exempt from the level-1 force-gate).
    ``slo=None`` (the default) changes nothing — not a record byte, a
    journal line, a compiled program or a metric family (the same
    disabled-mode discipline as chaos/flight/mesh).

    ``semcache`` (None | ``serve.semcache.SemCache``) enables
    content-addressed semantic caching (ISSUE 13, docs/SERVING.md
    "Semantic caching"): requests are addressed by their
    ``content_key`` (every output-determining field) and served from
    three layers — L1 text-encoder outputs inside the runners, L2
    phase-1 carry prefixes (a prefix hit enters the engine directly in
    phase 2, riding the hand-off resume path), and L3 exact results
    (bitwise, with single-flight collapsing: identical in-flight
    requests ride one leader and each follower still gets its own
    terminal record and flight trace). L3 inserts are journaled
    (``cache`` records) so a restart reseeds the cache and serves a
    killed leader's followers without recompute; under degradation the
    L2 spill disk is shed *before* any request is. ``semcache=None``
    (the default) changes nothing — not a record byte, a journal line,
    a compiled program or a metric family.

    ``costscope`` (None | ``obs.costmodel.CostScope``) enables the cost
    observatory (ISSUE 14, docs/OBSERVABILITY.md "Cost observatory"):
    every ``ProgramCache`` miss lowers+compiles the program's cost card
    (XLA ``cost_analysis``/``memory_analysis`` → flops, bytes, roofline
    verdict, model-predicted ms) with the miss's ``compile_ms`` split
    into ``build`` (lowering + XLA compile) vs ``warm`` (warm-up
    execution); every dispatch contributes a measured-MFU observation
    (``flops ÷ run_s ÷ peak``); flight ``run`` segments gain
    ``predicted_ms``/``mfu_pct`` attribution when a tracer is also
    armed; and the summary gains a ``cost`` block. The per-request
    record stream stays byte-identical either way — cost facts live in
    the summary, the metrics registry and the ``--programs-out``
    artifact, never in a request record or journal line.
    ``costscope=None`` (the default) changes nothing, same discipline
    as the other sidecars.

    ``prodscope`` (None | ``obs.prodscope.ProdScope``) enables in-engine
    sampled device profiling (ISSUE 18, docs/OBSERVABILITY.md
    "Production profiling"): a deterministic seeded per-pool sampling
    plan picks every Nth dispatch to run under a programmatic
    ``jax.profiler`` capture into a bounded on-disk trace ring; at each
    batch-boundary sync the stopped captures are folded (via the
    compiled programs' HLO op→site index) into a durable mergeable
    WorkloadProfile ledger — the seed artifact ``schedule_search
    --profile`` and ``perfscope --sites`` consume — and EWMA drift
    sentinels compare measured ms / site shares / MFU against their
    running baselines, journaling ``profile_drift`` events and feeding
    the ``serve_profile_drift`` gauges. The summary gains a ``profile``
    block. Profile facts never enter a request record; drift events are
    the ONLY journal addition, and only under an active scope.
    ``prodscope=None`` (the default) changes nothing — records, journal
    and compiled programs byte-identical (the quality gate's
    ``profile_parity`` leg pins it).

    ``elastic`` (None | ``True`` | ``'k=v,...'`` | ``serve.elastic.
    ElasticConfig``) enables elastic mesh serving (ISSUE 19,
    docs/SERVING.md "Elastic meshes"): an
    :class:`~p2p_tpu.serve.elastic.ElasticController` watches queue
    pressure through the degradation ladder's windowed detector run in
    both directions (separate up/down sustain windows + a cooldown, so
    the two can't flap) and the engine executes a journaled resize
    protocol at batch boundaries — prewarm the target topology's
    programs out-of-band, park in-flight phase-2 hand-offs via the
    preemption spill path, journal a ``resize`` event (old/new dp +
    parked ids), fsync, swap the mesh/runner-factory/bucket tables, and
    resume the parked carries restaged onto the new shards. A restart
    that lands between the durable ``resize`` record and cutover
    completion (the ``kill_during_resize`` chaos window) resumes on the
    WAL-recorded *target* topology. Elastic implies a mesh: with
    ``mesh=None`` the engine starts at ``dp=1`` (bitwise-identical to
    the mesh-less engine) and grows from there. ``elastic=None`` (the
    default) changes nothing — records, journal bytes and compiled
    programs byte-identical (the quality gate's ``elastic`` leg pins
    it).
    """
    from ..engine.sampler import lane_select
    from ..utils import progress as progress_mod

    # Mesh resolution first: the default runner factory and both batchers
    # are shaped by it. mesh=None keeps every value identical to the
    # pre-mesh engine (dp=1, the un-scaled bucket set, un-suffixed keys).
    mesh_spec = meshing_mod.as_spec(mesh)
    elastic_ctl = None
    if elastic is not None:
        import jax as _jax

        elastic_cfg = (
            elastic_mod.ElasticConfig() if elastic is True
            else elastic_mod.parse_elastic(elastic)
            if isinstance(elastic, str) else elastic)
        if mesh_spec is None:
            # Elastic serving is mesh-native: start at dp=1 (bitwise-
            # identical to the mesh-less engine) and let pressure grow it.
            mesh_spec = meshing_mod.MeshSpec(dp=1)
        if journal is not None and journal.replay_state.mesh_dp:
            # Mid-resize restart: the WAL's last committed ``resize``
            # record names the TARGET topology — come back on it (clamped
            # to what this machine can host), not on the width the
            # process was started with.
            mesh_spec = meshing_mod.MeshSpec(dp=min(
                int(journal.replay_state.mesh_dp),
                elastic_mod.pow2_floor(len(_jax.devices()))))
        elastic_ctl = elastic_mod.ElasticController(
            elastic_cfg, mesh_spec.dp, len(_jax.devices()))
    dp = 1 if mesh_spec is None else mesh_spec.dp
    dp0 = dp
    jmesh = None if mesh_spec is None else meshing_mod.build_mesh(mesh_spec)
    sizes = (BUCKET_SIZES if mesh_spec is None
             else meshing_mod.scaled_bucket_sizes(dp))
    if costscope is not None:
        # The scope scales peaks by the mesh width: a dp-sharded dispatch
        # runs its (global-batch) program across dp devices' peaks.
        costscope.devices = max(1, dp)
    if prodscope is not None:
        prodscope.devices = max(1, dp)

    def mkey(key):
        """Program-cache key for one dispatch: the mesh shape joins it so
        a mesh program can never be served to a differently-shaped mesh
        (cache poisoning by topology)."""
        return key if mesh_spec is None else meshing_mod.mesh_key(
            key, mesh_spec)

    make_runner = runner_factory or default_runner_factory(
        pipe, progress=progress, validate=validate_outputs,
        heartbeat=watchdog_ms is not None, mesh=jmesh, semcache=semcache)
    policy = retry_policy or RetryPolicy()
    queue = AdmissionQueue(queue_cap, slo=slo)
    if max_batch not in BUCKET_SIZES:
        # Validate the PER-DEVICE knob before scaling: the batcher would
        # reject max_batch*dp anyway, but its message would cite dp-scaled
        # numbers the operator never typed (and could list their actual
        # input as "valid").
        raise ValueError(f"max_batch must be one of {BUCKET_SIZES}, "
                         f"got {max_batch}")
    # Under an SloConfig the tier joins the BATCH keys only (never a
    # compile key): tiers batch apart — a premium lane never waits on
    # best-effort batchmates — while every tier still shares one compiled
    # program per bucket. slo=None keeps the historical keys bit-for-bit.
    main_key_fn = None if slo is None else (
        lambda e: e.prepared.batch_key + ("tier", slo.tier(e.request)))
    phase2_key_fn = (
        (lambda e: e.prepared.phase2_batch_key) if slo is None else
        (lambda e: e.prepared.phase2_batch_key
         + ("tier", slo.tier(e.request))))
    batcher = DynamicBatcher(max_batch=max_batch * dp,
                             max_wait_ms=max_wait_ms, bucket_sizes=sizes,
                             key_fn=main_key_fn)
    if phase2_max_batch is None:
        phase2_max_batch = _wider_bucket(max_batch)
    elif phase2_max_batch not in BUCKET_SIZES:
        raise ValueError(f"phase2_max_batch must be one of {BUCKET_SIZES}, "
                         f"got {phase2_max_batch}")
    batcher2 = DynamicBatcher(
        max_batch=phase2_max_batch * dp, max_wait_ms=max_wait_ms,
        key_fn=phase2_key_fn, pool="phase2",
        bucket_sizes=sizes)
    # The cache shares the loop's retry policy: transient *build* failures
    # (prewarm and in-band misses) back off on the wall clock inside the
    # cache; execution faults stay classified at dispatch and back off on
    # the virtual clock. retry_call only retries transients, so poison and
    # fatal builds still propagate to the taxonomy untouched.
    cache = ProgramCache(program_cache_cap, retry_policy=policy)
    trace = _Trace(requests)

    counts = {s: 0 for s in TERMINAL_STATUSES}
    fault_counts = {k: 0 for k in (faults_mod.TRANSIENT, faults_mod.POISON,
                                   faults_mod.FATAL, faults_mod.TIMEOUT)}
    retries_total = 0
    timeouts_total = 0
    degrade_transitions = 0
    degrade_level = 0
    pressure_since: Optional[float] = None
    calm_since: Optional[float] = None
    fatal_reason: List[Optional[str]] = [None]
    latencies: List[float] = []
    occupancies: List[int] = []
    batch_hits: List[bool] = []
    # Per-pool dispatch accounting (phase-disaggregated batching): the
    # flat lists above stay the whole-loop aggregate (every successful
    # dispatch, any pool), these split it per phase for the summary's
    # ``phases`` block and the ≥1.3× bench comparison.
    occ_by_phase = {"phase1": [], "phase2": []}
    handoffs_total = 0
    resumed_handoffs = 0
    prewarm_ms = 0.0
    # Cost-observatory dispatch attribution (obs.costmodel): the latest
    # dispatch's predicted-vs-measured attrs, merged into flight `run`
    # segments. Stays {} with costscope=None (flight parity unchanged).
    last_cost = [{}]
    vnow = 0.0
    batch_index = 0
    replayed_ids: set = set()
    forced_gate_ids: set = set()
    # Lifecycle state: the drain flag is polled at cycle boundaries (that
    # determinism is the point — see serve.lifecycle); an internal
    # controller stands in when the caller passes none so chaos 'sigterm'
    # faults always have somewhere to latch.
    drain_ctl = lifecycle if lifecycle is not None else \
        lifecycle_mod.DrainController()
    draining = False
    drain_wall0 = 0.0
    drain_timed_out = False
    last_snapshot_ms = 0.0
    snapshots_taken = 0
    restore_degrade_level = 0
    # SLO-tiered scheduling state (serve.scheduling). With slo=None all
    # of this stays inert — `parked`/`forced_preempt` can only fill via a
    # chaos `preempt_then_kill` plan, which is itself non-default.
    # Semantic-cache state (serve.semcache, ISSUE 13). With semcache=None
    # every structure stays empty and every branch below it is skipped —
    # the disabled-mode parity contract.
    sc = semcache
    leader_key: dict = {}       # leader request_id -> content digest
    inflight_key: dict = {}     # content digest -> in-flight leader id
    followers: dict = {}        # content digest -> waiting Entry list
    ready_followers: List = []  # (Entry, images) awaiting emission
    sc_served = {"l2": 0, "l3": 0, "collapsed": 0}
    parked: List[HandoffEntry] = []
    forced_preempt: set = set()      # chaos preempt_then_kill victims
    preemptions = 0
    preempt_resumes = 0
    deadline_jumps = 0
    tier_yields = 0
    quota_rejects = 0
    tier_by_id: dict = {}
    slo_tier_counts = ({t: {s: 0 for s in TERMINAL_STATUSES}
                        for t in scheduling_mod.TIERS}
                       if slo is not None else {})

    # Registry-backed aggregation alongside (never instead of) the JSONL
    # records: the per-request record schema is the stable contract, the
    # registry is the cross-run timeline (docs/OBSERVABILITY.md). Stage
    # histograms bound memory — the summary still computes its percentiles
    # from the raw latency list, and the test contract is that the two
    # agree within one histogram bucket.
    reg = obs_metrics.registry()
    m_requests = reg.counter("serve_requests_total",
                             "terminal per-request records by status",
                             labels=("status",))
    m_rejects = reg.counter("serve_admission_rejects_total",
                            "admission rejections by kind", labels=("kind",))
    # Stage histograms carry a ``phase`` label (phase-disaggregated
    # accounting): ``mono`` for single-pool requests; gated requests
    # observe their phase-1 and phase-2 components separately (and their
    # whole-request total under ``gated``) so the two pools' latency
    # stories never blur into one distribution.
    m_stage = {
        "queue_wait_ms": reg.histogram(
            "serve_queue_wait_ms",
            "arrival -> dispatch wait per request (phase2: hand-off -> "
            "phase-2 dispatch)", labels=("phase",)),
        "compile_ms": reg.histogram(
            "serve_compile_ms",
            "in-band build time of the request's batch (0 on cache hit; "
            "observed once per ok lane, like the record field — sum over "
            "a batch overcounts by its occupancy)", labels=("phase",)),
        "run_ms": reg.histogram(
            "serve_run_ms", "batch execution wall time per request",
            labels=("phase",)),
        "total_ms": reg.histogram(
            "serve_request_total_ms", "arrival -> images latency",
            labels=("phase",)),
    }
    # Occupancy buckets span the dp-SCALED lane sizes up to the 8-chip
    # ROADMAP target (dp>8 overflows the top bucket), and are the same
    # fixed tuple for every run: the registry's families are process-wide,
    # so a per-dp tuple would conflict when one process serves at two mesh
    # shapes (the bench serve.mesh A/B does exactly that).
    m_occupancy = reg.histogram(
        "serve_batch_occupancy", "real lanes per dispatched batch",
        buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0),
        labels=("phase",))
    m_handoffs = reg.counter(
        "serve_handoffs_total",
        "gated requests handed off from the phase-1 to the phase-2 pool")
    m_resumed = reg.counter(
        "serve_handoff_resumed_total",
        "crash-replayed requests resumed in phase 2 off a journaled carry")
    m_upsized = reg.counter(
        "serve_bucket_upsized_total",
        "batches padded up to a larger warm bucket (warm-preference)")
    m_isolated = reg.counter(
        "serve_isolation_retries_total",
        "lanes re-run alone after a poisoned batch")
    m_faults = reg.counter(
        "serve_faults_total", "dispatch failures by classified kind",
        labels=("kind",))
    m_retries = reg.counter(
        "serve_retries_total", "same-batch retries of transient faults")
    m_backoff = reg.histogram(
        "serve_retry_backoff_ms",
        "virtual-clock backoff before each transient retry")
    m_timeouts = reg.counter(
        "serve_watchdog_timeouts_total",
        "batches killed by the dispatch-time watchdog")
    m_invalid = reg.counter(
        "serve_invalid_output_lanes_total",
        "lanes converted to invalid_output by the post-run finite check")
    m_shed = reg.counter(
        "serve_shed_total", "requests shed under sustained overload")
    m_degrade_level = reg.gauge(
        "serve_degrade_level", "current degradation level (0 = normal)")
    m_degrade_trans = reg.counter(
        "serve_degrade_transitions_total",
        "degradation level changes by direction", labels=("direction",))
    m_degraded_gate = reg.counter(
        "serve_degraded_gate_total",
        "requests force-gated to 'auto' under degradation")
    m_replay = reg.counter(
        "serve_replay_total", "journal replay outcomes by kind",
        labels=("kind",))
    m_snapshots = reg.counter(
        "serve_snapshots_total",
        "journal snapshot+compaction passes by trigger",
        labels=("trigger",))
    m_snapshot_folded = reg.histogram(
        "serve_snapshot_wal_records",
        "WAL records folded away by each snapshot (the compaction win)")
    m_gc = reg.counter(
        "serve_compaction_gc_total",
        "files removed by compaction/replay GC by kind",
        labels=("kind",))
    m_draining = reg.gauge(
        "serve_draining", "1 while the graceful-drain protocol is active")
    m_drains = reg.counter(
        "serve_drains_total", "graceful-drain protocol entries")
    # SLO families exist only when the scheduler is active, so an slo-less
    # run's registry snapshot is byte-identical to the pre-SLO engine's
    # (the preemption counters are the one exception: a chaos
    # preempt_then_kill plan creates them on first use via reg get-or-
    # create inside park()/resume_parked() — chaos is itself non-default).
    m_tier = None
    if slo is not None:
        m_tier = reg.counter(
            "serve_tier_requests_total",
            "terminal records of admitted requests by SLO tier and status",
            labels=("tier", "status"))
    # Semantic-cache serve counts exist only with an active SemCache, so a
    # cache-less run's registry snapshot is byte-identical to the
    # pre-cache engine's (the SemCache object owns the per-layer
    # hit/miss/evict/bytes families the same way).
    m_sc_serves = None
    if sc is not None:
        m_sc_serves = reg.counter(
            "serve_semcache_served_total",
            "requests served from the semantic cache by layer "
            "('collapsed' = single-flight followers riding a leader)",
            labels=("layer",))
    # Mesh families are created (and observed) only when a mesh is active:
    # a mesh-less run's registry snapshot carries no mesh rows at all
    # (the record stream / journal / program halves of disabled-mode
    # parity are pinned by tests; the occupancy histogram's wider fixed
    # bucket set above is the one deliberate registry-schema change).
    if jmesh is not None:
        m_mesh_devices = reg.gauge(
            "serve_mesh_devices", "devices on the serve mesh's dp axis")
        m_mesh_devices.set(dp)
        m_mesh_lanes = reg.counter(
            "serve_mesh_lanes_total",
            "padded lanes dispatched per mesh device (bucket/dp each)",
            labels=("device",))
        _mesh_dev_ids = [str(d.id) for d in jmesh.devices.flat]
    # Elastic families exist only under an active controller (the same
    # disabled-mode registry discipline as slo/semcache). The mesh gauge
    # above is already resize-safe: Gauge.set overwrites in place and the
    # registry get-or-creates families, so a resize re-pointing the gauge
    # (and adding per-device counter children for new devices) can never
    # double-count.
    m_resizes = None
    if elastic_ctl is not None:
        m_resizes = reg.counter(
            "serve_resizes_total",
            "elastic mesh resizes committed by direction",
            labels=("direction",))

    def note_mesh_dispatch(bucket: int) -> None:
        """Per-device lane accounting for one successful dispatch: every
        device ran exactly bucket/dp lanes (whole per-device sub-batches
        by construction of the scaled bucket set)."""
        if jmesh is None:
            return
        for did in _mesh_dev_ids:
            m_mesh_lanes.labels(device=did).inc(bucket // dp)

    def record(status: str, request_id: str, *, release: bool = True,
               journal_write: bool = True, stage_phase: Optional[str] = "mono",
               **fields) -> dict:
        # release=False for admission rejections: a rejected submission was
        # never admitted, and its id may belong to a still-live earlier
        # request (duplicate-id rejection) whose capacity slot and cancel
        # marker must survive. journal_write=False for the same duplicate
        # case — a terminal WAL line for the duplicate's id would make a
        # crash-replay drop the still-live original. stage_phase labels the
        # auto-observed stage histograms of an ok record ("mono" for the
        # single-pool path); gated oks pass None and observe their per-phase
        # split at the phase-2 dispatch site instead.
        counts[status] += 1
        m_requests.labels(status=status).inc()
        if m_tier is not None and release:
            # Admitted requests only (rejections with release=False were
            # never admitted — and a duplicate-id rejection's id belongs
            # to a still-live original whose tier mapping must survive).
            tier = tier_by_id.pop(request_id, None)
            if tier is not None:
                m_tier.labels(tier=tier, status=status).inc()
                slo_tier_counts[tier][status] += 1
        if status == "ok" and stage_phase is not None:
            for key, hist in m_stage.items():
                if key in fields:
                    hist.labels(phase=stage_phase).observe(
                        float(fields[key]))
        if request_id in replayed_ids:
            fields.setdefault("replayed", True)
        if request_id in forced_gate_ids:
            fields.setdefault("degraded_gate", True)
        if sc is not None and release:
            # Single-flight leader resolution — BEFORE the terminal WAL
            # line, so the journaled cache insert is strictly earlier than
            # the leader's terminal (the kill_after_cache_insert window).
            resolve_leader(request_id, status, fields)
        if journal is not None and journal_write:
            journal.terminal(request_id, status, vnow)
            journal.discard_carry(request_id)
        if flight is not None and not (status == "rejected"
                                       and not journal_write):
            # One flight record per terminal. The duplicate-id rejection
            # (journal_write=False) is skipped for the same reason its WAL
            # line is: the id belongs to a still-live earlier request whose
            # open trace context must survive.
            flight.finish(request_id, status, vnow,
                          total_ms=fields.get("total_ms"),
                          reason=fields.get("reason"))
        if release:
            queue.release(request_id)
        return {"request_id": request_id, "status": status, **fields}

    def _trace_attach(entries):
        """Stamp the spans of a dispatch with the trace ids it carries
        (``obs.spans.attach``) — a no-op context when tracing is off, so
        the span event stream stays byte-stable for flight=None."""
        if flight is None:
            return contextlib.nullcontext()
        return obs_spans.attach(traces=",".join(
            flight.current_trace_id(e.request_id) for e in entries))

    def _loop_state():
        """Pool/queue snapshot for the blackbox bundle."""
        return {"vnow_ms": round(vnow, 3),
                "queue_waiting": len(queue),
                "outstanding": queue.outstanding,
                "batcher_waiting": {"main": len(batcher),
                                    "phase2": len(batcher2)},
                "parked": len(parked),
                "degrade_level": degrade_level,
                "draining": draining,
                "batches_dispatched": batch_index,
                "handoffs": handoffs_total,
                "counts": dict(counts),
                "program_cache": cache.stats()}

    def _build(factory, compile_key, bucket, entries):
        runner = factory(compile_key, bucket)
        warm = getattr(runner, "warm", None)
        lower = (getattr(runner, "cost_lowered", None)
                 if (costscope is not None or prodscope is not None)
                 else None)
        if lower is not None and jmesh is None:
            # Cost observatory: AOT-compile FIRST — the real XLA compile
            # is timed as compile_ms{what="build"} and populates the
            # persistent cache, so the jit-path warm that follows mostly
            # pays deserialization + the throwaway execution, timed as
            # {what="warm"}. The miss's what="program" lump (recorded by
            # ProgramCache) stays the total either way; build/warm is its
            # decomposition, present only under the observatory.
            compiled = None
            t0 = time.perf_counter()
            try:
                compiled = lower(entries).compile()
            except Exception:
                pass  # a card-less program still serves; never a fault
            build_ms = (time.perf_counter() - t0) * 1000.0
            obs_device.record_compile(build_ms, what="build")
            t1 = time.perf_counter()
            if warm is not None:
                warm(entries)
            warm_ms = (time.perf_counter() - t1) * 1000.0
            obs_device.record_compile(warm_ms, what="warm")
            if compiled is not None:
                if costscope is not None:
                    costscope.record_program(compile_key, bucket,
                                             compiled,
                                             build_ms=build_ms,
                                             warm_ms=warm_ms)
                if prodscope is not None:
                    # Production profiler: the compiled HLO text's
                    # op→site index is the join key that turns this
                    # program's sampled traces into per-site shares.
                    prodscope.record_program(compile_key, bucket,
                                             compiled)
        elif lower is not None:
            # Mesh serving: the card comes from the MESH-LESS logical
            # twin (cost_lowered lowers without shardings), which shares
            # no compile with the sharded program warm() builds — so the
            # real serving build runs FIRST (the warm), and the twin's
            # analysis compile is an observatory cost on top, recorded
            # under its own label instead of polluting the build/warm
            # decomposition (whose meaning is the serving path's split).
            if warm is not None:
                warm(entries)
            t0 = time.perf_counter()
            try:
                compiled = lower(entries).compile()
            except Exception:
                compiled = None
            card_ms = (time.perf_counter() - t0) * 1000.0
            obs_device.record_compile(card_ms, what="cost_card")
            if compiled is not None:
                if costscope is not None:
                    costscope.record_program(compile_key, bucket,
                                             compiled)
                if prodscope is not None:
                    prodscope.record_program(compile_key, bucket,
                                             compiled)
        elif warm is not None:
            warm(entries)
        return runner

    # ------------------------------------------------------------------
    # Semantic cache (serve.semcache): content-addressed admission, the
    # single-flight leader/follower protocol, and follower emission. All
    # of it is dead code with semcache=None.
    # ------------------------------------------------------------------

    def cache_admit(prep, now, *, replayed=False):
        """Cache-side admission for one validated request: an L3 exact
        hit serves it NOW (terminal record, no dispatch), an in-flight
        leader with the same content key collapses it into a follower,
        and an L2 prefix hit enters it directly in phase 2 (a hand-off
        resume off the cached carry). Returns ``(records, kind)`` with
        kind ∈ {None, "l3", "collapsed", "l2", "leader"}: None means
        un-handled — the caller admits normally and registers the
        content key's leader; "leader" means a presence test passed but
        every load refused (corrupt spill, template mismatch), so the
        already-admitted entry entered the pipeline as the key's leader
        instead. Presence is tested BEFORE admission and cache counters
        move only after it, so a ``Rejected`` (capacity / duplicate-id /
        quota — cache-agnostic, raised exactly like ``queue.submit``)
        never skews the hit/miss stats the bench sub-record reads."""
        rid = prep.request.request_id
        ck = sc.digest(prep.content_key)

        def next_kind(skip_l3):
            if not skip_l3 and sc.l3_has(ck):
                return "l3"
            if ck in inflight_key:
                return "collapsed"
            if prep.gated and phase_pools and sc.l2_has(ck):
                return "l2"
            return None

        kind = next_kind(skip_l3=False)
        if kind is None:
            return [], None
        entry = queue.admit_inflight(prep, now)
        if slo is not None:
            tier_by_id[rid] = slo.tier(prep.request)
        if journal is not None and not replayed:
            journal.admitted(prep.request.to_dict(), now)
        if flight is not None:
            flight.admit(rid, now, arrival_ms=entry.arrival_ms,
                         gated=prep.gated and phase_pools,
                         replayed=replayed)
        if kind == "l3":
            imgs = sc.l3_get(ck)      # counts the hit (corrupt ⇒ miss)
            if imgs is not None:
                if flight is not None:
                    flight.wait(rid, "cache_hit", now, layer="l3")
                sc_served["l3"] += 1
                m_sc_serves.labels(layer="l3").inc()
                return [record(
                    "ok", rid, stage_phase="cached", images=imgs,
                    arrival_ms=entry.arrival_ms,
                    queue_wait_ms=now - entry.arrival_ms,
                    compile_ms=0.0, run_ms=0.0,
                    total_ms=now - entry.arrival_ms,
                    gate_step=prep.gate_step,
                    cache={"layer": "l3"})], "l3"
            kind = next_kind(skip_l3=True) or "leader"
        else:
            sc.note_miss("l3")        # the admitted lookup really missed
        if kind == "collapsed":
            # Single-flight collapse: the leader computes once; this
            # follower waits for the leader's terminal and gets its own
            # record (and flight trace) off the leader's images.
            if flight is not None:
                flight.event(rid, "collapsed", now,
                             leader=inflight_key[ck])
            followers.setdefault(ck, []).append(entry)
            return [], "collapsed"
        if kind == "l2":
            carry = sc.l2_get(ck, handoff_mod.carry_template(pipe, prep))
            if carry is not None:
                # A prefix hit IS a hand-off resume: phase 1 never runs.
                if flight is not None:
                    flight.event(rid, "cache_hit", now, layer="l2")
                sc_served["l2"] += 1
                m_sc_serves.labels(layer="l2").inc()
                # The L2-served request is this content key's in-flight
                # leader: later identical arrivals collapse onto it.
                inflight_key[ck] = rid
                leader_key[rid] = ck
                batcher2.add(HandoffEntry(entry=entry, carry=carry,
                                          handoff_ms=now,
                                          cache_layer="l2"), now)
                return [], "l2"
        # Every load refused after admission (a rare corrupt window):
        # the admitted entry becomes this content key's leader and
        # computes normally — silent miss, never a fault.
        inflight_key[ck] = rid
        leader_key[rid] = ck
        batcher.add(entry, now)
        return [], "leader"

    def register_leader(rid, prep) -> None:
        ck = sc.digest(prep.content_key)
        # An admitted request heading to compute IS an L3 lookup that
        # missed (cache_admit tested presence without counting).
        sc.note_miss("l3")
        inflight_key[ck] = rid
        leader_key[rid] = ck

    def resolve_leader(request_id, status, fields) -> None:
        """One in-flight leader reached a terminal (called from record(),
        before the terminal WAL line). ``ok``: insert the result into L3
        (journaled ``cache`` record — the chaos kill_after_cache_insert
        window fires here, after the durable insert, before the terminal
        fsync) and release the followers. Anything else: promote the
        first follower into a fresh leader re-entering the pipeline —
        a leader's cancellation/expiry/poison must never starve its
        followers — except during a fatal or timed-out drain, where the
        leftover sweeps resolve them instead."""
        ck = leader_key.pop(request_id, None)
        if ck is None:
            return
        if inflight_key.get(ck) == request_id:
            del inflight_key[ck]
        waiting = followers.pop(ck, [])
        if status == "ok" and "images" in fields:
            path = sc.l3_put(ck, fields["images"])
            if path is not None and journal is not None:
                journal.cache_insert(ck, request_id, path, vnow)
            if chaos is not None and \
                    chaos.take_kill(chaos_mod.KILL_AFTER_CACHE_INSERT):
                # Die with the insert (and its WAL record) durable but
                # the leader's terminal unwritten — the restart reseeds
                # the cache off the journal and serves leader+followers
                # from it without recompute.
                if journal is not None:
                    journal.sync()
                raise chaos_mod.SimulatedKill(
                    "chaos kill_after_cache_insert")
            for f in waiting:
                ready_followers.append((f, fields["images"]))
        elif waiting:
            if fatal_reason[0] is not None or drain_timed_out:
                followers[ck] = waiting   # the drain sweeps resolve them
                return
            promoted = waiting[0]
            leader_key[promoted.request_id] = ck
            inflight_key[ck] = promoted.request_id
            if waiting[1:]:
                followers[ck] = waiting[1:]
            if flight is not None:
                flight.event(promoted.request_id, "promoted", vnow,
                             leader=request_id)
            batcher.add(promoted, vnow)

    def flush_followers() -> Iterator[dict]:
        """Emit the terminal records of followers whose leader resolved
        ok. Runs at cycle boundaries (and at the drain/fatal sweeps):
        cancellation and deadline expiry are checked NOW, exactly like a
        dispatching batch — a follower is a real request with its own
        lifecycle, not an alias of its leader."""
        while ready_followers:
            entry, imgs = ready_followers.pop(0)
            rid = entry.request_id
            if queue.is_cancelled(rid):
                yield record("cancelled", rid, arrival_ms=entry.arrival_ms,
                             queue_wait_ms=vnow - entry.arrival_ms)
            elif queue_mod.expired(entry, vnow):
                yield record(
                    "expired", rid, arrival_ms=entry.arrival_ms,
                    reason=(f"deadline {entry.request.deadline_ms}ms "
                            f"passed while collapsed on an in-flight "
                            f"leader (waited "
                            f"{vnow - entry.arrival_ms:.1f}ms)"))
            else:
                sc_served["collapsed"] += 1
                m_sc_serves.labels(layer="collapsed").inc()
                if flight is not None:
                    flight.wait(rid, "cache_hit", vnow, layer="l3",
                                collapsed=True)
                yield record(
                    "ok", rid, stage_phase="cached", images=imgs,
                    arrival_ms=entry.arrival_ms,
                    queue_wait_ms=vnow - entry.arrival_ms,
                    compile_ms=0.0, run_ms=0.0,
                    total_ms=vnow - entry.arrival_ms,
                    gate_step=entry.prepared.gate_step,
                    cache={"layer": "l3", "collapsed": True})

    def drain_follower_entries() -> List:
        """Pull every not-yet-ready follower out of the single-flight
        maps — the fatal-drain / drain-timeout sweeps resolve them with
        everything else outstanding (nothing may silently vanish)."""
        out = [f for fl in followers.values() for f in fl]
        followers.clear()
        return out

    def take_chaos(batch_idx, rids):
        """Chaos consultation shared by every dispatch site. Lifecycle
        kinds never reach the runner: 'sigterm' latches the drain flag at
        its keyed dispatch (the batch itself runs normally, like a real
        SIGTERM landing mid-batch), the kill_* kinds ARM a SimulatedKill
        that fires at the matching lifecycle point."""
        if chaos is None:
            return None
        fault = chaos.take(batch_idx, rids)
        if fault is not None and fault.kind in chaos_mod.LIFECYCLE_KINDS:
            if fault.kind == chaos_mod.SIGTERM:
                drain_ctl.request(f"chaos:{fault.target}")
            elif fault.kind == chaos_mod.PREEMPT_THEN_KILL:
                # The victims park at their next phase boundary (their
                # hand-off goes to `parked`, not the phase-2 batcher);
                # the armed kill fires at the first batch-boundary sync
                # after the park — before any resume can run.
                forced_preempt.update(fault.rids)
                chaos.arm_kill(fault.kind)
            else:
                chaos.arm_kill(fault.kind)
            return None
        return fault

    def _snapshot_kill_hook():
        # chaos kill_during_snapshot: dies with the snapshot durably
        # renamed but the WAL un-rotated — the nastiest real crash window;
        # the restart must fold snapshot + overlapping WAL idempotently.
        if chaos is not None and \
                chaos.take_kill(chaos_mod.KILL_DURING_SNAPSHOT):
            raise chaos_mod.SimulatedKill("chaos kill_during_snapshot")

    def _profile_extras():
        """Blackbox sidecar (ISSUE 18): a FATAL/watchdog bundle ships
        with the profiler's latest ledger and active sampling plan —
        the performance context that preceded the impact. None when the
        profiler is off, so bundles stay byte-identical without it."""
        if prodscope is None:
            return None
        return {"workload_profile": prodscope.blackbox_snapshot()}

    def _capture_kill_hook():
        # chaos kill_during_capture: dies inside the profiler's finalize
        # — a sampled capture's trace files durable in the ring's tmp dir
        # but the atomic commit rename not yet done. Terminals sync first
        # (matching the healthy loop's fsync point: the drill targets the
        # ring's orphan window, not the journal tail); the restart must
        # sweep the orphan and keep serving exactly-once.
        if chaos is not None and \
                chaos.take_kill(chaos_mod.KILL_DURING_CAPTURE):
            if journal is not None:
                journal.sync()
            raise chaos_mod.SimulatedKill("chaos kill_during_capture")

    def _profile_finalize():
        """Fold the profiler's stopped captures at the batch-boundary
        sync (drift events are journaled here, right before the fsync
        point, so a ``profile_drift`` line is durable with its batch)."""
        if prodscope is None or not prodscope.pending():
            return
        out = prodscope.finalize(kill_hook=_capture_kill_hook)
        for ev in out["drift_events"]:
            if journal is not None:
                journal.event("profile_drift", **ev)
            if flight is not None:
                flight.loop_event("profile_drift", vnow,
                                  kind=ev["drift"], key=ev["key"],
                                  deviation=ev["deviation"])

    def take_snapshot(trigger: str) -> dict:
        """One journal.compact pass + its bookkeeping (periodic + drain)."""
        nonlocal snapshots_taken
        extra = {"degrade_level": degrade_level}
        if elastic_ctl is not None:
            # The elastic topology rides the snapshot (an optional key —
            # elastic-off snapshots stay byte-identical) so a restart
            # long after the resize's WAL segment rotated away still
            # comes back on the committed width.
            extra["mesh_dp"] = dp
        with span("serve.snapshot", trigger=trigger):
            info = journal.compact(extra=extra,
                                   on_durable=_snapshot_kill_hook)
        snapshots_taken += 1
        m_snapshots.labels(trigger=trigger).inc()
        m_snapshot_folded.observe(float(info["wal_records_folded"]))
        if info["orphans_swept"]:
            m_gc.labels(kind="spill_orphan").inc(info["orphans_swept"])
        if journal is not None:
            journal.event("snapshot", seq=info["seq"], trigger=trigger,
                          vnow_ms=round(vnow, 3))
        if flight is not None:
            flight.loop_event("snapshot", vnow, trigger=trigger,
                              seq=info["seq"])
        return info

    # ------------------------------------------------------------------
    # Journal replay: reconstruct the queue from non-terminal WAL entries
    # (served exactly once; arrival restarts on this incarnation's clock)
    # and dedupe the incoming trace against everything the WAL already
    # resolved. Corrupt/duplicate WAL lines surface as counters only.
    # ------------------------------------------------------------------
    replay_skip: set = set()
    replay_info: Optional[dict] = None
    if journal is not None:
        rs = journal.replay_state
        replay_skip = set(rs.terminal) | set(rs.pending_ids)
        restore_degrade_level = rs.degrade_level if degrade is not None \
            else 0
        if sc is not None:
            # Reseed the L3 index from the journaled cache records: a
            # leader killed between its insert and its terminal fsync
            # left a durable result the restart serves followers from.
            # Run even with zero records — the journal is the authority
            # over a reused spill dir, so seed() sweeps r-* files no
            # replayed insert references (the disk-reclaim path).
            seeded = sc.seed(rs.cache_entries)
            if seeded:
                m_replay.labels(kind="cache_seeded").inc(seeded)
        if rs.orphans_swept:
            m_gc.labels(kind="spill_orphan").inc(rs.orphans_swept)
        if rs.segments_swept:
            m_gc.labels(kind="segment").inc(rs.segments_swept)
        if rs.pending or rs.terminal or rs.skipped_corrupt \
                or rs.snapshot_corrupt or rs.orphans_swept:
            replay_info = {"pending": len(rs.pending),
                           "terminal": len(rs.terminal),
                           "skipped_corrupt": rs.skipped_corrupt,
                           "duplicate_terminals": rs.duplicate_terminals,
                           "deduped": 0}
            if rs.snapshot_loaded:
                # The warm-restart receipt: how much history the snapshot
                # absorbed vs the tail this fold actually read.
                replay_info["snapshot"] = {
                    "seq": rs.snapshot_seq,
                    "wal_tail_records": rs.wal_records,
                    "folded_records": rs.folded_records}
            if rs.snapshot_corrupt:
                replay_info["snapshot_corrupt"] = True
                m_replay.labels(kind="snapshot_corrupt").inc()
            if rs.orphans_swept:
                replay_info["orphans_swept"] = rs.orphans_swept
            if rs.skipped_corrupt:
                m_replay.labels(kind="corrupt_skipped").inc(
                    rs.skipped_corrupt)
            if rs.duplicate_terminals:
                m_replay.labels(kind="duplicate_terminal").inc(
                    rs.duplicate_terminals)
            with span("serve.replay", pending=len(rs.pending),
                      terminal=len(rs.terminal)):
                for d in rs.pending:
                    try:
                        req = Request.from_dict(d)
                        req = dataclasses.replace(req, arrival_ms=0.0)
                        prep = prepare(req, pipe)
                        rid = req.request_id
                        if sc is not None:
                            replayed_ids.add(rid)
                            recs, ckind = cache_admit(prep, 0.0,
                                                      replayed=True)
                            if ckind is not None:
                                # "l3"/"l2" really served off the reseeded
                                # cache; a collapsed follower or a
                                # corrupt-entry leader recomputes — count
                                # it as what it is, not as a hit.
                                m_replay.labels(kind={
                                    "l3": "cache_hit", "l2": "cache_hit",
                                    "collapsed": "collapsed",
                                    "leader": "pending"}[ckind]).inc()
                                for r in recs:
                                    yield r
                                continue
                            replayed_ids.discard(rid)  # re-added below
                        ho = rs.handoffs.get(rid)
                        if (ho is not None and prep.gated and phase_pools):
                            # The WAL says phase 1 already ran: resume in
                            # phase 2 off the spilled carry — exactly-once
                            # state, and not even phase-1 compute is
                            # repeated. A lost/corrupt spill falls back to
                            # a full re-run (at-least-once compute, the
                            # journal's existing contract).
                            try:
                                carry = handoff_mod.load_carry(
                                    ho["carry_path"],
                                    handoff_mod.carry_template(pipe, prep))
                            except ValueError:
                                carry = None
                                m_replay.labels(kind="handoff_lost").inc()
                            if carry is not None:
                                entry = queue.admit_inflight(prep, 0.0)
                                if slo is not None:
                                    tier_by_id[rid] = slo.tier(req)
                                if sc is not None:
                                    register_leader(rid, prep)
                                batcher2.add(HandoffEntry(
                                    entry=entry, carry=carry,
                                    handoff_ms=0.0, resumed=True), 0.0)
                                resumed_handoffs += 1
                                m_resumed.inc()
                                replayed_ids.add(rid)
                                m_replay.labels(kind="handoff_resumed").inc()
                                if flight is not None:
                                    # Stitch this incarnation's timeline to
                                    # the pre-crash phase-1 segments the WAL
                                    # hand-off carried.
                                    flight.resume(rid, ho.get("trace"), 0.0)
                                continue
                        queue.submit(prep, 0.0)
                        if slo is not None:
                            tier_by_id[rid] = slo.tier(req)
                        if sc is not None:
                            register_leader(rid, prep)
                        replayed_ids.add(rid)
                        m_replay.labels(kind="pending").inc()
                        if flight is not None:
                            flight.admit(rid, 0.0,
                                         gated=prep.gated and phase_pools,
                                         replayed=True)
                            if ho is not None:
                                flight.event(rid, "handoff_lost", 0.0)
                    except (Rejected, ValueError) as e:
                        rid = d.get("request_id", "?")
                        m_rejects.labels(
                            kind=getattr(e, "kind", "invalid_spec")).inc()
                        yield record("rejected", rid, release=False,
                                     reason=f"replayed request no longer "
                                            f"admissible: {e}")
            journal.sync()

    if prewarm:
        t0 = timer()
        with span("serve.prewarm"):
            for req in prewarm:
                try:
                    prep = prepare(req, pipe)
                except ValueError:
                    # Prewarm is an optimization: an invalid spec here must
                    # not take the server down — the same request gets its
                    # proper 'rejected' record if/when it arrives in the
                    # trace.
                    continue
                entry = queue_mod.Entry(prepared=prep, arrival_ms=0.0)
                if prep.gated and phase_pools:
                    # A gated request compiles into TWO pool programs;
                    # warm both at their pools' max (mesh-scaled) buckets
                    # so neither phase pays a compile in-band.
                    keys = ((mkey(prep.phase1_key),
                             bucket_for(batcher.max_batch,
                                        batcher.max_batch, sizes)),
                            (mkey(prep.phase2_key),
                             bucket_for(batcher2.max_batch,
                                        batcher2.max_batch, sizes)))
                else:
                    keys = ((mkey(prep.compile_key),
                             bucket_for(batcher.max_batch,
                                        batcher.max_batch, sizes)),)
                for key, bucket in keys:
                    cache.get((key, bucket),
                              lambda k=key, b=bucket, e=entry: _build(
                                  make_runner, k, b, [e]))
        prewarm_ms = (timer() - t0) * 1000.0

    def run_entries(entries, compile_key, guidance, bucket, fault=None,
                    pool="mono"):
        """Run one padded batch; returns (images, run_ms, hit, steps_done,
        finite). The steps the compiled loop reports flow into per-request
        progress via the shared step hook — and, when the watchdog is
        armed, into its heartbeat (a batch still emitting steps is alive,
        however long it takes; a hung compile emits nothing). The watchdog
        covers the *build* too: an in-band compile miss that hangs raises
        the same :class:`WatchdogTimeout` as a hung execution — the cache
        insertion stays on this thread, so an abandoned build worker can
        never mutate the LRU if it eventually wakes up."""
        steps_seen = []
        beats = [0]
        last_cost[0] = {}
        if watchdog_ms is not None:
            # Armed before the build: warm() runs the compiled loop, whose
            # step callbacks re-arm the deadline — only a compile that
            # emits nothing for the full window is shot.
            progress_mod.set_watchdog_sink(
                lambda: beats.__setitem__(0, beats[0] + 1))
        raw_build = lambda: _build(make_runner, compile_key, bucket, entries)
        build = (raw_build if watchdog_ms is None else
                 lambda: faults_mod.run_with_watchdog(
                     raw_build, watchdog_ms, heartbeat=lambda: beats[0],
                     what="program build/warm"))
        try:
            runner, hit, _ = cache.get((compile_key, bucket), build)
        finally:
            if watchdog_ms is not None:
                progress_mod.set_watchdog_sink(None)
        # cache.get's build_ms times only the closure; re-derive compile_ms
        # from our own timer so injected timers see it too.
        t0 = timer()
        if progress:
            progress_mod.set_step_hook(lambda s: steps_seen.append(int(s)))
        if watchdog_ms is not None:
            progress_mod.set_watchdog_sink(
                lambda: beats.__setitem__(0, beats[0] + 1))

        def call():
            if fault is not None:
                if fault.kind == "hang":
                    # Chaos hang: block well past the watchdog deadline
                    # (wall clock — exactly what a wedged device looks
                    # like); without a watchdog it is a stall, then runs.
                    time.sleep((watchdog_ms * 3 / 1000.0)
                               if watchdog_ms else 0.05)
                elif fault.kind in (faults_mod.TRANSIENT, faults_mod.POISON,
                                    faults_mod.FATAL):
                    raise faults_mod.InjectedFault(fault.kind, fault.target)
            return runner(entries, guidance)

        # Production profiler bracket: a sampled dispatch runs under a
        # programmatic jax.profiler capture. begin/stop/abort only — the
        # trace FOLD happens at the batch-boundary sync, never here, so a
        # profiler problem can never be classified as a dispatch fault.
        cap = (prodscope.begin(pool, compile_key, bucket, len(entries))
               if prodscope is not None else None)
        try:
            if watchdog_ms is not None:
                imgs = faults_mod.run_with_watchdog(
                    call, watchdog_ms, heartbeat=lambda: beats[0])
            else:
                imgs = call()
        except BaseException:
            if cap is not None:
                prodscope.abort(cap)
            raise
        finally:
            if progress:
                progress_mod.set_step_hook(None)
            if watchdog_ms is not None:
                progress_mod.set_watchdog_sink(None)
        run_ms = (timer() - t0) * 1000.0
        if cap is not None:
            prodscope.stop(cap, run_ms, vnow)
        if costscope is not None:
            # One measured-MFU observation per dispatch; the returned
            # attrs ride the flight run segment (predicted-vs-measured).
            last_cost[0] = costscope.dispatch(compile_key, bucket, run_ms,
                                              lanes=len(entries))
        finite = (getattr(runner, "last_lane_finite", None)
                  if validate_outputs else None)
        return imgs, run_ms, hit, (
            max(steps_seen) + 1 if steps_seen else None), finite

    def _fault_verdict(exc):
        """Classify one dispatch failure and do the bookkeeping half of
        the verdict (taxonomy counters); returns ``(kind, reason)``.
        Shared by the primary dispatch and the isolation re-run so the
        two paths cannot drift. A FATAL verdict is the flight-recorder
        moment: the blackbox dumps here, at impact, while every doomed
        request's flight context is still open — the drain that follows
        resolves them all."""
        kind = faults_mod.classify(exc)
        fault_counts[kind] += 1
        m_faults.labels(kind=kind).inc()
        reason = f"{type(exc).__name__}: {exc}"
        if kind == faults_mod.FATAL and flight is not None:
            flight.loop_event("fatal", vnow, reason=reason)
            flight.blackbox("fatal_fault", _loop_state(),
                            extras=_profile_extras())
        return kind, reason

    def _note_timeout(compile_key, bucket):
        """Watchdog-timeout bookkeeping: the program handle is suspect, so
        quarantine it; the next miss rebuilds instead of reusing a
        possibly-wedged executable. Shared by both dispatch paths. A
        watchdog kill is a flight-recorder moment: the blackbox bundle is
        dumped *before* the victims' terminal records, so their still-open
        contexts land in ``inflight.jsonl``."""
        nonlocal timeouts_total
        timeouts_total += 1
        m_timeouts.inc()
        cache.quarantine((compile_key, bucket))
        if flight is not None:
            flight.loop_event("watchdog_timeout", vnow)
            flight.blackbox("watchdog_timeout", _loop_state(),
                            extras=_profile_extras())

    def _live_after_backoff(entries):
        """Split entries into (records to yield, survivors) after vnow
        moved: a backoff must never outspend a lane's own deadline."""
        recs, still = [], []
        for e in entries:
            if queue.is_cancelled(e.request_id):
                recs.append(record("cancelled", e.request_id,
                                   arrival_ms=e.arrival_ms,
                                   queue_wait_ms=vnow - e.arrival_ms))
            elif queue_mod.expired(e, vnow):
                recs.append(record(
                    "expired", e.request_id, arrival_ms=e.arrival_ms,
                    reason=(f"deadline {e.request.deadline_ms}ms passed "
                            f"during transient backoff (waited "
                            f"{vnow - e.arrival_ms:.1f}ms)")))
            else:
                still.append(e)
        return recs, still

    def dispatch(batch: Batch) -> Iterator[dict]:
        if phase_pools and batch.entries[0].prepared.gated:
            # Gated requests ride the disaggregated pools; everything else
            # falls through to the monolithic path below, which is the
            # pre-disaggregation engine bitwise-unchanged.
            yield from dispatch_phase1(batch)
            return
        nonlocal vnow, batch_index, retries_total
        live = []
        for e in batch.entries:
            if queue.is_cancelled(e.request_id):
                yield record("cancelled", e.request_id,
                             arrival_ms=e.arrival_ms,
                             queue_wait_ms=vnow - e.arrival_ms)
            elif queue_mod.expired(e, vnow):
                yield record(
                    "expired", e.request_id, arrival_ms=e.arrival_ms,
                    reason=(f"deadline {e.request.deadline_ms}ms passed "
                            f"before dispatch (waited "
                            f"{vnow - e.arrival_ms:.1f}ms)"))
            else:
                live.append(e)
        if not live:
            return
        batch_index += 1
        this_batch = batch_index
        guidance = live[0].request.guidance
        compile_key = mkey(live[0].prepared.compile_key)
        bucket = _pick_bucket(len(live), compile_key, batcher.max_batch,
                              cache, sizes)
        if bucket > bucket_for(len(live), batcher.max_batch, sizes):
            m_upsized.inc()  # warm-preference padded past the smallest fit
        if journal is not None:
            journal.dispatched([e.request_id for e in live], this_batch,
                               vnow)
        dispatch_ms = vnow
        if flight is not None:
            for e in live:
                flight.wait(e.request_id, "queue_wait", dispatch_ms,
                            pool="mono")
        attempt = 0
        while True:
            fault = take_chaos(this_batch, [e.request_id for e in live])
            t0 = timer()
            try:
                span_name = "serve.batch" if attempt == 0 else "serve.retry"
                with _trace_attach(live), \
                        span(span_name, batch=this_batch, lanes=bucket,
                             occupancy=len(live),
                             **({"attempt": attempt} if attempt else {})):
                    imgs, run_ms, hit, steps_done, finite = run_entries(
                        live, compile_key, guidance, bucket, fault=fault)
                total_ms = (timer() - t0) * 1000.0
                compile_ms = max(0.0, total_ms - run_ms)
                break
            except Exception as exc:  # noqa: BLE001 — classified below
                elapsed = (timer() - t0) * 1000.0
                vnow += elapsed
                kind, reason = _fault_verdict(exc)
                if flight is not None:
                    for e in live:
                        flight.segment(e.request_id, "fault",
                                       vnow - elapsed, elapsed, pool="mono",
                                       kind=kind, attempt=attempt)
                if kind == faults_mod.TIMEOUT:
                    # A hung compile/execute: terminal records instead of a
                    # wedged server.
                    _note_timeout(compile_key, bucket)
                    for e in live:
                        yield record("timeout", e.request_id,
                                     arrival_ms=e.arrival_ms, reason=reason,
                                     batch_id=this_batch)
                    return
                if kind == faults_mod.FATAL:
                    for e in live:
                        yield record("error", e.request_id,
                                     arrival_ms=e.arrival_ms,
                                     reason=f"fatal: {reason}",
                                     batch_id=this_batch)
                    fatal_reason[0] = reason
                    return
                if kind == faults_mod.TRANSIENT:
                    if attempt + 1 < policy.max_attempts:
                        backoff = policy.backoff_ms(
                            attempt, key=f"batch:{this_batch}")
                        retries_total += 1
                        m_retries.inc()
                        m_backoff.observe(backoff)
                        vnow += backoff
                        if flight is not None:
                            for e in live:
                                flight.segment(e.request_id, "backoff",
                                               vnow - backoff, backoff,
                                               pool="mono", attempt=attempt)
                        attempt += 1
                        # The backoff budget is each lane's deadline:
                        # entries it outspent expire now instead of
                        # burning further attempts.
                        recs, live = _live_after_backoff(live)
                        yield from recs
                        if not live:
                            return
                        continue
                    for e in live:
                        yield record(
                            "error", e.request_id, arrival_ms=e.arrival_ms,
                            reason=(f"transient fault persisted through "
                                    f"{policy.max_attempts} attempts: "
                                    f"{reason}"),
                            batch_id=this_batch)
                    return
                # poison: the pre-existing lane-isolation path.
                yield from isolate(live, compile_key, guidance, exc)
                return
        v0 = vnow
        vnow += compile_ms + run_ms
        if flight is not None:
            for e in live:
                flight.segment(e.request_id, "compile", v0, compile_ms,
                               pool="mono", cache_hit=hit)
                flight.segment(e.request_id, "run", v0 + compile_ms, run_ms,
                               pool="mono", batch_id=this_batch,
                               **last_cost[0])
        occupancies.append(len(live))
        # Observed only on success, next to the summary's list, so the
        # histogram and mean_batch_occupancy reconcile exactly (a poisoned
        # batch contributes to neither — its lanes re-dispatch via
        # isolate()).
        m_occupancy.labels(phase="mono").observe(float(len(live)))
        note_mesh_dispatch(bucket)
        batch_hits.append(hit)
        bad = set()
        if finite is not None:
            bad = {i for i in range(len(live)) if not bool(finite[i])}
        if (fault is not None and fault.kind == "nan" and validate_outputs):
            # Injected NaN: force the victim lanes' finite flags false —
            # the same conversion a real NaN-poisoned latent triggers.
            bad |= {i for i, e in enumerate(live)
                    if e.request_id in fault.rids}
        lanes = lane_select(imgs, range(len(live)))
        for i, e in enumerate(live):
            if i in bad:
                m_invalid.inc()
                yield record(
                    "invalid_output", e.request_id,
                    arrival_ms=e.arrival_ms,
                    reason="non-finite values (NaN/Inf) in this lane's "
                           "latents; image withheld",
                    batch_id=this_batch, batch_lanes=bucket,
                    batch_occupancy=len(live))
                continue
            latency = vnow - e.arrival_ms
            latencies.append(latency)
            yield record(
                "ok", e.request_id, images=lanes[i],
                arrival_ms=e.arrival_ms,
                queue_wait_ms=dispatch_ms - e.arrival_ms,
                compile_ms=compile_ms, run_ms=run_ms, total_ms=latency,
                batch_id=this_batch, batch_lanes=bucket,
                batch_occupancy=len(live), cache_hit=hit,
                gate_step=e.prepared.gate_step,
                **({"steps_done": steps_done} if steps_done else {}))

    def isolate(entries, compile_key, guidance, batch_exc) -> Iterator[dict]:
        """A batch failed: re-run each lane alone so one poisoned request
        fails alone; survivors still get served (one retry each). The
        survivors ride the warm larger bucket when available
        (warm-preference), which keeps their outputs bitwise-identical to
        the fault-free batch (padding invariance)."""
        nonlocal vnow, batch_index
        entries = list(entries)
        for idx, e in enumerate(entries):
            batch_index += 1
            m_isolated.inc()
            bucket = _pick_bucket(1, compile_key, batcher.max_batch, cache,
                                  sizes)
            if journal is not None:
                journal.dispatched([e.request_id], batch_index, vnow)
            dispatch_ms = vnow
            if flight is not None:
                # The time between the poisoned batch's failure and this
                # lane's solo dispatch (earlier lanes' re-runs) is real
                # latency the flight record must attribute.
                flight.wait(e.request_id, "requeue_wait", dispatch_ms,
                            pool="mono", isolated=True)
            fault = take_chaos(batch_index, [e.request_id])
            try:
                t0 = timer()
                with _trace_attach([e]), \
                        span("serve.isolate_retry", batch=batch_index,
                             lanes=bucket, request=e.request_id):
                    imgs, run_ms, hit, steps_done, finite = run_entries(
                        [e], compile_key, guidance, bucket, fault=fault)
                compile_ms = max(0.0, (timer() - t0) * 1000.0 - run_ms)
            except Exception as exc:  # noqa: BLE001 — classified below
                elapsed = (timer() - t0) * 1000.0
                vnow += elapsed
                kind, reason = _fault_verdict(exc)
                if flight is not None:
                    flight.segment(e.request_id, "fault", vnow - elapsed,
                                   elapsed, pool="mono", kind=kind,
                                   isolated=True)
                batch_err = f"{type(batch_exc).__name__}: {batch_exc}"
                if kind == faults_mod.TIMEOUT:
                    # Same verdict as a hung primary dispatch.
                    _note_timeout(compile_key, bucket)
                    yield record(
                        "timeout", e.request_id, arrival_ms=e.arrival_ms,
                        reason=reason, batch_id=batch_index,
                        batch_error=batch_err, isolated_retry=True)
                    continue
                if kind == faults_mod.FATAL:
                    # Fatal during isolation fails the remaining lanes too
                    # (they would all hit the same wall) and drains the
                    # loop, exactly like the primary-dispatch path.
                    fatal_reason[0] = reason
                    for rest in entries[idx:]:
                        yield record(
                            "error", rest.request_id,
                            arrival_ms=rest.arrival_ms,
                            reason=f"fatal: {reason}", batch_error=batch_err)
                    return
                yield record(
                    "error", e.request_id, arrival_ms=e.arrival_ms,
                    reason=reason, batch_error=batch_err)
                continue
            v0 = vnow
            vnow += compile_ms + run_ms
            if flight is not None:
                flight.segment(e.request_id, "compile", v0, compile_ms,
                               pool="mono", cache_hit=hit, isolated=True)
                flight.segment(e.request_id, "run", v0 + compile_ms, run_ms,
                               pool="mono", batch_id=batch_index,
                               isolated=True, **last_cost[0])
            occupancies.append(1)
            # success-only, mirroring dispatch()
            m_occupancy.labels(phase="mono").observe(1.0)
            note_mesh_dispatch(bucket)
            batch_hits.append(hit)
            if ((finite is not None and not bool(finite[0])) or
                    (fault is not None and fault.kind == "nan"
                     and validate_outputs)):
                m_invalid.inc()
                yield record(
                    "invalid_output", e.request_id, arrival_ms=e.arrival_ms,
                    reason="non-finite values (NaN/Inf) in this lane's "
                           "latents; image withheld",
                    batch_id=batch_index, batch_lanes=bucket,
                    batch_occupancy=1, isolated_retry=True)
                continue
            lanes = lane_select(imgs, range(1))
            latency = vnow - e.arrival_ms
            latencies.append(latency)
            yield record(
                "ok", e.request_id, images=lanes[0],
                arrival_ms=e.arrival_ms,
                queue_wait_ms=dispatch_ms - e.arrival_ms,
                compile_ms=compile_ms, run_ms=run_ms, total_ms=latency,
                batch_id=batch_index, batch_lanes=bucket, batch_occupancy=1,
                cache_hit=hit, isolated_retry=True,
                gate_step=e.prepared.gate_step,
                **({"steps_done": steps_done} if steps_done else {}))

    # ------------------------------------------------------------------
    # Phase-disaggregated pools: phase-1 dispatch → hand-off → phase-2
    # dispatch. Fault semantics (classify / retry / isolate / quarantine /
    # drain) apply per pool, mirroring the monolithic paths above.
    # ------------------------------------------------------------------

    def do_handoff(entries, carry_g, batch_id, lanes, occupancy,
                   dispatch_ms, compile_ms, run_ms, hit,
                   isolated: bool = False, fault=None) -> None:
        """Phase-1 success: split the pool carry per lane and queue each
        request (with its carry and phase-1 latency facts) into the
        phase-2 batcher. No record is emitted — the request is still
        live, mid-trajectory. A chaos 'nan' fault taken at this dispatch
        marks its victim lanes so the completion-time finite verdict
        converts them (the monolithic path's semantics)."""
        nonlocal handoffs_total
        nan_rids = (set(fault.rids)
                    if (fault is not None and fault.kind == "nan"
                        and validate_outputs) else set())
        carries = handoff_mod.lane_carries(carry_g, len(entries))
        for e, c in zip(entries, carries):
            if sc is not None:
                # L2 prefix insert: the carry is a pure function of the
                # content key, so a later identical request skips phase 1
                # entirely (content-addressed spill, LRU-bounded; the
                # journal spill below is the CRASH copy, this is the
                # cross-request one).
                sc.l2_put(sc.digest(e.prepared.content_key), c)
            p1 = {"batch_id": batch_id, "lanes": lanes,
                  "occupancy": occupancy,
                  "queue_wait_ms": dispatch_ms - e.arrival_ms,
                  "compile_ms": compile_ms, "run_ms": run_ms,
                  "cache_hit": hit}
            if isolated:
                p1["isolated_retry"] = True
            if flight is not None:
                flight.event(e.request_id, "handoff", vnow,
                             batch_id=batch_id)
            if journal is not None:
                path = journal.carry_path(e.request_id)
                spec = handoff_mod.spill_carry(c, path)
                if flight is not None:
                    flight.event(e.request_id, "carry_spilled", vnow)
                journal.handoff(e.request_id, vnow, path, spec,
                                trace=(flight.context(e.request_id)
                                       if flight is not None else None))
            handoffs_total += 1
            m_handoffs.inc()
            h = HandoffEntry(entry=e, carry=c, handoff_ms=vnow, phase1=p1,
                             nan_injected=e.request_id in nan_rids)
            if e.request_id in forced_preempt:
                # chaos preempt_then_kill: this lane's phase boundary IS
                # the forced preemption point — park instead of queueing.
                forced_preempt.discard(e.request_id)
                park(h, "chaos")
            else:
                batcher2.add(h, vnow)

    # ------------------------------------------------------------------
    # SLO scheduler: phase-boundary preemption (park / resume) and
    # deadline-aware batching. All of it runs at cycle boundaries on the
    # virtual clock, so every policy decision is drill-able.
    # ------------------------------------------------------------------

    def park(e: HandoffEntry, cause: str) -> None:
        """Preempt one between-phases request: its carry spills to disk
        via the hand-off path with a journaled ``preempted`` record (the
        crash copy — a preempted-then-killed request resumes off it
        exactly like a crashed hand-off), and the entry waits in
        ``parked`` until pressure clears. The in-memory carry is kept:
        an in-process resume is bitwise-trivially the same work."""
        nonlocal preemptions
        e.preempted_ms = vnow
        preemptions += 1
        tier = (slo.tier(e.request) if slo is not None
                else (getattr(e.request, "tier", None)
                      or scheduling_mod.TIERS[1]))
        reg.counter("serve_preemptions_total",
                    "phase-boundary preemptions by victim tier",
                    labels=("tier",)).labels(tier=tier).inc()
        if journal is not None:
            path = journal.carry_path(e.request_id)
            spec = handoff_mod.spill_carry(e.carry, path)
            journal.preempted(e.request_id, vnow, path, spec, tier=tier,
                              trace=(flight.context(e.request_id)
                                     if flight is not None else None))
        if flight is not None:
            # Close the pre-park hand-off wait here so the parked span
            # itself lands in its own `preempt_wait` stage at resume.
            flight.wait(e.request_id, "handoff_wait", vnow, pool="phase2",
                        preempted=True)
            flight.event(e.request_id, "preempted", vnow, cause=cause)
        parked.append(e)

    def resume_parked(reason: str) -> None:
        nonlocal preempt_resumes
        if not parked:
            return
        for e in parked:
            if e.preempted_ms is not None:
                e.preempt_wait_ms += vnow - e.preempted_ms
                e.preempted_ms = None
            preempt_resumes += 1
            reg.counter("serve_preempt_resumes_total",
                        "parked (preempted) requests resumed into the "
                        "phase-2 batcher").inc()
            if flight is not None:
                # A resize park is its own flight stage: the pause a
                # cutover cost this request is `resize_wait`, not the
                # scheduler's `preempt_wait`.
                flight.wait(e.request_id,
                            "resize_wait" if reason == "resize"
                            else "preempt_wait", vnow, pool="phase2")
                flight.event(e.request_id, "preempt_resumed", vnow,
                             reason=reason)
            batcher2.add(e, vnow)
        parked.clear()

    def preemption_cycle() -> Iterator[dict]:
        """One cycle-boundary pass of the preemption policy: resolve
        parked work that was cancelled or expired while parked (the
        terminal record's journal write discards the spill — no orphan),
        park lower-tier phase-2 waiters under pressure, resume when the
        pressure clears or nothing higher-tier is waiting (a queue made
        of parked requests must never deadlock itself)."""
        if parked:
            still = []
            for e in parked:
                if queue.is_cancelled(e.request_id) or \
                        queue_mod.expired(e, vnow):
                    if e.preempted_ms is not None:
                        e.preempt_wait_ms += vnow - e.preempted_ms
                        e.preempted_ms = None
                    if queue.is_cancelled(e.request_id):
                        yield record("cancelled", e.request_id,
                                     arrival_ms=e.arrival_ms,
                                     queue_wait_ms=vnow - e.arrival_ms)
                    else:
                        yield record(
                            "expired", e.request_id,
                            arrival_ms=e.arrival_ms,
                            reason=(f"deadline {e.request.deadline_ms}ms "
                                    f"passed while preempted (waited "
                                    f"{vnow - e.arrival_ms:.1f}ms)"))
                else:
                    still.append(e)
            parked[:] = still
        if slo is not None and slo.preempt_depth is not None and \
                not draining and queue.outstanding > slo.preempt_depth:
            # (never parks while draining: a drain completes in-flight
            # work, it does not create more of it)
            ranks = [slo.rank(e.request) for e in batcher.entries()]
            if ranks:
                best = min(ranks)
                for e in batcher2.remove_if(
                        lambda e: slo.rank(e.request) > best):
                    park(e, "pressure")
        if parked:
            if slo is not None and slo.preempt_depth is not None:
                min_parked = min(slo.rank(e.request) for e in parked)
                blocked = (
                    queue.outstanding > slo.effective_resume_depth
                    and any(slo.rank(e.request) < min_parked
                            for e in batcher.entries()))
            else:
                blocked = False   # chaos-forced parks: the kill fired (or
                #                   never will) — resume at this boundary
            if not blocked:
                resume_parked("pressure_cleared")

    def jump_urgent(b, compile_key_of) -> List[Batch]:
        """Deadline-aware batching: a bucket holding an entry whose
        deadline would expire waiting out ``max_wait`` flushes NOW — but
        only onto an already-warm program (warm-preference then pads it
        up to the smallest warm bucket that fits, at dispatch). The jump
        never pulls a compile in-band: cold buckets age out exactly as
        before."""
        nonlocal deadline_jumps
        out: List[Batch] = []
        for key in b.waiting_keys():
            group = b.group(key)
            if len(group) >= b.max_batch:
                continue               # full: flushes this cycle anyway
            flush_at = b.group_flush_at(key)
            if flush_at is None or flush_at <= vnow:
                continue               # aged out: flushes this cycle
            if not any(e.deadline_at is not None
                       and vnow <= e.deadline_at < flush_at
                       for e in group):
                continue
            ck = compile_key_of(group[0])
            if _warm_bucket(len(group), ck, b.max_batch, cache,
                            sizes) is None:
                continue
            jumped = b.flush_key(key, vnow)
            deadline_jumps += len(jumped)
            reg.counter("serve_deadline_jumps_total",
                        "urgent buckets flushed onto a warm program "
                        "ahead of max_wait").inc(len(jumped))
            out.extend(jumped)
        return out

    def _ck_main(e):
        prep = e.prepared
        return mkey(prep.phase1_key if (prep.gated and phase_pools)
                    else prep.compile_key)

    def _ck_phase2(e):
        return mkey(e.prepared.phase2_key)

    # ------------------------------------------------------------------
    # Elastic resize (serve.elastic, ISSUE 19): the controller decides in
    # observe() (called with update_degradation each cycle); the protocol
    # below executes at the batch-boundary fsync point. All of it is a
    # no-op with elastic=None.
    # ------------------------------------------------------------------

    def _prewarm_resize(target_dp: int) -> dict:
        """Compile-ahead on the target topology while the current mesh is
        still the serving one: build the target mesh + runner factory and
        warm a target-keyed program for every piece of live work (both
        pools + parked), at the pools' effective caps AND the operator
        caps (so a degradation restore right after the cutover stays
        warm too). Out-of-band by construction — the virtual clock does
        not advance, so no request's latency carries a resize build."""
        t_spec = meshing_mod.MeshSpec(dp=target_dp)
        t_jmesh = meshing_mod.build_mesh(t_spec)
        t_sizes = meshing_mod.scaled_bucket_sizes(target_dp)
        t_factory = runner_factory or default_runner_factory(
            pipe, progress=progress, validate=validate_outputs,
            heartbeat=watchdog_ms is not None, mesh=t_jmesh,
            semcache=semcache)
        caps1 = {batcher.max_batch // dp, max_batch}
        caps2 = {batcher2.max_batch // dp, phase2_max_batch}
        t0 = timer()
        seen: set = set()
        with span("serve.resize_prewarm", target_dp=target_dp):
            for e in (list(batcher.entries()) + list(batcher2.entries())
                      + list(parked)):
                prep = e.prepared
                if prep.gated and phase_pools:
                    keyed = (
                        [(meshing_mod.mesh_key(prep.phase1_key, t_spec), c)
                         for c in caps1]
                        + [(meshing_mod.mesh_key(prep.phase2_key, t_spec),
                            c) for c in caps2])
                else:
                    keyed = [(meshing_mod.mesh_key(prep.compile_key,
                                                   t_spec), c)
                             for c in caps1]
                for key, cap in keyed:
                    bucket = cap * target_dp
                    if (key, bucket) in seen:
                        continue
                    seen.add((key, bucket))
                    cache.get((key, bucket),
                              lambda k=key, b=bucket, ent=e: _build(
                                  t_factory, k, b, [ent]))
        return {"spec": t_spec, "jmesh": t_jmesh, "sizes": t_sizes,
                "factory": t_factory,
                "prewarm_ms": (timer() - t0) * 1000.0}

    def maybe_resize() -> None:
        """Execute a standing resize decision at this batch boundary:
        prewarm → park in-flight phase-2 work (spill carries — the crash
        copy) → journal the ``resize`` record → fsync → (chaos
        ``kill_during_resize`` window) → swap the topology state →
        resume the parked carries, restaged onto the new shards by the
        new runners' ``stack_carries(mesh=)``. Phase-1 work still queued
        has no device state to move — it just dispatches on the new
        mesh's keys next cycle."""
        nonlocal mesh_spec, dp, jmesh, sizes, make_runner, _mesh_dev_ids
        if elastic_ctl is None or draining or fatal_reason[0] is not None:
            return
        target = elastic_ctl.pending_target
        if target is None or target == dp:
            return
        direction = elastic_mod.UP if target > dp else elastic_mod.DOWN
        pre = _prewarm_resize(target)
        wall0 = timer()
        with span("serve.resize", old_dp=dp, new_dp=target,
                  direction=direction):
            for e in batcher2.remove_if(lambda _e: True):
                park(e, "resize")
            parked_ids = [e.request_id for e in parked]
            if journal is not None:
                journal.event("resize", old_dp=dp, new_dp=target,
                              direction=direction, parked=parked_ids,
                              vnow_ms=round(vnow, 3))
                journal.sync()
            if chaos is not None and \
                    chaos.take_kill(chaos_mod.KILL_DURING_RESIZE):
                # Dies with the resize record durable but the cutover
                # unfinished: the restart folds new_dp out of the WAL and
                # comes back on the TARGET topology, resuming the parked
                # carries off their spills exactly-once.
                raise chaos_mod.SimulatedKill("chaos kill_during_resize")
            mesh_spec = pre["spec"]
            dp = target
            jmesh = pre["jmesh"]
            sizes = pre["sizes"]
            make_runner = pre["factory"]
            _mesh_dev_ids = [str(d.id) for d in jmesh.devices.flat]
            batcher.bucket_sizes = sizes
            batcher2.bucket_sizes = sizes
            _apply_degrade_level()   # rescales both pools' caps by new dp
            m_mesh_devices.set(dp)   # time-varying: the topology gauge
            m_resizes.labels(direction=direction).inc()
            if costscope is not None:
                costscope.devices = max(1, dp)
            if prodscope is not None:
                prodscope.devices = max(1, dp)
            resumed = len(parked)
            resume_parked("resize")
            pause_ms = (timer() - wall0) * 1000.0
        entry = elastic_ctl.committed(
            vnow, dp, prewarm_ms=pre["prewarm_ms"], pause_ms=pause_ms,
            parked=len(parked_ids), resumed=resumed)
        if flight is not None:
            flight.loop_event("resize", vnow, old_dp=entry["old_dp"],
                              new_dp=entry["new_dp"], direction=direction,
                              parked=entry["parked"])

    def dispatch_phase1(batch: Batch) -> Iterator[dict]:
        nonlocal vnow, batch_index, retries_total
        live = []
        for e in batch.entries:
            if queue.is_cancelled(e.request_id):
                yield record("cancelled", e.request_id,
                             arrival_ms=e.arrival_ms,
                             queue_wait_ms=vnow - e.arrival_ms)
            elif queue_mod.expired(e, vnow):
                yield record(
                    "expired", e.request_id, arrival_ms=e.arrival_ms,
                    reason=(f"deadline {e.request.deadline_ms}ms passed "
                            f"before dispatch (waited "
                            f"{vnow - e.arrival_ms:.1f}ms)"))
            else:
                live.append(e)
        if not live:
            return
        batch_index += 1
        this_batch = batch_index
        guidance = live[0].request.guidance
        compile_key = mkey(live[0].prepared.phase1_key)
        bucket = _pick_bucket(len(live), compile_key, batcher.max_batch,
                              cache, sizes)
        if bucket > bucket_for(len(live), batcher.max_batch, sizes):
            m_upsized.inc()
        if journal is not None:
            journal.dispatched([e.request_id for e in live], this_batch,
                               vnow, phase=1)
        dispatch_ms = vnow
        if flight is not None:
            for e in live:
                flight.wait(e.request_id, "queue_wait", dispatch_ms,
                            pool="phase1")
        attempt = 0
        while True:
            fault = take_chaos(this_batch, [e.request_id for e in live])
            t0 = timer()
            try:
                span_name = "serve.batch" if attempt == 0 else "serve.retry"
                with _trace_attach(live), \
                        span(span_name, batch=this_batch, lanes=bucket,
                             occupancy=len(live), phase=1,
                             **({"attempt": attempt} if attempt else {})):
                    carry_g, run_ms, hit, _, _ = run_entries(
                        live, compile_key, guidance, bucket, fault=fault,
                        pool="phase1")
                total_ms = (timer() - t0) * 1000.0
                compile_ms = max(0.0, total_ms - run_ms)
                break
            except Exception as exc:  # noqa: BLE001 — classified below
                elapsed = (timer() - t0) * 1000.0
                vnow += elapsed
                kind, reason = _fault_verdict(exc)
                if flight is not None:
                    for e in live:
                        flight.segment(e.request_id, "fault",
                                       vnow - elapsed, elapsed,
                                       pool="phase1", kind=kind,
                                       attempt=attempt)
                if kind == faults_mod.TIMEOUT:
                    _note_timeout(compile_key, bucket)
                    for e in live:
                        yield record("timeout", e.request_id,
                                     arrival_ms=e.arrival_ms, reason=reason,
                                     batch_id=this_batch)
                    return
                if kind == faults_mod.FATAL:
                    for e in live:
                        yield record("error", e.request_id,
                                     arrival_ms=e.arrival_ms,
                                     reason=f"fatal: {reason}",
                                     batch_id=this_batch)
                    fatal_reason[0] = reason
                    return
                if kind == faults_mod.TRANSIENT:
                    if attempt + 1 < policy.max_attempts:
                        backoff = policy.backoff_ms(
                            attempt, key=f"batch:{this_batch}")
                        retries_total += 1
                        m_retries.inc()
                        m_backoff.observe(backoff)
                        vnow += backoff
                        if flight is not None:
                            for e in live:
                                flight.segment(e.request_id, "backoff",
                                               vnow - backoff, backoff,
                                               pool="phase1",
                                               attempt=attempt)
                        attempt += 1
                        recs, live = _live_after_backoff(live)
                        yield from recs
                        if not live:
                            return
                        continue
                    for e in live:
                        yield record(
                            "error", e.request_id, arrival_ms=e.arrival_ms,
                            reason=(f"transient fault persisted through "
                                    f"{policy.max_attempts} attempts: "
                                    f"{reason}"),
                            batch_id=this_batch)
                    return
                yield from isolate_phase1(live, compile_key, guidance, exc)
                return
        v0 = vnow
        vnow += compile_ms + run_ms
        if flight is not None:
            for e in live:
                flight.segment(e.request_id, "compile", v0, compile_ms,
                               pool="phase1", cache_hit=hit)
                flight.segment(e.request_id, "run", v0 + compile_ms, run_ms,
                               pool="phase1", batch_id=this_batch,
                               **last_cost[0])
        occupancies.append(len(live))
        occ_by_phase["phase1"].append(len(live))
        m_occupancy.labels(phase="phase1").observe(float(len(live)))
        note_mesh_dispatch(bucket)
        batch_hits.append(hit)
        do_handoff(live, carry_g, this_batch, bucket, len(live),
                   dispatch_ms, compile_ms, run_ms, hit, fault=fault)

    def isolate_phase1(entries, compile_key, guidance,
                       batch_exc) -> Iterator[dict]:
        """A phase-1 batch failed: re-run each lane alone; survivors hand
        off to the phase-2 pool exactly as a healthy batch's lanes do."""
        nonlocal vnow, batch_index
        entries = list(entries)
        for idx, e in enumerate(entries):
            batch_index += 1
            m_isolated.inc()
            bucket = _pick_bucket(1, compile_key, batcher.max_batch, cache,
                                  sizes)
            if journal is not None:
                journal.dispatched([e.request_id], batch_index, vnow,
                                   phase=1)
            dispatch_ms = vnow
            if flight is not None:
                flight.wait(e.request_id, "requeue_wait", dispatch_ms,
                            pool="phase1", isolated=True)
            fault = take_chaos(batch_index, [e.request_id])
            try:
                t0 = timer()
                with _trace_attach([e]), \
                        span("serve.isolate_retry", batch=batch_index,
                             lanes=bucket, request=e.request_id, phase=1):
                    carry_g, run_ms, hit, _, _ = run_entries(
                        [e], compile_key, guidance, bucket, fault=fault,
                        pool="phase1")
                compile_ms = max(0.0, (timer() - t0) * 1000.0 - run_ms)
            except Exception as exc:  # noqa: BLE001 — classified below
                elapsed = (timer() - t0) * 1000.0
                vnow += elapsed
                kind, reason = _fault_verdict(exc)
                if flight is not None:
                    flight.segment(e.request_id, "fault", vnow - elapsed,
                                   elapsed, pool="phase1", kind=kind,
                                   isolated=True)
                batch_err = f"{type(batch_exc).__name__}: {batch_exc}"
                if kind == faults_mod.TIMEOUT:
                    _note_timeout(compile_key, bucket)
                    yield record(
                        "timeout", e.request_id, arrival_ms=e.arrival_ms,
                        reason=reason, batch_id=batch_index,
                        batch_error=batch_err, isolated_retry=True)
                    continue
                if kind == faults_mod.FATAL:
                    fatal_reason[0] = reason
                    for rest in entries[idx:]:
                        yield record(
                            "error", rest.request_id,
                            arrival_ms=rest.arrival_ms,
                            reason=f"fatal: {reason}", batch_error=batch_err)
                    return
                yield record(
                    "error", e.request_id, arrival_ms=e.arrival_ms,
                    reason=reason, batch_error=batch_err)
                continue
            v0 = vnow
            vnow += compile_ms + run_ms
            if flight is not None:
                flight.segment(e.request_id, "compile", v0, compile_ms,
                               pool="phase1", cache_hit=hit, isolated=True)
                flight.segment(e.request_id, "run", v0 + compile_ms,
                               run_ms, pool="phase1", batch_id=batch_index,
                               isolated=True, **last_cost[0])
            occupancies.append(1)
            occ_by_phase["phase1"].append(1)
            m_occupancy.labels(phase="phase1").observe(1.0)
            note_mesh_dispatch(bucket)
            batch_hits.append(hit)
            do_handoff([e], carry_g, batch_index, bucket, 1, dispatch_ms,
                       compile_ms, run_ms, hit, isolated=True, fault=fault)

    def emit_phase2_lane(e: HandoffEntry, image, this_batch, bucket,
                         occupancy, dispatch_ms, compile_ms, run_ms, hit,
                         isolated: bool = False) -> dict:
        """One gated request completed: assemble its ok record (whole-
        request latency split + the per-phase `phases` detail) and feed
        the per-phase stage histograms."""
        latency = vnow - e.arrival_ms
        latencies.append(latency)
        p1 = e.phase1
        # The parked (preempted) span is split OUT of the hand-off wait:
        # the record's phases detail and the phase-2 queue-wait histogram
        # attribute the scheduler's milliseconds to preempt_wait_ms, not
        # to the batcher — the same split the flight tracer makes.
        handoff_wait = max(0.0, dispatch_ms - e.handoff_ms
                           - e.preempt_wait_ms)
        phases: dict = {
            "handoff_wait_ms": handoff_wait,
            "phase2": {"batch_id": this_batch, "lanes": bucket,
                       "occupancy": occupancy, "compile_ms": compile_ms,
                       "run_ms": run_ms, "cache_hit": hit},
        }
        stage = m_stage
        if p1 is not None:
            phases["phase1"] = dict(p1)
            stage["queue_wait_ms"].labels(phase="phase1").observe(
                float(p1["queue_wait_ms"]))
            stage["compile_ms"].labels(phase="phase1").observe(
                float(p1["compile_ms"]))
            stage["run_ms"].labels(phase="phase1").observe(
                float(p1["run_ms"]))
        else:
            # No phase-1 dispatch this incarnation: either a crash-replay
            # resume off the journal spill, or a semantic-cache L2 prefix
            # hit (the cached carry stood in for phase 1 entirely).
            phases["phase1"] = ({"cached": True} if e.cache_layer == "l2"
                                else {"resumed": True})
        if e.resumed:
            phases["resumed"] = True
        if e.preempt_wait_ms:
            # This request was preempted at the phase boundary and parked;
            # the parked span is split out of the hand-off wait so latency
            # attribution names the scheduler, not the batcher.
            phases["preempted"] = True
            phases["preempt_wait_ms"] = e.preempt_wait_ms
        stage["queue_wait_ms"].labels(phase="phase2").observe(handoff_wait)
        stage["compile_ms"].labels(phase="phase2").observe(compile_ms)
        stage["run_ms"].labels(phase="phase2").observe(run_ms)
        stage["total_ms"].labels(phase="gated").observe(latency)
        extra = {"isolated_retry": True} if isolated else {}
        if e.cache_layer is not None:
            extra["cache"] = {"layer": e.cache_layer}
        return record(
            "ok", e.request_id, stage_phase=None, images=image,
            arrival_ms=e.arrival_ms,
            queue_wait_ms=(p1["queue_wait_ms"] if p1 is not None else 0.0),
            compile_ms=(p1["compile_ms"] if p1 else 0.0) + compile_ms,
            run_ms=(p1["run_ms"] if p1 else 0.0) + run_ms,
            total_ms=latency, batch_id=this_batch, batch_lanes=bucket,
            batch_occupancy=occupancy,
            cache_hit=bool(hit and (p1 is None or p1["cache_hit"])),
            gate_step=e.prepared.gate_step, phases=phases, **extra)

    def dispatch_phase2(batch: Batch) -> Iterator[dict]:
        nonlocal vnow, batch_index, retries_total
        live = []
        for e in batch.entries:
            if queue.is_cancelled(e.request_id):
                yield record("cancelled", e.request_id,
                             arrival_ms=e.arrival_ms,
                             queue_wait_ms=vnow - e.arrival_ms)
            elif queue_mod.expired(e, vnow):
                yield record(
                    "expired", e.request_id, arrival_ms=e.arrival_ms,
                    reason=(f"deadline {e.request.deadline_ms}ms passed "
                            f"during the phase hand-off (waited "
                            f"{vnow - e.arrival_ms:.1f}ms)"))
            else:
                live.append(e)
        if not live:
            return
        batch_index += 1
        this_batch = batch_index
        guidance = live[0].request.guidance
        compile_key = mkey(live[0].prepared.phase2_key)
        bucket = _pick_bucket(len(live), compile_key, batcher2.max_batch,
                              cache, sizes)
        if bucket > bucket_for(len(live), batcher2.max_batch, sizes):
            m_upsized.inc()
        if journal is not None:
            journal.dispatched([e.request_id for e in live], this_batch,
                               vnow, phase=2)
        dispatch_ms = vnow
        if flight is not None:
            for e in live:
                # Cursor sits at the end of the phase-1 run (or at 0 for a
                # crash-resumed lane): the wait is hand-off → dispatch.
                flight.wait(e.request_id, "handoff_wait", dispatch_ms,
                            pool="phase2")
        attempt = 0
        while True:
            fault = take_chaos(this_batch, [e.request_id for e in live])
            t0 = timer()
            try:
                span_name = "serve.batch" if attempt == 0 else "serve.retry"
                with _trace_attach(live), \
                        span(span_name, batch=this_batch, lanes=bucket,
                             occupancy=len(live), phase=2,
                             **({"attempt": attempt} if attempt else {})):
                    imgs, run_ms, hit, _, finite = run_entries(
                        live, compile_key, guidance, bucket, fault=fault,
                        pool="phase2")
                total_ms = (timer() - t0) * 1000.0
                compile_ms = max(0.0, total_ms - run_ms)
                break
            except Exception as exc:  # noqa: BLE001 — classified below
                elapsed = (timer() - t0) * 1000.0
                vnow += elapsed
                kind, reason = _fault_verdict(exc)
                if flight is not None:
                    for e in live:
                        flight.segment(e.request_id, "fault",
                                       vnow - elapsed, elapsed,
                                       pool="phase2", kind=kind,
                                       attempt=attempt)
                if kind == faults_mod.TIMEOUT:
                    _note_timeout(compile_key, bucket)
                    for e in live:
                        yield record("timeout", e.request_id,
                                     arrival_ms=e.arrival_ms, reason=reason,
                                     batch_id=this_batch)
                    return
                if kind == faults_mod.FATAL:
                    for e in live:
                        yield record("error", e.request_id,
                                     arrival_ms=e.arrival_ms,
                                     reason=f"fatal: {reason}",
                                     batch_id=this_batch)
                    fatal_reason[0] = reason
                    return
                if kind == faults_mod.TRANSIENT:
                    if attempt + 1 < policy.max_attempts:
                        backoff = policy.backoff_ms(
                            attempt, key=f"batch:{this_batch}")
                        retries_total += 1
                        m_retries.inc()
                        m_backoff.observe(backoff)
                        vnow += backoff
                        if flight is not None:
                            for e in live:
                                flight.segment(e.request_id, "backoff",
                                               vnow - backoff, backoff,
                                               pool="phase2",
                                               attempt=attempt)
                        attempt += 1
                        recs, live = _live_after_backoff(live)
                        yield from recs
                        if not live:
                            return
                        continue
                    for e in live:
                        yield record(
                            "error", e.request_id, arrival_ms=e.arrival_ms,
                            reason=(f"transient fault persisted through "
                                    f"{policy.max_attempts} attempts: "
                                    f"{reason}"),
                            batch_id=this_batch)
                    return
                yield from isolate_phase2(live, compile_key, guidance, exc)
                return
        v0 = vnow
        vnow += compile_ms + run_ms
        if flight is not None:
            for e in live:
                flight.segment(e.request_id, "compile", v0, compile_ms,
                               pool="phase2", cache_hit=hit)
                flight.segment(e.request_id, "run", v0 + compile_ms, run_ms,
                               pool="phase2", batch_id=this_batch,
                               **last_cost[0])
        occupancies.append(len(live))
        occ_by_phase["phase2"].append(len(live))
        m_occupancy.labels(phase="phase2").observe(float(len(live)))
        note_mesh_dispatch(bucket)
        batch_hits.append(hit)
        bad = set()
        if finite is not None:
            bad = {i for i in range(len(live)) if not bool(finite[i])}
        if (fault is not None and fault.kind == "nan" and validate_outputs):
            bad |= {i for i, e in enumerate(live)
                    if e.request_id in fault.rids}
        # Lanes whose PHASE-1 dispatch took the nan injection: validation
        # is a completion-time verdict, so the marker converts them here.
        bad |= {i for i, e in enumerate(live) if e.nan_injected}
        lanes = lane_select(imgs, range(len(live)))
        for i, e in enumerate(live):
            if i in bad:
                m_invalid.inc()
                yield record(
                    "invalid_output", e.request_id,
                    arrival_ms=e.arrival_ms,
                    reason="non-finite values (NaN/Inf) in this lane's "
                           "latents; image withheld",
                    batch_id=this_batch, batch_lanes=bucket,
                    batch_occupancy=len(live))
                continue
            yield emit_phase2_lane(e, lanes[i], this_batch, bucket,
                                   len(live), dispatch_ms, compile_ms,
                                   run_ms, hit)

    def isolate_phase2(entries, compile_key, guidance,
                       batch_exc) -> Iterator[dict]:
        """A phase-2 batch failed: each lane re-runs alone off its own
        carry; the survivors still complete."""
        nonlocal vnow, batch_index
        entries = list(entries)
        for idx, e in enumerate(entries):
            batch_index += 1
            m_isolated.inc()
            bucket = _pick_bucket(1, compile_key, batcher2.max_batch, cache,
                                  sizes)
            if journal is not None:
                journal.dispatched([e.request_id], batch_index, vnow,
                                   phase=2)
            dispatch_ms = vnow
            if flight is not None:
                flight.wait(e.request_id, "requeue_wait", dispatch_ms,
                            pool="phase2", isolated=True)
            fault = take_chaos(batch_index, [e.request_id])
            try:
                t0 = timer()
                with _trace_attach([e]), \
                        span("serve.isolate_retry", batch=batch_index,
                             lanes=bucket, request=e.request_id, phase=2):
                    imgs, run_ms, hit, _, finite = run_entries(
                        [e], compile_key, guidance, bucket, fault=fault,
                        pool="phase2")
                compile_ms = max(0.0, (timer() - t0) * 1000.0 - run_ms)
            except Exception as exc:  # noqa: BLE001 — classified below
                elapsed = (timer() - t0) * 1000.0
                vnow += elapsed
                kind, reason = _fault_verdict(exc)
                if flight is not None:
                    flight.segment(e.request_id, "fault", vnow - elapsed,
                                   elapsed, pool="phase2", kind=kind,
                                   isolated=True)
                batch_err = f"{type(batch_exc).__name__}: {batch_exc}"
                if kind == faults_mod.TIMEOUT:
                    _note_timeout(compile_key, bucket)
                    yield record(
                        "timeout", e.request_id, arrival_ms=e.arrival_ms,
                        reason=reason, batch_id=batch_index,
                        batch_error=batch_err, isolated_retry=True)
                    continue
                if kind == faults_mod.FATAL:
                    fatal_reason[0] = reason
                    for rest in entries[idx:]:
                        yield record(
                            "error", rest.request_id,
                            arrival_ms=rest.arrival_ms,
                            reason=f"fatal: {reason}", batch_error=batch_err)
                    return
                yield record(
                    "error", e.request_id, arrival_ms=e.arrival_ms,
                    reason=reason, batch_error=batch_err)
                continue
            v0 = vnow
            vnow += compile_ms + run_ms
            if flight is not None:
                flight.segment(e.request_id, "compile", v0, compile_ms,
                               pool="phase2", cache_hit=hit, isolated=True)
                flight.segment(e.request_id, "run", v0 + compile_ms,
                               run_ms, pool="phase2", batch_id=batch_index,
                               isolated=True, **last_cost[0])
            occupancies.append(1)
            occ_by_phase["phase2"].append(1)
            m_occupancy.labels(phase="phase2").observe(1.0)
            note_mesh_dispatch(bucket)
            batch_hits.append(hit)
            if ((finite is not None and not bool(finite[0])) or
                    e.nan_injected or
                    (fault is not None and fault.kind == "nan"
                     and validate_outputs)):
                m_invalid.inc()
                yield record(
                    "invalid_output", e.request_id, arrival_ms=e.arrival_ms,
                    reason="non-finite values (NaN/Inf) in this lane's "
                           "latents; image withheld",
                    batch_id=batch_index, batch_lanes=bucket,
                    batch_occupancy=1, isolated_retry=True)
                continue
            lanes = lane_select(imgs, range(1))
            yield emit_phase2_lane(e, lanes[0], batch_index, bucket, 1,
                                   dispatch_ms, compile_ms, run_ms, hit,
                                   isolated=True)

    def update_degradation() -> None:
        """Pressure hysteresis: one level up per sustained-pressure window,
        one level down per sustained-calm window. Both directions are
        journaled and counted."""
        nonlocal degrade_level, pressure_since, calm_since, \
            degrade_transitions
        if degrade is None:
            return
        depth = queue.outstanding
        if depth > degrade.depth_threshold:
            calm_since = None
            if pressure_since is None:
                pressure_since = vnow
            elif (vnow - pressure_since >= degrade.window_ms
                  and degrade_level < 3):
                degrade_level += 1
                pressure_since = vnow  # re-arm toward the next level
                degrade_transitions += 1
                m_degrade_trans.labels(direction="up").inc()
                m_degrade_level.set(degrade_level)
                if journal is not None:
                    journal.event("degrade", level=degrade_level,
                                  depth=depth, vnow_ms=round(vnow, 3))
                if flight is not None:
                    flight.loop_event("degrade", vnow, level=degrade_level,
                                      depth=depth)
                if sc is not None and degrade_level >= 2:
                    # Eviction joins the ladder: spill disk is cheaper
                    # than any request — the L2 prefix store is shed one
                    # rung BEFORE level 3 starts shedding traffic (its
                    # entries rebuild from hand-offs once pressure
                    # clears; exact results and embeddings are kept —
                    # they are what absorbs the overload).
                    shed_entries = sc.shed_l2()
                    if shed_entries and journal is not None:
                        journal.event("cache_shed", layer="l2",
                                      entries=shed_entries,
                                      vnow_ms=round(vnow, 3))
                _apply_degrade_level()
        else:
            pressure_since = None
            if calm_since is None:
                calm_since = vnow
            elif (vnow - calm_since >= degrade.window_ms
                  and degrade_level > 0):
                degrade_level -= 1
                calm_since = vnow
                degrade_transitions += 1
                m_degrade_trans.labels(direction="down").inc()
                m_degrade_level.set(degrade_level)
                if journal is not None:
                    journal.event("restore", level=degrade_level,
                                  depth=depth, vnow_ms=round(vnow, 3))
                if flight is not None:
                    flight.loop_event("restore", vnow, level=degrade_level,
                                      depth=depth)
                _apply_degrade_level()

    def _apply_degrade_level() -> None:
        # Level 2+: smaller flush/padding bucket — shorter head-of-line
        # blocking when deadlines are the binding constraint. The batcher
        # caps stay within the fixed bucket set, preserving the padding
        # contract. Degradation is per-pool: both pools shrink one step
        # below their own cap, so the phase-2 pool keeps its relative
        # width. On a mesh the shrink happens per device (the operator
        # knobs' unit) and scales back up by dp.
        shrink = degrade_level >= 2
        batcher.max_batch = (_shrunken_bucket(max_batch, degrade.min_bucket)
                             if shrink else max_batch) * dp
        batcher2.max_batch = (
            _shrunken_bucket(phase2_max_batch, degrade.min_bucket)
            if shrink else phase2_max_batch) * dp

    if restore_degrade_level:
        # Warm restart: resume the snapshot's degradation level instead of
        # re-learning the pressure from scratch (transitions from here on
        # are journaled/counted as usual; recovery hysteresis applies).
        degrade_level = min(3, max(0, int(restore_degrade_level)))
        m_degrade_level.set(degrade_level)
        _apply_degrade_level()

    while True:
        if drain_ctl.requested and not draining:
            # Graceful drain latches here, at a cycle boundary — the
            # deterministic check point that makes drill drains replay
            # identically. From now on: no admissions, no waiting on
            # future arrivals; in-flight work completes (or the wall-clock
            # budget expires), then snapshot + summary + exit.
            draining = True
            drain_wall0 = timer()
            m_draining.set(1)
            m_drains.inc()
            if journal is not None:
                journal.event("drain", reason=drain_ctl.reason,
                              vnow_ms=round(vnow, 3))
            if flight is not None:
                flight.loop_event("drain", vnow, reason=drain_ctl.reason)
            # Parked (preempted) work is in-flight work: a graceful drain
            # completes it, so it re-enters the phase-2 batcher now.
            resume_parked("draining")
        # 1. Admit everything that has arrived by now.
        while trace.peek() is not None and \
                getattr(trace.peek(), "arrival_ms", vnow) <= vnow:
            item = trace.pop()
            if isinstance(item, Cancel):
                queue.cancel(item.request_id)  # unknown id: benign no-op
                continue
            if item.request_id in replay_skip:
                # The WAL already resolved (or re-admitted) this id:
                # exactly-once means the trace copy is a no-op, counted.
                m_replay.labels(kind="deduped").inc()
                if replay_info is not None:
                    replay_info["deduped"] += 1
                continue
            if draining:
                # Not journaled as terminal (journal_write=False): a
                # draining rejection is backpressure, not a resolution —
                # the restarted server must still serve a resubmission of
                # this id (the rolling-restart drill's re-fed trace relies
                # on exactly that).
                m_rejects.labels(kind="draining").inc()
                yield record(
                    "rejected", item.request_id, release=False,
                    journal_write=False, arrival_ms=item.arrival_ms,
                    reason=f"server draining ({drain_ctl.reason}); "
                           f"resubmit after restart")
                continue
            # Scheduled requests (ISSUE 15) are exempt from the level-1
            # force-gate: gate and schedule are mutually exclusive at the
            # schema level, and a reuse schedule already bought its own
            # cheaper sampling — forcing gate='auto' onto one would be a
            # clean-schema reject, not a degradation.
            forced_gate = degrade_level >= 1 and item.gate is None and \
                item.schedule is None and \
                (slo is None
                 or slo.tier(item) not in slo.protect_gate_tiers)
            if forced_gate:
                # Level 1+: cheaper phase-2 sampling instead of rejections
                # — approximate results are the graceful part.
                item = dataclasses.replace(item, gate="auto")
            try:
                prep = prepare(item, pipe)
                if sc is not None:
                    recs, ckind = cache_admit(prep, vnow)
                    if ckind is not None:
                        for r in recs:
                            yield r
                        continue
                queue.submit(prep, vnow)
                if sc is not None:
                    register_leader(item.request_id, prep)
                if slo is not None:
                    tier_by_id[item.request_id] = slo.tier(item)
                if forced_gate:
                    # Counted only on successful admission: a rejected
                    # request was never force-gated, it never ran.
                    forced_gate_ids.add(item.request_id)
                    m_degraded_gate.inc()
                if flight is not None:
                    flight.admit(item.request_id, vnow,
                                 arrival_ms=max(0.0, item.arrival_ms),
                                 gated=prep.gated and phase_pools,
                                 forced_gate=forced_gate)
                if journal is not None:
                    journal.admitted(item.to_dict(), vnow)
            except (Rejected, ValueError) as e:
                reason = e.reason if isinstance(e, Rejected) else str(e)
                # Bounded-cardinality reject classification (reasons are
                # free text): backpressure kinds come off the exception,
                # spec validation is "invalid_spec".
                kind = getattr(e, "kind", "invalid_spec")
                m_rejects.labels(kind=kind).inc()
                if kind == "quota":
                    quota_rejects += 1
                yield record("rejected", item.request_id, release=False,
                             journal_write=(kind != "duplicate_id"),
                             arrival_ms=item.arrival_ms, reason=reason)
        update_degradation()
        if elastic_ctl is not None and not draining:
            # The elastic detector samples the same pressure signal the
            # degradation ladder watches, every cycle. A standing shrink
            # decision is deferred while premium work is live anywhere
            # (premium traffic never waits on a cutover pause it didn't
            # need); the cutover itself runs at the batch boundary below.
            elastic_ctl.observe(
                queue.outstanding, vnow,
                premium_waiting=(slo is not None and any(
                    t == scheduling_mod.TIERS[0]
                    for t in tier_by_id.values())))
        # 2. Feed the batcher — at level 3, shedding what the threshold
        # cannot hold (lowest priority first, newest arrivals first).
        drained = queue.drain()
        victims: set = set()
        if degrade is not None and degrade_level >= 3:
            overshoot = queue.outstanding - degrade.depth_threshold
            if overshoot > 0:
                if slo is None:
                    by_value = sorted(
                        drained, key=lambda e: (e.request.priority, -e.seq))
                    victims = {id(e) for e in by_value[:overshoot]}
                else:
                    # Per-tier degradation: only the WORST tier present
                    # anywhere undispatched (this drain, both batchers,
                    # parked work) is sheddable — a paid tier is touched
                    # only when nothing lower remains at all. Victims
                    # come from the admission side (drain + main
                    # batcher): phase-2/parked work is past its phase-1
                    # compute and is preemption's job, not the shed's —
                    # but its presence still shields paid tiers.
                    pool = drained + list(batcher.entries())
                    present = (pool + parked + list(batcher2.entries()))
                    if pool:
                        worst = max(slo.rank(e.request) for e in present)
                        by_value = sorted(
                            (e for e in pool
                             if slo.rank(e.request) == worst),
                            key=lambda e: (e.request.priority, -e.seq))
                        victims = {id(e) for e in by_value[:overshoot]}
                        for entry in batcher.remove_if(
                                lambda e: id(e) in victims):
                            m_shed.inc()
                            yield record(
                                "shed", entry.request_id,
                                arrival_ms=entry.arrival_ms,
                                reason=(f"load shed at degradation level "
                                        f"{degrade_level}: outstanding "
                                        f"{queue.outstanding} > threshold "
                                        f"{degrade.depth_threshold}"))
        for entry in drained:
            if id(entry) in victims:
                m_shed.inc()
                yield record(
                    "shed", entry.request_id, arrival_ms=entry.arrival_ms,
                    reason=(f"load shed at degradation level "
                            f"{degrade_level}: outstanding "
                            f"{queue.outstanding} > threshold "
                            f"{degrade.depth_threshold}"))
            else:
                batcher.add(entry, vnow)
        # 2.5 Preemption policy at the cycle boundary: cancel/expire
        # parked work, park lower-tier phase-2 waiters under pressure,
        # resume when it clears (a no-op without an SloConfig or a chaos
        # forced preemption).
        yield from preemption_cycle()
        # 2.6 Single-flight followers whose leader resolved last cycle get
        # their terminals (cancel/expiry checked at emission).
        if sc is not None:
            yield from flush_followers()
        # 3. Flush whatever is due — phase-2 pool first: finishing
        # nearly-done requests frees outstanding slots and bounds their
        # p95 before new phase-1 work starts (the continuous-batching
        # priority). Deadline-urgent buckets jump the age-out onto warm
        # programs (serve.scheduling).
        batches2 = batcher2.ready(vnow)
        batches = batcher.ready(vnow)
        if slo is not None and slo.deadline_jump:
            batches2 += jump_urgent(batcher2, _ck_phase2)
            batches += jump_urgent(batcher, _ck_main)
        if not batches and not batches2:
            if journal is not None:
                journal.sync()  # going idle: everything admitted is durable
            # An idle cycle is a batch boundary too: a lull-driven
            # scale-down must not wait for the next dispatch to execute.
            maybe_resize()
            # Draining: never wait on future arrivals or bucket age-outs —
            # flush everything now and exit once the pipeline is empty.
            events = [] if draining else [
                t for t in (trace.next_arrival_ms,
                            batcher.next_flush_ms(),
                            batcher2.next_flush_ms())
                if t is not None]
            if events:
                vnow = max(vnow, min(events))
                continue
            # Trace done (or draining): drain both tails (hand-offs
            # produced by the phase-1 tail re-enter via the next loop
            # iteration). Parked work resumes first — the pipeline is not
            # empty while a preempted request still holds a carry.
            if parked:
                resume_parked("pipeline_drained")
            batches2 = batcher2.flush_all(vnow)
            batches = batcher.flush_all(vnow)
            if not batches and not batches2:
                if sc is not None and ready_followers:
                    # The pipeline is not empty while a resolved leader's
                    # followers still await their terminals.
                    yield from flush_followers()
                    continue
                break
        ordered = ([("phase2", b) for b in batches2]
                   + [("phase1", b) for b in batches])
        if slo is not None:
            # Tier-pure batches dispatch best tier first within each
            # pool; the phase-2 pool keeps its head start (finish
            # nearly-done work), and admission order breaks ties.
            ordered.sort(key=lambda pb: (
                0 if pb[0] == "phase2" else 1,
                min(slo.rank(e.request) for e in pb[1].entries),
                min(e.seq for e in pb[1].entries)))
        for bi, (pool, batch) in enumerate(ordered):
            if draining and drain_timeout_ms is not None and \
                    (timer() - drain_wall0) * 1000.0 > drain_timeout_ms:
                # Drain budget exhausted: fall back to snapshot-and-exit.
                # Journaled leftovers stay *pending* — no terminal record,
                # so the warm restart serves them exactly once (their
                # hand-off carries were already spilled at hand-off time);
                # without a journal there is no restart, so they resolve
                # to explicit draining rejections, never a silent drop.
                drain_timed_out = True
                leftover = [e for _, b in ordered[bi:] for e in b.entries]
                leftover += [e for b in batcher.flush_all(vnow)
                             for e in b.entries]
                leftover += [e for b in batcher2.flush_all(vnow)
                             for e in b.entries]
                leftover += parked
                parked.clear()
                if sc is not None:
                    # Ready followers have their images in hand: serve
                    # them even on a timed-out drain. The rest sweep with
                    # everything outstanding (journaled: stay pending;
                    # else: explicit draining rejections).
                    yield from flush_followers()
                    leftover += drain_follower_entries()
                leftover += queue.drain()
                if journal is not None:
                    journal.event("drain_timeout", pending=len(leftover),
                                  vnow_ms=round(vnow, 3))
                else:
                    for e in leftover:
                        m_rejects.labels(kind="draining").inc()
                        yield record(
                            "rejected", e.request_id,
                            arrival_ms=e.arrival_ms,
                            reason=f"drain timeout "
                                   f"({drain_timeout_ms:.0f}ms) before "
                                   f"dispatch; no journal to resume from")
                break
            if pool == "phase2":
                yield from dispatch_phase2(batch)
            else:
                yield from dispatch(batch)
            if draining and chaos is not None and \
                    chaos.take_kill(chaos_mod.KILL_DURING_DRAIN):
                # Simulated death mid-drain: batch-boundary durability
                # first (matching the healthy loop's fsync point), then
                # die without records or a summary — the restart's
                # exactly-once contract is what the drill asserts.
                if journal is not None:
                    journal.sync()
                raise chaos_mod.SimulatedKill("chaos kill_during_drain")
            if slo is not None and not draining and \
                    fatal_reason[0] is None and bi + 1 < len(ordered):
                # Dispatch-boundary tier yield: a higher-tier request has
                # ARRIVED (virtual time moved under this batch) while
                # every remaining batch this cycle is lower-tier — hand
                # the cycle back to admission instead of making the
                # arrival wait out the whole backlog. The remaining
                # entries re-enter their batchers (their buckets re-form
                # next cycle); under sustained higher-tier pressure the
                # ladder sheds them rather than starving them silently.
                nxt = trace.peek()
                if isinstance(nxt, Request) and nxt.arrival_ms <= vnow:
                    pending_rank = scheduling_mod.tier_rank(slo.tier(nxt))
                    rest = ordered[bi + 1:]
                    # Urgent (deadline-jumped) batches keep their dispatch
                    # slot: re-queueing one would void the jump it already
                    # took (its deadline can expire during the yielded
                    # cycle) and count the same jump again next cycle.
                    yieldable = [pb for pb in rest if not pb[1].urgent]
                    if yieldable and min(
                            min(slo.rank(e.request) for e in b.entries)
                            for _, b in yieldable) > pending_rank:
                        tier_yields += 1
                        for pool_name, b in yieldable:
                            for e in b.entries:
                                (batcher2 if pool_name == "phase2"
                                 else batcher).add(e, vnow)
                        if len(yieldable) == len(rest):
                            break
                        ordered[bi + 1:] = [pb for pb in rest
                                            if pb[1].urgent]
            if fatal_reason[0] is not None:
                # Fatal fault: drain cleanly — terminal records for every
                # outstanding request, then the summary. Nothing is left
                # wedged; a journaled restart re-serves what never ran.
                # (The blackbox already dumped at the fault itself, inside
                # _fault_verdict, while the doomed contexts were open.)
                leftover = [e for _, b in ordered[bi + 1:]
                            for e in b.entries]
                leftover += [e for b in batcher.flush_all(vnow)
                             for e in b.entries]
                leftover += [e for b in batcher2.flush_all(vnow)
                             for e in b.entries]
                leftover += parked
                parked.clear()
                if sc is not None:
                    # Followers whose leader already resolved ok have the
                    # images in hand — served even on a fatal drain; the
                    # rest fail with everything outstanding (promotion is
                    # suppressed under a fatal, so resolve_leader leaves
                    # them in the follower map for this sweep).
                    yield from flush_followers()
                    leftover += drain_follower_entries()
                leftover += queue.drain()
                for e in leftover:
                    yield record(
                        "error", e.request_id, arrival_ms=e.arrival_ms,
                        reason=f"drained after fatal fault: "
                               f"{fatal_reason[0]}")
                # The trace tail too: requests that had not yet *arrived*
                # still belong to this run's exactly-once contract — they
                # resolve here (never admitted, so no slot to release)
                # rather than silently vanishing with the loop.
                while trace.peek() is not None:
                    item = trace.pop()
                    if (isinstance(item, Cancel)
                            or item.request_id in replay_skip):
                        continue
                    yield record(
                        "error", item.request_id, release=False,
                        arrival_ms=item.arrival_ms,
                        reason=f"drained after fatal fault: "
                               f"{fatal_reason[0]}")
                if journal is not None:
                    journal.event("fatal", reason=fatal_reason[0],
                                  vnow_ms=round(vnow, 3))
                break
        _profile_finalize()
        if journal is not None:
            journal.sync()  # batch boundary: the fsync point
        if chaos is not None and \
                chaos.take_kill(chaos_mod.PREEMPT_THEN_KILL):
            # preempt_then_kill's second half: die at the first batch
            # boundary after the forced preemption — terminals and the
            # `preempted` record are durable (sync above), the parked
            # request has NOT resumed. The restart folds the preempted
            # record like a crashed hand-off and resumes in phase 2 off
            # the spill, exactly-once.
            raise chaos_mod.SimulatedKill("chaos preempt_then_kill")
        maybe_resize()
        if journal is not None:
            if snapshot_every_ms is not None and not draining and \
                    vnow - last_snapshot_ms >= snapshot_every_ms:
                # Periodic snapshot+compaction on the virtual clock, at
                # the fsync point (everything it folds is already
                # durable). Skipped while draining — the drain takes its
                # own final snapshot.
                take_snapshot("periodic")
                last_snapshot_ms = vnow
        if fatal_reason[0] is not None:
            break

    drain_info = None
    if draining:
        # The trace tail: requests that had not yet *arrived* when the
        # drain cut virtual time still resolve explicitly (the fatal-drain
        # discipline — never a silent drop): draining rejections,
        # un-journaled, so a restart's re-fed trace (or the client's
        # resubmission) still serves them.
        while trace.peek() is not None:
            item = trace.pop()
            if isinstance(item, Cancel):
                continue
            if item.request_id in replay_skip:
                m_replay.labels(kind="deduped").inc()
                if replay_info is not None:
                    replay_info["deduped"] += 1
                continue
            m_rejects.labels(kind="draining").inc()
            yield record(
                "rejected", item.request_id, release=False,
                journal_write=False, arrival_ms=item.arrival_ms,
                reason=f"server draining ({drain_ctl.reason}); "
                       f"resubmit after restart")
        if chaos is not None and \
                chaos.take_kill(chaos_mod.KILL_DURING_DRAIN):
            # Still-armed kill (the drain had no dispatches left to ride):
            # die at the drain's nastiest remaining window — terminals
            # flushed, final snapshot not yet taken.
            if journal is not None:
                journal.sync()
            raise chaos_mod.SimulatedKill("chaos kill_during_drain")
        m_draining.set(0)
        drain_info = {"reason": drain_ctl.reason,
                      "pending": queue.outstanding}
        if drain_timed_out:
            drain_info["timed_out"] = True
        if journal is not None:
            info = take_snapshot("drain")
            drain_info["snapshot"] = {
                "seq": info["seq"], "pending": info["pending"],
                "wal_records_folded": info["wal_records_folded"]}
        if flight is not None:
            flight.loop_event("drained", vnow,
                              pending=drain_info["pending"])

    # Final profiler flush: captures stopped by the last (or drain-mode)
    # dispatches fold before the summary reads the ledger.
    _profile_finalize()

    n_batches = len(occupancies)
    lat_sorted = sorted(latencies)
    summary = {
        "request_id": None, "status": "summary",
        "counts": dict(counts),
        "n_batches": n_batches,
        "mean_batch_occupancy": (sum(occupancies) / n_batches
                                 if n_batches else 0.0),
        "dispatch_hit_rate": (sum(batch_hits) / len(batch_hits)
                              if batch_hits else 0.0),
        "program_cache": cache.stats(),
        "prewarm_ms": prewarm_ms,
        "p50_ms": _percentile(lat_sorted, 50),
        "p95_ms": _percentile(lat_sorted, 95),
        "makespan_ms": vnow,
        "faults": dict(fault_counts),
        "retries": retries_total,
        "watchdog_timeouts": timeouts_total,
        "degrade_transitions": degrade_transitions,
    }
    if handoffs_total or resumed_handoffs or any(occ_by_phase.values()):
        # Present only when the disaggregated pools actually ran, so the
        # single-pool summary stays byte-identical (the disabled-mode
        # contract covers the record stream end to end).
        def _pool(occ: List[int]) -> dict:
            return {"batches": len(occ),
                    "mean_occupancy": (sum(occ) / len(occ)) if occ else 0.0}

        summary["phases"] = {
            "handoffs": handoffs_total,
            "resumed_handoffs": resumed_handoffs,
            "phase1": _pool(occ_by_phase["phase1"]),
            "phase2": {**_pool(occ_by_phase["phase2"]),
                       "pack_p50": _percentile(
                           sorted(occ_by_phase["phase2"]), 50)},
            # The pool's global lane cap: per-device knob × mesh width
            # (identical to the knob itself off-mesh / at dp=1).
            "phase2_max_batch": phase2_max_batch * dp,
        }
    if jmesh is not None:
        # Present only when a mesh is active, so the mesh-less summary
        # stays byte-identical (disabled-mode parity). Topology lives
        # HERE, in the ephemeral summary — never in the journal.
        summary["mesh"] = {
            "dp": dp,
            "devices": [int(d) for d in _mesh_dev_ids],
            "max_batch_per_device": max_batch,
            "phase2_max_batch_per_device": phase2_max_batch,
        }
        if elastic_ctl is not None:
            # Under elastic serving the topology is a TIMELINE, not a
            # shape: one epoch per committed width, starting at the
            # width the process came up on. `dp` above reports the final
            # epoch. Gated on the controller so elastic-off summaries
            # stay byte-identical (disabled-mode parity).
            summary["mesh"]["timeline"] = (
                [{"vnow_ms": 0.0, "dp": dp0}]
                + [{"vnow_ms": e["vnow_ms"], "dp": e["new_dp"]}
                   for e in elastic_ctl.timeline])
            summary["elastic"] = elastic_ctl.stats()
    if slo is not None:
        # Present only under an active SloConfig, so slo-less summaries
        # stay byte-identical (disabled-mode parity).
        summary["slo"] = {
            "tiers": {t: {s: n for s, n in c.items() if n}
                      for t, c in slo_tier_counts.items()
                      if any(c.values())},
            "preemptions": preemptions,
            "preempt_resumes": preempt_resumes,
            "deadline_jumps": deadline_jumps,
            "tier_yields": tier_yields,
            "quota_rejects": quota_rejects,
        }
    if costscope is not None:
        # Present only under an active CostScope, so cost-less summaries
        # stay byte-identical (disabled-mode parity).
        summary["cost"] = costscope.summary()
    if prodscope is not None:
        # Present only under an active ProdScope, same parity discipline.
        summary["profile"] = prodscope.summary()
    if sc is not None:
        # Present only under an active SemCache, so cache-less summaries
        # stay byte-identical (disabled-mode parity).
        summary["semcache"] = {
            "layers": sc.layer_stats(),
            "served": dict(sc_served),
            "served_from_cache": (sc_served["l2"] + sc_served["l3"]
                                  + sc_served["collapsed"]),
        }
    if replay_info is not None:
        summary["replay"] = replay_info
    if fatal_reason[0] is not None:
        summary["fatal"] = fatal_reason[0]
    if snapshots_taken:
        # Present only when a snapshot actually ran, so summaries of
        # lifecycle-less runs stay byte-identical (disabled-mode parity).
        summary["snapshots"] = snapshots_taken
    if drain_info is not None:
        summary["drain"] = drain_info
    if journal is not None:
        journal.sync()
    yield summary
