"""Engine lifecycle: graceful drain, signal handling, warm restart.

Before this layer, the serve loop had exactly one way to stop: die. Every
shutdown was a simulated crash — the journal's replay made that *safe*
(exactly-once state), but never *orderly*: in-flight batches were thrown
away, the summary was lost, and the next incarnation paid a full-WAL
replay. This module is the orderly half of the durability story
(``journal.compact`` is the other): a long-running server can now

- **drain** (``DrainController``): stop admitting — new arrivals resolve
  to ``rejected`` records with the ``draining`` kind, deliberately *not*
  journaled as terminal so a resubmission to the restarted server (or the
  re-fed trace of a rolling-restart drill) still serves them — flush both
  batchers, complete in-flight work (phase-2 hand-offs included), take a
  final snapshot, emit the summary, and exit 0;
- bound the drain (``serve_forever(drain_timeout_ms=)``): past the wall-
  clock budget the loop falls back to snapshot-and-exit — journaled
  leftovers stay *pending* (no terminal record, so the warm restart
  serves them exactly once; their hand-off carries were already spilled),
  un-journaled leftovers resolve to explicit draining rejections;
- **warm-restart**: ``--journal`` resumes from the snapshot + WAL tail
  (O(traffic since the last snapshot), not O(process history)), restoring
  the pending queue, the live phase-2 carries, the terminal dedupe set
  and the degradation level.

The controller is deliberately dumb — one latched flag the engine polls at
cycle boundaries — because that is what makes drains *deterministic* under
the virtual clock: a drill can request a drain at an exact record count
and replay the identical control flow every run. :func:`signal_drain`
wires the same flag to SIGTERM/SIGINT for the CLI: first signal = request
a graceful drain; a second = ``KeyboardInterrupt`` (force quit — the
journal's crash contract takes over, which is exactly what it is for).

The ``drain``/``drain_timeout`` events this layer journals, and the
crash contract the force-quit path leans on, are part of the declared
WAL protocol (``p2p_tpu.analysis.protocol``, ISSUE 20): the walcheck
pass replays every bounded schedule with a crash at every record
boundary, torn tail and snapshot window — including the
``drain_timeout``-leaves-pending-exactly-once property asserted by the
drills here — so "the journal's crash contract takes over" is a
machine-checked sentence, not a hopeful one.
"""

from __future__ import annotations

import contextlib
import signal
import threading
from typing import Iterator, Optional, Sequence


class DrainController:
    """A latched drain request the engine polls at cycle boundaries.

    ``request()`` is idempotent (the first reason wins) and safe to call
    from a signal handler, another thread, or mid-iteration from the code
    consuming the record stream — it only ever sets a flag; the engine
    does all the work at its next deterministic check point."""

    def __init__(self):
        self.requested = False
        self.reason: Optional[str] = None

    def request(self, reason: str = "request") -> None:
        if not self.requested:
            self.reason = reason
            self.requested = True


@contextlib.contextmanager
def signal_drain(controller: DrainController,
                 signums: Sequence[int] = (signal.SIGTERM, signal.SIGINT),
                 ) -> Iterator[DrainController]:
    """Route SIGTERM/SIGINT into ``controller`` while the body runs.

    First signal: request a graceful drain (the loop finishes in-flight
    work, snapshots, emits the summary, exits 0). Any further signal:
    raise ``KeyboardInterrupt`` — the operator wants out *now*; the
    journal's crash-replay contract covers what the force-quit abandons.
    Handlers are restored on exit. Off the main thread (where CPython
    forbids ``signal.signal``) this is a documented no-op wrapper."""
    if threading.current_thread() is not threading.main_thread():
        yield controller
        return
    seen = [0]

    def _handler(signum, frame):
        seen[0] += 1
        if seen[0] == 1:
            try:
                name = signal.Signals(signum).name
            except ValueError:
                name = f"signal {signum}"
            controller.request(name)
        else:
            raise KeyboardInterrupt(f"second {signum}: force quit")

    prev = {s: signal.signal(s, _handler) for s in signums}
    try:
        yield controller
    finally:
        for s, h in prev.items():
            signal.signal(s, h)
