"""Phase hand-off: the unit of transfer between the two program pools.

Phase-disaggregated continuous batching splits a gated request's
trajectory across two separately scheduled pools: a phase-1 program (full
CFG + controller hooks, steps ``[0, gate)``) produces a per-lane
:class:`~p2p_tpu.engine.sampler.PhaseCarry` — ``AttnCache`` + latent + CFG
residual + multistep scheduler state (+ the frozen store), ONE pytree with
a pinned treedef — and a phase-2 program (single-branch U-Net off the
cache) consumes it. This module is everything that crosses the boundary:

- :class:`HandoffEntry` — a queued-and-admitted request whose phase 1 has
  completed, waiting in the phase-2 batcher with its hand-off unit
  (``{"carry": PhaseCarry, "ctx": encoded cond context}`` from the real
  runners — the context rides along so phase 2 never re-runs the text
  encoder). The unit is *opaque* to the engine loop (tests hand fake
  runners fake carries); only the runners and the spill path touch its
  leaves.
- :func:`lane_carries` / :func:`stack_carries` — split a pool program's
  ``(G, ...)``-leading carry into per-lane units and re-pack lanes from
  *different* phase-1 batches into one phase-2 batch (padding replicates
  the last real lane, mirroring the batcher's input-padding contract).
- :func:`spill_carry` / :func:`load_carry` / :func:`carry_template` — the
  journal's crash-replay persistence: a carry round-trips through an
  ``.npz`` next to the WAL, validated leaf-by-leaf against the treedef the
  *request* implies, so a restart resumes the request in phase 2 instead
  of re-running phase 1 — and a corrupt/mismatched spill falls back to
  phase 1 instead of feeding a wrong-shaped carry to a compiled program.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, List, Optional

from .queue import Entry


@dataclasses.dataclass
class HandoffEntry:
    """One request between its phases: the original admission entry plus
    the per-lane carry its phase-1 batch produced. Exposes the same
    surface the batcher/queue code reads off an :class:`Entry`, so the
    phase-2 pool rides the identical machinery (aging, deadlines,
    cancellation, priority ordering)."""

    entry: Entry
    carry: Any                      # per-lane carry (opaque to the engine)
    handoff_ms: float               # virtual time phase 1 completed
    phase1: Optional[dict] = None   # phase-1 latency/batch facts for the record
    resumed: bool = False           # reloaded from a journal spill on replay
    #: A chaos 'nan' fault hit this lane's phase-1 dispatch: validation is
    #: a completion-time verdict, so the injection rides the hand-off and
    #: converts the lane to `invalid_output` at phase 2 — matching the
    #: monolithic engine, where the same injection poisons the one batch.
    nan_injected: bool = False
    #: SLO preemption bookkeeping (serve.scheduling): when this entry was
    #: last parked (None = not currently parked) and the total virtual
    #: time it has spent parked — surfaced in the record's ``phases``
    #: detail and attributed as the flight tracer's ``preempt_wait``
    #: stage. The in-memory carry survives a park (the journal spill is
    #: the *crash* copy, not the working copy), so an in-process resume
    #: is trivially bitwise.
    preempted_ms: Optional[float] = None
    preempt_wait_ms: float = 0.0
    #: ISSUE 13: this entry entered phase 2 off a semantic-cache prefix
    #: hit ("l2") instead of a phase-1 dispatch — a prefix hit IS a
    #: hand-off resume, surfaced as ``phases.phase1.cached`` in the
    #: record rather than ``resumed`` (which names the crash-replay path).
    cache_layer: Optional[str] = None

    @property
    def prepared(self):
        return self.entry.prepared

    @property
    def request(self):
        return self.entry.request

    @property
    def request_id(self) -> str:
        return self.entry.request_id

    @property
    def arrival_ms(self) -> float:
        return self.entry.arrival_ms

    @property
    def seq(self) -> int:
        return self.entry.seq

    @property
    def deadline_at(self) -> Optional[float]:
        return self.entry.deadline_at


def lane_carries(carry: Any, n: int) -> List[Any]:
    """Split a pool program's carry (leaves with a leading G axis) into the
    first ``n`` per-lane carries — the hand-off units. Pure tree indexing:
    works on real :class:`PhaseCarry` pytrees and on whatever fake carry a
    test runner returns, as long as leaves index on axis 0."""
    import jax

    return [jax.tree_util.tree_map(lambda x, i=i: x[i], carry)
            for i in range(n)]


def stack_carries(carries: List[Any], bucket: int, mesh=None) -> Any:
    """Re-pack per-lane carries into a phase-2 batch of ``bucket`` lanes,
    replicating the last real carry into the padding lanes (the same
    padding contract as the input batcher: padded lanes are masked out of
    results by ``lane_select``).

    ``mesh``: on a device mesh the lanes being packed may live on
    *different* shards (they came out of different phase-1 batches, each
    sharded over ``dp``), and ``jnp.stack`` refuses cross-committed
    operands. Each lane is staged straight to its TARGET device
    (explicit device-to-device ``device_put`` — no host round-trip), the
    per-device sub-batches are stacked locally, and the global
    ``P("dp")``-sharded batch is assembled from the shards. No device
    ever holds more than its own ``bucket/dp`` lanes — replicating the
    lanes first would transiently put the whole global batch (carry +
    AttnCache) on every chip, defeating the per-device footprint cap the
    dp-scaled phase-2 width exists to honor."""
    import jax
    import jax.numpy as jnp

    carries = list(carries)
    while len(carries) < bucket:
        carries.append(carries[-1])
    if mesh is None:
        return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *carries)
    from jax.sharding import NamedSharding, PartitionSpec

    devices = list(mesh.devices.flat)
    per_dev = bucket // len(devices)  # whole lanes by bucket construction
    gspec = NamedSharding(mesh, PartitionSpec("dp"))

    def pack(*xs):
        shards = []
        for i, d in enumerate(devices):
            block = [jax.device_put(x, d)
                     for x in xs[i * per_dev:(i + 1) * per_dev]]
            shards.append(jnp.stack(block))  # stays on d: all operands on d
        global_shape = (bucket,) + tuple(xs[0].shape)
        return jax.make_array_from_single_device_arrays(
            global_shape, gspec, shards)

    return jax.tree_util.tree_map(pack, *carries)


# ---------------------------------------------------------------------------
# Journal spill: crash-replay resumes in phase 2
# ---------------------------------------------------------------------------


def carry_template(pipe, prep):
    """The hand-off unit this request's phase-1 runner produces — derived
    from the *request* (shapes only, zero-valued), never from a live carry.
    ``{"carry": PhaseCarry, "ctx": (B, L, D) cond context}``: the encoded
    conditional half rides the hand-off so phase 2 (and a journal-resumed
    lane) never re-runs the text encoder. This is the pinned-treedef
    source :func:`load_carry` validates a spill against: the spec a spill
    must match is what the phase-2 program was compiled for, which the
    request alone determines."""
    import jax.numpy as jnp

    from ..controllers.base import init_store_state
    from ..engine.sampler import PhaseCarry
    from ..models.config import unet_layout
    from ..models.unet import init_attn_cache
    from ..ops import schedulers as sched_mod

    b = len(prep.request.prompts)
    cfg = pipe.config
    layout = unet_layout(cfg.unet)
    lat = jnp.zeros((b,) + pipe.latent_shape, jnp.float32)
    ctrl = prep.controller
    state = (init_store_state(layout, b)
             if (ctrl is not None and ctrl.needs_store) else ())
    sched = getattr(prep, "schedule", None)
    if sched is not None:
        # Per-site reuse schedule (ISSUE 15): the hand-off cache holds one
        # (B, P, C) leaf per EVER-CACHED site of the table (cross or
        # self), not the all-cross AttnCache of the uniform gate — the
        # request's schedule determines the spill spec exactly like it
        # determines the phase programs.
        from ..engine import reuse as reuse_mod

        cache = reuse_mod.init_schedule_cache(layout, sched, b, phase=2,
                                              dtype=lat.dtype)
    else:
        cache = init_attn_cache(layout, b, dtype=lat.dtype)
    carry = PhaseCarry(
        latents=lat,
        resid=jnp.zeros_like(lat),
        cache=cache,
        ms=sched_mod.init_multistep_state(prep.request.scheduler, lat.shape,
                                          lat.dtype),
        state=state)
    ctx = jnp.zeros((b, cfg.unet.context_len, cfg.unet.context_dim),
                    jnp.float32)
    return {"carry": carry, "ctx": ctx}


def spill_carry(carry: Any, path: str) -> str:
    """Persist one per-lane carry as an ``.npz`` (leaves in flatten order);
    returns the carry's pinned spec (``engine.sampler.carry_spec``) for the
    journal's ``handoff`` record. Written via a temp file + rename so a
    crash mid-write leaves either the old spill or none — never a torn
    file that parses."""
    import jax
    import numpy as np

    from ..engine.sampler import carry_spec

    leaves = jax.tree_util.tree_flatten(carry)[0]
    host = {f"leaf_{i}": np.asarray(jax.device_get(x))
            for i, x in enumerate(leaves)}
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **host)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return carry_spec(carry)


def load_carry(path: str, template: Any) -> Any:
    """Load a spilled carry, validated leaf-by-leaf (count, shape, dtype)
    against ``template`` (from :func:`carry_template`). Raises
    ``ValueError`` on any mismatch or unreadable file — the caller falls
    back to re-running phase 1 rather than feeding a compiled program a
    carry it was not built for. Leaves are staged back to device
    explicitly (``stage_host``) so a resumed lane dispatches as
    transfer-guard-clean as a fresh one."""
    import jax
    import numpy as np

    from ..engine.sampler import stage_host

    try:
        data = np.load(path)
    except Exception as e:  # noqa: BLE001 — any unreadable spill is a miss
        raise ValueError(f"unreadable carry spill {path!r}: {e}")
    t_leaves, treedef = jax.tree_util.tree_flatten(template)
    leaves = []
    for i, tl in enumerate(t_leaves):
        name = f"leaf_{i}"
        if name not in data:
            raise ValueError(f"carry spill {path!r} missing {name} "
                             f"(expected {len(t_leaves)} leaves)")
        arr = data[name]
        if tuple(arr.shape) != tuple(tl.shape) or \
                str(arr.dtype) != str(tl.dtype):
            raise ValueError(
                f"carry spill {path!r} leaf {i}: {arr.shape}/{arr.dtype} "
                f"does not match the request's pinned spec "
                f"{tuple(tl.shape)}/{tl.dtype}")
        leaves.append(stage_host(arr))
    if len(data.files) > len(t_leaves):
        raise ValueError(f"carry spill {path!r} has {len(data.files)} "
                         f"leaves, expected {len(t_leaves)}")
    return jax.tree_util.tree_unflatten(treedef, leaves)
