"""Bounded admission queue: backpressure, deadlines, cancellation.

Admission control for the serve loop. Capacity counts *outstanding* work —
everything admitted and not yet resolved to a record (waiting here, waiting
in the batcher, or in flight) — so a burst can't buffer unboundedly between
the queue and the batcher. A full queue rejects with a reason
(:class:`Rejected`), never a silent drop: every submitted request resolves
to exactly one structured record downstream.

Deadlines are *relative to arrival* and enforced before dispatch (the
engine calls :func:`expired` when a batch is about to run); cancellation is
a marker checked at the same point — both are only guaranteed for requests
that have not yet dispatched.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from ..obs import metrics as obs_metrics
from . import scheduling
from .request import PreparedRequest


class Rejected(Exception):
    """Admission refused; ``reason`` says why (surfaced in the record).
    ``kind`` is the bounded-cardinality classification telemetry counts by
    (reasons are free text and must not become label values)."""

    def __init__(self, reason: str, kind: str = "backpressure"):
        super().__init__(reason)
        self.reason = reason
        self.kind = kind


@dataclasses.dataclass
class Entry:
    """One admitted request riding the queue → batcher → dispatch path."""

    prepared: PreparedRequest
    arrival_ms: float
    seq: int = 0                 # admission order (stable sort tiebreak)
    dispatch_ms: Optional[float] = None
    ftag: float = 0.0            # weighted-fair finish tag (SLO mode only)

    @property
    def request(self):
        return self.prepared.request

    @property
    def request_id(self) -> str:
        return self.prepared.request.request_id

    @property
    def deadline_at(self) -> Optional[float]:
        d = self.prepared.request.deadline_ms
        return None if d is None else self.arrival_ms + d


def expired(entry: Entry, now_ms: float) -> bool:
    """True when ``entry``'s deadline passed before dispatch."""
    at = entry.deadline_at
    return at is not None and now_ms > at


class AdmissionQueue:
    """Bounded waiting room in front of the batcher.

    ``submit`` raises :class:`Rejected` when outstanding work is at
    capacity; ``drain`` hands waiting entries to the batcher ordered by
    (priority desc, arrival, admission order) while they stay *outstanding*
    until the engine resolves them via ``release`` — that is what makes the
    capacity a bound on the whole undispatched pipeline, not just this
    deque.

    ``slo`` (a :class:`~p2p_tpu.serve.scheduling.SloConfig`, default None)
    enables the SLO-tiered layer: per-tenant outstanding quotas (checked
    before global capacity — the more specific verdict wins, pinned by
    tests/test_slo.py — with the new reject kind ``quota``) and
    weighted-fair drain ordering (tier rank, then priority, then the
    tenants' fair-clock finish tags). ``slo=None`` leaves every byte of
    the original behavior in place."""

    def __init__(self, capacity: int, slo=None):
        if capacity < 1:
            raise ValueError(f"queue capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.slo = slo
        self._fair = scheduling.FairClock() if slo is not None else None
        self._tenant_out: Dict[str, int] = {}
        self._waiting: List[Entry] = []
        self._outstanding: Dict[str, Entry] = {}
        self._cancelled: set = set()
        self._seq = 0
        # Registry-backed depth tracking (docs/OBSERVABILITY.md). Families
        # are get-or-create on the process registry, so multiple queues (or
        # serve runs) share one timeline.
        reg = obs_metrics.registry()
        self._m_depth = reg.gauge(
            "serve_queue_depth", "entries waiting for the batcher")
        self._m_outstanding = reg.gauge(
            "serve_outstanding_requests",
            "admitted-but-unresolved requests (the backpressure bound)")
        self._m_admitted = reg.counter(
            "serve_admitted_total", "requests admitted past backpressure")

    def _update_gauges(self) -> None:
        self._m_depth.set(len(self._waiting))
        self._m_outstanding.set(len(self._outstanding))

    def __len__(self) -> int:
        return len(self._waiting)

    @property
    def outstanding(self) -> int:
        return len(self._outstanding)

    def submit(self, prepared: PreparedRequest, now_ms: float) -> Entry:
        rid = prepared.request.request_id
        if rid in self._outstanding:
            raise Rejected(f"duplicate request_id {rid!r} still in flight",
                           kind="duplicate_id")
        tenant = getattr(prepared.request, "tenant", None)
        if self.slo is not None and self.slo.tenant_quota is not None \
                and tenant is not None \
                and self._tenant_out.get(tenant, 0) >= self.slo.tenant_quota:
            # Checked BEFORE global capacity: when both bounds are blown
            # the tenant's own quota is the actionable verdict (backing
            # off that tenant helps; "retry later" does not) — precedence
            # pinned by tests/test_slo.py.
            raise Rejected(
                f"tenant {tenant!r} at quota "
                f"({self.slo.tenant_quota} outstanding)", kind="quota")
        if len(self._outstanding) >= self.capacity:
            raise Rejected(
                f"queue full ({self.capacity} outstanding); retry later",
                kind="queue_full")
        self._seq += 1
        # Latency accounting starts at the request's TRACE arrival, not the
        # (possibly later) moment the single-threaded loop got around to
        # admitting it — time spent blocked behind a running batch is real
        # queue wait the records must own up to.
        entry = Entry(prepared=prepared,
                      arrival_ms=max(0.0, prepared.request.arrival_ms),
                      seq=self._seq)
        if self.slo is not None:
            entry.ftag = self._fair.tag(
                tenant, self.slo.weight(self.slo.tier(prepared.request)))
            if tenant is not None:
                self._tenant_out[tenant] = \
                    self._tenant_out.get(tenant, 0) + 1
        self._waiting.append(entry)
        self._outstanding[rid] = entry
        self._m_admitted.inc()
        self._update_gauges()
        return entry

    def admit_inflight(self, prepared: PreparedRequest,
                       now_ms: float) -> Entry:
        """Admit a request directly into the *outstanding* set without
        queuing it for the batcher — the crash-replay path for a request
        whose journaled hand-off resumes it mid-pipeline (phase 2): it
        must hold a capacity slot and stay cancellable, but it re-enters
        at the hand-off batcher, not at admission. Same backpressure and
        duplicate-id rules as :meth:`submit`. (Popped by identity, not
        ``list.remove``: Entry equality would compare controller array
        leaves.)"""
        entry = self.submit(prepared, now_ms)
        self._waiting = [e for e in self._waiting if e is not entry]
        self._update_gauges()
        return entry

    def cancel(self, request_id: str) -> bool:
        """Mark an outstanding request cancelled. Returns False for an
        unknown/already-resolved id (the engine surfaces that as a no-op
        record rather than an error — cancelling finished work is benign)."""
        if request_id not in self._outstanding:
            return False
        self._cancelled.add(request_id)
        return True

    def is_cancelled(self, request_id: str) -> bool:
        return request_id in self._cancelled

    def drain(self) -> List[Entry]:
        """Pop every waiting entry for the batcher, highest priority first
        (FIFO within a priority level). Entries remain outstanding.

        Under an :class:`~p2p_tpu.serve.scheduling.SloConfig` the order is
        tier rank first (premium before best-effort), then priority
        within the tier, then the weighted-fair finish tag across
        tenants, then arrival/admission order."""
        if self.slo is None:
            out = sorted(self._waiting,
                         key=lambda e: (-e.request.priority, e.arrival_ms,
                                        e.seq))
        else:
            out = sorted(self._waiting,
                         key=lambda e: (self.slo.rank(e.request),
                                        -e.request.priority, e.ftag,
                                        e.arrival_ms, e.seq))
        self._waiting = []
        self._update_gauges()
        return out

    def release(self, request_id: str) -> None:
        """Resolve one admitted request (record emitted); frees capacity
        (and the tenant's quota slot)."""
        entry = self._outstanding.pop(request_id, None)
        if entry is not None and self.slo is not None:
            tenant = getattr(entry.request, "tenant", None)
            if tenant is not None and tenant in self._tenant_out:
                self._tenant_out[tenant] -= 1
                if self._tenant_out[tenant] <= 0:
                    del self._tenant_out[tenant]
        self._cancelled.discard(request_id)
        self._update_gauges()
