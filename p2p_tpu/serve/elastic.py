"""Elastic mesh serving: pressure-driven dp resize decisions (ISSUE 19).

``serve --elastic`` lets the engine change its own mesh width while
serving. The frozen-topology engine (PR 9) answers load swings only with
the degradation ladder — shed and shrink — which can never *grow*
capacity and wastes healthy chips on the way down. This module is the
ladder run in both directions: an :class:`ElasticController` watches the
same windowed queue-pressure signal ``DegradeConfig`` watches, with
separate sustain windows for scale-up and scale-down plus a cooldown, so
the two directions cannot flap against each other.

The controller only *decides*; the engine executes the journaled resize
protocol at a batch boundary (docs/SERVING.md "Elastic meshes"):

1. pick the target dp — the next power of two up or down, clamped to
   ``[min_dp, max_dp]`` where ``max_dp`` defaults to what the process
   actually has (a decision can never exceed local devices);
2. **prewarm** the target topology's programs out-of-band — compile-ahead
   on the target ``mesh_key`` buckets while the old mesh keeps serving,
   never an in-band compile after cutover;
3. park in-flight phase-1 hand-offs via the spill path (the PR-12
   preemption machinery), journal a ``resize`` event (old/new topology +
   parked ids), fsync;
4. swap the engine's mesh/runner-factory/bucket tables and resume the
   parked carries restaged onto the new shards (``stack_carries(mesh=)``).

Everything between the durable ``resize`` record and cutover completion
is a crash window the ``kill_during_resize`` chaos kind drills: a restart
folds the record's ``new_dp`` out of the WAL (``ReplayState.mesh_dp``)
and comes back *on the target topology*, replaying parked work
exactly-once. The ``resize`` event is declared in
``p2p_tpu.analysis.protocol.DECLARED_EVENTS`` and the restart-on-target
fold is the ``resize-target-restart`` invariant the walcheck pass
(ISSUE 20) machine-checks with a crash injected at every record boundary
around the event — the chaos kind samples the window, the model check
exhausts it.

SLO awareness: a scale-down is deferred while premium-tier work is
waiting (queued or parked) — shrinking under a premium backlog would put
the highest tier behind a cutover pause it never caused. Scale-ups are
never deferred.

Decision thresholds scale with the current width: pressure is judged
per-device (``depth > up_depth · dp`` sustained for ``up_window_ms`` ⇒
grow; ``depth < down_depth · dp`` sustained for ``down_window_ms`` ⇒
shrink), so a mesh twice as wide needs twice the backlog to grow again —
the same per-device-meaning discipline as ``--max-batch``.

Like every serve sidecar, off means off: ``elastic=None`` leaves
records, journal bytes and compiled programs byte-identical (the
disabled-mode parity contract, pinned by the quality gate's ``elastic``
leg).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

#: Resize directions (journal/metric label values).
UP = "up"
DOWN = "down"


@dataclasses.dataclass(frozen=True)
class ElasticConfig:
    """Knobs for the resize decision. Thresholds are *per device*: the
    controller multiplies by the current dp, so the config keeps one
    meaning on any mesh width (the ``--max-batch`` discipline)."""

    #: Grow when outstanding depth stays above ``up_depth · dp`` for
    #: ``up_window_ms`` of virtual time.
    up_depth: int = 8
    up_window_ms: float = 200.0
    #: Shrink when outstanding depth stays below ``down_depth · dp`` for
    #: ``down_window_ms``. The down window is deliberately longer than the
    #: up window (hysteresis): growing is cheap to regret, shrinking under
    #: a lull that was about to end costs a second cutover pause.
    down_depth: int = 2
    down_window_ms: float = 800.0
    #: Minimum virtual-time spacing between committed resizes — the other
    #: half of the anti-flap guarantee.
    cooldown_ms: float = 400.0
    #: dp bounds. ``max_dp=0`` means "what the process has": the engine
    #: resolves it to the largest power of two ≤ local device count.
    min_dp: int = 1
    max_dp: int = 0

    def __post_init__(self):
        if self.min_dp < 1 or self.min_dp & (self.min_dp - 1):
            raise ValueError(
                f"elastic min_dp must be a power of two >= 1, "
                f"got {self.min_dp}")
        if self.max_dp and (self.max_dp < self.min_dp
                            or self.max_dp & (self.max_dp - 1)):
            raise ValueError(
                f"elastic max_dp must be a power of two >= min_dp, "
                f"got {self.max_dp}")
        if self.up_depth <= self.down_depth:
            # The dead band between the two thresholds is the hysteresis;
            # without it a depth sitting on the line grows and shrinks
            # forever.
            raise ValueError(
                f"elastic up_depth ({self.up_depth}) must exceed "
                f"down_depth ({self.down_depth})")


def parse_elastic(spec: str) -> ElasticConfig:
    """Parse the CLI ``--elastic`` value: ``on`` (defaults) or a
    comma-separated ``k=v`` list over the config fields, e.g.
    ``up_depth=8,down_window_ms=800,max_dp=4``."""
    s = spec.strip()
    if s in ("", "on", "default"):
        return ElasticConfig()
    fields = {f.name: f.type for f in dataclasses.fields(ElasticConfig)}
    kw = {}
    for part in s.split(","):
        if "=" not in part:
            raise ValueError(f"--elastic expects 'on' or 'k=v,...', "
                             f"got {spec!r}")
        k, v = part.split("=", 1)
        k = k.strip()
        if k not in fields:
            raise ValueError(f"unknown --elastic field {k!r}; valid: "
                             f"{', '.join(sorted(fields))}")
        kw[k] = (float(v) if "window" in k or "cooldown" in k else int(v))
    return ElasticConfig(**kw)


class ElasticController:
    """The windowed up/down pressure detector plus resize bookkeeping.

    Pure control logic on the engine's virtual clock — no jax, no
    devices, no threads. The engine feeds it the queue depth each loop
    iteration (:meth:`observe`); a non-None return is a *decision* (the
    target dp) which stands until the engine either commits the cutover
    (:meth:`committed`) or the decision becomes stale (depth moved back
    inside the dead band before the cutover ran — :meth:`observe`
    withdraws it)."""

    def __init__(self, config: ElasticConfig, dp: int, ndev: int):
        self.config = config
        self.dp = int(dp)
        max_dp = config.max_dp
        if not max_dp:
            max_dp = 1
            while max_dp * 2 <= ndev:
                max_dp *= 2
        self.max_dp = min(max_dp, pow2_floor(ndev))
        self.min_dp = config.min_dp
        self._pressure_since: Optional[float] = None
        self._calm_since: Optional[float] = None
        self._last_resize: Optional[float] = None
        self.pending_target: Optional[int] = None
        # -- stats the summary/bench sub-record reports -------------------
        self.resizes_up = 0
        self.resizes_down = 0
        self.deferred_slo = 0
        self.prewarm_ms_total = 0.0
        self.pause_ms: List[float] = []
        self.timeline: List[dict] = []

    # -- decision ---------------------------------------------------------
    def observe(self, depth: int, vnow: float,
                premium_waiting: bool = False) -> Optional[int]:
        """Fold one loop iteration's pressure sample. Returns the target
        dp when a resize should run at the next batch boundary, else
        None. ``premium_waiting`` defers *shrink* decisions only."""
        cfg = self.config
        if self._last_resize is not None and \
                vnow - self._last_resize < cfg.cooldown_ms:
            return self.pending_target
        hi = cfg.up_depth * self.dp
        lo = cfg.down_depth * self.dp
        if depth > hi:
            self._calm_since = None
            if self._pressure_since is None:
                self._pressure_since = vnow
            if self.dp < self.max_dp and \
                    vnow - self._pressure_since >= cfg.up_window_ms:
                self.pending_target = self.dp * 2
        elif depth < lo:
            self._pressure_since = None
            if self._calm_since is None:
                self._calm_since = vnow
            if self.dp > self.min_dp and \
                    vnow - self._calm_since >= cfg.down_window_ms:
                if premium_waiting:
                    # Premium traffic never waits on a shrink: hold the
                    # calm timer (the lull is real) but defer the decision
                    # until the premium backlog clears.
                    self.deferred_slo += 1
                    return self.pending_target
                self.pending_target = max(self.min_dp, self.dp // 2)
        else:
            # Inside the dead band: both timers re-arm, and a not-yet-
            # executed decision is withdrawn — the pressure that justified
            # it is gone.
            self._pressure_since = None
            self._calm_since = None
            if self.pending_target is not None:
                self.pending_target = None
        if self.pending_target == self.dp:
            self.pending_target = None
        return self.pending_target

    # -- bookkeeping ------------------------------------------------------
    def committed(self, vnow: float, new_dp: int, *, prewarm_ms: float,
                  pause_ms: float, parked: int, resumed: int) -> dict:
        """The engine finished a cutover: fold the facts, re-arm the
        windows, start the cooldown. Returns the timeline entry."""
        direction = UP if new_dp > self.dp else DOWN
        entry = {"vnow_ms": round(vnow, 3), "old_dp": self.dp,
                 "new_dp": int(new_dp), "direction": direction,
                 "prewarm_ms": round(prewarm_ms, 3),
                 "pause_ms": round(pause_ms, 3),
                 "parked": int(parked), "resumed": int(resumed)}
        self.timeline.append(entry)
        if direction == UP:
            self.resizes_up += 1
        else:
            self.resizes_down += 1
        self.prewarm_ms_total += prewarm_ms
        self.pause_ms.append(pause_ms)
        self.dp = int(new_dp)
        self.pending_target = None
        self._pressure_since = None
        self._calm_since = None
        self._last_resize = vnow
        return entry

    def stats(self) -> dict:
        """The summary's ``elastic`` block / bench ``serve.elastic``
        sub-record (frozen keys — tests/test_bench_rehearsal.py)."""
        return {"resizes_up": self.resizes_up,
                "resizes_down": self.resizes_down,
                "deferred_slo": self.deferred_slo,
                "prewarm_ms": round(self.prewarm_ms_total, 3),
                "cutover_pause_p95_ms": round(_p95(self.pause_ms), 3),
                "parked": sum(e["parked"] for e in self.timeline),
                "resumed": sum(e["resumed"] for e in self.timeline),
                "timeline": list(self.timeline)}


def pow2_floor(n: int) -> int:
    """Largest power of two ≤ ``n`` (≥ 1) — the widest dp a machine with
    ``n`` devices can host."""
    p = 1
    while p * 2 <= max(1, n):
        p *= 2
    return p


def _p95(xs: List[float]) -> float:
    if not xs:
        return 0.0
    ys = sorted(xs)
    return ys[min(len(ys) - 1, int(0.95 * len(ys)))]
