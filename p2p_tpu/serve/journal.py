"""Crash-safe request journal: an append-only JSONL write-ahead log.

A process crash mid-trace must not lose in-flight work. The engine loop
writes one JSON line per request-state transition —

- ``admitted``   — the full request dict, at admission (before any compute)
- ``dispatched`` — the request ids of a batch, when it is handed to a runner
- ``handoff``    — a gated request crossed the phase boundary: its phase-1
  carry was spilled to a sidecar ``.npz`` (under ``<wal>.carry/``) whose
  path + pinned treedef spec ride the record — a restart resumes the
  request in phase 2 off the spill instead of re-running phase 1
- ``terminal``   — request id + final status, when the record is emitted
- ``cache``      — a semantic-cache L3 insert (content digest + result
  spill path), written before its leader's ``terminal`` so a crash in
  between still lets the restart serve the followers from the cache
- ``event``      — loop-level transitions (degradation level changes,
  elastic mesh ``resize`` commits — old/new topology + parked carry ids)

— buffered in userspace and :meth:`Journal.sync`'d (flush + ``os.fsync``)
at batch boundaries, so the fsync cost is paid once per dispatch, not once
per line. On restart, :func:`replay` folds the log into a
:class:`ReplayState`: requests admitted but with no terminal record are the
reconstructed queue (served exactly once by the restarted loop); requests
with a terminal record are never re-run (their ids are deduped out of the
incoming trace). A torn tail — the crash happened mid-``write`` — shows up
as a truncated or garbage line: the reader *skips* it and counts it
(``skipped_corrupt``); corruption is telemetry, never a crash. Duplicate
terminal lines (a crash between the terminal append and the fsync can
replay one) collapse to the first and are counted too.

Delivery semantics: a terminal line is appended when the record is emitted
to the caller, so a crash exactly between compute and emission re-runs that
request (at-least-once compute); a crash after the terminal line treats it
as delivered (outputs are not stored in the WAL — images are the caller's
to persist). Request *state* is exactly-once; see docs/SERVING.md.

**Snapshot + compaction** (:meth:`Journal.compact`): an append-only WAL
grows without bound — replay cost and disk footprint are O(process
history). A *snapshot* captures the replay-folded state — the pending
request dicts (in admission order), the live hand-off records (carry spill
path + pinned spec + optional trace context), the terminal-id dedupe map,
and the loop's degradation level — as an atomic tmp+rename+fsync JSON at
``<wal>.snapshot``, after which the WAL *rotates* (the folded segment is
garbage-collected) and orphaned carry spills (``*.npz.tmp`` from a crash
mid-spill, unreferenced ``*.npz`` from a lost terminal discard) are swept.
Restart cost becomes O(traffic since the last snapshot): :func:`replay`
seeds its fold from the snapshot and only reads the WAL *tail*. Every
crash window is safe by construction:

- crash mid-snapshot-write → only the ``.tmp`` is torn; the visible
  snapshot is the previous good one (or absent) and the WAL is untouched;
- crash between the snapshot rename and the WAL rotation → the snapshot
  and the WAL *overlap*; folding is idempotent (first admission wins,
  duplicate terminals collapse), so replaying both is still exact;
- crash between rotation and old-segment removal → the stale ``.old``
  segment's content is a subset of the snapshot (rotation only ever runs
  after the snapshot fsync) and is swept on the next replay;
- a snapshot that is nevertheless corrupt (operator damage) is ignored
  with a counter and replay falls back to full-WAL folding — correct
  whenever no rotation has discarded history, which is the only state the
  journal's own writer can produce alongside an unreadable snapshot.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Callable, Dict, List, Optional

ADMITTED = "admitted"
DISPATCHED = "dispatched"
HANDOFF = "handoff"
#: A mid-trajectory request was *preempted* at the phase boundary: its
#: carry is parked on disk (same spill machinery as ``handoff``) until
#: pressure clears. Replay folds it exactly like a hand-off — a
#: preempted-then-killed request resumes in phase 2 off the spill, the
#: same fold, the same exactly-once contract (docs/SERVING.md).
PREEMPTED = "preempted"
#: ISSUE 13: a semantic-cache L3 insert — the content-key digest, the
#: leader's request id and the (already durable) result-spill path.
#: Replay folds these into ``ReplayState.cache_entries`` so a restarted
#: engine reseeds its cache index (``SemCache.seed``) and serves a killed
#: leader's followers without recompute: the journal's dedupe map
#: generalized from trace-ids to content keys.
CACHE = "cache"
TERMINAL = "terminal"
EVENT = "event"

#: ISSUE 20: the WAL grammar as ONE registry. Every record type the writer
#: can append — :meth:`Journal._append` rejects anything else at WRITE
#: time, so an unregistered kind is a bug at the append site, never a
#: silently-skipped line discovered at replay. The read side stays
#: tolerant by design (it must survive anything a crash or an operator
#: leaves behind); the write side is strict. The declared-protocol twin
#: of this registry lives in ``analysis/protocol.DECLARED_PROTOCOL`` and
#: the walcheck pass cross-checks the two in both directions.
RECORD_KINDS = (ADMITTED, DISPATCHED, HANDOFF, PREEMPTED, CACHE, TERMINAL,
                EVENT)

#: EVENT sub-kind registry: kind -> the :class:`ReplayState` field the
#: event folds into (``None`` = informational, replay reads past it).
#: This is the single source the writer validates against
#: (:meth:`Journal.event` raises on an unregistered kind) AND the table
#: :func:`replay` folds by — there is no second hand-maintained list of
#: foldable kinds to drift. Adding an event kind means adding it here,
#: declaring it in ``analysis/protocol.DECLARED_EVENTS``, and (if it
#: folds) teaching the fold branch below its payload — the walcheck
#: completeness sweep hard-errors until all three agree.
EVENT_KINDS = {
    "degrade":       "degrade_level",  # pressure ladder up (payload: level)
    "restore":       "degrade_level",  # pressure ladder down (level)
    "resize":        "mesh_dp",        # elastic cutover commit (new_dp)
    "snapshot":      None,             # compaction bookkeeping (seq)
    "cache_shed":    None,             # L2 eviction under pressure
    "drain":         None,             # graceful drain began (reason)
    "drain_timeout": None,             # drain budget expired (pending)
    "fatal":         None,             # fatal-fault drain (reason)
    "profile_drift": None,             # prodscope ledger drift sentinel
}

#: Writer-method name -> the record kind it appends: the static protocol
#: sweep (``analysis/protocol.scan_append_sites``) maps ``journal.<m>()``
#: call sites through this table, so a new writer method is part of the
#: declared grammar or the sweep errors.
WRITER_KINDS = {"admitted": ADMITTED, "dispatched": DISPATCHED,
                "handoff": HANDOFF, "preempted": PREEMPTED,
                "cache_insert": CACHE, "terminal": TERMINAL,
                "event": EVENT}

#: Snapshot sidecar (``<wal>.snapshot``) and the rotated-away segment
#: (``<wal>.old``, transient: exists only inside compact()'s crash window).
SNAPSHOT_SUFFIX = ".snapshot"
OLD_SEGMENT_SUFFIX = ".old"
SNAPSHOT_VERSION = 1

#: Statuses that end a request's life; anything else in a ``terminal``
#: record is skipped as corrupt (a half-written status string).
TERMINAL_STATUSES = ("ok", "rejected", "expired", "timeout", "error",
                     "invalid_output", "cancelled", "shed")


@dataclasses.dataclass
class ReplayState:
    """What a WAL says about a previous incarnation of the loop."""

    pending: List[dict] = dataclasses.field(default_factory=list)
    terminal: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: request id -> its last ``handoff`` record (carry spill path + spec):
    #: a pending id present here resumes in phase 2 when the spill loads.
    handoffs: Dict[str, dict] = dataclasses.field(default_factory=dict)
    skipped_corrupt: int = 0
    duplicate_terminals: int = 0
    #: Degradation level the previous incarnation was running at (from the
    #: snapshot and any later journaled degrade/restore events) — a warm
    #: restart resumes it instead of re-learning the pressure from scratch.
    degrade_level: int = 0
    #: ISSUE 19: the dp the previous incarnation last *committed to* via a
    #: journaled ``resize`` event (0 = never resized / elastic off). A
    #: restart that lands inside the resize window — the record is durable
    #: but the cutover never finished — resumes on this TARGET topology,
    #: not the one the process was started with.
    mesh_dp: int = 0
    #: Snapshot fold facts: whether a snapshot seeded this state, whether a
    #: present-but-unreadable snapshot was ignored, and its sequence number.
    snapshot_loaded: bool = False
    snapshot_corrupt: int = 0
    snapshot_seq: int = 0
    #: WAL-tail records read by THIS fold (every non-blank line attempted),
    #: and the cumulative history (snapshot's folded count + the tail) —
    #: ``wal_records < folded_records`` is the compaction win, asserted by
    #: the rolling-restart drill rather than merely measured.
    wal_records: int = 0
    folded_records: int = 0
    #: Hygiene sweep counters (``sweep=True``): orphaned carry spills
    #: (``*.npz.tmp`` + unreferenced ``*.npz``) and stale rotated segments
    #: removed during this fold.
    orphans_swept: int = 0
    segments_swept: int = 0
    #: content-key digest -> its last ``cache`` record (result-spill path):
    #: the semantic cache's durable index (empty unless the previous
    #: incarnation ran with ``--cache``).
    cache_entries: Dict[str, dict] = dataclasses.field(default_factory=dict)

    @property
    def pending_ids(self):
        return [d["request_id"] for d in self.pending]


def _load_snapshot(spath: str):
    """Read + validate the snapshot sidecar. Returns ``(snap, corrupt)``:
    ``(dict, False)`` for a good snapshot, ``(None, False)`` when absent,
    ``(None, True)`` when present but unreadable/invalid — the caller
    falls back to full-WAL folding with a counter, never a crash."""
    if not os.path.exists(spath):
        return None, False
    try:
        with open(spath, "r", encoding="utf-8", errors="replace") as f:
            snap = json.load(f)
        if not isinstance(snap, dict) or \
                snap.get("version") != SNAPSHOT_VERSION:
            raise ValueError("bad version")
        if not (isinstance(snap.get("pending"), list)
                and all(isinstance(d, dict) and d.get("request_id")
                        for d in snap["pending"])):
            raise ValueError("bad pending")
        if not (isinstance(snap.get("terminal"), dict)
                and all(v in TERMINAL_STATUSES
                        for v in snap["terminal"].values())):
            raise ValueError("bad terminal")
        if not (isinstance(snap.get("handoffs"), dict)
                and all(isinstance(h, dict) and h.get("carry_path")
                        for h in snap["handoffs"].values())):
            raise ValueError("bad handoffs")
        # Optional (ISSUE 13): absent from every cache-less snapshot, so
        # pre-cache snapshots (and cache-off runs) stay byte-identical.
        if not (isinstance(snap.get("cache", {}), dict)
                and all(isinstance(r, dict) and r.get("path")
                        for r in snap.get("cache", {}).values())):
            raise ValueError("bad cache")
        int(snap.get("seq", 0))
        int(snap.get("degrade_level", 0))
        int(snap.get("mesh_dp", 0))
        int(snap.get("folded_records", 0))
        return snap, False
    except (OSError, ValueError, TypeError):
        return None, True


def _sweep(path: str, state: ReplayState, stale_old: bool) -> None:
    """Hygiene half of a fold: drop the stale rotated segment (its content
    is a subset of the snapshot — rotation only runs after the snapshot
    fsync), a leftover snapshot ``.tmp`` (crash mid-write), and orphaned
    carry spills: every ``*.npz.tmp`` (a crash between ``open(tmp)`` and
    ``os.replace``) plus every ``*.npz`` no live hand-off references (a
    crash between the terminal record and its spill discard). Counted on
    ``state``; all removals best-effort."""
    if stale_old and state.snapshot_loaded:
        # Only GC the segment when a snapshot subsumes it. The
        # operator-damage case (segment, no snapshot) keeps the segment on
        # disk: it is the sole durable copy of its pending admissions.
        try:
            os.remove(path + OLD_SEGMENT_SUFFIX)
            state.segments_swept += 1
        except OSError:
            pass
    try:
        os.remove(path + SNAPSHOT_SUFFIX + ".tmp")
        state.orphans_swept += 1
    except OSError:
        pass
    carry_dir = path + ".carry"
    if not os.path.isdir(carry_dir):
        return
    # A spill is referenced while its hand-off record is retained — every
    # NON-terminal id, the same rule compact() snapshots by (a torn WAL
    # can order a hand-off before its readable admission; sweeping the
    # spill while keeping the record would defeat the retention).
    referenced = {os.path.abspath(rec["carry_path"])
                  for rid, rec in state.handoffs.items()
                  if rid not in state.terminal}
    for name in sorted(os.listdir(carry_dir)):
        full = os.path.join(carry_dir, name)
        if name.endswith(".tmp") or \
                (name.endswith(".npz")
                 and os.path.abspath(full) not in referenced):
            try:
                os.remove(full)
                state.orphans_swept += 1
            except OSError:
                pass


def replay(path: str, *, sweep: bool = True) -> ReplayState:
    """Fold the snapshot (if any) plus the WAL at ``path`` into a
    :class:`ReplayState`. Missing file(s) = empty state. Corrupt lines
    (torn tail, garbage bytes, wrong shapes) are skipped and counted — the
    reader must survive anything a crash can leave behind. A corrupt
    snapshot is ignored the same way (``snapshot_corrupt``), falling back
    to full-WAL folding. ``sweep`` (the default) also garbage-collects
    orphaned carry spills and stale rotated segments — pass ``False`` for
    a read-only fold (e.g. :meth:`Journal.compact`'s own)."""
    state = ReplayState()
    admitted: Dict[str, dict] = {}
    order: List[str] = []

    snap, corrupt = _load_snapshot(path + SNAPSHOT_SUFFIX)
    if corrupt:
        state.snapshot_corrupt = 1
    if snap is not None:
        state.snapshot_loaded = True
        state.snapshot_seq = int(snap.get("seq", 0))
        state.degrade_level = int(snap.get("degrade_level", 0))
        state.mesh_dp = int(snap.get("mesh_dp", 0))
        state.folded_records = int(snap.get("folded_records", 0))
        for req in snap["pending"]:
            rid = req["request_id"]
            if rid not in admitted:
                admitted[rid] = req
                order.append(rid)
        state.terminal.update(snap["terminal"])
        state.handoffs.update(snap["handoffs"])
        state.cache_entries.update(snap.get("cache", {}))

    def fold_file(p: str) -> None:
        with open(p, "r", encoding="utf-8", errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                state.wal_records += 1
                try:
                    rec = json.loads(line)
                except ValueError:
                    state.skipped_corrupt += 1
                    continue
                if not isinstance(rec, dict):
                    state.skipped_corrupt += 1
                    continue
                kind = rec.get("type")
                if kind == ADMITTED:
                    req = rec.get("request")
                    rid = isinstance(req, dict) and req.get("request_id")
                    if not rid:
                        state.skipped_corrupt += 1
                        continue
                    if rid not in admitted:  # first admission wins
                        admitted[rid] = req
                        order.append(rid)
                elif kind == TERMINAL:
                    rid = rec.get("id")
                    status = rec.get("status")
                    if not rid or status not in TERMINAL_STATUSES:
                        state.skipped_corrupt += 1
                        continue
                    if rid in state.terminal:
                        state.duplicate_terminals += 1
                    else:
                        state.terminal[rid] = status
                elif kind in (HANDOFF, PREEMPTED):
                    # A preempted record is a hand-off the scheduler made
                    # early: same spill, same resume point, same fold.
                    rid = rec.get("id")
                    if not rid or not rec.get("carry_path"):
                        state.skipped_corrupt += 1
                        continue
                    state.handoffs[rid] = rec  # last hand-off wins (retries)
                elif kind == CACHE:
                    key = rec.get("key")
                    if not key or not rec.get("path"):
                        state.skipped_corrupt += 1
                        continue
                    state.cache_entries[key] = rec  # last insert wins
                elif kind in (DISPATCHED, EVENT):
                    # Informational for replay — except the EVENT sub-kinds
                    # the registry marks foldable: degradation transitions
                    # (the warm restart resumes the level) and the elastic
                    # ``resize`` commits (whose TARGET topology a
                    # mid-resize restart must come back on). The fold field
                    # comes from EVENT_KINDS, so a foldable kind cannot be
                    # registered without a fold rule here (the walcheck
                    # model checker exercises every registered kind).
                    folds = (EVENT_KINDS.get(rec.get("kind"))
                             if kind == EVENT else None)
                    if folds == "degrade_level":
                        try:
                            state.degrade_level = int(rec.get("level"))
                        except (TypeError, ValueError):
                            pass
                    elif folds == "mesh_dp":
                        try:
                            state.mesh_dp = int(rec.get("new_dp"))
                        except (TypeError, ValueError):
                            pass
                else:
                    state.skipped_corrupt += 1

    stale_old = os.path.exists(path + OLD_SEGMENT_SUFFIX)
    if stale_old and snap is None:
        # A rotated segment with no readable snapshot can only come from
        # operator damage (the writer rotates strictly after the snapshot
        # fsync): fold it best-effort before the tail.
        fold_file(path + OLD_SEGMENT_SUFFIX)
    if os.path.exists(path):
        fold_file(path)
    state.folded_records += state.wal_records
    state.pending = [admitted[rid] for rid in order
                     if rid not in state.terminal]
    if sweep:
        _sweep(path, state, stale_old)
    return state


class Journal:
    """Append handle + the replay state of whatever the file already held.

    Opening reads the existing log first (:func:`replay`), then appends —
    one file is both the previous incarnation's evidence and the current
    one's WAL, so a chain of crashes keeps folding into one history."""

    def __init__(self, path: str):
        self.path = path
        self.replay_state = replay(path)
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")
        self._dirty = False

    # -- writers ----------------------------------------------------------
    def _append(self, rec: dict) -> None:
        # Unregistered kinds fail HERE, at write time — a typo'd record
        # type would otherwise be appended fine and only surface as a
        # skipped_corrupt line at the next crash's replay (ISSUE 20).
        if rec.get("type") not in RECORD_KINDS:
            raise ValueError(
                f"unregistered journal record type {rec.get('type')!r}; "
                f"registered: {', '.join(RECORD_KINDS)}")
        self._f.write(json.dumps(rec) + "\n")
        self._dirty = True

    def admitted(self, request_dict: dict, vnow: float) -> None:
        self._append({"type": ADMITTED, "request": request_dict,
                      "vnow_ms": round(vnow, 3)})

    def dispatched(self, request_ids, batch_index: int, vnow: float,
                   phase: int = 0) -> None:
        rec = {"type": DISPATCHED, "ids": list(request_ids),
               "batch": batch_index, "vnow_ms": round(vnow, 3)}
        if phase:
            rec["phase"] = phase
        self._append(rec)

    def handoff(self, request_id: str, vnow: float, carry_path: str,
                spec: str, trace: dict = None) -> None:
        """One gated request crossed the phase boundary; its carry spill at
        ``carry_path`` (already durably written) matches ``spec``.
        ``trace`` is the request's flight-trace context (``obs.flight``):
        it rides the WAL so a crash-replayed request resumed in phase 2 by
        a different process can stitch its timeline to the pre-crash
        phase-1 segments (absent when flight tracing is off — the record
        stays byte-identical to the pre-tracing schema)."""
        rec = {"type": HANDOFF, "id": request_id,
               "carry_path": carry_path, "spec": spec,
               "vnow_ms": round(vnow, 3)}
        if trace is not None:
            rec["trace"] = trace
        self._append(rec)

    def preempted(self, request_id: str, vnow: float, carry_path: str,
                  spec: str, tier: str = None, trace: dict = None) -> None:
        """One request was preempted at the phase boundary (its carry is
        parked at ``carry_path``, durably spilled, matching ``spec``).
        Schema = the ``handoff`` record plus the victim's ``tier`` —
        replay folds the two identically, so a preempted-then-killed
        request resumes exactly like a crashed hand-off."""
        rec = {"type": PREEMPTED, "id": request_id,
               "carry_path": carry_path, "spec": spec,
               "vnow_ms": round(vnow, 3)}
        if tier is not None:
            rec["tier"] = tier
        if trace is not None:
            rec["trace"] = trace
        self._append(rec)

    def carry_path(self, request_id: str) -> str:
        """Where this WAL spills a request's hand-off carry: a sidecar dir
        next to the log, one ``.npz`` per request id."""
        import hashlib

        # Request ids are caller-chosen free text: hash them into the
        # filename so a hostile/awkward id ("../x", 300 chars) cannot
        # escape or break the sidecar dir; the id itself stays in the WAL.
        digest = hashlib.sha256(request_id.encode()).hexdigest()[:24]
        return os.path.join(self.path + ".carry", digest + ".npz")

    def discard_carry(self, request_id: str) -> None:
        """Drop a terminal request's spill (hygiene; best-effort)."""
        try:
            os.remove(self.carry_path(request_id))
        except OSError:
            pass

    def cache_insert(self, key: str, request_id: str, path: str,
                     vnow: float) -> None:
        """One semantic-cache L3 insert (ISSUE 13): ``key`` is the content
        digest, ``path`` the result spill (already durably written by
        ``SemCache.l3_put`` — tmp+fsync+rename — so this record can never
        point at a file a crash loses). Appended *before* the leader's
        terminal line: the ``kill_after_cache_insert`` chaos window is a
        durable insert with no terminal, which replay must serve the
        followers from."""
        self._append({"type": CACHE, "key": key, "id": request_id,
                      "path": path, "vnow_ms": round(vnow, 3)})

    def terminal(self, request_id: str, status: str, vnow: float) -> None:
        self._append({"type": TERMINAL, "id": request_id, "status": status,
                      "vnow_ms": round(vnow, 3)})

    def event(self, kind: str, **fields) -> None:
        """Append a loop-level EVENT. ``kind`` must be registered in
        :data:`EVENT_KINDS` — the raise happens at the append site, not as
        a silent informational line a replay ignores forever."""
        if kind not in EVENT_KINDS:
            raise ValueError(
                f"unregistered journal event kind {kind!r}; registered: "
                f"{', '.join(sorted(EVENT_KINDS))}")
        self._append({"type": EVENT, "kind": kind, **fields})

    def sync(self) -> None:
        """Flush + fsync — called at batch boundaries, not per line."""
        if not self._dirty:
            return
        self._f.flush()
        os.fsync(self._f.fileno())
        self._dirty = False

    def compact(self, extra: Optional[dict] = None,
                on_durable: Optional[Callable[[], None]] = None) -> dict:
        """Snapshot the replay-folded state, then rotate + GC the WAL.

        1. sync the WAL, fold it (plus any previous snapshot) read-only;
        2. write the new snapshot to ``<wal>.snapshot.tmp``, fsync, rename
           over ``<wal>.snapshot``, fsync the directory — atomic: a crash
           leaves either the previous snapshot or the new one, never a
           torn file;
        3. (``on_durable`` fires here — the chaos ``kill_during_snapshot``
           hook: the snapshot is durable but the WAL has not rotated, so a
           restart must fold the two idempotently);
        4. rotate: the WAL moves aside and a fresh empty segment opens —
           replay cost is now O(traffic since this snapshot);
        5. GC: the rotated segment and orphaned carry spills are removed.

        ``extra`` merges engine-side state the WAL itself cannot fold
        (currently ``degrade_level``). Returns the compaction facts the
        engine's summary/metrics report."""
        self.sync()
        state = replay(self.path, sweep=False)
        # Keep every non-terminal hand-off, not just currently-pending
        # ones: a torn WAL can order a hand-off before its admission is
        # readable, and dropping it here would lose the resume if the
        # admission only lands in the post-snapshot tail.
        handoffs = {rid: rec for rid, rec in state.handoffs.items()
                    if rid not in state.terminal}
        snap = {"version": SNAPSHOT_VERSION,
                "seq": state.snapshot_seq + 1,
                "pending": state.pending,
                "handoffs": handoffs,
                "terminal": state.terminal,
                "degrade_level": int((extra or {}).get(
                    "degrade_level", state.degrade_level)),
                "folded_records": state.folded_records}
        # Optional (ISSUE 19): only elastic runs that have resized carry
        # a topology, so pre-elastic snapshots stay byte-identical.
        mesh_dp = int((extra or {}).get("mesh_dp", state.mesh_dp))
        if mesh_dp:
            snap["mesh_dp"] = mesh_dp
        # Cache index entries whose spill still exists (eviction deletes
        # the file but cannot rewrite history — the snapshot drops the
        # stale pointer instead). Key absent when empty, so cache-less
        # snapshots stay byte-identical to the pre-cache schema.
        cache = {k: r for k, r in state.cache_entries.items()
                 if os.path.exists(str(r.get("path", "")))}
        if cache:
            snap["cache"] = cache
        spath = self.path + SNAPSHOT_SUFFIX
        tmp = spath + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(snap, f)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, spath)
        dfd = os.open(os.path.dirname(spath) or ".", os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        if on_durable is not None:
            on_durable()
        # Rotate: everything in the current segment is folded into the
        # durable snapshot, so the segment is garbage. A crash anywhere in
        # here leaves a state replay() folds exactly (idempotent overlap /
        # stale-segment sweep — see the module docstring).
        self._f.close()
        old = self.path + OLD_SEGMENT_SUFFIX
        os.replace(self.path, old)
        self._f = open(self.path, "a", encoding="utf-8")
        self._dirty = False
        try:
            os.remove(old)
        except OSError:
            pass
        gc_state = ReplayState(pending=state.pending, handoffs=handoffs,
                               snapshot_loaded=True)
        _sweep(self.path, gc_state, stale_old=False)
        return {"seq": snap["seq"],
                "pending": len(state.pending),
                "terminal": len(state.terminal),
                "handoffs": len(handoffs),
                "wal_records_folded": state.wal_records,
                "folded_records": state.folded_records,
                "orphans_swept": gc_state.orphans_swept,
                "bytes": os.path.getsize(spath)}

    def close(self) -> None:
        try:
            self.sync()
        finally:
            self._f.close()

    def __enter__(self) -> "Journal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
